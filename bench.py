"""Benchmark: ALL FIVE BASELINE.json configs (+ a scaled addsum), measured
every run.

1. ``addsum`` — config #1: ``xp.add(a, b).sum()`` on 5000x5000 f64 at
   (1000, 1000) chunks.
2. ``matmul`` — config #4: ``sum(a @ b)`` on 4000x4000 at (1000, 1000)
   chunks — the blockwise contraction + tree-reduce path, reported in
   GFLOP/s (the MXU configuration).
3. ``elemwise`` — config #2: a fused unary+binary elementwise chain
   ``sum(sqrt(|sin(a)*b + cos(b)|))`` on 6000x6000.
4. ``reduce`` — config #3: 2-level axis reduction ``max(mean(a, axis=0))``
   on 8000x8000 via the reduction tree.
5. ``vorticity`` — config #5: the pangeo-vorticity pipeline (reference
   examples/pangeo-vorticity.ipynb): four random arrays,
   ``mean(a[1:]*x + b[1:]*y)`` at (500, 450, 400) f64, chunks=100 (the
   notebook's (1000,900,800) exceeds one chip's HBM; the driver's mesh
   dryrun covers the sharded path).

A sixth metric line, ``addsum_scaled`` (16000x16000), keeps config #1
informative: the canonical 400 MB shape completes inside the ~70 ms
dispatch/sync latency floor on device, so only the scaled variant can
detect framework-level changes.

Driver-survivable by construction: the parent process never imports jax and
never touches the device tunnel; each phase runs in a subprocess with its
own timeout; a cheap smoke subprocess detects a dead/wedged tunnel up front
so its budget isn't burned by hangs; and one JSON line per config is always
printed before the overall deadline (the driver parses the LAST line — the
vorticity headline). A dead tunnel is retried, not just tolerated: the CPU
fallbacks are measured first (numbers in hand), with bounded re-probes of
the tunnel in between — it has recovered mid-round before — and a revival
switches the run back to device measurement.

- The numpy baselines (reference's single-process PythonDagExecutor
  semantics) are measured once and recorded in ``BASELINE_RECORDED.json``
  (committed); they are only re-measured if the record is absent.
- The TPU phases run with the inherited (device) environment. If the smoke
  test or a phase fails, the framework is re-measured on the virtual CPU
  backend in a tunnel-free subprocess and reported with an explicit
  ``cpu_fallback`` metric name — degraded, never silent.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
RECORD_PATH = os.path.join(REPO, "BASELINE_RECORDED.json")

OVERALL_DEADLINE_S = 540  # print the JSON lines well inside 10 minutes
BASELINE_TIMEOUT_S = 240
SMOKE_TIMEOUT_S = 75

SHAPE = (500, 450, 400)
CHUNK = 100
_elems = SHAPE[0] * SHAPE[1] * SHAPE[2]
#: bytes flowing through the pipeline: 4 generated arrays + 2 sliced reads
WORK_BYTES = 6 * _elems * 8

#: BASELINE.json config #1: xp.add(a, b).sum() on 5000x5000 f64 @ (1000,1000)
ADDSUM_SHAPE = (5000, 5000)
ADDSUM_CHUNK = 1000
#: 2 generated arrays + 1 fused add+sum pass over both
ADDSUM_WORK_BYTES = 2 * ADDSUM_SHAPE[0] * ADDSUM_SHAPE[1] * 8

#: scaled addsum variant: the canonical 400 MB config finishes in the ~70 ms
#: dispatch/sync latency floor on device (BENCH_PROFILE.md), so it can no
#: longer detect framework changes; 16000x16000 (4.1 GB through the pipe)
#: runs ~10x the floor while keeping the same op shape
ADDSUM_SCALED_SHAPE = (16000, 16000)
ADDSUM_SCALED_CHUNK = 2000
ADDSUM_SCALED_WORK_BYTES = 2 * ADDSUM_SCALED_SHAPE[0] * ADDSUM_SCALED_SHAPE[1] * 8

#: BASELINE.json config #4: matmul/tensordot via blockwise contraction.
#: sum(a @ b) keeps the output on-device (a scalar fetch, not a 128MB
#: transfer), so the number measures the contraction, not the tunnel.
MATMUL_N = 4000
MATMUL_CHUNK = 1000
MATMUL_FLOPS = 2 * MATMUL_N**3

#: BASELINE.json config #2: unary+binary elementwise chain (the Array-API
#: elementwise suite shape): sum(sqrt(|sin(a)*b + cos(b)|)) — 2 generated
#: arrays, 6 elementwise ops fused into one pass, then a tree-reduce.
ELEMWISE_SHAPE = (6000, 6000)
ELEMWISE_CHUNK = 1000
ELEMWISE_WORK_BYTES = 2 * ELEMWISE_SHAPE[0] * ELEMWISE_SHAPE[1] * 8

#: BASELINE.json config #3: axis reductions via core.ops.reduction
#: tree-reduce: max(mean(a, axis=0)) — a 2-level reduction over both axes.
REDUCE_SHAPE = (8000, 8000)
REDUCE_CHUNK = 1000
REDUCE_WORK_BYTES = REDUCE_SHAPE[0] * REDUCE_SHAPE[1] * 8

_T0 = time.monotonic()


def _remaining(cap: float) -> float:
    return max(10.0, min(cap, OVERALL_DEADLINE_S - (time.monotonic() - _T0)))


WORKLOAD = r"""
import json, sys, tempfile, time
sys.path.insert(0, {repo!r})
import cubed_tpu as ct
import cubed_tpu.array_api as xp
import cubed_tpu.random

spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="4GB")
workload = {workload!r}
executor = None
if {use_jax_executor!r}:
    from cubed_tpu.runtime.executors.jax import JaxExecutor
    if workload == "matmul_bf16":
        # the MXU opt-in: f32 storage/elementwise, one-pass bf16 contractions
        executor = JaxExecutor(
            compute_dtype="float32", matmul_precision="bfloat16"
        )
    elif workload == "vorticity_f32":
        # f32 ingestion for the f64 pipeline (v5e has no native f64)
        executor = JaxExecutor(compute_dtype="float32")
    else:
        executor = JaxExecutor()

def build():
    if workload in ("addsum", "addsum_scaled"):
        if workload == "addsum":
            shape, chunk = {addsum_shape!r}, {addsum_chunk!r}
        else:
            shape, chunk = {addsum_scaled_shape!r}, {addsum_scaled_chunk!r}
        a = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
        b = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
        return xp.sum(xp.add(a, b))
    if workload in ("matmul", "matmul_bf16"):
        n, chunk = {matmul_n!r}, {matmul_chunk!r}
        a = cubed_tpu.random.random((n, n), chunks=chunk, spec=spec)
        b = cubed_tpu.random.random((n, n), chunks=chunk, spec=spec)
        return xp.sum(xp.matmul(a, b))
    if workload == "elemwise":
        shape, chunk = {elemwise_shape!r}, {elemwise_chunk!r}
        a = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
        b = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
        return xp.sum(
            xp.sqrt(xp.abs(xp.add(xp.multiply(xp.sin(a), b), xp.cos(b))))
        )
    if workload == "reduce":
        shape, chunk = {reduce_shape!r}, {reduce_chunk!r}
        a = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
        return xp.max(xp.mean(a, axis=0))
    shape, chunk = {shape!r}, {chunk!r}
    a = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
    b = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
    x = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
    y = cubed_tpu.random.random(shape, chunks=chunk, spec=spec)
    return xp.mean(xp.add(xp.multiply(a[1:], x[1:]), xp.multiply(b[1:], y[1:])))

kw = dict(executor=executor) if executor is not None else {{}}
if {warmup!r}:
    # compile warmup (persistent cache + in-process caches)
    w0 = time.perf_counter()
    build().compute(**kw)
    print("warmup done in", round(time.perf_counter() - w0, 2), "s",
          file=sys.stderr, flush=True)

# capture the per-compute observability snapshot (task counters, IO bytes,
# per-op wall clock) so bench records carry metric trajectories for free
class _StatsCapture:
    stats = None
    def on_compute_end(self, event):
        self.stats = event.executor_stats

cap = _StatsCapture()
s = build()
t0 = time.perf_counter()
val = s.compute(callbacks=[cap], **kw)
t1 = time.perf_counter()
v = float(val)
if workload in ("addsum", "addsum_scaled"):
    sh = {addsum_shape!r} if workload == "addsum" else {addsum_scaled_shape!r}
    n = sh[0] * sh[1]
    assert 0.95 < v / n < 1.05, v  # sum of u1+u2 has mean 1.0 per element
elif workload in ("matmul", "matmul_bf16"):
    n = {matmul_n!r}
    # E[sum(A@B)] = n^3/4 for uniforms; bf16 input rounding widens the window
    lo, hi = (0.85, 1.15) if workload == "matmul_bf16" else (0.9, 1.1)
    assert lo < v / (0.25 * n**3) < hi, v
elif workload == "elemwise":
    n = {elemwise_shape!r}[0] * {elemwise_shape!r}[1]
    assert 0.5 < v / n < 1.1, v  # E[sqrt(|sin(u)v + cos(v)|)] is O(1)
elif workload == "reduce":
    assert 0.45 < v < 0.55, v  # max over 8000 column means of uniforms ~ 0.5
else:
    assert 0.45 < v < 0.55, v  # mean of u1*u2 + u3*u4 over uniforms is ~0.5
print(json.dumps(
    {{"elapsed": t1 - t0, "value": v, "executor_stats": cap.stats}},
    default=str,
), flush=True)
"""

SMOKE = r"""
import time, sys
import jax, jax.numpy as jnp
t0 = time.perf_counter()
x = jax.jit(lambda: jnp.sum(jnp.ones((256, 256), jnp.float32)))()
print("smoke ok", float(x), round(time.perf_counter() - t0, 2), flush=True)
"""

#: fleet sizes for the scaling sweep (tasks/sec per size; efficiency is
#: tps(n) / (n * tps(1))). 16/32 are production-ish fleet sizes: the
#: ROADMAP item-5 target is that scaling efficiency there is a TRACKED,
#: gated number, not an anecdote — worker processes are sleep-bound, so a
#: 2-core container can still host 32 of them meaningfully
FLEET_SIZES = (1, 2, 4, 8, 16, 32)
#: tasks in the sweep workload and the per-task sleep: sleep-bound bodies
#: make tasks/sec measure the FLEET's dispatch/requeue machinery (what the
#: autoscaler and drain path touch), not this host's core count. 128
#: tasks keep the largest fleet at 4 tasks/worker so the number still
#: measures sustained dispatch, not a one-round burst
FLEET_TASKS = 128
FLEET_TASK_DELAY_S = 0.05

FLEET_SCALING = r"""
import json, sys, tempfile, threading, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor


class SleepAdd:
    def __init__(self, delay_s):
        self.delay_s = delay_s

    def __call__(self, x):
        time.sleep(self.delay_s)
        return x + 1.0


an = np.arange({tasks!r} * 4, dtype=np.float64).reshape(-1, 4)
out = {{}}
reg = get_registry()
for n in {sizes!r}:
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB")
    a = ct.from_array(an, chunks=(1, 4), spec=spec)  # one row per task
    r = ct.map_blocks(SleepAdd({delay!r}), a, dtype=np.float64)
    ex = DistributedDagExecutor(n_local_workers=n)
    # the dispatch_utilization gauge is live only while the dispatch loop
    # runs (the loop zeroes it on exit), so sample it from the side during
    # the compute; overhead/frame numbers are counter deltas (full
    # snapshot(), not snapshot_delta — gauges never survive the delta)
    before = reg.snapshot()
    util_samples = []
    stop = threading.Event()

    def sample(samples=util_samples, ev=stop):
        while not ev.wait(0.2):
            u = reg.snapshot().get("dispatch_utilization")
            if u:
                samples.append(u)

    try:
        ex._ensure_fleet()  # boot outside the timed window
        threading.Thread(target=sample, daemon=True).start()
        t0 = time.perf_counter()
        val = np.asarray(r.compute(executor=ex))
        elapsed = time.perf_counter() - t0
    finally:
        stop.set()
        ex.close()
    assert (val == an + 1.0).all()
    after = reg.snapshot()
    delta = lambda k: (after.get(k) or 0) - (before.get(k) or 0)
    out[str(n)] = {{
        "tasks_per_s": {tasks!r} / elapsed,
        # peak windowed utilization: the saturation signal ("pegged at
        # ~1.0 while queue_depth grows" is what the alert fires on)
        "dispatch_utilization": (
            max(util_samples) if util_samples else None
        ),
        "dispatch_overhead_ms": delta("dispatch_submit_s")
        / {tasks!r} * 1000.0,
        "coord_frames_sent": delta("coord_frames_sent"),
    }}
    print("fleet", n, "workers:",
          round(out[str(n)]["tasks_per_s"], 1), "tasks/s,",
          "dispatch", round(out[str(n)]["dispatch_overhead_ms"], 3),
          "ms/task, util", out[str(n)]["dispatch_utilization"],
          file=sys.stderr, flush=True)
print(json.dumps(out), flush=True)
"""


#: deep-chain critical-path config (pangeo-vorticity-style depth without
#: its volume): DEPTH non-fusable map_blocks steps over an NxN grid of
#: CHUNKxCHUNK blocks, with a ROTATING straggler — at depth d, block
#: (d mod nblocks) sleeps DELAY. Under the op-level scheduler every op
#: waits for its own straggler (wall ≈ DEPTH x DELAY); under the dataflow
#: scheduler the straggler chains are independent 1:1 chunk chains, so
#: wall ≈ DELAY + work. The ratio is the number the barrier kill is on
#: the hook for.
SCHED_DEPTH = 6
SCHED_N = 8
SCHED_CHUNK = 2
SCHED_DELAY_S = 0.4

SCHEDULER_OVERLAP = r"""
import json, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor

DEPTH, N, CHUNK, DELAY = {depth!r}, {n!r}, {chunk!r}, {delay!r}
NBR = N // CHUNK


class StragglerStep:
    def __init__(self, depth):
        self.depth = depth

    def __call__(self, x, block_id=None):
        if block_id[0] * NBR + block_id[1] == self.depth % (NBR * NBR):
            time.sleep(DELAY)
        return x + 1.0


an = np.arange(N * N, dtype=np.float64).reshape(N, N)
out = {{}}
for mode in ("oplevel", "dataflow"):
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB",
                   scheduler=mode)
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    r = a
    for d in range(DEPTH):
        r = ct.map_blocks(StragglerStep(d), r, dtype=np.float64)
    reg = get_registry()
    before = reg.snapshot()
    t0 = time.perf_counter()
    # optimize_graph=False keeps the chain DEEP (fusion would collapse a
    # pure elementwise chain into one op and hide the barrier question)
    val = np.asarray(r.compute(executor=AsyncPythonDagExecutor(),
                               optimize_graph=False))
    elapsed = time.perf_counter() - t0
    delta = reg.snapshot_delta(before)
    assert (val == an + DEPTH).all()
    out[mode] = {{
        "elapsed": elapsed,
        "tasks_dispatched_early": delta.get("tasks_dispatched_early", 0),
        "op_barrier_waits": delta.get("op_barrier_waits", 0),
    }}
    print("scheduler", mode, round(elapsed, 2), "s",
          file=sys.stderr, flush=True)
out["speedup"] = out["oplevel"]["elapsed"] / max(
    out["dataflow"]["elapsed"], 1e-9
)
print(json.dumps(out), flush=True)
"""


def measure_scheduler_overlap(timeout: float):
    """Deep-chain critical path: op-level vs dataflow wall clock.

    Runs tunnel-free (threaded executor, host compute only). Returns
    ``{"oplevel": {...}, "dataflow": {...}, "speedup": x}`` or None on
    failure — additive, never the reason a bench run dies."""
    script = SCHEDULER_OVERLAP.format(
        repo=REPO, depth=SCHED_DEPTH, n=SCHED_N, chunk=SCHED_CHUNK,
        delay=SCHED_DELAY_S,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"scheduler overlap failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        return json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:
        print(f"scheduler overlap sweep skipped: {e}", file=sys.stderr)
        return None


def measure_fleet_scaling(timeout: float):
    """tasks/sec on the distributed fleet at 1→2→4→8→16→32 local workers.

    Runs tunnel-free (the fleet path never touches a device); each size
    boots a fresh fleet, runs a sleep-bound ``FLEET_TASKS``-task compute,
    and reports tasks/sec. The parent derives per-size scaling efficiency
    (``tps(n) / (n * tps(1))``) so fleet-dispatch regressions become a
    tracked number instead of an anecdote — and, per size, the
    control-plane story behind the curve: peak ``dispatch_utilization``,
    mean per-task ``dispatch_overhead_ms`` and coordinator frames sent,
    so "the coordinator saturates" is a recorded trajectory, not a
    profiling session. Returns ``None`` on failure — the scaling record
    is additive, never the reason a bench run dies."""
    script = FLEET_SCALING.format(
        repo=REPO, sizes=list(FLEET_SIZES), tasks=FLEET_TASKS,
        delay=FLEET_TASK_DELAY_S,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"fleet scaling failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        rows = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception as e:
        print(f"fleet scaling sweep skipped: {e}", file=sys.stderr)
        return None
    tps = {size: row["tasks_per_s"] for size, row in rows.items()}
    dispatch = {
        size: {
            k: row.get(k)
            for k in (
                "dispatch_utilization", "dispatch_overhead_ms",
                "coord_frames_sent",
            )
        }
        for size, row in rows.items()
    }
    base = tps.get("1")
    efficiency = {
        size: tp / (int(size) * base)
        for size, tp in tps.items()
        if base and int(size) > 1
    }
    return {
        "tasks_per_s": tps, "efficiency": efficiency, "dispatch": dispatch,
    }


#: coordinator-recovery workload: enough sleep-bound tasks that the kill
#: reliably lands mid-compute, small enough to keep the 3-phase sweep
#: (uninterrupted / killed-at-50% / resume) under ~30s of compute
RECOVERY_TASKS = 36
RECOVERY_TASK_DELAY_S = 0.12

COORD_RECOVERY = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

mode = sys.argv[1]


def sleep_add(x):
    time.sleep({delay!r})
    return x + 1.0


spec = ct.Spec(work_dir={work_dir!r}, allowed_mem="2GB",
               journal={journal!r})
an = np.arange({tasks!r} * 4, dtype=np.float64).reshape(-1, 4)
a = ct.from_array(an, chunks=(1, 4), spec=spec)  # one row per task
r = ct.map_blocks(sleep_add, a, dtype=np.float64)
total = r.plan.num_tasks()

ex = DistributedDagExecutor(n_local_workers=2)
try:
    ex._ensure_fleet()  # boot outside the timed window
    reg = get_registry()
    before = reg.snapshot()
    t0 = time.perf_counter()
    if mode == "resume":
        val = ex.resume_compute(r, {journal!r})
    else:
        val = np.asarray(r.compute(executor=ex))
    elapsed = time.perf_counter() - t0
    delta = reg.snapshot_delta(before)
    assert (val == an + 1.0).all()
    print(json.dumps({{
        "elapsed": elapsed, "total": total,
        "tasks_skipped_resume": delta.get("tasks_skipped_resume", 0),
        "resumed_tasks": delta.get("tasks_completed", 0),
    }}), flush=True)
finally:
    ex.close()
"""


def measure_coordinator_recovery(timeout: float):
    """Kill-the-coordinator-at-50%-then-resume vs an uninterrupted run.

    Three phases over the same plan (deterministic op names via a pinned
    CUBED_TPU_CONTEXT_ID): (1) uninterrupted with the journal armed — the
    baseline, journal overhead included; (2) the same compute SIGKILLed
    when the fsync'd journal shows ~50% of tasks complete; (3)
    ``resume_compute`` from the journal in a fresh process. ``elapsed`` is
    the total recovery wall clock (run-to-kill + resume), so the generic
    perf gate flags a >20% regression like any other config. Returns None
    on failure — additive, never the reason a bench run dies."""
    import shutil
    import signal
    import tempfile

    deadline = time.monotonic() + timeout
    work_dir = tempfile.mkdtemp()
    journal = os.path.join(work_dir, "bench.journal.jsonl")
    script = COORD_RECOVERY.format(
        repo=REPO, work_dir=work_dir, journal=journal,
        tasks=RECOVERY_TASKS, delay=RECOVERY_TASK_DELAY_S,
    )
    env = dict(_scrubbed_cpu_env(), CUBED_TPU_CONTEXT_ID="cubed-benchrec")
    try:
        from cubed_tpu.runtime.journal import load_journal

        # phase 1: uninterrupted baseline (journal on, like the real run)
        out = subprocess.run(
            [sys.executable, "-c", script, "full"], env=env,
            capture_output=True, text=True,
            timeout=max(10.0, deadline - time.monotonic()),
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"uninterrupted run failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        full = json.loads(out.stdout.strip().splitlines()[-1])
        os.unlink(journal)  # phase 2 writes a fresh journal

        # phase 2: the same compute, coordinator hard-killed at ~50%.
        # Its own session/process group, so the kill takes the client AND
        # its local worker subprocesses — orphaned workers would otherwise
        # burn CPU (and hammer the dead port) throughout the timed resume
        proc = subprocess.Popen(
            [sys.executable, "-c", script, "run"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True,
        )
        t0 = time.perf_counter()
        killed = False
        try:
            while time.monotonic() < deadline and proc.poll() is None:
                if os.path.exists(journal) and len(
                    load_journal(journal)["completed"]
                ) >= RECOVERY_TASKS // 2 + 1:
                    os.killpg(proc.pid, signal.SIGKILL)
                    killed = True
                    break
                time.sleep(0.05)
            run_to_kill = time.perf_counter() - t0
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait(timeout=30)
        if not killed:
            raise RuntimeError("compute finished before the kill landed")

        # phase 3: resume from the journal in a fresh process
        out = subprocess.run(
            [sys.executable, "-c", script, "resume"], env=env,
            capture_output=True, text=True,
            timeout=max(10.0, deadline - time.monotonic()),
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"resume failed (rc={out.returncode}): {out.stderr[-2000:]}"
            )
        resume = json.loads(out.stdout.strip().splitlines()[-1])
        recovery_total = run_to_kill + resume["elapsed"]
        rec = {
            # the gated number: kill-at-50% + resume, end to end
            "elapsed": recovery_total,
            "uninterrupted_s": full["elapsed"],
            "interrupted_run_s": run_to_kill,
            "resume_s": resume["elapsed"],
            "recovery_overhead_x": (
                recovery_total / full["elapsed"] if full["elapsed"] else None
            ),
            "tasks_skipped_resume": resume["tasks_skipped_resume"],
            "resumed_tasks": resume["resumed_tasks"],
            "total_tasks": resume["total"],
        }
        print(
            f"coordinator recovery: uninterrupted {full['elapsed']:.2f}s, "
            f"kill@50%+resume {recovery_total:.2f}s "
            f"({resume['tasks_skipped_resume']} task(s) skipped on resume)",
            file=sys.stderr, flush=True,
        )
        return rec
    except Exception as e:
        print(f"coordinator recovery sweep skipped: {e}", file=sys.stderr)
        return None
    finally:
        shutil.rmtree(work_dir, ignore_errors=True)


COORD_FAILOVER = r"""
import json, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

mode = sys.argv[1]


def sleep_add(x):
    time.sleep({delay!r})
    return x + 1.0


spec = ct.Spec(work_dir={work_dir!r}, allowed_mem="2GB",
               journal={journal!r})
an = np.arange({tasks!r} * 4, dtype=np.float64).reshape(-1, 4)
a = ct.from_array(an, chunks=(1, 4), spec=spec)  # one row per task
r = ct.map_blocks(sleep_add, a, dtype=np.float64)
total = r.plan.num_tasks()

if mode == "adopt":
    # the successor: no workers of its own — it adopts the orphaned
    # fleet the killed coordinator left running
    ex = DistributedDagExecutor(
        n_local_workers=0, worker_threads=1,
        control_dir={control_dir!r}, worker_start_timeout=60.0,
    )
else:
    ex = DistributedDagExecutor(
        n_local_workers=2, worker_threads=1, control_dir={control_dir!r},
    )
try:
    reg = get_registry()
    before = reg.snapshot()
    t0 = time.perf_counter()
    if mode == "adopt":
        val = ex.resume_compute(r, {journal!r})
    else:
        ex._ensure_fleet()  # boot outside the timed window (full mode)
        t0 = time.perf_counter()
        val = np.asarray(r.compute(executor=ex))
    elapsed = time.perf_counter() - t0
    delta = reg.snapshot_delta(before)
    assert (np.asarray(val) == an + 1.0).all()
    print(json.dumps({{
        "elapsed": elapsed, "total": total,
        "takeovers": ex.stats.get("coordinator_takeovers", 0),
        "readopted": ex.stats.get("tasks_readopted", 0),
        "workers_lost": ex.stats.get("workers_lost", 0),
        "tasks_skipped_resume": delta.get("tasks_skipped_resume", 0),
        "resumed_tasks": delta.get("tasks_completed", 0),
    }}), flush=True)
finally:
    ex.close()
"""


def measure_coordinator_failover(timeout: float):
    """Live takeover vs an uninterrupted run: SIGKILL the coordinator
    PROCESS at ~50% (its local worker subprocesses survive as orphans),
    then a successor pointed at the same control_dir adopts the live
    fleet and finishes the compute.

    ``elapsed`` is the total failover wall clock (run-to-kill + the
    successor's adopt-and-finish), gated >20% like any other config;
    ``failover_overhead_x`` is the ratio against the uninterrupted
    baseline (the acceptance bound is < 2x). Returns None on failure —
    additive, never the reason a bench run dies."""
    import shutil
    import signal
    import tempfile

    deadline = time.monotonic() + timeout
    work_dir = tempfile.mkdtemp()
    journal = os.path.join(work_dir, "bench.journal.jsonl")
    control_dir = os.path.join(work_dir, "ctrl")
    script = COORD_FAILOVER.format(
        repo=REPO, work_dir=work_dir, journal=journal,
        control_dir=control_dir,
        tasks=RECOVERY_TASKS, delay=RECOVERY_TASK_DELAY_S,
    )
    env = dict(_scrubbed_cpu_env(), CUBED_TPU_CONTEXT_ID="cubed-benchfo")

    def _reap_fleet():
        # kill any orphaned worker processes the control log records (a
        # failed takeover must not leak fleet processes into later sweeps)
        from cubed_tpu.runtime.journal import control_log_path, load_control

        try:
            prior = load_control(control_log_path(control_dir))
        except Exception:
            return
        for wrec in prior["workers"].values():
            pid = wrec.get("pid")
            if isinstance(pid, int) and pid > 1:
                try:
                    os.kill(pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass

    try:
        from cubed_tpu.runtime.journal import load_journal

        # phase 1: uninterrupted baseline (journal + control log armed,
        # like the real run, so their overhead is in both numbers)
        out = subprocess.run(
            [sys.executable, "-c", script, "full"], env=env,
            capture_output=True, text=True,
            timeout=max(10.0, deadline - time.monotonic()),
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"uninterrupted run failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        full = json.loads(out.stdout.strip().splitlines()[-1])
        _reap_fleet()
        os.unlink(journal)  # phase 2 writes fresh logs
        shutil.rmtree(control_dir, ignore_errors=True)

        # phase 2: the same compute, the coordinator PROCESS hard-killed
        # at ~50% — NOT its process group: the local worker subprocesses
        # must survive as the orphaned fleet the successor adopts
        proc = subprocess.Popen(
            [sys.executable, "-c", script, "run"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        t0 = time.perf_counter()
        killed = False
        try:
            while time.monotonic() < deadline and proc.poll() is None:
                if os.path.exists(journal) and len(
                    load_journal(journal)["completed"]
                ) >= RECOVERY_TASKS // 2 + 1:
                    os.kill(proc.pid, signal.SIGKILL)
                    killed = True
                    break
                time.sleep(0.05)
            run_to_kill = time.perf_counter() - t0
            proc.wait(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        if not killed:
            raise RuntimeError("compute finished before the kill landed")

        # phase 3: the successor adopts the live fleet and finishes
        out = subprocess.run(
            [sys.executable, "-c", script, "adopt"], env=env,
            capture_output=True, text=True,
            timeout=max(10.0, deadline - time.monotonic()),
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"takeover failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        adopt = json.loads(out.stdout.strip().splitlines()[-1])
        failover_total = run_to_kill + adopt["elapsed"]
        rec = {
            # the gated number: kill-at-50% + live takeover, end to end
            "elapsed": failover_total,
            "uninterrupted_s": full["elapsed"],
            "interrupted_run_s": run_to_kill,
            "takeover_s": adopt["elapsed"],
            "failover_overhead_x": (
                failover_total / full["elapsed"] if full["elapsed"] else None
            ),
            "takeovers": adopt["takeovers"],
            "tasks_readopted": adopt["readopted"],
            "workers_lost": adopt["workers_lost"],
            "tasks_skipped_resume": adopt["tasks_skipped_resume"],
            "resumed_tasks": adopt["resumed_tasks"],
            "total_tasks": adopt["total"],
        }
        print(
            f"coordinator failover: uninterrupted {full['elapsed']:.2f}s, "
            f"kill@50%+takeover {failover_total:.2f}s "
            f"({adopt['readopted']} readopted, "
            f"workers_lost={adopt['workers_lost']})",
            file=sys.stderr, flush=True,
        )
        return rec
    except Exception as e:
        print(f"coordinator failover sweep skipped: {e}", file=sys.stderr)
        return None
    finally:
        _reap_fleet()
        shutil.rmtree(work_dir, ignore_errors=True)


#: p2p-transfer workload: a deep elementwise chain on the fleet — every
#: inter-op edge is one store write+read round-trip per chunk without peer
#: transfer, and (depth-1)/depth of the reads are cache-servable with it
P2P_DEPTH = 6
P2P_N = 16
P2P_CHUNK = 4

P2P_TRANSFER = r"""
import json, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

DEPTH, N, CHUNK = {depth!r}, {n!r}, {chunk!r}


def bump(x):
    return x + 1.0


an = np.arange(N * N, dtype=np.float64).reshape(N, N)
out = {{}}
for mode in ("store_only", "peer"):
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB",
                   scheduler="dataflow")
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    r = a
    for _ in range(DEPTH):
        r = ct.map_blocks(bump, r, dtype=np.float64)
    ex = DistributedDagExecutor(
        n_local_workers=2, peer_transfer=(mode == "peer")
    )
    try:
        ex._ensure_fleet()  # boot outside the timed window
        reg = get_registry()
        before = reg.snapshot()
        t0 = time.perf_counter()
        # optimize_graph=False keeps the chain DEEP (fusion would collapse
        # it into one op and remove the inter-op edges being measured)
        val = np.asarray(r.compute(executor=ex, optimize_graph=False))
        elapsed = time.perf_counter() - t0
        delta = reg.snapshot_delta(before)
    finally:
        ex.close()
    assert (val == an + DEPTH).all()
    out[mode] = {{
        "elapsed": elapsed,
        "bytes_read": delta.get("bytes_read", 0),
        "store_read_bytes_saved": delta.get("store_read_bytes_saved", 0),
        "peer_hits": delta.get("peer_hits", 0),
        "peer_misses": delta.get("peer_misses", 0),
        "peer_bytes_fetched": delta.get("peer_bytes_fetched", 0),
        "peer_fetch_fallbacks": delta.get("peer_fetch_fallbacks", 0),
        "placement_locality_hits": delta.get("placement_locality_hits", 0),
    }}
    print("p2p", mode, round(elapsed, 2), "s", file=sys.stderr, flush=True)
hits = out["peer"]["peer_hits"]
misses = out["peer"]["peer_misses"]
out["hit_rate"] = hits / max(hits + misses, 1)
# the headline: fraction of the store-only read volume the caches absorbed
out["saved_fraction"] = out["peer"]["store_read_bytes_saved"] / max(
    out["store_only"]["bytes_read"], 1
)
print(json.dumps(out), flush=True)
"""


def measure_p2p_transfer(timeout: float):
    """Deep-chain fleet run, store-only vs peer-transfer-enabled.

    Same plan twice on a 2-worker local fleet under the dataflow
    scheduler: once with the historical store-only data plane, once with
    the p2p chunk cache + locality placement. Records wall clock per mode,
    the peer hit rate, and ``saved_fraction`` — ``store_read_bytes_saved``
    over the store-only run's ``bytes_read`` (the acceptance bar is
    >=30%). Rides the same history/perf-gate pipeline as every other
    config. Returns None on failure — additive, never the reason a bench
    run dies."""
    script = P2P_TRANSFER.format(
        repo=REPO, depth=P2P_DEPTH, n=P2P_N, chunk=P2P_CHUNK,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"p2p transfer failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"p2p transfer: saved_fraction {res['saved_fraction']:.0%}, "
            f"hit rate {res['hit_rate']:.0%}, "
            f"wall {res['store_only']['elapsed']:.2f}s store-only vs "
            f"{res['peer']['elapsed']:.2f}s peer",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"p2p transfer sweep skipped: {e}", file=sys.stderr)
        return None


#: rechunk-shuffle workload: a transpose-heavy pipeline (two all-to-all
#: rechunks between elementwise maps) where the rechunk exchange
#: dominates bytes moved — the last store round-trip the peer data plane
#: kills. allowed_mem is sized so the copy regions stay strips (several
#: shuffle tasks per stage) instead of consolidating into one whole-array
#: copy
RECHUNK_N = 128
RECHUNK_CHUNK = 32
RECHUNK_ALLOWED = "700KB"

RECHUNK_SHUFFLE = r"""
import json, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.dataflow import build_chunk_graph
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

N, CHUNK, ALLOWED = {n!r}, {chunk!r}, {allowed!r}


def bump(x):
    return x + 1.0


an = np.arange(N * N, dtype=np.float64).reshape(N, N)
out = {{}}


def build(mode):
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem=ALLOWED)
    a = ct.from_array(an, chunks=(CHUNK, N), spec=spec)
    r = ct.map_blocks(bump, a, dtype=np.float64)
    r = r.rechunk((N, CHUNK))          # row chunks -> column chunks
    r = ct.map_blocks(bump, r, dtype=np.float64)
    r = r.rechunk((CHUNK, N))          # ... and back: transpose-heavy
    r = ct.map_blocks(bump, r, dtype=np.float64)
    return r


for mode in ("store_only", "peer"):
    if mode == "store_only":
        # the acceptance fact the scheduler is on the hook for: the
        # chunk graph classifies every rechunk stage as chunked, never a
        # barrier (recorded into BENCH_METRICS.json, asserted in tests)
        g = build_chunk_graph(
            build(mode).plan._finalize(optimize_graph=False).dag
        )
        rechunk_kinds = [
            k for n_, k in g.op_kind.items() if "rechunk" in n_
        ]
        out["rechunk_chunked"] = bool(rechunk_kinds) and all(
            k == "rechunk" for k in rechunk_kinds
        ) and not any("rechunk" in n_ for n_ in g.barrier_ops)
    # best-of-2: these computes are sub-second, and container scheduling
    # noise would otherwise drown the wall-clock comparison
    best = None
    for _attempt in range(2):
        r = build(mode)
        ex = DistributedDagExecutor(
            n_local_workers=2, peer_transfer=(mode == "peer")
        )
        try:
            ex._ensure_fleet()  # boot outside the timed window
            reg = get_registry()
            before = reg.snapshot()
            t0 = time.perf_counter()
            # optimize_graph=False keeps the maps unfused so the exchange
            # stages read real intermediate arrays
            val = np.asarray(r.compute(executor=ex, optimize_graph=False))
            elapsed = time.perf_counter() - t0
            delta = reg.snapshot_delta(before)
        finally:
            ex.close()
        assert (val == an + 3.0).all()
        rec = {{
            "elapsed": elapsed,
            "bytes_read": delta.get("bytes_read", 0),
            "store_read_bytes_saved": delta.get(
                "store_read_bytes_saved", 0
            ),
            "peer_hits": delta.get("peer_hits", 0),
            "peer_misses": delta.get("peer_misses", 0),
            "peer_bytes_fetched": delta.get("peer_bytes_fetched", 0),
            "peer_range_fetches": delta.get("peer_range_fetches", 0),
            "shuffle_bytes_peer": delta.get("shuffle_bytes_peer", 0),
            "peer_fetch_fallbacks": delta.get("peer_fetch_fallbacks", 0),
            "placement_locality_hits": delta.get(
                "placement_locality_hits", 0
            ),
        }}
        if best is None or rec["elapsed"] < best["elapsed"]:
            best = rec
    out[mode] = best
    print("rechunk_shuffle", mode, round(best["elapsed"], 2), "s",
          file=sys.stderr, flush=True)
hits = out["peer"]["peer_hits"]
misses = out["peer"]["peer_misses"]
out["hit_rate"] = hits / max(hits + misses, 1)
# the headline: fraction of the store-only read volume the peer-routed
# shuffle eliminated (the acceptance bar is >=40%)
out["saved_fraction"] = out["peer"]["store_read_bytes_saved"] / max(
    out["store_only"]["bytes_read"], 1
)
out["wall_ratio"] = out["peer"]["elapsed"] / max(
    out["store_only"]["elapsed"], 1e-9
)
print(json.dumps(out), flush=True)
"""


def measure_rechunk_shuffle(timeout: float):
    """Transpose-heavy (rechunk-dominated) fleet run, store-only vs
    peer-shuffle.

    Same plan twice on a 2-worker local fleet under the default dataflow
    scheduler: once with every rechunk byte round-tripping through the
    store, once with the all-to-all routed over the peer data plane
    (sub-chunk range fetches + locality-placed fan-in). Records wall
    clock per mode, ``saved_fraction`` (store read bytes eliminated; the
    acceptance bar is >=40%), and ``rechunk_chunked`` (the chunk graph
    classified every rechunk stage as chunked). Rides the same
    history/perf-gate pipeline as ``p2p_transfer``. Returns None on
    failure — additive, never the reason a bench run dies."""
    script = RECHUNK_SHUFFLE.format(
        repo=REPO, n=RECHUNK_N, chunk=RECHUNK_CHUNK, allowed=RECHUNK_ALLOWED,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"rechunk shuffle failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"rechunk shuffle: saved_fraction {res['saved_fraction']:.0%}, "
            f"hit rate {res['hit_rate']:.0%}, "
            f"{res['peer']['peer_range_fetches']} range fetch(es), "
            f"rechunk_chunked={res['rechunk_chunked']}, "
            f"wall {res['store_only']['elapsed']:.2f}s store-only vs "
            f"{res['peer']['elapsed']:.2f}s peer",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"rechunk shuffle sweep skipped: {e}", file=sys.stderr)
        return None


#: telemetry-overhead config: the scheduler deep chain (same shape, no
#: injected straggler — sleep would mask sampler cost) run twice, live
#: telemetry off vs armed (1s sampler + HTTP endpoint + a 0.5s scraper
#: hitting /metrics throughout), so the "on" wall clock carries the whole
#: observation cost a production scrape would
TELEMETRY_OVERHEAD = r"""
import json, os, sys, tempfile, threading, time, urllib.request
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor

DEPTH, N, CHUNK = {depth!r}, {n!r}, {chunk!r}

# an operator's scrape config must not arm the OFF mode (the runbook in
# docs/operations.md exports this var fleet-wide); the ON mode sets it
# explicitly below so Plan.execute takes the REAL production arming path
# (incl. the per-task progress callback), not a test shortcut
os.environ.pop("CUBED_TPU_TELEMETRY_PORT", None)


def bump(x):
    return x + 1.0


an = np.arange(N * N, dtype=np.float64).reshape(N, N)


def run_chain():
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB")
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    r = a
    for _ in range(DEPTH):
        r = ct.map_blocks(bump, r, dtype=np.float64)
    t0 = time.perf_counter()
    val = np.asarray(r.compute(executor=AsyncPythonDagExecutor(),
                               optimize_graph=False))
    elapsed = time.perf_counter() - t0
    assert (val == an + DEPTH).all()
    return elapsed


run_chain()  # warm-up outside both timed windows (imports, tracing, IO)
out = {{}}
for mode in ("off", "on"):
    scrape_stop = None
    if mode == "on":
        from cubed_tpu.observability import export

        # the env var is how production arms it: Plan.execute resolves it,
        # attaches the progress callback, and adopts this same runtime
        os.environ["CUBED_TPU_TELEMETRY_PORT"] = "0"
        rt = export.ensure_started(0)
        scrape_stop = threading.Event()

        def scrape():
            url = f"http://127.0.0.1:{{rt.port}}/metrics"
            while not scrape_stop.wait(0.5):
                try:
                    urllib.request.urlopen(url, timeout=2).read()
                except OSError:
                    pass

        threading.Thread(target=scrape, daemon=True).start()
    # best-of-3 per mode: this chain is sub-second, and scheduling noise
    # on a small container would otherwise drown the number being measured
    elapsed = min(run_chain() for _ in range(3))
    if scrape_stop is not None:
        scrape_stop.set()
    out[mode] = {{"elapsed": elapsed}}
    print("telemetry", mode, round(elapsed, 3), "s",
          file=sys.stderr, flush=True)
off_s = max(out["off"]["elapsed"], 1e-9)
out["overhead_pct"] = (out["on"]["elapsed"] - off_s) / off_s * 100.0
# the generic perf gate reads this key: the ARMED wall clock is the one
# that must not regress (it contains the off cost plus the telemetry tax)
out["elapsed"] = out["on"]["elapsed"]
print(json.dumps(out), flush=True)
"""


def measure_telemetry_overhead(timeout: float):
    """Deep-chain wall clock, live telemetry armed vs off.

    Records ``{"off": {...}, "on": {...}, "overhead_pct": x, "elapsed":
    on_wall}`` into BENCH_METRICS.json as ``telemetry_overhead``; the
    top-level ``elapsed`` rides the generic >20% perf gate, so the armed
    path must stay within wall-clock noise of unobserved runs forever.
    Returns None on failure — additive, never the reason a bench run
    dies."""
    script = TELEMETRY_OVERHEAD.format(
        repo=REPO, depth=SCHED_DEPTH, n=SCHED_N, chunk=SCHED_CHUNK,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"telemetry overhead failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"telemetry overhead: {res['overhead_pct']:+.1f}% "
            f"({res['off']['elapsed']:.2f}s off -> "
            f"{res['on']['elapsed']:.2f}s armed)",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"telemetry overhead sweep skipped: {e}", file=sys.stderr)
        return None


#: dispatch-profiler-overhead config: the same deep chain run twice, the
#: coordinator self-profiler (~75 Hz sys._current_frames sampler) off vs
#: armed via the production env-var path — the issue's acceptance bar is
#: that arming costs <5% wall, and the armed elapsed riding the generic
#: perf gate keeps that from rotting
DISPATCH_PROFILE_OVERHEAD = r"""
import json, os, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor

DEPTH, N, CHUNK = {depth!r}, {n!r}, {chunk!r}

# the OFF mode must be the true default (a leaked operator env var would
# arm both halves and hide the tax); the ON mode sets the var explicitly
# below so Plan.execute takes the REAL arming path — profile_enabled() ->
# profile_scoped() -> a sampler thread per compute
os.environ.pop("CUBED_TPU_DISPATCH_PROFILE", None)


def bump(x):
    return x + 1.0


an = np.arange(N * N, dtype=np.float64).reshape(N, N)


def run_chain():
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB")
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    r = a
    for _ in range(DEPTH):
        r = ct.map_blocks(bump, r, dtype=np.float64)
    t0 = time.perf_counter()
    val = np.asarray(r.compute(executor=AsyncPythonDagExecutor(),
                               optimize_graph=False))
    elapsed = time.perf_counter() - t0
    assert (val == an + DEPTH).all()
    return elapsed


run_chain()  # warm-up outside both timed windows (imports, tracing, IO)
out = {{}}
for mode in ("off", "on"):
    if mode == "on":
        os.environ["CUBED_TPU_DISPATCH_PROFILE"] = "1"
    # best-of-3 per mode: the chain is sub-second and container
    # scheduling noise would otherwise drown a <5% tax
    elapsed = min(run_chain() for _ in range(3))
    out[mode] = {{"elapsed": elapsed}}
    print("dispatch profile", mode, round(elapsed, 3), "s",
          file=sys.stderr, flush=True)
off_s = max(out["off"]["elapsed"], 1e-9)
out["overhead_pct"] = (out["on"]["elapsed"] - off_s) / off_s * 100.0
# the generic perf gate reads this key: the ARMED wall clock is the one
# that must not regress (it contains the off cost plus the sampler tax)
out["elapsed"] = out["on"]["elapsed"]
print(json.dumps(out), flush=True)
"""


def measure_dispatch_profile_overhead(timeout: float):
    """Deep-chain wall clock, coordinator self-profiler armed vs off.

    Records ``{"off": {...}, "on": {...}, "overhead_pct": x, "elapsed":
    on_wall}`` into BENCH_METRICS.json as ``dispatch_profile_overhead``;
    the top-level ``elapsed`` rides the generic >20% perf gate, so the
    armed sampler must stay within wall-clock noise of unprofiled runs
    forever (the issue's <5% bar, with gate headroom for container
    noise). Returns None on failure — additive, never the reason a
    bench run dies."""
    script = DISPATCH_PROFILE_OVERHEAD.format(
        repo=REPO, depth=SCHED_DEPTH, n=SCHED_N, chunk=SCHED_CHUNK,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"dispatch profile overhead failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"dispatch profile overhead: {res['overhead_pct']:+.1f}% "
            f"({res['off']['elapsed']:.2f}s off -> "
            f"{res['on']['elapsed']:.2f}s armed)",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"dispatch profile overhead sweep skipped: {e}",
              file=sys.stderr)
        return None


ANALYTICS_OVERHEAD = r"""
import json, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability import TraceCollector
from cubed_tpu.observability.analytics import analyze
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor

DEPTH, N, CHUNK = {depth!r}, {n!r}, {chunk!r}


def bump(x):
    return x + 1.0


an = np.arange(N * N, dtype=np.float64).reshape(N, N)


def run_chain(collector=None):
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB",
                   scheduler="dataflow")
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    r = a
    for _ in range(DEPTH):
        r = ct.map_blocks(bump, r, dtype=np.float64)
    callbacks = [collector] if collector is not None else None
    t0 = time.perf_counter()
    val = np.asarray(r.compute(executor=AsyncPythonDagExecutor(),
                               callbacks=callbacks, optimize_graph=False))
    elapsed = time.perf_counter() - t0
    analyze_s = 0.0
    if collector is not None:
        t1 = time.perf_counter()
        rep = analyze(collector)
        analyze_s = time.perf_counter() - t1
        assert rep.to_dict()["critical_path"], "empty critical path"
    assert (val == an + DEPTH).all()
    return elapsed, analyze_s


run_chain()  # warm-up outside both timed windows (imports, tracing, IO)
out = {{}}
# best-of-3 per mode (sub-second chain; scheduling noise would otherwise
# drown the tax being measured). ARMED = a TraceCollector attached (span
# recording + chunk-graph capture active) and analyze() run post-compute
# — the full analytics cost a compute pays when someone is watching
for mode in ("off", "on"):
    best = None
    for _ in range(3):
        collector = TraceCollector(trace_dir=None) if mode == "on" else None
        elapsed, analyze_s = run_chain(collector)
        total = elapsed + analyze_s
        if best is None or total < best[0]:
            best = (total, elapsed, analyze_s)
    out[mode] = {{"elapsed": best[1], "analyze_s": best[2]}}
    print("analytics", mode, round(best[0], 3), "s",
          file=sys.stderr, flush=True)
off_s = max(out["off"]["elapsed"], 1e-9)
on_total = out["on"]["elapsed"] + out["on"]["analyze_s"]
out["overhead_pct"] = (on_total - off_s) / off_s * 100.0
out["analyze_s"] = out["on"]["analyze_s"]
# the generic perf gate reads this key: the ARMED total (compute with the
# collector attached + the analyze() pass) is what must not regress
out["elapsed"] = on_total
print(json.dumps(out), flush=True)
"""


STORE_BROWNOUT = r"""
import itertools, json, os, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu import utils as ct_utils
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.runtime.resilience import RetryPolicy
from cubed_tpu.storage import health

N, CHUNK, RATE = 24, 2, 0.25
an = np.arange(N * N, dtype=np.float64).reshape(N, N)


def run(base):
    # pinned gensym names: both modes must roll IDENTICAL seeded
    # throttle decisions (chunk keys embed the array names)
    ct_utils.sym_counter = itertools.count(base)
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB",
                   fault_injection=dict(seed=23, storage_throttle_rate=RATE))
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    b = a * 2.0 + 1.0
    before = get_registry().snapshot()
    t0 = time.perf_counter()
    val = np.asarray(b.compute(
        executor=AsyncPythonDagExecutor(
            max_workers=4,
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
        ),
    ))
    elapsed = time.perf_counter() - t0
    assert (val == an * 2.0 + 1.0).all(), "brownout result not bitwise"
    d = get_registry().snapshot_delta(before)
    return {{
        "elapsed": elapsed,
        "task_retries": int(d.get("task_retries", 0) or 0),
        "store_throttled": int(d.get("store_throttled", 0) or 0),
        "store_breaker_trips": int(d.get("store_breaker_trips", 0) or 0),
    }}


out = {{}}
os.environ[health.BREAKER_ENV_VAR] = "off"
out["breaker_off"] = run(90_000)
health.reset_breakers()
os.environ.pop(health.BREAKER_ENV_VAR, None)
out["breaker_on"] = run(90_000)
out["retry_draw_saved"] = (
    out["breaker_off"]["task_retries"] - out["breaker_on"]["task_retries"]
)
# the generic perf gate reads this key: the breaker-ON wall clock under
# a seeded brownout is what must not regress
out["elapsed"] = out["breaker_on"]["elapsed"]
print(json.dumps(out), flush=True)
"""


CHAOS_DEGRADATION = r"""
import itertools, json, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu import utils as ct_utils
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.runtime.resilience import RetryPolicy

N, CHUNK, DEPTH = 24, 2, 4
an = np.arange(N * N, dtype=np.float64).reshape(N, N)
# the composed schedule: three failure domains at campaign-grade rates
# (storage flakiness + injected task crashes + stragglers), all seeded
FAULTS = dict(seed=1800,
              storage_read_failure_rate=0.08,
              storage_write_failure_rate=0.08,
              task_failure_rate=0.05,
              straggler_rate=0.1, straggler_delay_s=0.02)


def run(base, faults):
    # pinned gensym names: the faulty mode must roll IDENTICAL seeded
    # decisions run over run (chunk keys embed the array names)
    ct_utils.sym_counter = itertools.count(base)
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB",
                   fault_injection=faults)
    a = ct.from_array(an, chunks=(CHUNK, CHUNK), spec=spec)
    b = a
    for _ in range(DEPTH):
        b = b * 2.0 + 1.0
    expected = an.copy()
    for _ in range(DEPTH):
        expected = expected * 2.0 + 1.0
    before = get_registry().snapshot()
    t0 = time.perf_counter()
    val = np.asarray(b.compute(
        executor=AsyncPythonDagExecutor(
            max_workers=4,
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
        ),
    ))
    elapsed = time.perf_counter() - t0
    assert (val == expected).all(), "chaos result not bitwise"
    d = get_registry().snapshot_delta(before)
    return {{
        "elapsed": elapsed,
        "task_retries": int(d.get("task_retries", 0) or 0),
        "faults_injected": int(d.get("faults_injected", 0) or 0),
    }}


out = {{}}
out["clean"] = run(92_000, None)
out["composed"] = run(92_000, FAULTS)
clean_s = max(out["clean"]["elapsed"], 1e-9)
out["degradation_ratio"] = out["composed"]["elapsed"] / clean_s
# the generic perf gate reads this key: the wall clock under composed
# chaos is what must not regress — absorbing the same seeded failure
# load more slowly is a real resilience regression
out["elapsed"] = out["composed"]["elapsed"]
print(json.dumps(out), flush=True)
"""


def measure_chaos_degradation(timeout: float):
    """Composed-failure degradation: the deep elementwise chain clean vs
    under a seeded three-domain schedule (storage flakiness + task
    crashes + stragglers, the campaign-suite shape). Records both wall
    clocks, the retry/injection draw, and the degradation ratio into
    BENCH_METRICS.json as ``chaos_degradation``; the composed wall rides
    the generic >20% perf gate."""
    script = CHAOS_DEGRADATION.format(repo=REPO)
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"chaos degradation failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            "chaos degradation: composed "
            f"{res['composed']['elapsed']:.2f}s "
            f"({res['composed']['faults_injected']} injected / "
            f"{res['composed']['task_retries']} retries) vs clean "
            f"{res['clean']['elapsed']:.2f}s — ratio "
            f"{res['degradation_ratio']:.2f}x",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"chaos degradation sweep skipped: {e}", file=sys.stderr)
        return None


def measure_store_brownout(timeout: float):
    """Seeded store brownout (25% 429/503-shaped throttles), health
    breaker on vs off: retry-budget draw and wall clock for both modes
    into BENCH_METRICS.json as ``store_brownout``. The breaker-on wall
    rides the generic >20% perf gate; the breaker must also draw
    strictly less retry budget than the off baseline (asserted in
    tier-1 chaos, recorded here as a tracked number)."""
    script = STORE_BROWNOUT.format(repo=REPO)
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"store brownout failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            "store brownout: breaker on "
            f"{res['breaker_on']['elapsed']:.2f}s / "
            f"{res['breaker_on']['task_retries']} retries drawn vs off "
            f"{res['breaker_off']['elapsed']:.2f}s / "
            f"{res['breaker_off']['task_retries']} retries "
            f"({res['retry_draw_saved']} saved)",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"store brownout sweep skipped: {e}", file=sys.stderr)
        return None


def measure_analytics_overhead(timeout: float):
    """Deep-chain wall clock, analytics armed (TraceCollector + post-hoc
    ``analyze()``) vs off.

    Records ``{"off": {...}, "on": {...}, "overhead_pct": x, "analyze_s":
    s, "elapsed": armed_total}`` into BENCH_METRICS.json as
    ``analytics_overhead``; the top-level ``elapsed`` rides the generic
    >20% perf gate, so span recording + chunk-graph capture + the
    critical-path pass must stay cheap forever. Returns None on failure —
    additive, never the reason a bench run dies."""
    script = ANALYTICS_OVERHEAD.format(
        repo=REPO, depth=SCHED_DEPTH, n=SCHED_N, chunk=SCHED_CHUNK,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"analytics overhead failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"analytics overhead: {res['overhead_pct']:+.1f}% "
            f"({res['off']['elapsed']:.2f}s off -> "
            f"{res['on']['elapsed']:.2f}s armed + "
            f"{res['analyze_s']:.3f}s analyze)",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"analytics overhead sweep skipped: {e}", file=sys.stderr)
        return None


#: multi-tenant service bench: N synthetic tenants sustaining submissions
#: against one threaded service — QPS, latency quantiles, fairness
MT_TENANTS = 3
MT_REQUESTS_PER_TENANT = 8
MT_REPEAT_EVERY = 4  # every 4th submission repeats an earlier query

MULTITENANT_SERVICE = r"""
import json, os, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import ComputeService

TENANTS = {tenants!r}
R = {requests!r}
REPEAT = {repeat!r}

an = np.arange(64 * 64, dtype=np.float64).reshape(64, 64)
spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB")


def build(k):
    def kernel(x, _k=float(k)):
        return x + _k

    a = ct.from_array(an, chunks=(16, 16), spec=spec)
    return ct.map_blocks(kernel, a, dtype=np.float64)


reg = get_registry()
before = reg.snapshot()
svc = ComputeService(
    executor=AsyncPythonDagExecutor(), max_concurrent=2,
).start()
handles = []
t0 = time.perf_counter()
try:
    for i in range(R):
        for t in range(TENANTS):
            # every REPEAT-th submission repeats that tenant's first
            # query: the sustained mix exercises the plan/result caches
            k = (t * 1000) + (0 if (i and i % REPEAT == 0) else i)
            handles.append(
                (svc.submit(build(k), tenant=f"tenant-{{t}}"), t, k)
            )
    for h, t, k in handles:
        val = h.result(timeout=600)
        assert (val == an + float(k)).all()
    elapsed = time.perf_counter() - t0
finally:
    svc.close()

lat = sorted(
    (h._request.ended_at - h._request.submitted_at) for h, _, _ in handles
)
per_tenant = {{}}
per_tenant_lat = {{}}
for h, t, _ in handles:
    per_tenant.setdefault(t, []).append(h._request.ended_at)
    per_tenant_lat.setdefault(t, []).append(
        h._request.ended_at - h._request.submitted_at
    )
# per-tenant throughput over the tenant's own submit->last-done window
tps = {{
    t: len(ends) / max(1e-9, max(ends) - t0)
    for t, ends in per_tenant.items()
}}
# per-tenant latency percentiles: the SLO-facing numbers — a regression
# hitting ONE tenant must not hide inside the global percentile
tenants = {{}}
for t, ls in per_tenant_lat.items():
    ls = sorted(ls)
    tenants[f"tenant-{{t}}"] = {{
        "p50_s": ls[len(ls) // 2],
        "p99_s": ls[min(len(ls) - 1, (len(ls) * 99) // 100)],
    }}
delta = reg.snapshot_delta(before)
n = len(handles)
print(json.dumps({{
    "elapsed": elapsed,
    "requests": n,
    "qps": n / max(1e-9, elapsed),
    "p50_s": lat[n // 2],
    "p99_s": lat[min(n - 1, (n * 99) // 100)],
    "fairness_ratio": max(tps.values()) / max(1e-9, min(tps.values())),
    "tenants": tenants,
    "plan_cache_hits": delta.get("plan_cache_hits", 0),
    "result_cache_hits": delta.get("result_cache_hits", 0),
}}), flush=True)
"""


def measure_multitenant_service(timeout: float):
    """Sustained submissions from N synthetic tenants against one
    threaded service: QPS, p50/p99 request latency, and the fairness
    ratio (max/min per-tenant throughput; 1.0 = perfectly fair under the
    equal weights used here). Recorded as ``multitenant_service`` in
    BENCH_METRICS.json — ``elapsed`` and ``qps`` ride the >20% perf gate.
    Returns None on failure — additive, never the reason a bench run
    dies."""
    script = MULTITENANT_SERVICE.format(
        repo=REPO, tenants=MT_TENANTS, requests=MT_REQUESTS_PER_TENANT,
        repeat=MT_REPEAT_EVERY,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"multitenant service failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"multitenant service: {res['requests']} requests in "
            f"{res['elapsed']:.2f}s ({res['qps']:.1f} QPS, p50 "
            f"{res['p50_s'] * 1000:.0f}ms, p99 {res['p99_s'] * 1000:.0f}ms, "
            f"fairness {res['fairness_ratio']:.2f}, "
            f"{res['result_cache_hits']} result-cache hit(s))",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"multitenant service sweep skipped: {e}", file=sys.stderr)
        return None


#: SLO/archive overhead A/B: the same 2-tenant request mix against a
#: bare service (off) vs one with the durable run archive + per-tenant
#: SLO board armed (on: service_dir + slos + Spec(run_history=...)) —
#: the SLI record, the fsync'd archive append, and the per-compute
#: analyze() digest must all be wall-clock noise. Requests are 64-task
#: computes (not single-chunk toys): the archive tax is fixed per
#: compute, so the ratio is only meaningful against a request that does
#: representative work
SLO_TENANTS = 2
SLO_REQUESTS_PER_TENANT = 4

SLO_OVERHEAD = r"""
import json, os, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import ComputeService

TENANTS = {tenants!r}
R = {requests!r}

an = np.arange(128 * 128, dtype=np.float64).reshape(128, 128)


def run_mix(spec, **svc_kwargs):
    def build(k):
        def kernel(x, _k=float(k)):
            return x + _k

        a = ct.from_array(an, chunks=(16, 16), spec=spec)
        return ct.map_blocks(kernel, a, dtype=np.float64)

    svc = ComputeService(
        executor=AsyncPythonDagExecutor(), max_concurrent=2,
        result_cache=False, spec=spec, **svc_kwargs,
    ).start()
    t0 = time.perf_counter()
    try:
        handles = [
            svc.submit(build(t * 1000 + i), tenant=f"tenant-{{t}}")
            for i in range(R) for t in range(TENANTS)
        ]
        for h in handles:
            h.result(timeout=600)
        return time.perf_counter() - t0
    finally:
        svc.close()


out = {{}}
# warm-up outside both timed windows (imports, tracing, first zarr IO)
run_mix(ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB"))
for mode in ("off", "on"):
    if mode == "on":
        base = tempfile.mkdtemp()
        spec = ct.Spec(
            work_dir=base, allowed_mem="2GB",
            run_history=os.path.join(base, "hist"),
        )
        kwargs = dict(
            service_dir=os.path.join(base, "svc"),
            slos={{
                f"tenant-{{t}}": {{"latency_s": 30.0,
                                   "availability_objective": 0.999}}
                for t in range(TENANTS)
            }},
        )
    else:
        spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB")
        kwargs = {{}}
    # best-of-3 per mode: sub-second mixes, scheduling noise would
    # otherwise drown the number being measured
    elapsed = min(run_mix(spec, **kwargs) for _ in range(3))
    out[mode] = {{"elapsed": elapsed}}
    print("slo", mode, round(elapsed, 3), "s", file=sys.stderr, flush=True)
off_s = max(out["off"]["elapsed"], 1e-9)
out["overhead_pct"] = (out["on"]["elapsed"] - off_s) / off_s * 100.0
# the generic perf gate reads this key: the ARMED wall clock is the one
# that must not regress (it contains the off cost plus the SLO/archive tax)
out["elapsed"] = out["on"]["elapsed"]
print(json.dumps(out), flush=True)
"""


def measure_slo_overhead(timeout: float):
    """Service request mix, SLO board + durable run archive armed vs off.

    Records ``{"off": {...}, "on": {...}, "overhead_pct": x, "elapsed":
    on_wall}`` into BENCH_METRICS.json as ``slo_overhead``; the armed
    elapsed rides the generic >20% perf gate, so the per-request SLI
    record, the fsync'd ``runs.jsonl`` append, and the per-compute
    ``analyze()`` digest must stay within wall-clock noise forever.
    Returns None on failure — additive, never the reason a bench run
    dies."""
    script = SLO_OVERHEAD.format(
        repo=REPO, tenants=SLO_TENANTS, requests=SLO_REQUESTS_PER_TENANT,
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", script],
            env=_scrubbed_cpu_env(),
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        if out.returncode != 0:
            raise RuntimeError(
                f"slo overhead failed (rc={out.returncode}): "
                f"{out.stderr[-2000:]}"
            )
        res = json.loads(out.stdout.strip().splitlines()[-1])
        print(
            f"slo overhead: {res['overhead_pct']:+.1f}% "
            f"({res['off']['elapsed']:.2f}s off -> "
            f"{res['on']['elapsed']:.2f}s armed)",
            file=sys.stderr, flush=True,
        )
        return res
    except Exception as e:
        print(f"slo overhead sweep skipped: {e}", file=sys.stderr)
        return None


#: overload-shedding bench: 2 tenants at ~2x the service's capacity, the
#: degradation ladder on vs CUBED_TPU_OVERLOAD=off — goodput is requests
#: that SUCCEEDED (deadline met) per second; shed-on must beat shed-off
OVL_TASK_S = 0.08         # per-request kernel sleep (1 chunk = 1 task)
OVL_N_PER_TENANT = 16     # submissions per tenant (2 tenants)
OVL_SUBMIT_GAP_S = 0.04   # ~2x overload vs the single admission slot
OVL_DEADLINE_S = 0.5

OVERLOAD_SHEDDING = r"""
import json, os, sys, tempfile, time
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import (
    ComputeService, OverloadPolicy, ServiceOverloadedError,
)

TASK_S = {task_s!r}
N = {n!r}
GAP = {gap!r}
DEADLINE = {deadline!r}

an = np.arange(16, dtype=np.float64).reshape(4, 4)
spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="2GB")


def build(k):
    def kernel(x, _k=float(k)):
        time.sleep(TASK_S)
        return x + _k

    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    return ct.map_blocks(kernel, a, dtype=np.float64)


svc = ComputeService(
    executor=AsyncPythonDagExecutor(),
    max_concurrent=1,
    result_cache=False,  # every request must EXECUTE (goodput, not reuse)
    overload_policy=OverloadPolicy(
        queue_l1=2, queue_l2=4, queue_l3=1000,
        down_dwell_s=10.0, tick_interval_s=0.02,
    ),
    breaker_threshold=3, breaker_cooldown_s=0.5,
).start()
handles, shed = [], 0
t0 = time.perf_counter()
try:
    for i in range(N):
        for tenant, klass in (("slo", "interactive"), ("bulk", "batch")):
            try:
                handles.append(svc.submit(
                    build(i * 10 + (tenant == "bulk")), tenant=tenant,
                    deadline_s=DEADLINE, request_class=klass,
                ))
            except ServiceOverloadedError:
                shed += 1
        time.sleep(GAP)
    ok = failed = 0
    for h in handles:
        try:
            h.result(timeout=600)
            ok += 1
        except ServiceOverloadedError:
            shed += 1
        except Exception:
            failed += 1  # deadline blown (or aborted mid-run)
    elapsed = time.perf_counter() - t0
    ovl = svc.stats_snapshot()["overload"]
finally:
    svc.close()

print(json.dumps({{
    "elapsed": elapsed,
    "submitted": 2 * N,
    "ok": ok,
    "shed": shed,
    "failed": failed,
    "goodput": ok / max(1e-9, elapsed),
    "overload_enabled": ovl["enabled"],
    "max_level_seen": ovl.get("level", 0),
    "transitions": ovl.get("transitions", 0),
}}), flush=True)
"""


def measure_overload_shedding(timeout: float):
    """Two tenants at ~2x capacity against a one-slot service, run twice:
    degradation ladder ON, then ``CUBED_TPU_OVERLOAD=off``. Goodput is
    deadline-met successes per second — shedding trades rejected requests
    (fast, typed, retry-after attached) for requests that finish on time,
    so ``goodput_on`` must beat ``goodput_off``. Recorded as
    ``overload_shedding`` in BENCH_METRICS.json; the intra-run ratio and
    the goodput_on trajectory ride the perf gate. Returns None on
    failure — additive, never the reason a bench run dies."""
    script = OVERLOAD_SHEDDING.format(
        repo=REPO, task_s=OVL_TASK_S, n=OVL_N_PER_TENANT,
        gap=OVL_SUBMIT_GAP_S, deadline=OVL_DEADLINE_S,
    )
    try:
        arms = {}
        for arm in ("on", "off"):
            env = _scrubbed_cpu_env()
            if arm == "off":
                env["CUBED_TPU_OVERLOAD"] = "off"
            out = subprocess.run(
                [sys.executable, "-c", script],
                env=env,
                capture_output=True,
                text=True,
                timeout=timeout / 2,
            )
            if out.returncode != 0:
                raise RuntimeError(
                    f"overload arm {arm} failed (rc={out.returncode}): "
                    f"{out.stderr[-2000:]}"
                )
            arms[arm] = json.loads(out.stdout.strip().splitlines()[-1])
        on, off = arms["on"], arms["off"]
        res = {
            "elapsed": on["elapsed"] + off["elapsed"],
            "goodput_on": on["goodput"],
            "goodput_off": off["goodput"],
            "goodput_ratio": on["goodput"] / max(1e-9, off["goodput"]),
            "shed_on": on["shed"],
            "failed_on": on["failed"],
            "failed_off": off["failed"],
            "max_level_seen": on["max_level_seen"],
            "transitions": on["transitions"],
        }
        print(
            f"overload shedding: goodput {res['goodput_on']:.2f}/s (ladder "
            f"on, {on['ok']} ok / {on['shed']} shed / {on['failed']} "
            f"failed) vs {res['goodput_off']:.2f}/s (off, {off['ok']} ok / "
            f"{off['failed']} failed) — ratio "
            f"{res['goodput_ratio']:.2f}x, peak L{on['max_level_seen']}",
            file=sys.stderr, flush=True,
        )
        if res["goodput_ratio"] < 1.0:
            print(
                "OVERLOAD REGRESSION: shedding did not beat the off arm "
                f"(ratio {res['goodput_ratio']:.2f}x)",
                file=sys.stderr,
            )
        return res
    except Exception as e:
        print(f"overload shedding sweep skipped: {e}", file=sys.stderr)
        return None


def _scrubbed_cpu_env() -> dict:
    """Tunnel-free env: no plugin-gating vars, ONE CPU device.

    Virtual CPU devices split the host threadpool; the fallback runs
    unsharded on device 0, so 8 virtual devices would throttle it ~8x."""
    from __graft_entry__ import _scrubbed_cpu_env as scrub

    return scrub(1)


def _run_phase(
    *, env: dict, timeout: float, use_jax_executor: bool, warmup: bool,
    workload: str,
) -> dict:
    script = WORKLOAD.format(
        repo=REPO,
        shape=SHAPE,
        chunk=CHUNK,
        addsum_shape=ADDSUM_SHAPE,
        addsum_chunk=ADDSUM_CHUNK,
        addsum_scaled_shape=ADDSUM_SCALED_SHAPE,
        addsum_scaled_chunk=ADDSUM_SCALED_CHUNK,
        matmul_n=MATMUL_N,
        matmul_chunk=MATMUL_CHUNK,
        elemwise_shape=ELEMWISE_SHAPE,
        elemwise_chunk=ELEMWISE_CHUNK,
        reduce_shape=REDUCE_SHAPE,
        reduce_chunk=REDUCE_CHUNK,
        use_jax_executor=use_jax_executor,
        warmup=warmup,
        workload=workload,
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    if out.returncode != 0:
        raise RuntimeError(f"phase failed (rc={out.returncode}): {out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def device_smoke_ok(timeout: float = SMOKE_TIMEOUT_S) -> bool:
    """A trivial jitted dispatch through the inherited (device) env. A dead
    or wedged tunnel hangs here for the probe timeout instead of eating a
    full phase budget."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", SMOKE],
            env=dict(os.environ),
            capture_output=True,
            text=True,
            timeout=_remaining(timeout),
        )
        return out.returncode == 0 and "smoke ok" in out.stdout
    except Exception:
        return False


def get_baselines() -> dict:
    """Recorded numpy-executor baselines; measure + record only if absent."""
    rec: dict = {}
    try:
        with open(RECORD_PATH) as f:
            rec = json.load(f)
        if "elapsed" in rec:  # legacy single-config record -> vorticity
            rec = {"vorticity": rec}
    except (OSError, ValueError):
        rec = {}

    changed = False
    for workload, shape, chunk in [
        ("vorticity", SHAPE, CHUNK),
        ("addsum", ADDSUM_SHAPE, ADDSUM_CHUNK),
        ("addsum_scaled", ADDSUM_SCALED_SHAPE, ADDSUM_SCALED_CHUNK),
        ("matmul", (MATMUL_N, MATMUL_N), MATMUL_CHUNK),
        ("elemwise", ELEMWISE_SHAPE, ELEMWISE_CHUNK),
        ("reduce", REDUCE_SHAPE, REDUCE_CHUNK),
    ]:
        entry = rec.get(workload)
        if (
            isinstance(entry, dict)
            and entry.get("shape") == list(shape)
            and entry.get("chunk") == chunk
            and isinstance(entry.get("elapsed"), (int, float))
        ):
            continue
        env = _scrubbed_cpu_env()
        env["CUBED_TPU_BACKEND"] = "numpy"
        try:
            res = _run_phase(
                env=env,
                timeout=_remaining(BASELINE_TIMEOUT_S),
                use_jax_executor=False,
                warmup=False,
                workload=workload,
            )
        except Exception as e:
            print(f"{workload} baseline measurement failed: {e}", file=sys.stderr)
            continue
        rec[workload] = {
            "metric": f"{workload} numpy-backend PythonDagExecutor elapsed",
            "shape": list(shape),
            "chunk": chunk,
            "elapsed": res["elapsed"],
            "value": res["value"],
            "measured": time.strftime("%Y-%m-%d")
            + ", single-process numpy backend, scrubbed env",
        }
        changed = True
    if changed:
        try:  # atomic write so a killed run can't leave a corrupt record
            tmp = RECORD_PATH + ".tmp"
            with open(tmp, "w") as f:
                json.dump(rec, f, indent=1)
            os.replace(tmp, RECORD_PATH)
        except OSError:
            pass
    return rec


def measure_device(workload: str, timeout: float):
    """One device-phase attempt; None on failure (caller decides fallback)."""
    try:
        return _run_phase(
            env=dict(os.environ),
            timeout=_remaining(timeout),
            use_jax_executor=True,
            warmup=True,
            workload=workload,
        )
    except Exception as e:
        print(f"{workload} TPU phase failed: {str(e)[:1200]}", file=sys.stderr)
        return None


def measure_cpu(workload: str, timeout: float):
    """Tunnel-free CPU fallback: still the real framework + JaxExecutor,
    labelled honestly as not-a-TPU number."""
    try:
        return _run_phase(
            env=_scrubbed_cpu_env(),
            timeout=_remaining(timeout),
            use_jax_executor=True,
            warmup=True,
            workload=workload,
        )
    except Exception as e:
        print(f"{workload} CPU fallback failed too: {str(e)[:800]}", file=sys.stderr)
        return None


#: context attached to degraded emissions so a dead tunnel at measurement
#: time doesn't read as a perf regression (the TPU numbers were measured and
#: committed when the tunnel was alive — benchmarks/BENCH_PROFILE.md)
FALLBACK_NOTE = (
    "device tunnel dead at measurement time; NOT a perf regression — see "
    "benchmarks/BENCH_PROFILE.md for the committed TPU measurements"
)


def _committed_device_numbers() -> dict:
    """metric -> committed device record from benchmarks/DEVICE_R5.jsonl.

    Lets a degraded (tunnel-dead) emission carry the real TPU number that
    WAS measured when the tunnel was alive, explicitly labelled with its
    provenance, instead of only pointing at a doc.
    """
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "benchmarks", "DEVICE_R5.jsonl")
    out = {}
    try:
        with open(path) as f:
            for ln in f:
                try:
                    r = json.loads(ln)
                except ValueError:
                    continue
                if r.get("phase") == "device" and "value" in r:
                    out[r["metric"]] = r
                elif r.get("phase") == "bench":
                    for m in r.get("metrics", []):
                        # exact metric name == measured on device that run
                        if isinstance(m, dict) and not str(
                            m.get("metric", "")
                        ).endswith(("_cpu_fallback", "_unavailable")):
                            out[m["metric"]] = {**m, "t": r.get("t", "")}
    except OSError:
        pass
    return out


def _degraded_note(metric: str) -> str:
    base = metric.rsplit("_cpu_fallback", 1)[0].rsplit("_unavailable", 1)[0]
    dev = _committed_device_numbers().get(base)
    if dev:
        vs = dev.get("vs_baseline")
        return (
            f"{FALLBACK_NOTE}; committed TPU number for this config "
            f"(benchmarks/DEVICE_R5.jsonl, {dev.get('t', '')}): "
            f"{dev['value']} {dev.get('unit', '')}"
            + (f" = {vs}x baseline" if vs is not None else "")
        )
    return FALLBACK_NOTE


def emit(metric: str, res, baseline, work: int, unit: str = "GB/s/chip") -> None:
    degraded = metric.endswith(("_cpu_fallback", "_unavailable"))
    if res is None:
        line = {"metric": metric, "value": 0.0, "unit": unit, "vs_baseline": None}
        if degraded:
            line["note"] = _degraded_note(metric)
        print(json.dumps(line), flush=True)
        return
    elapsed = max(res["elapsed"], 1e-9)
    vs = round(baseline["elapsed"] / elapsed, 3) if baseline else None
    line = {
        "metric": metric,
        "value": round(work / elapsed / 1e9, 3),
        "unit": unit,
        "vs_baseline": vs,
    }
    if degraded:
        line["note"] = _degraded_note(metric)
    print(json.dumps(line), flush=True)


#: (workload — doubles as the baselines key, metric name, work units, unit,
#: cpu-phase timeout cap)
#: Device-phase order is wedge-aware: both observed tunnel wedges (r3 tile
#: sweep, r5 device session — benchmarks/BENCH_PROFILE.md) followed multi-GB
#: HBM allocations, so the small-footprint configs that have never produced
#: a device number (matmul: ~130 MB/operand) run FIRST and the ~4 GB
#: addsum_scaled runs second-to-last; a mid-run wedge then costs the configs
#: with the least new information. vorticity stays LAST (the driver parses
#: the last line).
CONFIGS = [
    ("matmul", "matmul_4000x4000_blockwise_contraction", MATMUL_FLOPS,
     "GFLOP/s/chip", 100),
    ("matmul_bf16", "matmul_4000x4000_bf16_mxu", MATMUL_FLOPS,
     "GFLOP/s/chip", 100),
    ("elemwise", "elementwise_chain_6000x6000_f64", ELEMWISE_WORK_BYTES,
     "GB/s/chip", 100),
    ("reduce", "axis_reductions_8000x8000_f64", REDUCE_WORK_BYTES,
     "GB/s/chip", 100),
    ("addsum", "blockwise_addsum_5000x5000_f64", ADDSUM_WORK_BYTES,
     "GB/s/chip", 120),
    # physical bytes under f32 ingestion are half the declared-f64 bytes
    ("vorticity_f32", "pangeo_vorticity_500x450x400_f32_ingest",
     WORK_BYTES // 2, "GB/s/chip", 200),
    ("addsum_scaled", "blockwise_addsum_16000x16000_f64_scaled",
     ADDSUM_SCALED_WORK_BYTES, "GB/s/chip", 150),
    # vorticity LAST (the driver parses the last line)
    ("vorticity", "pangeo_vorticity_500x450x400_f64_throughput", WORK_BYTES,
     "GB/s/chip", 300),
]

#: precision-opt-in variants compare against their full-precision config's
#: numpy baseline (the speedup the opt-in buys over the same reference math)
BASELINE_KEY = {"matmul_bf16": "matmul", "vorticity_f32": "vorticity"}

#: measured after the canonical BASELINE.json configs when budget is tight
VARIANT_WORKLOADS = {"addsum_scaled", "matmul_bf16", "vorticity_f32"}

#: don't start re-probing a dead tunnel unless this much budget remains —
#: a revival needs enough room to actually re-measure on device
REPROBE_MIN_BUDGET_S = 200
REPROBE_TIMEOUT_S = 45


def main() -> None:
    baselines = get_baselines()
    device_ok = device_smoke_ok()
    cpu_results: dict = {}

    if not device_ok:
        # The tunnel has recovered mid-round before (BENCH_PROFILE.md §TPU
        # re-measurement), so don't give up after one probe: measure the CPU
        # fallbacks now (numbers in hand whatever happens), re-probing the
        # tunnel between configs while enough budget remains to use a
        # revival.
        print("device smoke failed: tunnel dead/wedged; measuring CPU "
              "fallbacks while re-probing", file=sys.stderr)
        cpu_order = sorted(
            CONFIGS, key=lambda c: (c[0] in VARIANT_WORKLOADS, c[0] == "vorticity")
        )
        probes_left = 3  # a dead-tunnel probe costs its full timeout
        for workload, _, _, _, cap in cpu_order:
            cpu_results[workload] = measure_cpu(workload, cap)
            budget = OVERALL_DEADLINE_S - (time.monotonic() - _T0)
            if probes_left > 0 and budget > REPROBE_MIN_BUDGET_S:
                probes_left -= 1
                if device_smoke_ok(timeout=REPROBE_TIMEOUT_S):
                    device_ok = True
                    print("tunnel recovered mid-run; switching to device "
                          "measurement", file=sys.stderr)
                    break

    device_results: dict = {}
    if device_ok:
        for workload, _, _, _, _cap in CONFIGS:
            res = measure_device(workload, 300 if workload == "vorticity" else 120)
            if res is None:
                if device_smoke_ok(timeout=REPROBE_TIMEOUT_S):
                    # phase-specific failure with a live tunnel: one retry
                    res = measure_device(workload, 90)
                else:
                    # the documented MID-RUN wedge (smoke passed, tunnel
                    # died later): stop burning budget on device phases so
                    # the CPU fallback pass below still fits the deadline
                    print("tunnel wedged mid-run; remaining configs go to "
                          "CPU fallback", file=sys.stderr)
                    break
            device_results[workload] = res

    # CPU fallbacks for anything the device path didn't cover, in priority
    # order (canonical BASELINE.json configs before variants) so a tight
    # budget spends itself on the required metrics first
    cpu_order = sorted(
        CONFIGS, key=lambda c: (c[0] in VARIANT_WORKLOADS, c[0] == "vorticity")
    )
    for workload, _, _, _, cap in cpu_order:
        if device_results.get(workload) is None and workload not in cpu_results:
            if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 30:
                cpu_results[workload] = measure_cpu(workload, cap)

    metrics_record: dict = {}
    for workload, metric, work, unit, cap in CONFIGS:
        res, sfx = device_results.get(workload), ""
        if res is None:
            res, sfx = cpu_results.get(workload), "_cpu_fallback"
            if res is None:
                sfx = "_unavailable"
        base = baselines.get(BASELINE_KEY.get(workload, workload))
        emit(metric + sfx, res, base, work, unit=unit)
        if res is not None:
            stats = res.get("executor_stats") or {}
            metrics_record[metric + sfx] = {
                "elapsed": res.get("elapsed"),
                "value": res.get("value"),
                # resilience trajectory: retry overhead and injected faults
                # ride alongside the perf numbers so a regression in either
                # is visible from BENCH_METRICS.json history alone
                "task_retries": stats.get("task_retries", 0),
                "faults_injected": stats.get("faults_injected", 0),
                # integrity trajectory: verification volume, detected
                # corruption, and resume's chunk-granular skips
                "chunks_verified": stats.get("chunks_verified", 0),
                "chunks_corrupt_detected": stats.get(
                    "chunks_corrupt_detected", 0
                ),
                "tasks_skipped_resume": stats.get("tasks_skipped_resume", 0),
                # memory-guard trajectory: observe-mode exceedances,
                # admission throttling, and peak worker RSS per config —
                # guard overhead or pressure regressions show up here
                # before anyone has to profile (the sampler must stay <2%
                # wall-clock on the threaded bench, visible via elapsed)
                "mem_guard_soft_exceeded": stats.get(
                    "mem_guard_soft_exceeded", 0
                ),
                "tasks_throttled": stats.get("tasks_throttled", 0),
                # gauge for in-process/threaded runs, heartbeat gauge for
                # fleets, and per-op worker VmHWM (measured where each task
                # ran, riding TaskEndEvent) for multiprocess pools whose
                # worker-local gauges never reach the client registry
                "worker_rss_peak": (
                    stats.get("worker_rss_bytes_max")
                    or stats.get("fleet_worker_rss_bytes_max")
                    or max(
                        (
                            (row.get("peak_measured_mem") or 0)
                            for row in (stats.get("per_op") or {}).values()
                        ),
                        default=0,
                    )
                ),
                "executor_stats": stats or None,
            }

    # fleet scaling: tasks/sec at 1→2→4→8→16→32 workers, budget
    # permitting — sleep-bound tasks, so the sweep cost is dominated by
    # the 63 worker boots, not compute
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 110:
        scaling = measure_fleet_scaling(_remaining(180))
        if scaling is not None:
            metrics_record["fleet_scaling"] = scaling
    else:
        print("fleet scaling sweep skipped: out of budget", file=sys.stderr)

    # scheduler overlap: the deep-chain critical path, op-level vs
    # dataflow (~DEPTH x DELAY + DELAY of sleeping, well under a minute)
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        sched = measure_scheduler_overlap(_remaining(90))
        if sched is not None:
            metrics_record["scheduler_deepchain"] = sched
    else:
        print("scheduler overlap sweep skipped: out of budget",
              file=sys.stderr)

    # coordinator crash recovery: kill-at-50%-then-resume-from-journal vs
    # an uninterrupted run (three fleet boots + ~3x a short sleep-bound
    # compute); `elapsed` is the recovery total so the generic perf gate
    # flags regressions like any other config
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 75:
        recovery = measure_coordinator_recovery(_remaining(120))
        if recovery is not None:
            metrics_record["coordinator_recovery"] = recovery
    else:
        print("coordinator recovery sweep skipped: out of budget",
              file=sys.stderr)

    # live coordinator failover: SIGKILL the coordinator process at ~50%
    # and let a successor adopt the still-running worker fleet via the
    # control log + rendezvous file; `elapsed` (run-to-kill + takeover)
    # rides the same >20% perf gate, and failover_overhead_x tracks the
    # < 2x-of-uninterrupted acceptance bound
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 75:
        failover = measure_coordinator_failover(_remaining(120))
        if failover is not None:
            metrics_record["coordinator_failover"] = failover
    else:
        print("coordinator failover sweep skipped: out of budget",
              file=sys.stderr)

    # p2p chunk transfer: the deep chain store-only vs peer-enabled (two
    # fleet boots + two short elementwise-chain computes)
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 60:
        p2p = measure_p2p_transfer(_remaining(120))
        if p2p is not None:
            metrics_record["p2p_transfer"] = p2p
    else:
        print("p2p transfer sweep skipped: out of budget", file=sys.stderr)

    # rechunk shuffle: the transpose-heavy pipeline store-only vs the
    # peer-routed all-to-all (two fleet boots + two short computes)
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 60:
        shuf = measure_rechunk_shuffle(_remaining(120))
        if shuf is not None:
            metrics_record["rechunk_shuffle"] = shuf
    else:
        print("rechunk shuffle sweep skipped: out of budget",
              file=sys.stderr)

    # telemetry-sampler overhead: the deep chain with the live-telemetry
    # pipeline armed (1s sampler + scraped /metrics endpoint) vs off —
    # the armed wall clock rides the generic >20% perf gate
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        tele = measure_telemetry_overhead(_remaining(90))
        if tele is not None:
            metrics_record["telemetry_overhead"] = tele
    else:
        print("telemetry overhead sweep skipped: out of budget",
              file=sys.stderr)

    # dispatch-profiler overhead: the deep chain with the coordinator
    # self-profiler armed (~75 Hz sys._current_frames sampler) vs off —
    # the armed wall clock rides the generic >20% perf gate
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        dpo = measure_dispatch_profile_overhead(_remaining(90))
        if dpo is not None:
            metrics_record["dispatch_profile_overhead"] = dpo
    else:
        print("dispatch profile overhead sweep skipped: out of budget",
              file=sys.stderr)

    # analytics overhead: the deep chain with a TraceCollector attached +
    # a post-compute analyze() pass vs unobserved — the armed total rides
    # the generic >20% perf gate
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        ana = measure_analytics_overhead(_remaining(90))
        if ana is not None:
            metrics_record["analytics_overhead"] = ana
    else:
        print("analytics overhead sweep skipped: out of budget",
              file=sys.stderr)

    # store brownout: seeded 429/503 throttles, health breaker on vs off
    # (wall clock + retry-budget draw; the breaker-on wall rides the
    # generic perf gate)
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        brn = measure_store_brownout(_remaining(90))
        if brn is not None:
            metrics_record["store_brownout"] = brn
    else:
        print("store brownout sweep skipped: out of budget",
              file=sys.stderr)

    # chaos degradation: the deep chain clean vs under a composed
    # three-domain fault schedule (the campaign-suite shape) — the
    # composed wall clock rides the generic perf gate
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        chd = measure_chaos_degradation(_remaining(90))
        if chd is not None:
            metrics_record["chaos_degradation"] = chd
    else:
        print("chaos degradation sweep skipped: out of budget",
              file=sys.stderr)

    # multi-tenant service: sustained submissions from N synthetic
    # tenants (QPS, p50/p99 latency, fairness ratio, cache hits) — the
    # front-door overhead number the service is on the hook for
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        mt = measure_multitenant_service(_remaining(90))
        if mt is not None:
            metrics_record["multitenant_service"] = mt
    else:
        print("multitenant service sweep skipped: out of budget",
              file=sys.stderr)

    # SLO/archive overhead: the same request mix with the per-tenant SLO
    # board + durable run archive armed vs off — observing the front door
    # must not slow it down
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        slo = measure_slo_overhead(_remaining(90))
        if slo is not None:
            metrics_record["slo_overhead"] = slo
    else:
        print("slo overhead sweep skipped: out of budget", file=sys.stderr)

    # overload shedding: 2-tenant goodput at ~2x overload, degradation
    # ladder on vs CUBED_TPU_OVERLOAD=off — the robustness win the
    # overload controller is on the hook for (shed-on must beat shed-off)
    if OVERALL_DEADLINE_S - (time.monotonic() - _T0) > 45:
        ovl = measure_overload_shedding(_remaining(90))
        if ovl is not None:
            metrics_record["overload_shedding"] = ovl
    else:
        print("overload shedding sweep skipped: out of budget",
              file=sys.stderr)

    # per-op timing / IO-byte trajectories ride alongside the headline
    # numbers so future rounds can localize regressions without re-profiling
    prev_trajectory = _previous_trajectory()
    record = {
        "t": time.strftime("%Y-%m-%dT%H:%M:%S"), "configs": metrics_record
    }
    try:
        path = os.path.join(REPO, "BENCH_METRICS.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, default=str)
        os.replace(tmp, path)
    except OSError as e:
        print(f"could not write BENCH_METRICS.json: {e}", file=sys.stderr)
    _append_history(record)
    _print_trajectory_deltas(metrics_record, prev_trajectory)


#: bound on retained history records (one JSON line per bench run); the
#: perf-regression gate (tests/test_perf_gate.py) compares the last two
HISTORY_PATH = os.path.join(REPO, "BENCH_METRICS_HISTORY.jsonl")
HISTORY_KEEP = 50


def _append_history(record: dict) -> None:
    """Append this run to the rolling history the perf gate reads.

    BENCH_METRICS.json is overwrite-per-run, so by itself a regression is
    only visible to whoever ran both benches; the history file keeps the
    trajectory on disk (bounded), compactly — per-config scalars only,
    no nested executor_stats blobs."""
    slim_cfgs = {}
    for name, cfg in (record.get("configs") or {}).items():
        if not isinstance(cfg, dict):
            continue
        slim = {
            k: v for k, v in cfg.items()
            if isinstance(v, (int, float, str)) or k in (
                "tasks_per_s", "efficiency", "dispatch", "oplevel",
                "dataflow", "tenants",
            )
        }
        slim.pop("executor_stats", None)
        slim_cfgs[name] = slim
    line = json.dumps({"t": record.get("t"), "configs": slim_cfgs},
                      default=str)
    try:
        lines = []
        try:
            with open(HISTORY_PATH) as f:
                lines = [ln for ln in f.read().splitlines() if ln.strip()]
        except OSError:
            pass
        lines.append(line)
        tmp = HISTORY_PATH + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines[-HISTORY_KEEP:]) + "\n")
        os.replace(tmp, HISTORY_PATH)
    except OSError as e:
        print(f"could not append BENCH_METRICS_HISTORY.jsonl: {e}",
              file=sys.stderr)


def _previous_trajectory():
    """The most recent prior bench record to compare this run against.

    Prefers a previous ``BENCH_METRICS.json`` (full per-config elapsed +
    peak-RSS), falling back to the newest committed ``BENCH_r*.json``
    driver record (throughput-only, parsed from its emitted tail lines).
    Returns ``(configs_dict, label)``; empty dict when there is nothing.
    """
    path = os.path.join(REPO, "BENCH_METRICS.json")
    try:
        with open(path) as f:
            prev = json.load(f)
        configs = prev.get("configs") or {}
        if configs:
            return configs, f"BENCH_METRICS.json ({prev.get('t', '?')})"
    except (OSError, ValueError):
        pass
    import glob

    best: dict = {}
    label = ""
    for p in sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json"))):
        try:
            with open(p) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        metrics = {}
        for ln in str(rec.get("tail") or "").splitlines():
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if isinstance(d, dict) and "metric" in d and "value" in d:
                metrics[d["metric"]] = {"value": d["value"]}
        if metrics:
            best, label = metrics, os.path.basename(p)
    return best, label


def _delta_pct(cur, old):
    if not isinstance(cur, (int, float)) or not isinstance(old, (int, float)):
        return None
    if old == 0:
        return None
    return (cur - old) / old * 100.0


def _print_scaling_deltas(cur: dict, old: dict, label: str) -> None:
    """Fleet-scaling trajectory: per-size tasks/sec and scaling efficiency
    vs the previous record, with a LOUD flag on any >20 % efficiency drop
    — the number the autoscaler/drain machinery is on the hook for, so it
    must not be able to rot silently."""
    tps, eff = cur.get("tasks_per_s") or {}, cur.get("efficiency") or {}
    line = ", ".join(
        f"{n}w {tp:.1f}/s" + (
            f" (eff {eff[n]:.2f})" if n in eff else ""
        )
        for n, tp in sorted(tps.items(), key=lambda kv: int(kv[0]))
    )
    print(f"trajectory fleet_scaling: {line}", file=sys.stderr)
    # the control-plane story behind the efficiency curve: per-size
    # dispatch overhead and peak utilization — the ISSUE-16 measurement
    # substrate the sharded-dispatch refactor will be judged against
    disp = cur.get("dispatch") or {}
    if disp:
        dline = ", ".join(
            f"{n}w "
            + (
                f"{row.get('dispatch_overhead_ms'):.2f}ms/task"
                if isinstance(
                    row.get("dispatch_overhead_ms"), (int, float)
                )
                else "?ms/task"
            )
            + (
                f" util {row.get('dispatch_utilization'):.2f}"
                if isinstance(
                    row.get("dispatch_utilization"), (int, float)
                )
                else ""
            )
            for n, row in sorted(disp.items(), key=lambda kv: int(kv[0]))
            if isinstance(row, dict)
        )
        print(f"trajectory fleet_scaling dispatch: {dline}",
              file=sys.stderr)
    old_tps = old.get("tasks_per_s") or {}
    old_eff = old.get("efficiency") or {}
    if not old_tps:
        print("trajectory fleet_scaling: no prior record to compare "
              f"against in {label}" if label else
              "trajectory fleet_scaling: first record", file=sys.stderr)
        return
    regressed = []
    for size in sorted(eff, key=int):
        pct = _delta_pct(eff.get(size), old_eff.get(size))
        if pct is not None and pct <= -20.0:
            regressed.append(
                f"{size}w efficiency {eff[size]:.2f} vs "
                f"{old_eff[size]:.2f} ({pct:+.1f}%)"
            )
    # absolute throughput at each size backs the efficiency ratios: a run
    # where EVERY size slowed equally keeps its efficiency but is still a
    # fleet-dispatch regression
    for size in sorted(tps, key=int):
        pct = _delta_pct(tps.get(size), old_tps.get(size))
        if pct is not None and pct <= -20.0:
            regressed.append(
                f"{size}w {tps[size]:.1f} tasks/s vs "
                f"{old_tps[size]:.1f} ({pct:+.1f}%)"
            )
    if regressed:
        print(
            "SCALING REGRESSION (>20% vs " + (label or "prior record")
            + "): " + "; ".join(regressed),
            file=sys.stderr,
        )
    else:
        print(f"trajectory fleet_scaling: within 20% of {label}",
              file=sys.stderr)


#: relative change beyond which the perf gate calls a regression (the
#: container's own run-to-run noise is ~±15%)
PERF_GATE_THRESHOLD_PCT = 20.0


def perf_regressions(prev: dict, cur: dict) -> list:
    """Compare two bench records' configs; return regression strings.

    The contract the tier-1 gate (tests/test_perf_gate.py) enforces: no
    config's wall clock grows >20%, no fleet-scaling throughput drops
    >20%, and the dataflow scheduler keeps beating the op barrier within
    20% of its recorded margin. Shared here so bench.py's delta printer
    and the test gate can never disagree about what a regression is."""
    out = []
    pcfgs = prev.get("configs") or {}
    for name, cfg in (cur.get("configs") or {}).items():
        old = pcfgs.get(name)
        if not isinstance(old, dict) or not isinstance(cfg, dict):
            continue
        if name == "fleet_scaling":
            old_tps = old.get("tasks_per_s") or {}
            for size, tp in (cfg.get("tasks_per_s") or {}).items():
                pct = _delta_pct(tp, old_tps.get(size))
                if pct is not None and pct <= -PERF_GATE_THRESHOLD_PCT:
                    out.append(
                        f"fleet_scaling {size}w throughput {tp:.1f} vs "
                        f"{old_tps[size]:.1f} tasks/s ({pct:+.1f}%)"
                    )
            # per-task dispatch overhead growing >20% is a control-plane
            # regression even when throughput survives (sleep-bound tasks
            # can hide it); sub-0.05ms values are sampling noise, not a
            # trend, so they never gate
            old_disp = old.get("dispatch") or {}
            for size, row in (cfg.get("dispatch") or {}).items():
                if not isinstance(row, dict):
                    continue
                ov = row.get("dispatch_overhead_ms")
                old_ov = (old_disp.get(size) or {}).get(
                    "dispatch_overhead_ms"
                )
                pct = _delta_pct(ov, old_ov)
                if (
                    pct is not None
                    and pct >= PERF_GATE_THRESHOLD_PCT
                    and isinstance(ov, (int, float))
                    and ov > 0.05
                ):
                    out.append(
                        f"fleet_scaling {size}w dispatch overhead "
                        f"{ov:.3f}ms/task vs {old_ov:.3f}ms/task "
                        f"({pct:+.1f}%)"
                    )
            continue
        if name == "scheduler_deepchain":
            pct = _delta_pct(cfg.get("speedup"), old.get("speedup"))
            if pct is not None and pct <= -PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"scheduler_deepchain speedup {cfg['speedup']:.2f}x vs "
                    f"{old['speedup']:.2f}x ({pct:+.1f}%)"
                )
            cur_df = (cfg.get("dataflow") or {}).get("elapsed")
            old_df = (old.get("dataflow") or {}).get("elapsed")
            pct = _delta_pct(cur_df, old_df)
            if pct is not None and pct >= PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"scheduler_deepchain dataflow wall {cur_df:.2f}s vs "
                    f"{old_df:.2f}s ({pct:+.1f}%)"
                )
            continue
        if name in ("p2p_transfer", "rechunk_shuffle"):
            # the data-plane wins must not rot: saved bytes dropping >20%
            # or the peer-enabled wall clock growing >20% both gate
            # (p2p_transfer is the deep elementwise chain; rechunk_shuffle
            # the transpose-heavy all-to-all — same record shape)
            pct = _delta_pct(
                cfg.get("saved_fraction"), old.get("saved_fraction")
            )
            if pct is not None and pct <= -PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"{name} saved_fraction "
                    f"{cfg['saved_fraction']:.2f} vs "
                    f"{old['saved_fraction']:.2f} ({pct:+.1f}%)"
                )
            cur_pe = (cfg.get("peer") or {}).get("elapsed")
            old_pe = (old.get("peer") or {}).get("elapsed")
            pct = _delta_pct(cur_pe, old_pe)
            if pct is not None and pct >= PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"{name} peer wall {cur_pe:.2f}s vs "
                    f"{old_pe:.2f}s ({pct:+.1f}%)"
                )
            continue
        if name == "overload_shedding":
            # the ladder's reason to exist: shed-on goodput must beat
            # shed-off in the SAME run, and must not rot run-over-run
            ratio = cfg.get("goodput_ratio")
            if isinstance(ratio, (int, float)) and ratio < 1.0:
                out.append(
                    f"overload_shedding ladder-on goodput no longer beats "
                    f"ladder-off (ratio {ratio:.2f}x)"
                )
            pct = _delta_pct(cfg.get("goodput_on"), old.get("goodput_on"))
            if pct is not None and pct <= -PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"overload_shedding goodput {cfg['goodput_on']:.2f}/s "
                    f"vs {old['goodput_on']:.2f}/s ({pct:+.1f}%)"
                )
            continue  # a paced, fixed-length scenario: wall is by design
        if name == "multitenant_service":
            # the front door must not rot: QPS dropping >20% or p99
            # latency growing >20% both gate (elapsed rides the generic
            # wall check below like every other config)
            pct = _delta_pct(cfg.get("qps"), old.get("qps"))
            if pct is not None and pct <= -PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"multitenant_service QPS {cfg['qps']:.1f} vs "
                    f"{old['qps']:.1f} ({pct:+.1f}%)"
                )
            pct = _delta_pct(cfg.get("p99_s"), old.get("p99_s"))
            if pct is not None and pct >= PERF_GATE_THRESHOLD_PCT:
                out.append(
                    f"multitenant_service p99 {cfg['p99_s']:.3f}s vs "
                    f"{old['p99_s']:.3f}s ({pct:+.1f}%)"
                )
            # per-tenant p99: one tenant's SLO rotting must gate even
            # when the other tenants keep the GLOBAL percentile flat
            old_tenants = old.get("tenants") or {}
            for tenant, row in (cfg.get("tenants") or {}).items():
                if not isinstance(row, dict):
                    continue
                old_p99 = (old_tenants.get(tenant) or {}).get("p99_s")
                pct = _delta_pct(row.get("p99_s"), old_p99)
                if pct is not None and pct >= PERF_GATE_THRESHOLD_PCT:
                    out.append(
                        f"multitenant_service {tenant} p99 "
                        f"{row['p99_s']:.3f}s vs {old_p99:.3f}s "
                        f"({pct:+.1f}%)"
                    )
        pct = _delta_pct(cfg.get("elapsed"), old.get("elapsed"))
        if pct is not None and pct >= PERF_GATE_THRESHOLD_PCT:
            out.append(
                f"{name} wall {cfg['elapsed']:.2f}s vs "
                f"{old['elapsed']:.2f}s ({pct:+.1f}%)"
            )
    return out


def _print_scheduler_deltas(cur: dict, old: dict, label: str) -> None:
    """Scheduler trajectory: deep-chain wall clock per mode plus the
    dataflow speedup, with a LOUD flag when the dataflow path stops
    beating the op barrier (>20 % speedup drop or wall-clock regression)
    — the number the chunk-granular scheduler is on the hook for."""
    op = (cur.get("oplevel") or {}).get("elapsed")
    df = (cur.get("dataflow") or {}).get("elapsed")
    speedup = cur.get("speedup")
    early = (cur.get("dataflow") or {}).get("tasks_dispatched_early", 0)
    print(
        f"trajectory scheduler_deepchain: oplevel {op:.2f}s, dataflow "
        f"{df:.2f}s, speedup {speedup:.2f}x, {early} task(s) dispatched "
        "early" if isinstance(op, (int, float)) and isinstance(
            df, (int, float)
        ) else "trajectory scheduler_deepchain: incomplete record",
        file=sys.stderr,
    )
    if isinstance(speedup, (int, float)) and speedup < 1.05:
        print(
            "SCHEDULER REGRESSION: dataflow no longer beats the op-level "
            f"barrier on the deep chain (speedup {speedup:.2f}x)",
            file=sys.stderr,
        )
    if not old:
        print("trajectory scheduler_deepchain: no prior record to compare "
              f"against in {label}" if label else
              "trajectory scheduler_deepchain: first record",
              file=sys.stderr)
        return
    # same rules (and threshold) as the tier-1 gate, via the shared helper
    regressed = [
        r for r in perf_regressions(
            {"configs": {"scheduler_deepchain": old}},
            {"configs": {"scheduler_deepchain": cur}},
        )
    ]
    if regressed:
        print(
            f"SCHEDULER REGRESSION (>{PERF_GATE_THRESHOLD_PCT:.0f}% vs "
            + (label or "prior record") + "): " + "; ".join(regressed),
            file=sys.stderr,
        )
    else:
        print(
            f"trajectory scheduler_deepchain: within "
            f"{PERF_GATE_THRESHOLD_PCT:.0f}% of {label}",
            file=sys.stderr,
        )


def _print_p2p_deltas(
    cur: dict, old: dict, label: str,
    name: str = "p2p_transfer", bar: float = 0.30,
) -> None:
    """Data-plane trajectory (the deep-chain ``p2p_transfer`` and the
    transpose-heavy ``rechunk_shuffle`` share a record shape): saved read
    bytes, hit rate, and per-mode wall clock, with a LOUD flag when the
    saved fraction falls under the config's acceptance bar (30% for the
    chain, 40% for the shuffle) or the shared gate rules flag a
    regression."""
    sf = cur.get("saved_fraction")
    hr = cur.get("hit_rate")
    so = (cur.get("store_only") or {}).get("elapsed")
    pe = (cur.get("peer") or {}).get("elapsed")
    if isinstance(sf, (int, float)) and isinstance(pe, (int, float)):
        print(
            f"trajectory {name}: saved_fraction {sf:.0%}, hit rate "
            f"{(hr or 0):.0%}, store-only {so:.2f}s vs peer {pe:.2f}s",
            file=sys.stderr,
        )
        if sf < bar:
            print(
                f"P2P REGRESSION: {name} store_read_bytes_saved fell under "
                f"the {bar:.0%} acceptance bar (saved_fraction {sf:.0%})",
                file=sys.stderr,
            )
    else:
        print(f"trajectory {name}: incomplete record", file=sys.stderr)
    if not old:
        print(f"trajectory {name}: no prior record to compare against "
              f"in {label}" if label else
              f"trajectory {name}: first record", file=sys.stderr)
        return
    regressed = perf_regressions(
        {"configs": {name: old}},
        {"configs": {name: cur}},
    )
    if regressed:
        print(
            f"P2P REGRESSION (>{PERF_GATE_THRESHOLD_PCT:.0f}% vs "
            + (label or "prior record") + "): " + "; ".join(regressed),
            file=sys.stderr,
        )
    else:
        print(
            f"trajectory {name}: within "
            f"{PERF_GATE_THRESHOLD_PCT:.0f}% of {label}",
            file=sys.stderr,
        )


def _print_multitenant_deltas(cur: dict, old: dict, label: str) -> None:
    """Multi-tenant service trajectory: QPS, latency quantiles, fairness,
    with a LOUD flag on the shared gate rules (QPS drop / p99 growth /
    wall regression) and on a fairness ratio leaving its bound."""
    qps = cur.get("qps")
    fr = cur.get("fairness_ratio")
    if isinstance(qps, (int, float)):
        print(
            f"trajectory multitenant_service: {qps:.1f} QPS, p50 "
            f"{(cur.get('p50_s') or 0) * 1000:.0f}ms, p99 "
            f"{(cur.get('p99_s') or 0) * 1000:.0f}ms, fairness "
            f"{(fr or 0):.2f}, {cur.get('result_cache_hits', 0)} "
            "result-cache hit(s)",
            file=sys.stderr,
        )
        if isinstance(fr, (int, float)) and fr > 2.0:
            print(
                "SERVICE FAIRNESS REGRESSION: max/min per-tenant "
                f"throughput ratio {fr:.2f} exceeds the 2.0 bound for "
                "equal-weight tenants",
                file=sys.stderr,
            )
    else:
        print("trajectory multitenant_service: incomplete record",
              file=sys.stderr)
    if not old:
        print("trajectory multitenant_service: no prior record to compare "
              f"against in {label}" if label else
              "trajectory multitenant_service: first record",
              file=sys.stderr)
        return
    regressed = perf_regressions(
        {"configs": {"multitenant_service": old}},
        {"configs": {"multitenant_service": cur}},
    )
    if regressed:
        print(
            f"SERVICE REGRESSION (>{PERF_GATE_THRESHOLD_PCT:.0f}% vs "
            + (label or "prior record") + "): " + "; ".join(regressed),
            file=sys.stderr,
        )
    else:
        print(
            f"trajectory multitenant_service: within "
            f"{PERF_GATE_THRESHOLD_PCT:.0f}% of {label}",
            file=sys.stderr,
        )


def _print_trajectory_deltas(metrics_record: dict, prev_trajectory) -> None:
    """One line per config vs the previous trajectory (stderr — stdout's
    last line belongs to the driver), so the bench history stops being
    write-only: a wall-clock or peak-RSS regression is visible in the run
    output itself, without anyone diffing JSON files."""
    prev, label = prev_trajectory
    if not prev:
        print("trajectory: no previous bench record to compare against",
              file=sys.stderr)
        return
    for metric, cur in metrics_record.items():
        old = prev.get(metric)
        if metric == "fleet_scaling":
            _print_scaling_deltas(cur, old if isinstance(old, dict) else {},
                                  label)
            continue
        if metric == "scheduler_deepchain":
            _print_scheduler_deltas(
                cur, old if isinstance(old, dict) else {}, label
            )
            continue
        if metric == "p2p_transfer":
            _print_p2p_deltas(cur, old if isinstance(old, dict) else {},
                              label)
            continue
        if metric == "rechunk_shuffle":
            _print_p2p_deltas(cur, old if isinstance(old, dict) else {},
                              label, name="rechunk_shuffle", bar=0.40)
            continue
        if metric == "multitenant_service":
            _print_multitenant_deltas(
                cur, old if isinstance(old, dict) else {}, label
            )
            continue
        if not isinstance(old, dict):
            print(f"trajectory {metric}: new config (no prior record in "
                  f"{label})", file=sys.stderr)
            continue
        parts = []
        for key, name, fmt in (
            ("elapsed", "wall", "{:.2f}s"),
            ("worker_rss_peak", "peak-rss", "{:.0f}B"),
            ("value", "throughput", "{:.3f}"),
        ):
            pct = _delta_pct(cur.get(key), old.get(key))
            if pct is None:
                continue
            # wall clock / RSS: up is worse; throughput: up is better
            worse = pct > 0 if key != "value" else pct < 0
            tag = "regressed" if abs(pct) >= 5 and worse else (
                "improved" if abs(pct) >= 5 else "~flat")
            parts.append(
                f"{name} {fmt.format(cur[key])} vs {fmt.format(old[key])} "
                f"({pct:+.1f}%, {tag})"
            )
        if parts:
            print(f"trajectory {metric}: " + "; ".join(parts) +
                  f"  [vs {label}]", file=sys.stderr)


if __name__ == "__main__":
    main()
