"""Array-API indexing functions. Reference parity:
cubed/array_api/indexing_functions.py (4 LoC; ``take_along_axis`` is a
2024.12 extension the reference lacks — it pairs with argsort, which the
reference also lacks)."""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from .dtypes import _integer_dtypes


def take(x, indices, /, *, axis=None):
    if axis is None:
        if x.ndim != 1:
            raise ValueError("axis must be specified for multi-dimensional take")
        axis = 0
    axis = axis % x.ndim
    return x[(slice(None),) * axis + (indices,)]


def take_along_axis(x, indices, /, *, axis=-1):
    """2024.12 ``take_along_axis``: gather values along ``axis`` at
    per-position ``indices`` (the natural consumer of ``argsort``).

    Memory-bounded and oblivious, in the same style as ``searchsorted``:
    the output's chunk grid is ``indices``'s; each output block streams
    x's chunks along ``axis`` one at a time, gathering the in-chunk
    positions and masking by chunk ownership — so an ``axis`` larger than
    ``allowed_mem`` gathers fine (one x chunk resident per step), and the
    per-round kernel is identical across blocks (static plan, jittable).
    Out-of-range indices are unspecified per the standard (values clamp to
    the nearest chunk edge; no error is raised — a plan-time check cannot
    see data)."""
    if x.ndim == 0:
        raise ValueError("take_along_axis requires at least 1 dimension")
    if indices.dtype not in _integer_dtypes:
        raise TypeError("indices must have an integer dtype")
    if indices.ndim != x.ndim:
        raise ValueError(
            f"indices must have the same rank as x ({indices.ndim} != {x.ndim})"
        )
    axis = axis % x.ndim
    # per spec, indices must be broadcast-compatible with x except along
    # ``axis`` — size-1 dims on either side stretch to the other's extent
    try:
        out_nonaxis = [
            np.broadcast_shapes((indices.shape[d],), (x.shape[d],))[0]
            if d != axis
            else None
            for d in range(x.ndim)
        ]
    except ValueError:
        raise ValueError(
            "indices shape must be broadcast-compatible with x except "
            f"along axis; got {indices.shape} vs {x.shape} (axis={axis})"
        ) from None
    from .manipulation_functions import broadcast_to

    x_target = tuple(
        x.shape[axis] if d == axis else out_nonaxis[d] for d in range(x.ndim)
    )
    idx_target = tuple(
        indices.shape[axis] if d == axis else out_nonaxis[d]
        for d in range(x.ndim)
    )
    if tuple(x.shape) != x_target:
        x = broadcast_to(x, x_target)
    if tuple(indices.shape) != idx_target:
        indices = broadcast_to(indices, idx_target)

    from ..core.ops import general_blockwise

    # align non-axis chunk grids: the gather pairs each output block with
    # the x blocks sharing its non-axis coordinates
    target = tuple(
        indices.chunks[d] if d == axis else x.chunks[d]
        for d in range(x.ndim)
    )
    if indices.chunks != target:
        indices = indices.rechunk(target)

    n = x.shape[axis]
    sizes = [int(c) for c in x.chunks[axis]]
    starts = np.cumsum([0] + sizes[:-1]).tolist()
    m = len(sizes)
    idx_name, x_name = indices.name, x.name

    def block_function(out_key):
        coords = out_key[1:]
        x_keys = [
            (x_name, *(j if d == axis else c for d, c in enumerate(coords)))
            for j in range(m)
        ]
        return ((idx_name, *coords), iter(x_keys))

    def gather_kernel(idx_chunk, x_iter):
        # all index arithmetic in int64: small index dtypes (e.g. uint8)
        # would overflow on idx+n or idx-lo for perfectly valid indices
        idxn = nxp.astype(idx_chunk, np.dtype(np.int64))
        idxn = nxp.where(idxn < 0, idxn + n, idxn)
        acc = None
        for j, xb in enumerate(x_iter):
            lo, size = starts[j], sizes[j]
            loc = nxp.clip(idxn - lo, 0, size - 1)
            gathered = nxp.take_along_axis(xb, loc, axis=axis)
            if acc is None:
                acc = gathered
            else:
                hit = nxp.logical_and(idxn >= lo, idxn < lo + size)
                acc = nxp.where(hit, gathered, acc)
        return acc

    gather_kernel.__name__ = "take_along_axis"

    out_chunk = tuple(
        indices.chunksize[d] if d == axis else x.chunksize[d]
        for d in range(x.ndim)
    )
    # streamed temporaries: loc (int64) + gathered + hit + the where copy
    extra = (
        2 * int(np.prod(out_chunk)) * x.dtype.itemsize
        + 2 * int(np.prod(out_chunk)) * 8
    )
    return general_blockwise(
        gather_kernel,
        block_function,
        indices,
        x,
        shape=indices.shape,
        dtype=x.dtype,
        chunks=indices.chunks,
        extra_projected_mem=extra,
        num_input_blocks=(1, m),
        op_name="take_along_axis",
    )
