"""ComputeService API + behavior: submission lifecycle, fair-share
interleaving across tenants, flood isolation, throttling, cancellation,
durable request records, in-process recovery, config/env resolution, and
per-tenant stats."""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.service import (
    ComputeService,
    RequestCancelledError,
    ServiceConfig,
    TenantThrottledError,
)
from cubed_tpu.service.durability import load_requests


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


AN = np.arange(16, dtype=np.float64).reshape(4, 4)


def _build(spec, k=1.0, delay=0.0, chunks=(2, 2)):
    def kernel(x, _k=k, _d=delay):
        if _d:
            time.sleep(_d)
        return x + _k

    a = ct.from_array(AN, chunks=chunks, spec=spec)
    return ct.map_blocks(kernel, a, dtype=np.float64)


# ----------------------------------------------------------------------
# lifecycle basics
# ----------------------------------------------------------------------


def test_submit_result_status_roundtrip(spec):
    with ComputeService(max_concurrent=2) as svc:
        h = svc.submit(_build(spec, k=7.0), tenant="t1")
        value = h.result(timeout=60)
        np.testing.assert_array_equal(value, AN + 7.0)
        assert h.status() == "done"
        assert h.done()
        assert h.tenant == "t1"
        assert h.compute_id  # joined to traces/logs/journals
        row = svc.stats_snapshot()["tenants"]["t1"]
        assert row["accepted"] == 1 and row["completed"] == 1


def test_failure_surfaces_through_the_handle(spec):
    def boom(x):
        raise ValueError("kernel exploded")

    a = ct.from_array(AN, chunks=(2, 2), spec=spec)
    bad = ct.map_blocks(boom, a, dtype=np.float64)
    with ComputeService(max_concurrent=1) as svc:
        h = svc.submit(bad, tenant="t1")
        with pytest.raises(ValueError, match="kernel exploded"):
            h.result(timeout=60)
        assert h.status() == "failed"
        assert svc.stats_snapshot()["tenants"]["t1"]["failed"] == 1


def test_cancel_queued_request(spec):
    with ComputeService(max_concurrent=1) as svc:
        h1 = svc.submit(_build(spec, delay=0.2), tenant="t1")
        h2 = svc.submit(_build(spec, k=2.0, delay=0.2), tenant="t1")
        # h2 is behind h1 on a 1-slot service: cancellable while queued
        assert h2.cancel() or h2.done()
        if h2.status() == "cancelled":
            with pytest.raises(RequestCancelledError):
                h2.result(timeout=5)
        np.testing.assert_array_equal(h1.result(60), AN + 1.0)
        assert not h1.cancel()  # finished requests don't cancel


def test_tenant_throttle_bound(spec):
    reg = get_registry()
    before = reg.snapshot()
    with ComputeService(
        max_concurrent=1, max_queued_per_tenant=2, plan_cache=False,
        result_cache=False,
    ) as svc:
        accepted = []
        with pytest.raises(TenantThrottledError):
            # a flood from one tenant hits its backlog bound within a few
            # submissions (2 queued + whatever the dispatcher drained)
            for i in range(20):
                accepted.append((
                    svc.submit(
                        _build(spec, k=float(i), delay=0.3), tenant="noisy"
                    ),
                    float(i),
                ))
        assert 2 <= len(accepted) < 20
        assert svc.stats_snapshot()["tenants"]["noisy"]["throttled"] >= 1
        for h, k in accepted:
            np.testing.assert_array_equal(h.result(120), AN + k)
    assert reg.snapshot_delta(before).get("tenant_throttled", 0) >= 1


# ----------------------------------------------------------------------
# fair share across tenants
# ----------------------------------------------------------------------


def test_three_tenants_interleaved_fair_share(spec):
    """The acceptance shape: >=3 tenants, interleaved submissions, all
    bitwise-correct, admissions interleaved by weight with the fairness
    ratio within the configured bound."""
    weights = {"gold": 2.0, "silver": 1.0, "bronze": 1.0}
    n_each = 6
    with ComputeService(
        max_concurrent=1, tenants=weights, plan_cache=False,
        result_cache=False,
    ) as svc:
        handles = {}
        for i in range(n_each):  # interleaved submission order
            for tenant in weights:
                k = float(hash((tenant, i)) % 97)
                handles[(tenant, i)] = (
                    svc.submit(_build(spec, k=k, delay=0.02), tenant=tenant),
                    k,
                )
        for (tenant, i), (h, k) in handles.items():
            np.testing.assert_array_equal(h.result(180), AN + k)

        # admission order from the started_at stamps
        reqs = sorted(
            (h._request for h, _ in handles.values()),
            key=lambda r: r.started_at,
        )
        order = [r.tenant for r in reqs]
        # over the window where every tenant was still backlogged (gold
        # drains last at 2x weight: use the first 2 * n_bronze picks),
        # admission counts follow the weights
        window = order[: 2 * n_each]
        counts = {t: window.count(t) for t in weights}
        shares = {t: counts[t] / weights[t] for t in weights}
        ratio = max(shares.values()) / max(1e-9, min(shares.values()))
        assert ratio <= 2.0, (counts, order)
        row = svc.stats_snapshot()["tenants"]
        assert all(row[t]["completed"] == n_each for t in weights)


def test_flooding_tenant_cannot_starve_light_tenant(spec):
    """A tenant flooding the queue buys throughput proportional to its
    weight, never the whole service: the light tenant's requests all
    complete while the flood is still draining."""
    with ComputeService(
        max_concurrent=1, tenants={"flood": 1.0, "light": 1.0},
        plan_cache=False, result_cache=False,
    ) as svc:
        flood = [
            svc.submit(_build(spec, k=float(i), delay=0.05), tenant="flood")
            for i in range(12)
        ]
        light = [
            svc.submit(
                _build(spec, k=100.0 + i, delay=0.05), tenant="light"
            )
            for i in range(3)
        ]
        for i, h in enumerate(light):
            np.testing.assert_array_equal(h.result(120), AN + 100.0 + i)
        light_done = time.time()
        for i, h in enumerate(flood):
            np.testing.assert_array_equal(h.result(120), AN + float(i))
        # starvation bound: while both were backlogged the light tenant
        # was admitted at least every ceil(W/w)=2 picks, so its 3 requests
        # finished within the first ~8 admissions — long before the
        # 12-deep flood drained
        reqs = sorted(
            (h._request for h in flood + light),
            key=lambda r: r.started_at,
        )
        light_positions = [
            i for i, r in enumerate(reqs) if r.tenant == "light"
        ]
        assert light_positions, "light tenant never admitted"
        assert max(light_positions) <= 8, light_positions
        assert light_done  # noqa: B018 — document the timeline var


# ----------------------------------------------------------------------
# durability (in-process restart; the SIGKILL proof is in test_service_chaos)
# ----------------------------------------------------------------------


def test_durable_records_and_in_process_recovery(tmp_path, spec):
    sdir = str(tmp_path / "svc")
    svc = ComputeService(
        max_concurrent=1, service_dir=sdir, recover=False,
        plan_cache=False, result_cache=False,
    ).start()
    handles = [
        svc.submit(_build(spec, k=float(i), delay=0.1), tenant="t")
        for i in range(4)
    ]
    svc.close(timeout=60)
    # close() completes the queued tail's handles as CANCELLED (no client
    # may block forever) but does NOT seal their journal records: they
    # stay accepted + durable for the next service on this directory
    unfinished = [h for h in handles if h.status() == "cancelled"]
    assert unfinished, "all requests finished before close; nothing to recover"
    for h in unfinished:
        with pytest.raises(RequestCancelledError):
            h.result(timeout=1)
    pending = load_requests(sdir)
    assert {r["request_id"] for r in pending.get("t", [])} == {
        h.request_id for h in unfinished
    }

    reg = get_registry()
    before = reg.snapshot()
    svc2 = ComputeService(max_concurrent=2, service_dir=sdir).start()
    try:
        assert svc2.wait_idle(timeout=120)
        delta = reg.snapshot_delta(before)
        assert delta.get("service_requests_recovered", 0) == len(unfinished)
        for h in unfinished:
            h2 = svc2.handle(h.request_id)
            assert h2 is not None and h2.status() == "done"
            k = float(handles.index(h))
            np.testing.assert_array_equal(h2.result(10), AN + k)
        assert load_requests(sdir) == {}  # every accepted request sealed
    finally:
        svc2.close()


# ----------------------------------------------------------------------
# config / env resolution
# ----------------------------------------------------------------------


def test_spec_service_config_flows_through(tmp_path):
    cfg = ServiceConfig(tenants={"vip": 3.0}, max_concurrent=4)
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", service=cfg
    )
    svc = ComputeService(spec=spec)
    assert svc.config.max_concurrent == 4
    assert svc.arbiter.weight("vip") == 3.0
    # a dict works too
    spec2 = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        service={"max_concurrent": 3},
    )
    assert ComputeService(spec=spec2).config.max_concurrent == 3
    with pytest.raises(ValueError):
        ct.Spec(work_dir=str(tmp_path), service="not-a-config")


def test_env_overrides_win(monkeypatch, tmp_path):
    monkeypatch.setenv("CUBED_TPU_SERVICE_MAX_CONCURRENT", "5")
    monkeypatch.setenv("CUBED_TPU_SERVICE_RESULT_CACHE", "off")
    monkeypatch.setenv("CUBED_TPU_SERVICE_DIR", str(tmp_path / "envdir"))
    cfg = ServiceConfig.resolve(config=ServiceConfig(max_concurrent=2))
    assert cfg.max_concurrent == 5
    assert cfg.result_cache is False
    assert cfg.service_dir == str(tmp_path / "envdir")


def test_malformed_env_raises(monkeypatch):
    monkeypatch.setenv("CUBED_TPU_SERVICE_MAX_CONCURRENT", "many")
    with pytest.raises(ValueError, match="CUBED_TPU_SERVICE_MAX_CONCURRENT"):
        ServiceConfig.resolve()
    monkeypatch.delenv("CUBED_TPU_SERVICE_MAX_CONCURRENT")
    monkeypatch.setenv("CUBED_TPU_SERVICE_PLAN_CACHE", "maybe")
    with pytest.raises(ValueError, match="CUBED_TPU_SERVICE_PLAN_CACHE"):
        ServiceConfig.resolve()


def test_stats_snapshot_shape(spec):
    with ComputeService(tenants={"a": 2.0}) as svc:
        h = svc.submit(_build(spec), tenant="a")
        h.result(60)
        snap = svc.stats_snapshot()
        assert snap["durable"] is False
        assert snap["slots"] >= 1
        row = snap["tenants"]["a"]
        for key in (
            "weight", "queued", "running", "accepted", "completed",
            "failed", "cancelled", "throttled", "recovered",
            "plan_cache_hits", "result_cache_hits",
        ):
            assert key in row
        assert row["weight"] == 2.0
