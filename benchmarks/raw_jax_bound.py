"""Raw-JAX lower bounds for every bench.py config (VERDICT r3 #3).

Each BASELINE.json config re-expressed as ONE hand-written ``jax.jit`` of
the same math (including RNG), with the cache/latency-robust harness from
``benchmarks/BENCH_PROFILE.md``:

- every timed iteration consumes a DISTINCT seed (defeats the tunnel's
  (executable, args) result cache — trap #1);
- timing forces a scalar fetch (``float(...)``), because
  ``block_until_ready`` does not actually block through the tunnel
  (trap #2);
- the ~70 ms dispatch/sync latency floor is measured separately and
  reported so short phases can be floor-subtracted.

Dividing the framework's ``bench.py`` elapsed by these numbers gives the
framework-overhead ratio per config. Run with the inherited (device) env
for TPU numbers, or ``--cpu`` for a tunnel-free scrubbed-env run.

Output: one JSON line per config plus a ``latency_floor`` line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: shapes mirror bench.py exactly (import-free so this file runs standalone)
ADDSUM_SHAPE, ADDSUM_CHUNK = (5000, 5000), 1000
MATMUL_N = 4000
ELEMWISE_SHAPE = (6000, 6000)
REDUCE_SHAPE = (8000, 8000)
VORT_SHAPE = (500, 450, 400)

REPS = 3


def _work(config: str) -> tuple[float, str]:
    """(work units, unit) matching bench.py's accounting."""
    if config == "addsum":
        return 2 * ADDSUM_SHAPE[0] * ADDSUM_SHAPE[1] * 8, "GB/s"
    if config in ("matmul", "matmul_bf16"):
        return 2 * MATMUL_N**3, "GFLOP/s"
    if config == "elemwise":
        return 2 * ELEMWISE_SHAPE[0] * ELEMWISE_SHAPE[1] * 8, "GB/s"
    if config == "reduce":
        return REDUCE_SHAPE[0] * REDUCE_SHAPE[1] * 8, "GB/s"
    n = VORT_SHAPE[0] * VORT_SHAPE[1] * VORT_SHAPE[2]
    itemsize = 4 if config == "vorticity_f32" else 8
    return 6 * n * itemsize, "GB/s"


def build_fns():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_enable_x64", True)
    jax.config.update("jax_threefry_partitionable", True)

    def _u(seed, salt, shape, dtype=jnp.float64):
        key = jax.random.fold_in(jax.random.key(0), seed * 7919 + salt)
        return jax.random.uniform(key, shape, dtype=dtype)

    @jax.jit
    def addsum(seed):
        a = _u(seed, 1, ADDSUM_SHAPE)
        b = _u(seed, 2, ADDSUM_SHAPE)
        return jnp.sum(a + b)

    @jax.jit
    def matmul(seed):
        a = _u(seed, 1, (MATMUL_N, MATMUL_N))
        b = _u(seed, 2, (MATMUL_N, MATMUL_N))
        return jnp.sum(a @ b)

    @jax.jit
    def matmul_bf16(seed):
        # the MXU configuration the framework's opt-in targets: f32
        # generation, one-pass bf16 contraction, f32 accumulation
        a = _u(seed, 1, (MATMUL_N, MATMUL_N), jnp.float32)
        b = _u(seed, 2, (MATMUL_N, MATMUL_N), jnp.float32)
        with jax.default_matmul_precision("bfloat16"):
            return jnp.sum(a @ b)

    @jax.jit
    def elemwise(seed):
        a = _u(seed, 1, ELEMWISE_SHAPE)
        b = _u(seed, 2, ELEMWISE_SHAPE)
        return jnp.sum(jnp.sqrt(jnp.abs(jnp.sin(a) * b + jnp.cos(b))))

    @jax.jit
    def reduce(seed):
        a = _u(seed, 1, REDUCE_SHAPE)
        return jnp.max(jnp.mean(a, axis=0))

    @jax.jit
    def vorticity(seed):
        a = _u(seed, 1, VORT_SHAPE)
        b = _u(seed, 2, VORT_SHAPE)
        x = _u(seed, 3, VORT_SHAPE)
        y = _u(seed, 4, VORT_SHAPE)
        return jnp.mean(a[1:] * x[1:] + b[1:] * y[1:])

    @jax.jit
    def trivial(seed):
        return jnp.sum(jnp.full((8, 8), seed, jnp.float32))

    @jax.jit
    def vorticity_f32(seed):
        a = _u(seed, 1, VORT_SHAPE, jnp.float32)
        b = _u(seed, 2, VORT_SHAPE, jnp.float32)
        x = _u(seed, 3, VORT_SHAPE, jnp.float32)
        y = _u(seed, 4, VORT_SHAPE, jnp.float32)
        return jnp.mean(a[1:] * x[1:] + b[1:] * y[1:])

    return {
        "addsum": addsum,
        "matmul": matmul,
        "matmul_bf16": matmul_bf16,
        "elemwise": elemwise,
        "reduce": reduce,
        "vorticity": vorticity,
        "vorticity_f32": vorticity_f32,
        "_trivial": trivial,
    }


def time_fn(fn, *, reps=REPS, seed0=100) -> float:
    """Best-of-reps wall seconds, distinct seed each rep, scalar-fetch sync."""
    float(fn(seed0 - 1))  # warmup compile + first dispatch
    best = float("inf")
    for i in range(reps):
        t0 = time.perf_counter()
        float(fn(seed0 + i))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    # --configs a,b,c selects a subset in the given order (the wedge-prone
    # tunnel means callers want the highest-information configs first);
    # validate BEFORE the expensive jit builds so a typo costs nothing
    all_configs = (
        "addsum", "matmul", "matmul_bf16", "elemwise", "reduce",
        "vorticity", "vorticity_f32",
    )
    selected = all_configs
    if "--configs" in sys.argv:
        idx = sys.argv.index("--configs")
        if idx + 1 >= len(sys.argv):
            sys.exit("--configs requires a comma-separated value")
        selected = tuple(sys.argv[idx + 1].split(","))
        unknown = [c for c in selected if c not in all_configs]
        if unknown:
            sys.exit(f"unknown configs {unknown}; choose from {all_configs}")

    fns = build_fns()
    import jax

    platform = jax.devices()[0].platform
    floor = time_fn(fns["_trivial"], reps=5, seed0=900)
    print(json.dumps({
        "config": "latency_floor", "platform": platform,
        "elapsed_s": round(floor, 4),
    }), flush=True)
    for config in selected:
        elapsed = time_fn(fns[config])
        work, unit = _work(config)
        print(json.dumps({
            "config": config,
            "platform": platform,
            "elapsed_s": round(elapsed, 4),
            "rate": round(work / elapsed / 1e9, 3),
            "unit": unit,
            "rate_floor_subtracted": round(
                work / max(elapsed - floor, 1e-9) / 1e9, 3
            ),
        }), flush=True)


if __name__ == "__main__":
    if "--cpu" in sys.argv and os.environ.get("_RAW_BOUND_CHILD") != "1":
        sys.path.insert(0, REPO)
        from __graft_entry__ import _scrubbed_cpu_env

        env = _scrubbed_cpu_env(1)
        env["_RAW_BOUND_CHILD"] = "1"
        out = subprocess.run(
            # forward the full argv (e.g. --configs) to the scrubbed child
            [sys.executable, os.path.abspath(__file__), *sys.argv[1:]],
            env=env,
        )
        sys.exit(out.returncode)
    main()
