"""Array API surface tests against numpy reference semantics.

Reference parity: cubed/tests/test_array_api.py (600 LoC, behavioral).
"""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp


@pytest.fixture
def nums(spec):
    an = np.arange(24.0).reshape(4, 6) + 1.0
    return an, ct.from_array(an, chunks=(2, 3), spec=spec)


def assert_eq(actual, expect, **kw):
    np.testing.assert_allclose(np.asarray(actual), expect, **kw)


# -- creation ----------------------------------------------------------------


def test_arange(spec):
    assert_eq(xp.arange(20, chunks=6, spec=spec).compute(), np.arange(20))
    assert_eq(
        xp.arange(3, 21, 2, chunks=5, spec=spec).compute(), np.arange(3, 21, 2)
    )


def test_linspace(spec):
    assert_eq(
        xp.linspace(0.0, 1.0, 13, chunks=5, spec=spec).compute(),
        np.linspace(0.0, 1.0, 13),
    )


def test_asarray_roundtrip(spec):
    an = np.arange(12).reshape(3, 4)
    assert_eq(xp.asarray(an, chunks=2, spec=spec).compute(), an)


def test_eye(spec):
    assert_eq(xp.eye(7, 5, k=1, chunks=3, spec=spec).compute(), np.eye(7, 5, k=1))
    assert_eq(xp.eye(6, chunks=2, spec=spec).compute(), np.eye(6))


def test_ones_zeros_full(spec):
    assert_eq(xp.ones((3, 4), chunks=2, spec=spec).compute(), np.ones((3, 4)))
    assert_eq(xp.zeros((3, 4), chunks=2, spec=spec).compute(), np.zeros((3, 4)))
    assert_eq(xp.full((3, 4), 7, chunks=2, spec=spec).compute(), np.full((3, 4), 7))


def test_tril_triu(spec):
    an = np.arange(25.0).reshape(5, 5)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    assert_eq(xp.tril(a).compute(), np.tril(an))
    assert_eq(xp.triu(a, k=1).compute(), np.triu(an, k=1))


def test_meshgrid(spec):
    xn = np.arange(4.0)
    yn = np.arange(3.0)
    x = ct.from_array(xn, chunks=2, spec=spec)
    y = ct.from_array(yn, chunks=2, spec=spec)
    gx, gy = xp.meshgrid(x, y)
    exp_x, exp_y = np.meshgrid(xn, yn)
    assert_eq(gx.compute(), exp_x)
    assert_eq(gy.compute(), exp_y)


# -- elementwise / operators -------------------------------------------------


def test_operators(nums):
    an, a = nums
    assert_eq((a + a).compute(), an + an)
    assert_eq((a - 2.0).compute(), an - 2.0)
    assert_eq((3.0 * a).compute(), 3.0 * an)
    assert_eq((a / a).compute(), an / an)
    assert_eq((a // 2.0).compute(), an // 2.0)
    assert_eq((a % 3.0).compute(), an % 3.0)
    assert_eq((a ** 2.0).compute(), an ** 2.0)
    assert_eq((-a).compute(), -an)
    assert_eq(abs(-a).compute(), an)


def test_comparison_ops(nums):
    an, a = nums
    assert_eq((a > 5.0).compute(), an > 5.0)
    assert_eq((a <= 5.0).compute(), an <= 5.0)
    assert_eq((a == 4.0).compute(), an == 4.0)
    assert_eq((a != 4.0).compute(), an != 4.0)


def test_bitwise_ops(spec):
    an = np.arange(16, dtype=np.int64).reshape(4, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    assert_eq((a & 3).compute(), an & 3)
    assert_eq((a | 3).compute(), an | 3)
    assert_eq((a ^ 3).compute(), an ^ 3)
    assert_eq((~a).compute(), ~an)
    assert_eq((a << 2).compute(), an << 2)
    assert_eq((a >> 1).compute(), an >> 1)


def test_elementwise_functions(nums):
    an, a = nums
    assert_eq(xp.sqrt(a).compute(), np.sqrt(an))
    assert_eq(xp.exp(a).compute(), np.exp(an), rtol=1e-12)
    assert_eq(xp.log(a).compute(), np.log(an))
    assert_eq(xp.sin(a).compute(), np.sin(an))
    assert_eq(xp.square(a).compute(), np.square(an))
    assert_eq(xp.sign(a).compute(), np.sign(an))
    assert_eq(xp.floor(a / 2).compute(), np.floor(an / 2))
    assert_eq(xp.ceil(a / 2).compute(), np.ceil(an / 2))
    assert_eq(xp.round(a / 3).compute(), np.round(an / 3))
    assert_eq(xp.logaddexp(a, a).compute(), np.logaddexp(an, an))


def test_isnan_isinf(spec):
    an = np.array([[1.0, np.nan], [np.inf, -np.inf]])
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    assert_eq(xp.isnan(a).compute(), np.isnan(an))
    assert_eq(xp.isinf(a).compute(), np.isinf(an))
    assert_eq(xp.isfinite(a).compute(), np.isfinite(an))


def test_where(nums):
    an, a = nums
    r = xp.where(a > 10.0, a, 0.0 * a)
    assert_eq(r.compute(), np.where(an > 10.0, an, 0.0))


def test_scalar_promotion_errors(nums):
    an, a = nums
    with pytest.raises(TypeError):
        a + True  # bool scalar with float array
    b = xp.asarray([True, False], spec=a.spec)
    with pytest.raises(TypeError):
        b + 1  # int scalar with bool array


# -- statistical -------------------------------------------------------------


def test_reductions(nums):
    an, a = nums
    assert_eq(xp.sum(a).compute(), an.sum())
    assert_eq(xp.prod(a / 4.0).compute(), (an / 4.0).prod(), rtol=1e-10)
    assert_eq(xp.max(a, axis=0).compute(), an.max(axis=0))
    assert_eq(xp.min(a, axis=1).compute(), an.min(axis=1))
    assert_eq(xp.mean(a, axis=1).compute(), an.mean(axis=1))


def test_sum_dtype_upcast(spec):
    an = np.arange(6, dtype=np.int32)
    a = ct.from_array(an, chunks=2, spec=spec)
    s = xp.sum(a)
    assert s.dtype == np.dtype(np.int64)
    assert int(s.compute()) == an.sum()


def test_var_std(nums):
    an, a = nums
    assert_eq(xp.var(a).compute(), an.var(), rtol=1e-12)
    assert_eq(xp.std(a, axis=0).compute(), an.std(axis=0), rtol=1e-12)
    assert_eq(
        xp.var(a, correction=1).compute(), an.var(ddof=1), rtol=1e-12
    )


def test_argmax_argmin(spec):
    an = np.random.default_rng(42).random((8, 10))
    a = ct.from_array(an, chunks=(3, 4), spec=spec)
    assert_eq(xp.argmax(a, axis=1).compute(), an.argmax(axis=1))
    assert_eq(xp.argmin(a, axis=0).compute(), an.argmin(axis=0))
    assert int(xp.argmax(a).compute()) == an.argmax()


def test_mean_var_intermediates_are_multioutput_plain_arrays(spec):
    """mean/var pytree intermediates ride as N plain arrays from multi-output
    ops — no structured-dtype array anywhere in the plan (mesh-shardable)."""
    an = np.random.default_rng(1).random((16, 12))
    a = ct.from_array(an, chunks=(4, 3), spec=spec)
    for expr in (xp.mean(a, axis=0), xp.var(a)):
        dag = expr.plan.dag
        for n, d in dag.nodes(data=True):
            if d.get("type") == "array" and d.get("target") is not None:
                dt = np.dtype(d["target"].dtype)
                assert dt.fields is None, f"structured array node {n}: {dt}"
        multi_ops = [
            n for n, d in dag.nodes(data=True)
            if d.get("type") == "op"
            and d.get("primitive_op") is not None
            and d["primitive_op"].target_arrays is not None
        ]
        assert multi_ops, "expected multi-output ops in the reduction tree"
    assert_eq(xp.mean(a, axis=0).compute(), an.mean(axis=0))


def test_arg_reduction_traces(spec):
    """arg_reduction's initial op reads the block index from the traced
    offset (no host_block_id), so the whole tree joins fused segments."""
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(3).random((9, 14))
    a = ct.from_array(an, chunks=(3, 5), spec=spec)
    expr = xp.argmax(a, axis=1)
    dag = expr.plan.dag
    for n, d in dag.nodes(data=True):
        if d.get("type") == "op" and d.get("primitive_op") is not None:
            f = d["primitive_op"].pipeline.config.function if hasattr(
                d["primitive_op"].pipeline.config, "function"
            ) else None
            assert not getattr(f, "host_block_id", False), n
    ex = JaxExecutor()
    assert_eq(expr.compute(executor=ex), an.argmax(axis=1))
    assert ex.stats.get("segments_traced", 0) >= 1


def test_all_any(spec):
    an = np.array([[True, False], [True, True]])
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    assert bool(xp.all(a).compute()) == an.all()
    assert bool(xp.any(a).compute()) == an.any()
    assert_eq(xp.all(a, axis=0).compute(), an.all(axis=0))


# -- linalg ------------------------------------------------------------------


def test_matmul_1d(spec):
    an = np.arange(6.0)
    bn = np.arange(6.0) + 1
    a = ct.from_array(an, chunks=3, spec=spec)
    b = ct.from_array(bn, chunks=3, spec=spec)
    assert_eq(xp.matmul(a, b).compute(), an @ bn)


def test_matmul_batched(spec):
    rng = np.random.default_rng(0)
    an = rng.random((2, 4, 6))
    bn = rng.random((2, 6, 5))
    a = ct.from_array(an, chunks=(1, 2, 3), spec=spec)
    b = ct.from_array(bn, chunks=(1, 3, 5), spec=spec)
    assert_eq(xp.matmul(a, b).compute(), an @ bn, rtol=1e-12)


def test_tensordot_axes2(spec):
    rng = np.random.default_rng(0)
    an = rng.random((4, 5, 6))
    bn = rng.random((5, 6, 3))
    a = ct.from_array(an, chunks=(2, 5, 3), spec=spec)
    b = ct.from_array(bn, chunks=(5, 3, 3), spec=spec)
    assert_eq(
        xp.tensordot(a, b, axes=2).compute(), np.tensordot(an, bn, axes=2), rtol=1e-12
    )


def test_outer_vecdot(spec):
    an = np.arange(4.0)
    bn = np.arange(5.0)
    a = ct.from_array(an, chunks=2, spec=spec)
    b = ct.from_array(bn, chunks=2, spec=spec)
    assert_eq(xp.outer(a, b).compute(), np.outer(an, bn))
    c = ct.from_array(bn, chunks=2, spec=spec)
    assert_eq(xp.vecdot(b, c).compute(), np.dot(bn, bn))


def test_matrix_transpose(nums):
    an, a = nums
    assert_eq(a.T.compute(), an.T)
    assert_eq(xp.matrix_transpose(a).compute(), an.T)


# -- manipulation ------------------------------------------------------------


def test_broadcast_to(spec):
    an = np.arange(6.0)
    a = ct.from_array(an, chunks=2, spec=spec)
    assert_eq(
        xp.broadcast_to(a, (4, 6)).compute(), np.broadcast_to(an, (4, 6))
    )


def test_concat(spec):
    an = np.arange(12.0).reshape(3, 4)
    bn = np.arange(8.0).reshape(2, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(bn, chunks=(2, 2), spec=spec)
    assert_eq(xp.concat([a, b], axis=0).compute(), np.concatenate([an, bn], axis=0))
    c = ct.from_array(an, chunks=(2, 2), spec=spec)
    assert_eq(xp.concat([a, c], axis=1).compute(), np.concatenate([an, an], axis=1))


def test_stack_expand_squeeze(spec):
    an = np.arange(12.0).reshape(3, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(an, chunks=(2, 2), spec=spec)
    s = xp.stack([a, b], axis=0)
    assert_eq(s.compute(), np.stack([an, an], axis=0))
    e = xp.expand_dims(a, axis=1)
    assert_eq(e.compute(), np.expand_dims(an, 1))
    assert_eq(xp.squeeze(e, axis=1).compute(), an)


def test_reshape_flatten(spec):
    an = np.arange(24.0).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    assert_eq(xp.reshape(a, (6, 4)).compute(), an.reshape(6, 4))
    assert_eq(xp.reshape(a, (-1,)).compute(), an.ravel())
    assert_eq(xp.flatten(a).compute(), an.ravel())


def test_permute_moveaxis(spec):
    an = np.arange(24.0).reshape(2, 3, 4)
    a = ct.from_array(an, chunks=(1, 2, 2), spec=spec)
    assert_eq(xp.permute_dims(a, (2, 0, 1)).compute(), an.transpose(2, 0, 1))
    assert_eq(xp.moveaxis(a, 0, -1).compute(), np.moveaxis(an, 0, -1))


def test_flip(spec):
    an = np.arange(24.0).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    assert_eq(xp.flip(a).compute(), np.flip(an))
    assert_eq(xp.flip(a, axis=0).compute(), np.flip(an, axis=0))


def test_roll(spec):
    an = np.arange(24.0).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    assert_eq(xp.roll(a, 2, axis=1).compute(), np.roll(an, 2, axis=1))
    assert_eq(xp.roll(a, -1, axis=0).compute(), np.roll(an, -1, axis=0))
    assert_eq(xp.roll(a, 5).compute(), np.roll(an, 5))


def test_repeat(spec):
    an = np.arange(6.0).reshape(2, 3)
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    assert_eq(xp.repeat(a, 3, axis=1).compute(), np.repeat(an, 3, axis=1))


def test_broadcast_arrays(spec):
    an = np.arange(3.0)
    bn = np.arange(4.0).reshape(4, 1)
    a = ct.from_array(an, chunks=2, spec=spec)
    b = ct.from_array(bn, chunks=(2, 1), spec=spec)
    ra, rb = xp.broadcast_arrays(a, b)
    ea, eb = np.broadcast_arrays(an, bn)
    assert_eq(ra.compute(), ea)
    assert_eq(rb.compute(), eb)


# -- indexing ----------------------------------------------------------------


def test_take(spec):
    an = np.arange(24.0).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    assert_eq(xp.take(a, [0, 2, 3], axis=1).compute(), np.take(an, [0, 2, 3], axis=1))


def test_newaxis(spec):
    an = np.arange(6.0)
    a = ct.from_array(an, chunks=2, spec=spec)
    assert_eq(a[xp.newaxis, :].compute(), an[np.newaxis, :])


# -- dtype functions ---------------------------------------------------------


def test_astype(nums):
    an, a = nums
    assert_eq(xp.astype(a, np.int32).compute(), an.astype(np.int32))


def test_result_type_and_can_cast():
    assert xp.result_type(xp.int32, xp.int64) == np.dtype(np.int64)
    assert xp.result_type(xp.float32, xp.float64) == np.dtype(np.float64)
    assert xp.result_type(xp.int8, xp.uint8) == np.dtype(np.int16)
    assert xp.can_cast(xp.int32, xp.int64)
    assert not xp.can_cast(xp.int64, xp.int32)
    with pytest.raises(TypeError):
        xp.result_type(xp.int32, xp.bool)


def test_finfo_iinfo():
    assert xp.finfo(xp.float64).bits == 64
    assert xp.iinfo(xp.int32).max == 2**31 - 1
    assert xp.isdtype(xp.float32, "real floating")
    assert not xp.isdtype(xp.int32, "real floating")


# -- 0-d / scalar conversion -------------------------------------------------


def test_scalar_conversions(spec):
    s = xp.sum(xp.ones((3,), chunks=2, spec=spec))
    assert float(s) == 3.0
    i = xp.sum(xp.asarray([1, 2, 3], spec=spec))
    assert int(i) == 6


# -- cumulative_sum / cumulative_prod (2023.12; beyond-reference) ----------


@pytest.mark.parametrize("axis", [0, 1, -1])
@pytest.mark.parametrize("chunks", [(2, 3), (4, 4), (3, 7)])
def test_cumulative_sum_matches_numpy(spec, axis, chunks):
    an = np.arange(28.0).reshape(4, 7)
    a = ct.from_array(an, chunks=chunks, spec=spec)
    got = xp.cumulative_sum(a, axis=axis).compute()
    np.testing.assert_allclose(got, np.cumsum(an, axis=axis))


def test_cumulative_sum_1d_default_axis(spec):
    an = np.arange(11.0)
    a = ct.from_array(an, chunks=(4,), spec=spec)
    np.testing.assert_allclose(xp.cumulative_sum(a).compute(), np.cumsum(an))


def test_cumulative_sum_multidim_requires_axis(spec):
    a = ct.from_array(np.ones((4, 4)), chunks=(2, 2), spec=spec)
    with pytest.raises(ValueError):
        xp.cumulative_sum(a)


def test_cumulative_sum_int_upcast(spec):
    an = np.arange(10, dtype=np.int32)
    a = ct.from_array(an, chunks=(3,), spec=spec)
    r = xp.cumulative_sum(a)
    assert r.dtype == np.int64
    np.testing.assert_array_equal(r.compute(), np.cumsum(an, dtype=np.int64))


def test_cumulative_sum_include_initial(spec):
    an = np.arange(12.0).reshape(3, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    got = xp.cumulative_sum(a, axis=1, include_initial=True).compute()
    expect = np.concatenate(
        [np.zeros((3, 1)), np.cumsum(an, axis=1)], axis=1
    )
    np.testing.assert_allclose(got, expect)


def test_cumulative_prod_matches_numpy(spec):
    rng = np.random.default_rng(0)
    an = rng.uniform(0.5, 1.5, (5, 6))
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    got = xp.cumulative_prod(a, axis=0).compute()
    np.testing.assert_allclose(got, np.cumprod(an, axis=0))


def test_cumulative_prod_with_zeros(spec):
    an = np.array([2.0, 0.0, 3.0, 4.0, 5.0, 6.0])
    a = ct.from_array(an, chunks=(2,), spec=spec)
    np.testing.assert_allclose(
        xp.cumulative_prod(a).compute(), np.cumprod(an)
    )


def test_cumulative_prod_include_initial(spec):
    an = np.arange(1.0, 7.0)
    a = ct.from_array(an, chunks=(2,), spec=spec)
    got = xp.cumulative_prod(a, include_initial=True).compute()
    np.testing.assert_allclose(
        got, np.concatenate([[1.0], np.cumprod(an)])
    )


def test_cumulative_sum_single_block_axis(spec):
    an = np.arange(12.0).reshape(3, 4)
    a = ct.from_array(an, chunks=(3, 2), spec=spec)  # one block on axis 0
    np.testing.assert_allclose(
        xp.cumulative_sum(a, axis=0).compute(), np.cumsum(an, axis=0)
    )


def test_cumulative_sum_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.arange(60.0).reshape(6, 10)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    got = xp.cumulative_sum(a, axis=1).compute(executor=JaxExecutor())
    np.testing.assert_allclose(got, np.cumsum(an, axis=1))


# -- searchsorted (2023.12; beyond-reference) ------------------------------


@pytest.mark.parametrize("side", ["left", "right"])
def test_searchsorted_matches_numpy(spec, side):
    x1n = np.sort(np.random.default_rng(0).integers(0, 50, 23).astype(np.float64))
    x2n = np.random.default_rng(1).integers(-5, 55, (4, 9)).astype(np.float64)
    x1 = ct.from_array(x1n, chunks=(5,), spec=spec)
    x2 = ct.from_array(x2n, chunks=(2, 4), spec=spec)
    got = xp.searchsorted(x1, x2, side=side).compute()
    np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n, side=side))


def test_searchsorted_with_sorter(spec):
    rng = np.random.default_rng(2)
    x1n = rng.permutation(np.arange(17.0))
    sorter_n = np.argsort(x1n)
    x2n = rng.uniform(-1, 18, 11)
    x1 = ct.from_array(x1n, chunks=(6,), spec=spec)
    x2 = ct.from_array(x2n, chunks=(4,), spec=spec)
    sorter = ct.from_array(sorter_n, chunks=(17,), spec=spec)
    got = xp.searchsorted(x1, x2, sorter=sorter).compute()
    np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n, sorter=sorter_n))


def test_searchsorted_validation(spec):
    a = ct.from_array(np.ones((3, 3)), chunks=(2, 2), spec=spec)
    v = ct.from_array(np.arange(3.0), chunks=(3,), spec=spec)
    with pytest.raises(ValueError):
        xp.searchsorted(a, v)  # x1 must be 1-d
    with pytest.raises(ValueError):
        xp.searchsorted(v, v, side="middle")


def test_searchsorted_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    x1n = np.arange(0.0, 40.0, 2.0)
    x2n = np.linspace(-3, 45, 24).reshape(6, 4)
    x1 = ct.from_array(x1n, chunks=(7,), spec=spec)
    x2 = ct.from_array(x2n, chunks=(3, 2), spec=spec)
    got = xp.searchsorted(x1, x2).compute(executor=JaxExecutor())
    np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n))


def test_searchsorted_float_sorter_rejected(spec):
    v = ct.from_array(np.arange(3.0), chunks=(3,), spec=spec)
    s = ct.from_array(np.array([0.0, 1.0, 2.0]), chunks=(3,), spec=spec)
    with pytest.raises(TypeError, match="integer"):
        xp.searchsorted(v, v, sorter=s)


def test_searchsorted_wrong_length_sorter_rejected(spec):
    v = ct.from_array(np.arange(3.0), chunks=(3,), spec=spec)
    s = ct.from_array(np.array([0, 1]), chunks=(2,), spec=spec)
    with pytest.raises(ValueError, match="sorter.shape"):
        xp.searchsorted(v, v, sorter=s)


# -- 2023.12 elementwise additions (beyond-reference) ----------------------


def test_maximum_minimum(spec):
    an = np.array([[1.0, -5.0], [3.0, 8.0]])
    bn = np.array([[2.0, -7.0], [3.0, 4.0]])
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    b = ct.from_array(bn, chunks=(1, 2), spec=spec)
    np.testing.assert_array_equal(xp.maximum(a, b).compute(), np.maximum(an, bn))
    np.testing.assert_array_equal(xp.minimum(a, b).compute(), np.minimum(an, bn))
    # scalar promotion
    np.testing.assert_array_equal(xp.maximum(a, 2.5).compute(), np.maximum(an, 2.5))


def test_hypot_copysign_signbit(spec):
    an = np.array([3.0, -3.0, 0.0, -0.0])
    bn = np.array([4.0, -4.0, 1.0, -1.0])
    a = ct.from_array(an, chunks=(2,), spec=spec)
    b = ct.from_array(bn, chunks=(2,), spec=spec)
    np.testing.assert_allclose(xp.hypot(a, b).compute(), np.hypot(an, bn))
    np.testing.assert_array_equal(xp.copysign(a, b).compute(), np.copysign(an, bn))
    sb = xp.signbit(a)
    assert sb.dtype == np.bool_
    np.testing.assert_array_equal(sb.compute(), np.signbit(an))


@pytest.mark.parametrize(
    "lo,hi",
    [(2.0, 7.0), (None, 5.0), (3.0, None), (None, None)],
)
def test_clip_scalars(spec, lo, hi):
    an = np.arange(10.0)
    a = ct.from_array(an, chunks=(4,), spec=spec)
    got = xp.clip(a, min=lo, max=hi).compute()
    # spec: both bounds None -> x unchanged (np.clip rejects that case)
    expect = an if lo is None and hi is None else np.clip(an, lo, hi)
    np.testing.assert_array_equal(got, expect)
    assert got.dtype == an.dtype


def test_clip_array_bounds(spec):
    an = np.arange(12.0).reshape(3, 4)
    lon = np.full((3, 4), 2.0)
    hin = np.full((3, 4), 8.0)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    lo = ct.from_array(lon, chunks=(2, 2), spec=spec)
    hi = ct.from_array(hin, chunks=(2, 2), spec=spec)
    np.testing.assert_array_equal(
        xp.clip(a, min=lo, max=hi).compute(), np.clip(an, lon, hin)
    )


def test_clip_int_dtype_preserved(spec):
    an = np.arange(10, dtype=np.int32)
    a = ct.from_array(an, chunks=(4,), spec=spec)
    got = xp.clip(a, min=2, max=7).compute()
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, np.clip(an, 2, 7))


def test_clip_rejects_out_of_range_bounds_on_int(spec):
    an = np.arange(10, dtype=np.int32)
    a = ct.from_array(an, chunks=(4,), spec=spec)
    # integer-valued but unrepresentable in int32: would wrap in the kernel
    for bad in (1e30, 2**40, -(2**40), np.float64(2**31)):
        with pytest.raises(TypeError, match="not representable"):
            xp.clip(a, min=bad)
        with pytest.raises(TypeError, match="not representable"):
            xp.clip(a, max=bad)
    # boundary values are fine
    info = np.iinfo(np.int32)
    got = xp.clip(a, min=float(info.min), max=float(info.max)).compute()
    np.testing.assert_array_equal(got, an)


def test_clip_rejects_raw_ndarray_bounds(spec):
    a = ct.from_array(np.arange(4.0), chunks=(2,), spec=spec)
    with pytest.raises(TypeError, match="cubed arrays"):
        xp.clip(a, min=np.array([1.0, 2.0, 3.0, 4.0]))


def test_clip_both_none_is_same_plan(spec):
    a = ct.from_array(np.arange(4.0), chunks=(2,), spec=spec)
    assert xp.clip(a) is a  # no kernel scheduled


# -- 2023.12/2024.12 additions: unstack, tile, count_nonzero, diff,
#    nextafter, reciprocal (the reference stops at 2022.12) ------------------


def test_unstack(spec):
    an = np.random.default_rng(0).random((3, 4, 5))
    a = ct.from_array(an, chunks=(2, 2, 3), spec=spec)
    for axis in (0, 1, -1):
        parts = xp.unstack(a, axis=axis)
        expect = tuple(np.moveaxis(an, axis, 0))
        assert len(parts) == an.shape[axis]
        for p, e in zip(parts, expect):
            np.testing.assert_array_equal(np.asarray(p.compute()), e)


def test_tile(spec):
    an = np.arange(12.0).reshape(3, 4)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    for reps in ((2, 3), (2,), (1, 2, 2), (1, 1), (0, 2)):
        got = np.asarray(xp.tile(a, reps).compute())
        np.testing.assert_array_equal(got, np.tile(an, reps))


def test_count_nonzero(spec):
    an = np.random.default_rng(1).integers(-1, 2, (6, 8))
    a = ct.from_array(an, chunks=(3, 4), spec=spec)
    assert int(xp.count_nonzero(a).compute()) == np.count_nonzero(an)
    np.testing.assert_array_equal(
        np.asarray(xp.count_nonzero(a, axis=0).compute()),
        np.count_nonzero(an, axis=0),
    )
    got = xp.count_nonzero(a, axis=1, keepdims=True)
    assert got.dtype == np.dtype(np.int64)
    np.testing.assert_array_equal(
        np.asarray(got.compute()), np.count_nonzero(an, axis=1, keepdims=True)
    )


def test_diff(spec):
    an = np.random.default_rng(2).random((5, 12))
    a = ct.from_array(an, chunks=(2, 5), spec=spec)
    for kwargs in (
        {},
        {"axis": 0},
        {"n": 2},
        {"n": 0},
        {"n": 3, "axis": 1},
    ):
        got = np.asarray(xp.diff(a, **kwargs).compute())
        np.testing.assert_allclose(got, np.diff(an, **kwargs), rtol=1e-12)
    pre = ct.from_array(np.zeros((5, 1)), chunks=(2, 1), spec=spec)
    app = ct.from_array(np.ones((5, 2)), chunks=(2, 2), spec=spec)
    got = np.asarray(xp.diff(a, prepend=pre, append=app).compute())
    np.testing.assert_allclose(
        got, np.diff(an, prepend=np.zeros((5, 1)), append=np.ones((5, 2))),
        rtol=1e-12,
    )


def test_nextafter_reciprocal(spec):
    an = np.asarray([1.0, -2.5, 0.125, 3e300])
    bn = np.asarray([2.0, -3.0, 0.0, -1.0])
    a = ct.from_array(an, chunks=(2,), spec=spec)
    b = ct.from_array(bn, chunks=(2,), spec=spec)
    np.testing.assert_array_equal(
        np.asarray(xp.nextafter(a, b).compute()), np.nextafter(an, bn)
    )
    np.testing.assert_allclose(
        np.asarray(xp.reciprocal(a).compute()), np.reciprocal(an), rtol=1e-15
    )
    i = ct.from_array(np.arange(4), chunks=(2,), spec=spec)
    with pytest.raises(TypeError):
        xp.reciprocal(i)
    with pytest.raises(TypeError):
        xp.nextafter(i, i)
