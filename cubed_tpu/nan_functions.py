"""NaN-aware reductions (non-standard extensions).

Reference parity: cubed/nan_functions.py:21-79. ``nanmean`` uses a {n, total}
pytree intermediate counting only non-NaN elements.
"""

from __future__ import annotations

import functools

import numpy as np

from .backend_array_api import nxp
from .core.ops import reduction
from .array_api.dtypes import (
    _numeric_dtypes,
    _real_numeric_dtypes,
    _signed_integer_dtypes,
    _unsigned_integer_dtypes,
    complex64,
    float32,
    int64,
    uint64,
)


def _count_not_nan(a, axis=None, keepdims=True):
    return nxp.sum(
        nxp.astype(nxp.logical_not(nxp.isnan(a)), np.int64),
        axis=axis, keepdims=keepdims,
    )


def nanmean(x, /, *, axis=None, keepdims=False, split_every=None):
    """Mean ignoring NaNs."""
    dtype = x.dtype
    intermediate_dtype = np.dtype([("n", np.int64), ("total", np.float64)])
    return reduction(
        x,
        _nanmean_func,
        combine_func=_nanmean_combine,
        aggregate_func=_nanmean_aggregate,
        axis=axis,
        intermediate_dtype=intermediate_dtype,
        dtype=dtype,
        keepdims=keepdims,
        split_every=split_every,
    )


def _nanmean_func(a, axis=None, keepdims=True, **kw):
    n = _count_not_nan(a, axis=axis, keepdims=keepdims)
    total = _nansum_arr(a, axis=axis, keepdims=keepdims, dtype=np.float64)
    return {"n": n, "total": total}


def _nanmean_combine(a, axis=None, keepdims=True, **kw):
    n = nxp.sum(a["n"], axis=axis, keepdims=keepdims)
    total = nxp.sum(a["total"], axis=axis, keepdims=keepdims)
    return {"n": n, "total": total}


def _nanmean_aggregate(a):
    # avoid divide-by-zero: all-NaN regions produce NaN like numpy.nanmean
    n = nxp.asarray(a["n"], dtype=np.float64)
    return nxp.where(n > 0, nxp.divide(a["total"], nxp.where(n > 0, n, 1)), np.nan)


def nansum(x, /, *, axis=None, dtype=None, keepdims=False, split_every=None):
    """Sum ignoring NaNs."""
    if x.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in nansum")
    if dtype is None:
        if x.dtype in _signed_integer_dtypes:
            dtype = int64
        elif x.dtype in _unsigned_integer_dtypes:
            dtype = uint64
        elif x.dtype == float32:
            dtype = float32
        elif x.dtype == complex64:
            dtype = complex64
        else:
            dtype = x.dtype
    dtype = np.dtype(dtype)
    return reduction(
        x,
        _nansum_arr,
        combine_func=_sum_arr,
        axis=axis,
        intermediate_dtype=dtype,
        dtype=dtype,
        keepdims=keepdims,
        split_every=split_every,
        extra_func_kwargs=dict(dtype=dtype),
    )


def _nansum_arr(a, axis=None, keepdims=True, dtype=None, **kw):
    if np.dtype(a.dtype).kind in "fc":
        a = nxp.where(nxp.isnan(a), nxp.asarray(0, dtype=a.dtype), a)
    return nxp.sum(a, axis=axis, keepdims=keepdims, dtype=dtype)


def _sum_arr(a, axis=None, keepdims=True, dtype=None, **kw):
    return nxp.sum(a, axis=axis, keepdims=keepdims, dtype=dtype)


# -- nanmax / nanmin (beyond the reference's nanmean/nansum pair) ----------
#
# {m, n} pytree intermediates: m is the extremum over NaN-masked values, n
# counts non-NaN contributors, and the aggregate restores numpy semantics
# (all-NaN region -> NaN) without numpy's all-NaN-slice RuntimeWarning.


def nanmax(x, /, *, axis=None, keepdims=False, split_every=None):
    """Maximum ignoring NaNs (all-NaN regions yield NaN, warning-free)."""
    return _nan_extremum(x, axis, keepdims, split_every, op="max")


def nanmin(x, /, *, axis=None, keepdims=False, split_every=None):
    """Minimum ignoring NaNs (all-NaN regions yield NaN, warning-free)."""
    return _nan_extremum(x, axis, keepdims, split_every, op="min")


def _nan_extremum(x, axis, keepdims, split_every, *, op):
    if x.dtype not in _real_numeric_dtypes:
        raise TypeError(f"Only real numeric dtypes are allowed in nan{op}")
    reduced = (
        tuple(range(x.ndim)) if axis is None
        else (axis,) if isinstance(axis, int) else tuple(axis)
    )
    if any(x.shape[ax % x.ndim] == 0 for ax in reduced):
        raise ValueError(f"zero-size array to reduction operation nan{op}")
    if np.dtype(x.dtype).kind in "iub":
        # integers hold no NaN: a plain exact extremum (routing through the
        # float64 {m,n} machinery would corrupt int64 values above 2^53)
        from .array_api.statistical_functions import max as _xmax, min as _xmin

        f = _xmax if op == "max" else _xmin
        return f(x, axis=axis, keepdims=keepdims, split_every=split_every)

    intermediate_dtype = np.dtype([("m", np.float64), ("n", np.int64)])
    return reduction(
        x,
        functools.partial(_nan_extremum_func, op=op),
        combine_func=functools.partial(_nan_extremum_combine, op=op),
        aggregate_func=_nan_extremum_aggregate,
        axis=axis,
        intermediate_dtype=intermediate_dtype,
        dtype=x.dtype,
        keepdims=keepdims,
        split_every=split_every,
    )


def _nan_extremum_func(a, axis=None, keepdims=True, op="max", **kw):
    fill = -np.inf if op == "max" else np.inf
    masked = nxp.where(nxp.isnan(a), nxp.asarray(fill, dtype=a.dtype), a)
    n = _count_not_nan(a, axis=axis, keepdims=keepdims)
    reducer = nxp.max if op == "max" else nxp.min
    m = reducer(
        nxp.astype(masked, np.float64), axis=axis, keepdims=keepdims
    )
    return {"m": m, "n": n}


def _nan_extremum_combine(a, axis=None, keepdims=True, op="max", **kw):
    reducer = nxp.max if op == "max" else nxp.min
    return {
        "m": reducer(a["m"], axis=axis, keepdims=keepdims),
        "n": nxp.sum(a["n"], axis=axis, keepdims=keepdims),
    }


def _nan_extremum_aggregate(a):
    return nxp.where(a["n"] > 0, a["m"], np.nan)
