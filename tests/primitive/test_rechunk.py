"""Rechunk primitive unit tests. Reference parity:
cubed/tests/primitive/test_rechunk.py."""

import numpy as np
import pytest

from cubed_tpu.primitive.rechunk import rechunk, rechunking_plan
from cubed_tpu.storage.store import open_zarr_array

from ..utils import execute_pipeline


def make_zarr(tmp_path, name, arr, chunks):
    store = str(tmp_path / name)
    z = open_zarr_array(store, mode="w", shape=arr.shape, dtype=arr.dtype, chunks=chunks)
    z[...] = arr
    return z


def test_rechunk_direct(tmp_path):
    an = np.arange(100.0).reshape(10, 10)
    src = make_zarr(tmp_path, "src.zarr", an, (2, 10))
    ops = rechunk(
        src,
        source_chunks=(2, 10),
        target_chunks=(10, 2),
        allowed_mem=10**7,
        reserved_mem=0,
        target_store=str(tmp_path / "dst.zarr"),
        temp_store=str(tmp_path / "tmp.zarr"),
    )
    assert len(ops) == 1
    execute_pipeline(ops[0])
    out = ops[0].target_array.open()
    np.testing.assert_array_equal(out[...], an)
    assert out.chunks == (10, 2)


def test_rechunk_staged(tmp_path):
    an = np.arange(900.0).reshape(30, 30)
    src = make_zarr(tmp_path, "src.zarr", an, (30, 2))
    # tight budget: covering region of a (2,30) write chunk is the whole array
    ops = rechunk(
        src,
        source_chunks=(30, 2),
        target_chunks=(2, 30),
        allowed_mem=20000,
        reserved_mem=0,
        target_store=str(tmp_path / "dst.zarr"),
        temp_store=str(tmp_path / "tmp.zarr"),
    )
    assert len(ops) == 2
    execute_pipeline(ops[0])
    execute_pipeline(ops[1])
    out = ops[1].target_array.open()
    np.testing.assert_array_equal(out[...], an)
    assert out.chunks == (2, 30)
    # both stages respect the memory budget
    for op in ops:
        assert op.projected_mem <= 20000


def test_rechunk_allowed_mem_exceeded(tmp_path):
    an = np.zeros((100, 100))
    src = make_zarr(tmp_path, "src.zarr", an, (100, 1))
    with pytest.raises(ValueError, match="exceeds allowed_mem"):
        rechunk(
            src,
            source_chunks=(100, 1),
            target_chunks=(1, 100),
            allowed_mem=2000,  # cannot even hold one min-chunk copy
            reserved_mem=0,
            target_store=str(tmp_path / "dst.zarr"),
            temp_store=str(tmp_path / "tmp.zarr"),
        )


def test_rechunking_plan_direct_when_fits():
    read, inter, write = rechunking_plan(
        shape=(100, 100),
        source_chunks=(10, 100),
        target_chunks=(100, 10),
        itemsize=8,
        max_mem=10**7,
    )
    assert inter is None


def test_rechunking_plan_staged_when_tight():
    read, inter, write = rechunking_plan(
        shape=(1000, 1000),
        source_chunks=(1000, 1),
        target_chunks=(1, 1000),
        itemsize=8,
        max_mem=100_000,
    )
    assert inter == (1, 1)


def test_rechunk_ragged(tmp_path):
    an = np.arange(35.0).reshape(7, 5)
    src = make_zarr(tmp_path, "src.zarr", an, (3, 2))
    ops = rechunk(
        src,
        source_chunks=(3, 2),
        target_chunks=(2, 4),
        allowed_mem=10**6,
        reserved_mem=0,
        target_store=str(tmp_path / "dst.zarr"),
        temp_store=str(tmp_path / "tmp.zarr"),
    )
    for op in ops:
        execute_pipeline(op)
    out = ops[-1].target_array.open()
    np.testing.assert_array_equal(out[...], an)


# ---------------------------------------------------------------------------
# multistage geometric planning (reference: vendored rechunker
# algorithm.py:200-318 — stage search with IO-op counting)
# ---------------------------------------------------------------------------


def test_multistage_plan_beats_min_intermediate_on_transpose():
    from cubed_tpu.primitive.rechunk import (
        _copy_io_ops,
        multistage_rechunking_plan,
    )

    shape = (1000, 1000)
    src, tgt = (1000, 1), (1, 1000)
    max_mem = 200_000  # forces staging; direct copy needs the whole array
    seq = multistage_rechunking_plan(shape, src, tgt, 8, max_mem)
    assert seq is not None and len(seq) > 2, seq
    io_geo = sum(_copy_io_ops(shape, a, b) for a, b in zip(seq, seq[1:]))
    min_seq = [src, (1, 1), tgt]
    io_min = sum(_copy_io_ops(shape, a, b) for a, b in zip(min_seq, min_seq[1:]))
    # the (1,1) intermediate costs ~2M ops; geometric stages orders less
    assert io_geo * 10 < io_min, (io_geo, io_min)
    # every stage is memory-feasible by construction
    for a, b in zip(seq, seq[1:]):
        from cubed_tpu.primitive.rechunk import _covering_bytes
        import math as _math

        assert _covering_bytes(shape, b, a, 8) + _math.prod(b) * 8 <= max_mem


def test_multistage_rechunk_end_to_end(tmp_path):
    # small shape-transpose rechunk executed through the real pipelines
    an = np.arange(64.0 * 64).reshape(64, 64)
    src = make_zarr(tmp_path, "src64.zarr", an, (64, 2))
    ops = rechunk(
        src,
        source_chunks=(64, 2),
        target_chunks=(2, 64),
        allowed_mem=40_000,  # tight: forces a staged plan
        reserved_mem=0,
        target_store=str(tmp_path / "dst64.zarr"),
        temp_store=str(tmp_path / "tmp64.zarr"),
    )
    assert len(ops) >= 2
    for op in ops:
        execute_pipeline(op)
    out = ops[-1].target_array.open()
    np.testing.assert_array_equal(out[...], an)
    assert out.chunks == (2, 64)


def test_multistage_rechunk_via_core_plan(tmp_path):
    """N-op rechunks chain correctly through core.ops.rechunk plan nodes."""
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem=60_000, reserved_mem=0)
    an = np.arange(48.0 * 48).reshape(48, 48)
    a = ct.from_array(an, chunks=(48, 2), spec=spec)
    b = a.rechunk((2, 48))
    np.testing.assert_array_equal(np.asarray(b.compute()), an)
