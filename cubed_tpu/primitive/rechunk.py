"""The rechunk primitive: change an array's chunking without changing its
shape or dtype, under the plan-time memory bound.

Planning reimplements the rechunker algorithm's essence (reference vendors it:
cubed/vendor/rechunker/algorithm.py): copy directly when the source region
covering one write chunk fits in the memory budget; otherwise stage through an
intermediate array chunked at the elementwise minimum of source and target
chunks (which always fits), giving two bounded copy passes. Read/write chunks
are consolidated up to the budget to reduce task counts.

On the TPU executor this storage round-trip is replaced by an in-HBM reshard
(XLA all-to-all over the mesh) whenever the array is resident — see
cubed_tpu/runtime/executors/jax.py. This primitive remains the spill path for
arrays exceeding aggregate HBM.

Reference parity: cubed/primitive/rechunk.py (behavioral; clean-room).
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Optional

import numpy as np

from ..chunks import blockdims_from_blockshape
from ..storage.zarr import LazyZarrArray, lazy_empty
from ..utils import chunk_memory, get_item, itemsize as dtype_itemsize, memory_repr
from .types import (
    CubedArrayProxy,
    CubedCopySpec,
    CubedPipeline,
    PrimitiveOperation,
)
from .blockwise import gensym


def copy_read_to_write(chunk_key, *, config: CubedCopySpec) -> None:
    """Task body: read one region from the source and write it to the target.

    The read runs inside a shuffle exchange scope: on an armed fleet the
    region's source chunks arrive over the peer data plane (sub-chunk byte
    ranges when the region barely touches a chunk — runtime/transfer.py),
    with any miss/peer-death/mismatch falling back to the store read
    inside the storage layer; observability attributes the peer time to
    the ``shuffle`` bucket (span ``shuffle_fetch``)."""
    from ..runtime.shuffle import exchange_scope

    read_arr = config.read.open()
    write_arr = config.write.open()
    sel = chunk_key
    with exchange_scope():
        data = read_arr[sel]
    write_arr[sel] = data


class ChunkKeys:
    """Iterable of slice-tuples over the write-chunk grid (lazily enumerated)."""

    def __init__(self, shape: tuple[int, ...], write_chunks: tuple[int, ...]):
        self.shape = shape
        self.write_chunks = write_chunks

    def __iter__(self):
        chunkset = blockdims_from_blockshape(self.shape, self.write_chunks)
        nb = tuple(len(c) for c in chunkset)
        for idx in itertools.product(*(range(n) for n in nb)):
            yield get_item(chunkset, idx)

    def __len__(self):
        chunkset = blockdims_from_blockshape(self.shape, self.write_chunks)
        return math.prod(len(c) for c in chunkset)


def _covering_bytes(
    shape: tuple[int, ...],
    region_chunks: tuple[int, ...],
    source_chunks: tuple[int, ...],
    itemsize: int,
) -> int:
    """Worst-case bytes of the source-chunk-aligned region covering one
    region_chunks-sized write region."""
    total = itemsize
    for s, r, c in zip(shape, region_chunks, source_chunks):
        covered = min(s, (math.ceil((r - 1) / c) + 1) * c)
        total *= max(1, covered)
    return total


def _consolidate_chunks(
    shape: tuple[int, ...],
    chunks: tuple[int, ...],
    itemsize: int,
    max_mem: int,
    multiple_of: Optional[tuple[int, ...]] = None,
) -> tuple[int, ...]:
    """Grow chunks (last axis first) while staying under max_mem, keeping each
    grown chunk an exact multiple of the original (so region writes stay
    aligned to the original chunk grid)."""
    chunks = list(int(c) for c in chunks)
    for axis in reversed(range(len(chunks))):
        base = chunks[axis]
        while True:
            candidate = list(chunks)
            grown = min(shape[axis], chunks[axis] * 2)
            # keep multiples of the base chunk unless we span the whole axis
            if grown != shape[axis]:
                grown = (grown // base) * base
            if grown == chunks[axis]:
                break
            candidate[axis] = grown
            if math.prod(candidate) * itemsize > max_mem:
                break
            chunks = candidate
    return tuple(chunks)


def _stage_chunks(
    shape: tuple[int, ...],
    source_chunks: tuple[int, ...],
    target_chunks: tuple[int, ...],
    t: float,
) -> tuple[int, ...]:
    """Geometric interpolation between source and target chunk shapes at
    fraction ``t`` (reference: vendored rechunker
    algorithm.py:calculate_stage_chunks, 114-145 — geomspace per dim)."""
    out = []
    for s, r, w in zip(shape, source_chunks, target_chunks):
        c = round(math.exp(math.log(r) * (1 - t) + math.log(w) * t))
        out.append(max(1, min(s, int(c))))
    return tuple(out)


def _copy_io_ops(
    shape: tuple[int, ...],
    read_chunks: tuple[int, ...],
    write_chunks: tuple[int, ...],
) -> int:
    """IO operations for one copy pass: one write per task plus the covering
    source-chunk reads per task (reference: vendored rechunker
    algorithm.py:148-185, LCM-based op counting — here the worst-case
    straddle count, which upper-bounds it)."""
    tasks = math.prod(max(1, math.ceil(s / w)) for s, w in zip(shape, write_chunks))
    reads_per_task = math.prod(
        min(math.ceil(s / r), math.ceil((w - 1) / r) + 1)
        for s, r, w in zip(shape, read_chunks, write_chunks)
    )
    return tasks * (1 + reads_per_task)


def _copy_feasible(
    shape: tuple[int, ...],
    read_chunks: tuple[int, ...],
    write_chunks: tuple[int, ...],
    itemsize: int,
    max_mem: int,
) -> bool:
    """ONE memory-feasibility rule for a direct copy pass, shared by the
    single-stage planner, the multistage planner, and mirrored (with the
    reference's x2 compressed/uncompressed factors) by _copy_op's
    plan-time ValueError check."""
    return (
        _covering_bytes(shape, write_chunks, read_chunks, itemsize)
        + math.prod(write_chunks) * itemsize
        <= max_mem
    )


def _plan_io_ops(shape: tuple[int, ...], seq: list[tuple[int, ...]]) -> int:
    """Total IO operations of a staged chunking sequence."""
    return sum(_copy_io_ops(shape, a, b) for a, b in zip(seq, seq[1:]))


def multistage_rechunking_plan(
    shape: tuple[int, ...],
    source_chunks: tuple[int, ...],
    target_chunks: tuple[int, ...],
    itemsize: int,
    max_mem: int,
    max_stages: int = 8,
) -> Optional[list[tuple[int, ...]]]:
    """An N-stage sequence of chunkings [source, c_1, .., c_{n}, target] where
    every adjacent pair is a memory-feasible direct copy, minimizing total IO
    operations.

    Solves the pathological shape-transpose rechunks — e.g. (1, N) -> (N, 1)
    chunks — where the elementwise-min intermediate degenerates to (1, 1)
    chunks and O(N^2) one-element IO ops; geometric stages keep every pass
    O(N·sqrt(N)) or better (reference: vendored rechunker
    algorithm.py:multistage_rechunking_plan, 200-318). Returns None when no
    stage count up to ``max_stages`` yields a feasible plan (caller falls
    back to the min-intermediate 2-pass).
    """
    best: Optional[list[tuple[int, ...]]] = None
    best_io = None
    for n_stages in range(0, max_stages + 1):
        seq = [tuple(source_chunks)]
        for k in range(1, n_stages + 1):
            c = _stage_chunks(shape, source_chunks, target_chunks, k / (n_stages + 1))
            if c != seq[-1]:
                seq.append(c)
        if tuple(target_chunks) != seq[-1]:
            seq.append(tuple(target_chunks))
        if any(
            not _copy_feasible(shape, a, b, itemsize, max_mem)
            for a, b in zip(seq, seq[1:])
        ):
            continue
        io = _plan_io_ops(shape, seq)
        if best_io is None or io < best_io:
            best, best_io = seq, io
    return best


def rechunking_plan(
    shape: tuple[int, ...],
    source_chunks: tuple[int, ...],
    target_chunks: tuple[int, ...],
    itemsize: int,
    max_mem: int,
) -> tuple[tuple[int, ...], Optional[tuple[int, ...]], tuple[int, ...]]:
    """Choose (read_chunks, int_chunks, write_chunks) for a bounded rechunk.

    int_chunks is None when a single direct copy pass suffices.
    """
    # direct: write at target granularity, reading the covering source region
    write_chunks = tuple(min(t, s) for t, s in zip(target_chunks, shape))
    if _copy_feasible(shape, source_chunks, write_chunks, itemsize, max_mem):
        # grow write chunks while the (recomputed) covering read still fits
        grown = write_chunks
        while True:
            candidate = _consolidate_chunks(shape, grown, itemsize, 2 * math.prod(grown) * itemsize)
            if candidate == grown:
                break
            if not _copy_feasible(shape, source_chunks, candidate, itemsize, max_mem):
                break
            grown = candidate
        # grown write chunks must remain aligned to the target chunk grid
        if all(g % t == 0 or g == s for g, t, s in zip(grown, write_chunks, shape)):
            write_chunks = grown
        return source_chunks, None, write_chunks

    # staged: intermediate at elementwise min; both passes are bounded
    int_chunks = tuple(min(s, t) for s, t in zip(source_chunks, target_chunks))
    return source_chunks, int_chunks, tuple(min(t, s) for t, s in zip(target_chunks, shape))


def _copy_op(
    source,
    target: LazyZarrArray,
    write_chunks: tuple[int, ...],
    allowed_mem: int,
    reserved_mem: int,
    source_chunks: tuple[int, ...],
) -> PrimitiveOperation:
    shape = tuple(target.shape)
    isz = target.dtype.itemsize
    read_bytes = _covering_bytes(shape, write_chunks, source_chunks, isz)
    write_bytes = math.prod(write_chunks) * isz if write_chunks else isz
    projected_mem = reserved_mem + 2 * read_bytes + 2 * write_bytes
    if projected_mem > allowed_mem:
        raise ValueError(
            f"Projected rechunk memory ({memory_repr(projected_mem)}) exceeds "
            f"allowed_mem ({memory_repr(allowed_mem)}), including "
            f"reserved_mem ({memory_repr(reserved_mem)})"
        )
    spec = CubedCopySpec(
        read=CubedArrayProxy(source, source_chunks),
        write=CubedArrayProxy(target, tuple(target.chunks)),
    )
    keys = ChunkKeys(shape, write_chunks)
    pipeline = CubedPipeline(copy_read_to_write, gensym("rechunk"), keys, spec)
    return PrimitiveOperation(
        pipeline=pipeline,
        source_array_names=[],
        target_array=target,
        projected_mem=projected_mem,
        allowed_mem=allowed_mem,
        reserved_mem=reserved_mem,
        num_tasks=len(keys),
        fusable=False,
        write_chunks=write_chunks,
    )


def rechunk(
    source,
    source_chunks: tuple[int, ...],
    target_chunks: tuple[int, ...],
    allowed_mem: int,
    reserved_mem: int,
    target_store: str,
    temp_store: Optional[str] = None,
    storage_options: Optional[dict] = None,
) -> list[PrimitiveOperation]:
    """Rechunk *source* to *target_chunks*, as one or two bounded copy ops."""
    shape = tuple(source.shape)
    dtype = source.dtype
    isz = np.dtype(dtype).itemsize

    # the factor-of-4 headroom mirrors the reference's compressed/uncompressed
    # x read/write safety margin (cubed/primitive/rechunk.py:52-57)
    max_mem = (allowed_mem - reserved_mem) // 4
    read_chunks, int_chunks, write_chunks = rechunking_plan(
        shape, tuple(source_chunks), tuple(target_chunks), isz, max_mem
    )

    target = lazy_empty(
        shape, dtype=dtype, chunks=tuple(min(t, s) for t, s in zip(target_chunks, shape)) if shape else (),
        store=target_store, storage_options=storage_options,
    )

    if int_chunks is None:
        return [
            _copy_op(source, target, write_chunks, allowed_mem, reserved_mem, tuple(source_chunks))
        ]
    if temp_store is None:
        raise ValueError("temp_store required for staged rechunk")

    # choose between the min-intermediate 2-pass and an N-stage geometric
    # plan by total IO operations (the multistage plan wins on
    # shape-transpose rechunks where the elementwise min degenerates)
    eff_target = tuple(min(t, s) for t, s in zip(target_chunks, shape))
    min_seq = [tuple(source_chunks), int_chunks, eff_target]
    seq = multistage_rechunking_plan(
        shape, tuple(source_chunks), eff_target, isz, max_mem
    )
    if seq is None or len(seq) <= 2 or _plan_io_ops(shape, seq) >= _plan_io_ops(
        shape, min_seq
    ):
        seq = min_seq

    ops = []
    prev_arr, prev_chunks = source, tuple(source_chunks)
    for k, stage in enumerate(seq[1:], start=1):
        last = k == len(seq) - 1
        if last:
            arr = target
        else:
            arr = lazy_empty(
                shape, dtype=dtype, chunks=stage,
                store=temp_store if k == 1 else f"{temp_store}-s{k}",
                storage_options=storage_options,
            )
        ops.append(
            _copy_op(prev_arr, arr, stage, allowed_mem, reserved_mem, prev_chunks)
        )
        prev_arr, prev_chunks = arr, stage
    return ops
