"""Task execution instrumentation: wall time + peak host memory per task.

Reference parity: cubed/runtime/utils.py:17-64.
"""

from __future__ import annotations

import itertools
import time
from functools import partial
from typing import Iterable, Iterator, Optional, Sequence

from ..utils import peak_measured_mem
from .types import Callback, OperationStartEvent, TaskEndEvent, callbacks_on


def execute_with_stats(function, *args, **kwargs):
    """Run a task function, returning (result, stats-dict)."""
    peak_before = peak_measured_mem()
    start = time.time()
    result = function(*args, **kwargs)
    end = time.time()
    peak_after = peak_measured_mem()
    return result, dict(
        function_start_tstamp=start,
        function_end_tstamp=end,
        peak_measured_mem_start=peak_before,
        peak_measured_mem_end=peak_after,
    )


def execution_stats(function):
    """Decorator adding timing/memory stats to a task function's return value."""
    return partial(execute_with_stats, function)


def handle_callbacks(callbacks: Optional[Sequence[Callback]], stats: dict) -> None:
    if not callbacks:
        return
    if "task_result_tstamp" not in stats:
        stats = dict(stats, task_result_tstamp=time.time())
    event = TaskEndEvent(**stats)
    for cb in callbacks:
        cb.on_task_end(event)


def merge_generation(generation, callbacks) -> tuple[list, dict]:
    """Interleave one topological generation's tasks for a single map.

    Fires ``on_operation_start`` for every op in the generation and returns
    ``(items, pipelines)``: ``items`` is the merged ``(op_name, task_input)``
    list and ``pipelines`` maps op name → its pipeline, so the caller can
    resolve each item's ``(function, config)``. Shared by every executor
    that supports ``compute_arrays_in_parallel`` (reference:
    cubed/runtime/executors/python_async.py:93-114).
    """
    items: list = []
    pipelines: dict = {}
    for name, node in generation:
        primitive_op = node["primitive_op"]
        callbacks_on(
            callbacks, "on_operation_start",
            OperationStartEvent(name, primitive_op.num_tasks),
        )
        pipelines[name] = primitive_op.pipeline
        for m in primitive_op.pipeline.mappable:
            items.append((name, m))
    return items, pipelines


def batched(iterable: Iterable, n: int) -> Iterator[list]:
    """Yield successive lists of up to *n* items."""
    it = iter(iterable)
    while True:
        batch = list(itertools.islice(it, n))
        if not batch:
            return
        yield batch
