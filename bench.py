"""Benchmark: the BASELINE.json north-star workload — the pangeo-vorticity
pipeline (reference examples/pangeo-vorticity.ipynb): four random arrays,
``mean(a[1:]*x + b[1:]*y)`` — rechunk-free fused elementwise + orthogonal
index + tree reduction. Run at (500,450,400) f64, chunks=100 (the notebook's
(1000,900,800) exceeds one chip's HBM; the driver's mesh dryrun covers the
sharded path).

Compares the JaxExecutor on the real TPU chip against the single-process
numpy-backend PythonDagExecutor (the reference's baseline executor semantics)
running the identical plan in a subprocess.

Prints ONE JSON line: value = array data processed per second on the TPU path
(4 generated arrays + 2 sliced operands), vs_baseline = speedup over the
numpy executor.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

SHAPE = (500, 450, 400)
CHUNK = 100
_elems = SHAPE[0] * SHAPE[1] * SHAPE[2]
#: bytes flowing through the pipeline: 4 generated arrays + 2 sliced reads
WORK_BYTES = 6 * _elems * 8

WORKLOAD = r"""
import json, sys, tempfile, time
import numpy as np
sys.path.insert(0, {repo!r})
import cubed_tpu as ct
import cubed_tpu.array_api as xp
import cubed_tpu.random

spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="4GB")
shape = {shape!r}

def build():
    a = cubed_tpu.random.random(shape, chunks={chunk}, spec=spec)
    b = cubed_tpu.random.random(shape, chunks={chunk}, spec=spec)
    x = cubed_tpu.random.random(shape, chunks={chunk}, spec=spec)
    y = cubed_tpu.random.random(shape, chunks={chunk}, spec=spec)
    return xp.mean(xp.add(xp.multiply(a[1:], x[1:]), xp.multiply(b[1:], y[1:])))

t0 = time.perf_counter()
val = build().compute()
t1 = time.perf_counter()
print(json.dumps({{"elapsed": t1 - t0, "value": float(val)}}))
"""


def run_baseline() -> dict:
    env = dict(os.environ, CUBED_TPU_BACKEND="numpy")
    script = WORKLOAD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), shape=SHAPE, chunk=CHUNK
    )
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True,
        timeout=3000,
    )
    if out.returncode != 0:
        raise RuntimeError(f"baseline failed: {out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_tpu() -> dict:
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    import cubed_tpu.random
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="4GB")
    executor = JaxExecutor()

    def build():
        a = cubed_tpu.random.random(SHAPE, chunks=CHUNK, spec=spec)
        b = cubed_tpu.random.random(SHAPE, chunks=CHUNK, spec=spec)
        x = cubed_tpu.random.random(SHAPE, chunks=CHUNK, spec=spec)
        y = cubed_tpu.random.random(SHAPE, chunks=CHUNK, spec=spec)
        return xp.mean(xp.add(xp.multiply(a[1:], x[1:]), xp.multiply(b[1:], y[1:])))

    # warmup: compile kernels (persistent cache makes this cheap after round 1)
    build().compute(executor=executor)

    s = build()
    t0 = time.perf_counter()
    val = s.compute(executor=executor)
    t1 = time.perf_counter()
    # mean of u1*u2 + u3*u4 over uniforms is ~0.5
    assert 0.45 < float(val) < 0.55, float(val)
    return {"elapsed": t1 - t0, "value": float(val)}


def main() -> None:
    tpu = run_tpu()
    try:
        baseline = run_baseline()
        vs_baseline = baseline["elapsed"] / tpu["elapsed"]
    except Exception as e:
        print(f"baseline run failed: {e}", file=sys.stderr)
        vs_baseline = None

    gbps = WORK_BYTES / tpu["elapsed"] / 1e9
    print(
        json.dumps(
            {
                "metric": "pangeo_vorticity_500x450x400_f64_throughput",
                "value": round(gbps, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(vs_baseline, 3) if vs_baseline is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main()
