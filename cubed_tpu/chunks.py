"""Chunk normalization and unification.

Reimplements (from semantics, not source) the chunk-grid algebra the reference
vendors from dask: ``normalize_chunks`` including ``"auto"`` sizing,
``common_blockdim`` unification, and broadcast chunk computation.
Reference parity: cubed/vendor/dask/array/core.py:21-532.
"""

from __future__ import annotations

from math import prod
from numbers import Integral
from typing import Any, Sequence

import numpy as np

from .utils import accumulate_prepend_zero, convert_to_bytes, itemsize

#: Default target bytes per chunk when chunks="auto" (128 MiB, the common
#: operating point; cf. reference docs/user-guide/memory.md "Chunk sizes").
DEFAULT_CHUNK_BYTES = 128 * 1024 * 1024


def blockdims_from_blockshape(
    shape: Sequence[int], chunkshape: Sequence[int]
) -> tuple[tuple[int, ...], ...]:
    """Expand a single chunk shape into per-dim tuples of block sizes."""
    if len(shape) != len(chunkshape):
        raise ValueError(f"shape {shape} and chunk shape {chunkshape} differ in rank")
    out = []
    for s, c in zip(shape, chunkshape):
        s, c = int(s), int(c)
        if s == 0:
            out.append((0,))
            continue
        if c <= 0:
            raise ValueError(f"Chunk size must be positive, got {c}")
        c = min(c, s)
        blocks = (c,) * (s // c)
        if s % c:
            blocks = blocks + (s % c,)
        out.append(blocks)
    return tuple(out)


def normalize_chunks(
    chunks: Any,
    shape: tuple[int, ...],
    dtype: Any = None,
    limit: int | str | None = None,
    previous_chunks: tuple[tuple[int, ...], ...] | None = None,
) -> tuple[tuple[int, ...], ...]:
    """Normalize any accepted chunks argument to a tuple-of-tuples of block sizes.

    Accepts an int (same size every dim), a str/int byte limit, ``"auto"``, a
    tuple mixing ints / ``-1`` / ``None`` / ``"auto"`` / explicit per-dim tuples,
    or a dict mapping axis to any of the above.
    """
    ndim = len(shape)
    if chunks is None:
        chunks = "auto"
    if isinstance(chunks, dict):
        chunks = tuple(chunks.get(i, "auto") for i in range(ndim))
    if isinstance(chunks, (int, np.integer, float)):
        chunks = (int(chunks),) * ndim
    if isinstance(chunks, str):
        if chunks.lower() == "auto":
            chunks = ("auto",) * ndim
        else:
            # a byte-string limit like "128MB" applies auto-chunking with that target
            limit = convert_to_bytes(chunks)
            chunks = ("auto",) * ndim
    chunks = tuple(chunks)
    if len(chunks) != ndim:
        raise ValueError(f"chunks {chunks} do not match array rank {ndim}")

    # substitute full-extent markers
    norm: list[Any] = []
    for i, c in enumerate(chunks):
        if c is None or (isinstance(c, (int, np.integer)) and int(c) == -1):
            norm.append(shape[i])
        elif isinstance(c, str) and c.lower() == "auto":
            norm.append("auto")
        elif isinstance(c, (int, np.integer)):
            norm.append(int(c))
        elif isinstance(c, (tuple, list)):
            t = tuple(int(x) for x in c)
            if sum(t) != shape[i]:
                raise ValueError(
                    f"explicit chunks {t} for axis {i} do not sum to extent {shape[i]}"
                )
            norm.append(t)
        else:
            raise ValueError(f"Unrecognized chunks element {c!r}")

    if any(c == "auto" for c in norm):
        norm = _auto_chunks(norm, shape, dtype, limit, previous_chunks)

    out = []
    for i, c in enumerate(norm):
        if isinstance(c, tuple):
            out.append(c)
        else:
            out.append(blockdims_from_blockshape((shape[i],), (c,))[0])
    return tuple(out)


def _auto_chunks(
    norm: list[Any],
    shape: tuple[int, ...],
    dtype: Any,
    limit: int | str | None,
    previous_chunks: tuple[tuple[int, ...], ...] | None,
) -> list[Any]:
    """Resolve ``"auto"`` markers so chunk bytes approach the target limit.

    All auto dims get (approximately) equal extents chosen so the product of all
    chunk extents times the itemsize is at most the byte limit.
    """
    if dtype is None:
        raise ValueError("dtype must be known to use chunks='auto'")
    limit_bytes = convert_to_bytes(limit) if limit is not None else DEFAULT_CHUNK_BYTES
    isize = itemsize(dtype)

    fixed_elems = 1
    for i, c in enumerate(norm):
        if c == "auto":
            continue
        fixed_elems *= max(c) if isinstance(c, tuple) else int(c)

    auto_axes = [i for i, c in enumerate(norm) if c == "auto"]
    budget = max(1, limit_bytes // max(1, isize * fixed_elems))

    # distribute the element budget over auto axes, clamping at each extent
    remaining = sorted(auto_axes, key=lambda i: shape[i])
    sizes: dict[int, int] = {}
    while remaining:
        per_axis = max(1, int(round(budget ** (1.0 / len(remaining)))))
        axis = remaining[0]
        if shape[axis] <= per_axis:
            sizes[axis] = max(1, shape[axis])
            budget = max(1, budget // max(1, shape[axis]))
            remaining.pop(0)
        else:
            for ax in remaining:
                sizes[ax] = max(1, min(shape[ax], per_axis))
            remaining = []
    for i in auto_axes:
        norm[i] = sizes[i]
    return norm


def common_blockdim(blockdims: Sequence[tuple[int, ...]]) -> tuple[int, ...]:
    """Unify several chunkings of the same extent into their common refinement.

    Dims of total extent 1 (broadcast candidates) are ignored. If the extents
    disagree otherwise, raises. The result's block boundaries are the union of
    every input's boundaries, so each input can be resliced without crossing a
    block boundary. Reference parity: cubed/vendor/dask/array/core.py:467.
    """
    non_trivial = [b for b in blockdims if sum(b) != 1 or len(b) > 1]
    if not non_trivial:
        return blockdims[0] if blockdims else ()
    totals = {sum(b) for b in non_trivial}
    if len(totals) > 1:
        raise ValueError(f"Chunks do not align: extents {sorted(totals)}")
    uniq = set(non_trivial)
    if len(uniq) == 1:
        return non_trivial[0]
    boundaries: set[int] = set()
    for b in non_trivial:
        boundaries.update(accumulate_prepend_zero(b)[1:])
        boundaries.add(sum(b))
    cuts = sorted(boundaries)
    return tuple(b - a for a, b in zip([0] + cuts, cuts))


def broadcast_chunks(*chunkss: tuple[tuple[int, ...], ...]) -> tuple[tuple[int, ...], ...]:
    """Chunks of the array resulting from broadcasting the given chunked arrays."""
    if not chunkss:
        return ()
    ndim = max(len(c) for c in chunkss)
    padded = [((1,),) * (ndim - len(c)) + tuple(c) for c in chunkss]
    out = []
    for dim in range(ndim):
        dims = [p[dim] for p in padded]
        non_unit = [d for d in dims if sum(d) != 1]
        if not non_unit:
            out.append((1,))
            continue
        extents = {sum(d) for d in non_unit}
        if len(extents) > 1:
            raise ValueError(f"operands could not be broadcast together at dim {dim}")
        out.append(common_blockdim(non_unit))
    return tuple(out)


def numblocks(chunks: tuple[tuple[int, ...], ...]) -> tuple[int, ...]:
    return tuple(len(c) for c in chunks)


def chunk_offsets(chunks: tuple[tuple[int, ...], ...]) -> tuple[list[int], ...]:
    """Per-dim start offsets of each block."""
    return tuple(accumulate_prepend_zero(c) for c in chunks)


def reshape_rechunk(
    inshape: tuple[int, ...],
    outshape: tuple[int, ...],
    inchunks: tuple[tuple[int, ...], ...],
) -> tuple[tuple[tuple[int, ...], ...], tuple[tuple[int, ...], ...]]:
    """Factor a reshape into (rechunk-to, result-chunks) so blocks map 1:1.

    Greedily matches runs of input dims to runs of output dims with equal element
    products (the only reshapes expressible block-preserving). Within each run,
    the slowest-varying dim keeps its chunking (adjusted) and all faster dims are
    collapsed to full extent. Reference parity: cubed/vendor/dask/array/reshape.py:20.
    """
    if prod(inshape) != prod(outshape):
        raise ValueError(f"cannot reshape {inshape} -> {outshape}")

    # split both shapes into aligned groups with equal products
    groups: list[tuple[list[int], list[int]]] = []
    i = j = 0
    while i < len(inshape) or j < len(outshape):
        gi, gj = [i], [j]
        pi = inshape[i] if i < len(inshape) else 1
        pj = outshape[j] if j < len(outshape) else 1
        i += 1
        j += 1
        while pi != pj:
            if pi < pj:
                if i >= len(inshape):
                    raise ValueError("cannot align reshape groups")
                pi *= inshape[i]
                gi.append(i)
                i += 1
            else:
                if j >= len(outshape):
                    raise ValueError("cannot align reshape groups")
                pj *= outshape[j]
                gj.append(j)
                j += 1
        # absorb trailing 1s
        while i < len(inshape) and inshape[i] == 1:
            gi.append(i)
            i += 1
        while j < len(outshape) and outshape[j] == 1:
            gj.append(j)
            j += 1
        groups.append((gi, gj))

    rechunk_to: list[tuple[int, ...]] = [None] * len(inshape)  # type: ignore
    outchunks: list[tuple[int, ...]] = [None] * len(outshape)  # type: ignore
    for gi, gj in groups:
        lead_in, rest_in = gi[0], gi[1:]
        lead_out, rest_out = gj[0], gj[1:]
        rest_in_elems = prod(inshape[k] for k in rest_in) if rest_in else 1
        rest_out_elems = prod(outshape[k] for k in rest_out) if rest_out else 1
        if len(gi) == 1 and len(gj) == 1:
            # 1:1 dim, keep chunking as-is
            rechunk_to[lead_in] = inchunks[lead_in]
            outchunks[lead_out] = inchunks[lead_in]
            continue
        # collapse: rest dims single-block; lead dim carries the block structure.
        for k in rest_in:
            rechunk_to[k] = (inshape[k],) if inshape[k] > 0 else (0,)
        lead_chunks = inchunks[lead_in]
        # blocks in the lead-in dim must land on boundaries that are expressible
        # in the lead-out dim: each lead-in block of b rows covers
        # b*rest_in_elems elements = (b*rest_in_elems/rest_out_elems) lead-out rows
        factor = rest_in_elems
        ok = all((b * factor) % rest_out_elems == 0 for b in lead_chunks)
        if not ok:
            # fall back to one block along this group
            lead_chunks = (inshape[lead_in],) if inshape[lead_in] > 0 else (0,)
        rechunk_to[lead_in] = lead_chunks
        outchunks[lead_out] = tuple((b * factor) // rest_out_elems for b in lead_chunks)
        for k in rest_out:
            outchunks[k] = (outshape[k],) if outshape[k] > 0 else (0,)
    return tuple(rechunk_to), tuple(outchunks)
