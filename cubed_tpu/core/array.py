"""CoreArray: the chunked-array handle tying together a name, a (possibly lazy)
Zarr target, a Spec, and a Plan.

Reference parity: cubed/core/array.py (behavioral; clean-room).
"""

from __future__ import annotations

from math import prod
from operator import mul
from typing import Optional, Sequence, TypeVar

import numpy as np

from ..chunks import blockdims_from_blockshape
from ..runtime.types import Callback
from ..spec import Spec, spec_from_config
from ..storage.zarr import LazyZarrArray, open_if_lazy_zarr_array
from ..utils import chunk_memory, memory_repr, to_chunksize

T_ChunkedArray = TypeVar("T_ChunkedArray", bound="CoreArray")


class CoreArray:
    """A chunked n-dimensional array handle participating in a lazy plan."""

    def __init__(self, name: str, zarray_maybe_lazy, spec: Spec, plan):
        self.name = name
        self.zarray_maybe_lazy = zarray_maybe_lazy
        self.spec = spec
        self.plan = plan

    # -- metadata ----------------------------------------------------------

    @property
    def chunkmem(self) -> int:
        """Bytes of one chunk of this array."""
        return chunk_memory(self.dtype, self.chunksize)

    @property
    def chunks(self) -> tuple[tuple[int, ...], ...]:
        return blockdims_from_blockshape(self.shape, self.zarray_maybe_lazy.chunks)

    @property
    def chunksize(self) -> tuple[int, ...]:
        return tuple(self.zarray_maybe_lazy.chunks)

    @property
    def dtype(self):
        return self.zarray_maybe_lazy.dtype

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def numblocks(self) -> tuple[int, ...]:
        return tuple(len(c) for c in self.chunks)

    @property
    def npartitions(self) -> int:
        return prod(self.numblocks) if self.shape else 1

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(self.zarray_maybe_lazy.shape)

    @property
    def size(self) -> int:
        return prod(self.shape) if self.shape else 1

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    @property
    def itemsize(self) -> int:
        return self.dtype.itemsize

    @property
    def zarray(self):
        """The concrete storage array (opens a lazy target)."""
        return open_if_lazy_zarr_array(self.zarray_maybe_lazy)

    # -- compute -----------------------------------------------------------

    def compute(self, **kwargs):
        """Execute the plan for this array and return it as a numpy array."""
        result = compute(self, **kwargs)
        return result[0] if result else None

    def _read_stored(self) -> np.ndarray:
        arr = self.zarray
        out = arr[...] if self.shape else arr[()]
        return np.asarray(out)

    def rechunk(self, chunks, **kwargs):
        from .ops import rechunk

        return rechunk(self, chunks, **kwargs)

    def visualize(self, *args, **kwargs):
        return self.plan.visualize(*args, **kwargs)

    def explain(self, **kwargs):
        """EXPLAIN the plan that computes this array (``Plan.explain``),
        defaulting the spec and target array name to this array's."""
        kwargs.setdefault("spec", self.spec)
        kwargs.setdefault("array_names", (self.name,))
        return self.plan.explain(**kwargs)

    def __getitem__(self, key):
        from .ops import index

        return index(self, key)

    def __repr__(self) -> str:
        return f"cubed_tpu.CoreArray<{self.name}, shape={self.shape}, dtype={self.dtype}, chunks={self.chunks}>"


def check_array_specs(arrays: Sequence) -> Optional[Spec]:
    """All arrays in one computation must share an equivalent Spec."""
    specs = [a.spec for a in arrays if hasattr(a, "spec")]
    if not specs:
        return None
    first = specs[0]
    for other in specs[1:]:
        if other != first:
            raise ValueError(
                f"Arrays must have same spec in single computation. "
                f"Specs: {first!r} and {other!r}"
            )
    return first


def compute(
    *arrays,
    executor=None,
    callbacks: Optional[Sequence[Callback]] = None,
    optimize_graph: bool = True,
    optimize_function=None,
    resume: Optional[bool] = None,
    **kwargs,
) -> list[np.ndarray]:
    """Compute multiple arrays in one plan execution; return numpy results."""
    from .plan import arrays_to_plan

    if not arrays:
        return []
    spec = check_array_specs(arrays)
    plan = arrays_to_plan(*arrays)
    if executor is None:
        executor = spec.executor if spec is not None else None
    if executor is None:
        from ..runtime.executors.python import PythonDagExecutor

        executor = PythonDagExecutor()
    plan.execute(
        executor=executor,
        callbacks=callbacks,
        optimize_graph=optimize_graph,
        optimize_function=optimize_function,
        resume=resume,
        array_names=tuple(a.name for a in arrays),
        spec=spec,
        **kwargs,
    )
    return [a._read_stored() for a in arrays]


def visualize(*arrays, filename="cubed", format=None, **kwargs):
    """Produce a visualization of the combined plan of the given arrays."""
    from .plan import arrays_to_plan

    plan = arrays_to_plan(*arrays)
    return plan.visualize(filename=filename, format=format, **kwargs)


def measure_reserved_mem(executor=None, work_dir: Optional[str] = None, **kwargs) -> int:
    """Measure memory used by the runtime before any task data is loaded.

    Runs a trivial computation and reports the worker's peak measured memory,
    for use as ``reserved_mem``. Reference parity: cubed/core/array.py:343-388.
    """
    from ..array_api.creation_functions import ones
    from ..extensions.history import HistoryCallback

    a = ones((1,), chunks=(1,), spec=Spec(work_dir=work_dir, allowed_mem="100MB"))
    history = HistoryCallback()
    a.compute(executor=executor, callbacks=[history], **kwargs)
    events = history.events
    if events:
        peaks = [
            e.peak_measured_mem_start
            for e in events
            if e.peak_measured_mem_start is not None
        ]
        if peaks:
            return max(peaks)
    from ..utils import peak_measured_mem

    return peak_measured_mem()
