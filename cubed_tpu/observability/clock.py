"""The observability wall clock: ``time.time`` plus a settable skew.

Every span/heartbeat timestamp in the tracing pipeline goes through
``now()`` instead of ``time.time()`` so tests can inject per-process clock
skew deterministically and prove the cross-process alignment machinery
corrects it (``docs/observability.md``, "Distributed traces"). In
production the skew is always 0 and ``now()`` is ``time.time()`` plus one
float add.

Skew is configured per process:

- ``set_skew(seconds)`` — programmatic.
- env ``CUBED_TPU_CLOCK_SKEW_S`` — either a plain float (skew every
  process that reads it) or a JSON object mapping worker names to floats
  (``{"local-0": 2.0, "local-1": -3.0}``) so each fleet worker in a test
  gets its own wrong clock. ``configure_from_env(name)`` resolves it; the
  fleet worker entry point calls it with its ``--name``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

SKEW_ENV_VAR = "CUBED_TPU_CLOCK_SKEW_S"

_skew = 0.0


def now() -> float:
    """Epoch seconds on this process's (possibly skewed) observability clock."""
    return time.time() + _skew


def get_skew() -> float:
    return _skew


def set_skew(seconds: float) -> None:
    global _skew
    _skew = float(seconds)


def skew_for(name: Optional[str] = None) -> float:
    """The env-configured skew for this process (0.0 when unset).

    A malformed env value raises loudly — a silently unskewed clock-skew
    test would pass for the wrong reason.
    """
    raw = os.environ.get(SKEW_ENV_VAR)
    if not raw:
        return 0.0
    try:
        return float(raw)
    except ValueError:
        pass
    mapping = json.loads(raw)
    if not isinstance(mapping, dict):
        raise ValueError(f"{SKEW_ENV_VAR} must be a float or a JSON object")
    return float(mapping.get(name or "", 0.0))


def configure_from_env(name: Optional[str] = None) -> float:
    """Adopt the env-configured skew (worker entry points call this)."""
    skew = skew_for(name)
    set_skew(skew)
    return skew
