from ..observability import TracingCallback  # noqa: F401
from .history import HistoryCallback  # noqa: F401
from .timeline import TimelineVisualizationCallback  # noqa: F401
from .tqdm import TqdmProgressBar  # noqa: F401
