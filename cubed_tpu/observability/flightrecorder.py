"""Post-mortem flight recorder: a self-contained bundle per failed compute.

``FlightRecorder`` rides a compute like any callback (it extends
:class:`~cubed_tpu.observability.collect.TraceCollector`, so it already
holds the merged clock-aligned trace) and, when the compute fails — or on
demand via :meth:`dump` — assembles everything a post-mortem needs into one
directory:

.. code-block:: text

    <bundle_dir>/bundle-<compute_id>/
        manifest.json   # status, error + failing op/chunk, metrics snapshot,
                        # per-op projected-vs-measured memory, coordinator
                        # worker table, decision timeline, alert timeline +
                        # time-series dump (when live telemetry was armed),
                        # stragglers, per-worker clock offsets
        trace.json      # the merged Perfetto trace (open in ui.perfetto.dev)
        logs.jsonl      # last-N correlated structured log records
        profile-<compute_id>.folded
                        # collapsed coordinator stacks when the dispatch
                        # profiler was armed (flamegraph.pl/speedscope-ready)

Read it with ``python -m cubed_tpu.diagnose <bundle>`` — slowest ops, top
stragglers, retry/quarantine/guard timelines, per-worker skew — or any JSON
tooling. Arm it per compute by passing the callback, or fleet-wide with
``CUBED_TPU_FLIGHT_RECORDER=<dir>`` (``Plan.execute`` then attaches one to
every compute automatically).
"""

from __future__ import annotations

import json
import logging
import os
import time
import traceback
from typing import Optional

from . import logs
from .collect import TraceCollector, decisions_since
from .metrics import get_registry

logger = logging.getLogger(__name__)

#: env var naming a bundle directory: when set, every Plan.execute attaches
#: a FlightRecorder writing there
FLIGHT_RECORDER_ENV_VAR = "CUBED_TPU_FLIGHT_RECORDER"

BUNDLE_MANIFEST = "manifest.json"
BUNDLE_TRACE = "trace.json"
BUNDLE_LOGS = "logs.jsonl"


class FlightRecorder(TraceCollector):
    """Assemble a post-mortem bundle on compute failure (or on demand).

    A FlightRecorder IS a :class:`TraceCollector` — attach one or the
    other, not both: each attached collector counts ``spans_dropped`` /
    ``stragglers_detected`` and records straggler instants independently,
    so doubling up double-counts them. To get a loose trace file AND
    bundles, attach one recorder and call its inherited ``export(path)``.

    Parameters
    ----------
    bundle_dir : str
        Where bundles are written (one ``bundle-<compute_id>`` dir each).
    on_failure : bool
        Assemble automatically when the compute ends with an error.
    always : bool
        Assemble for successful computes too.
    max_log_records : int
        How many trailing structured log records the bundle keeps.
    """

    def __init__(
        self,
        bundle_dir: str = "flight-recorder",
        on_failure: bool = True,
        always: bool = False,
        max_log_records: int = 400,
        **collector_kwargs,
    ):
        # the merged trace lives inside the bundle, not as a loose file
        collector_kwargs.setdefault("trace_dir", None)
        super().__init__(**collector_kwargs)
        self.bundle_dir = bundle_dir
        self.on_failure = on_failure
        self.always = always
        self.max_log_records = max_log_records
        self.bundle_path: Optional[str] = None
        # capture log records from the moment the recorder exists
        logs.install()

    def on_compute_end(self, event) -> None:
        super().on_compute_end(event)
        if self.always or (self.on_failure and self.error is not None):
            try:
                self.bundle_path = self.dump()
                logger.warning(
                    "flight-recorder bundle written: %s (read it with "
                    "'python -m cubed_tpu.diagnose %s')",
                    self.bundle_path, self.bundle_path,
                )
            except Exception:
                # the recorder must never mask the compute's own failure
                logger.exception(
                    "failed to assemble flight-recorder bundle for "
                    "compute %s", self.compute_id,
                )

    # -- bundle assembly -----------------------------------------------

    def _failing_tasks(self) -> list[dict]:
        """The failure timeline: task_failed decisions recorded during this
        compute, most recent last (the last one usually names the killer;
        fail-fasts arrive as classification="fail_fast")."""
        return [
            d for d in decisions_since(self._t0)
            if d["kind"] == "task_failed"
        ][-50:]

    def _alert_timeline(self) -> list:
        """Alert firings recorded during this compute (the alert engine
        lands every firing on the decision ring, so the bundle carries the
        alert timeline even when the telemetry endpoint is gone by
        post-mortem time)."""
        return [
            d for d in decisions_since(self._t0)
            if d["kind"] == "alert_fired"
        ]

    def _timeseries_dump(self) -> Optional[list]:
        """A bounded dump of the live time-series store covering this
        compute's window, or None when telemetry was never armed."""
        from .export import get_runtime

        runtime = get_runtime()
        if runtime is None:
            return None
        window_s = max(60.0, time.time() - self._t0 + 5.0)
        return runtime.store.to_dict(window_s=window_s, max_points=120)

    def manifest(self) -> dict:
        error = self.error
        err_block = None
        if error is not None:
            err_block = {
                "type": type(error).__name__,
                "message": str(error),
                "traceback": "".join(
                    traceback.format_exception(
                        type(error), error, error.__traceback__
                    )
                )[-8000:],
            }
            failures = self._failing_tasks()
            if failures:
                last = failures[-1]
                err_block["op"] = last.get("op")
                err_block["chunk"] = last.get("chunk")
        return {
            "compute_id": self.compute_id,
            "created_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "status": "failed" if error is not None else "succeeded",
            "wall_clock_s": (
                (self.end_tstamp - self.start_tstamp)
                if self.end_tstamp and self.start_tstamp
                else None
            ),
            "error": err_block,
            "failing_tasks": self._failing_tasks(),
            "executor_stats": self.executor_stats,
            "metrics": get_registry().snapshot(),
            # the plan joined against measured peaks: the bounded-memory
            # promise vs what actually happened, per op
            "plan": self.projected_vs_measured(),
            "op_wall_clock": {
                name: t.wall_clock for name, t in self.op_timings.items()
            },
            "decisions": decisions_since(self._t0),
            # the live-telemetry layer's post-mortem residue: every alert
            # that fired during the compute, plus the sampled time series
            # covering its window (None when telemetry was unarmed)
            "alerts": self._alert_timeline(),
            "timeseries": self._timeseries_dump(),
            "stragglers": self.stragglers(),
            "clock_offsets": self.clock_offsets(),
            # the dependency structure analytics (analyze()/diagnose
            # --analyze) walks for the critical path: the op-level skeleton
            # always, the chunk-level edges when the dataflow scheduler
            # recorded them (spans armed)
            "op_graph": self.op_graph(),
            "chunk_graph": self.chunk_graph(),
            "task_records": len(self._records),
            "task_records_dropped": self.records_dropped,
            # the coordinator self-profiler's summary (top folded stacks,
            # sample/overflow counts) when the dispatch profiler was armed
            # for this compute — the collapsed stacks themselves land as
            # profile-<compute_id>.folded beside the trace
            "dispatch_profile": self._dispatch_profile_summary(),
        }

    def _dispatch_profile_summary(self) -> Optional[dict]:
        from .dispatchprofile import profile_for

        prof = profile_for(self.compute_id)
        return prof.summary() if prof is not None else None

    def dump(self, path: Optional[str] = None) -> str:
        """Write the bundle directory now; returns its path."""
        if path is None:
            path = os.path.join(self.bundle_dir, f"bundle-{self.compute_id}")
        os.makedirs(path, exist_ok=True)
        self.export(os.path.join(path, BUNDLE_TRACE))
        from .dispatchprofile import profile_for

        prof = profile_for(self.compute_id)
        if prof is not None:
            # flamegraph-ready collapsed stacks: feed straight to
            # flamegraph.pl / speedscope / inferno
            folded = os.path.join(
                path, f"profile-{self.compute_id}.folded"
            )
            with open(folded, "w") as f:
                f.write("\n".join(prof.folded_lines()) + "\n")
        with open(os.path.join(path, BUNDLE_LOGS), "w") as f:
            for rec in logs.recent_records(self.max_log_records):
                f.write(json.dumps(rec, default=str) + "\n")
        manifest = self.manifest()
        tmp = os.path.join(path, BUNDLE_MANIFEST + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1, default=str)
        os.replace(tmp, os.path.join(path, BUNDLE_MANIFEST))
        return path


def load_bundle(path: str) -> dict:
    """Read a bundle directory (or its manifest path) into a dict with
    ``manifest``, ``trace`` (parsed, or None), and ``logs`` (list)."""
    if os.path.isfile(path):
        path = os.path.dirname(path) or "."
    with open(os.path.join(path, BUNDLE_MANIFEST)) as f:
        manifest = json.load(f)
    trace = None
    trace_path = os.path.join(path, BUNDLE_TRACE)
    if os.path.exists(trace_path):
        try:
            with open(trace_path) as f:
                trace = json.load(f)
        except ValueError:
            trace = None
    records: list = []
    logs_path = os.path.join(path, BUNDLE_LOGS)
    if os.path.exists(logs_path):
        with open(logs_path) as f:
            for line in f:
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue  # torn line: tolerate, like manifest shards
    return {"path": path, "manifest": manifest, "trace": trace, "logs": records}
