"""map_overlap — the chunked stencil primitive (no reference counterpart;
dask.array.map_overlap semantics)."""

import numpy as np
import pytest

import cubed_tpu as ct


def asnp(x):
    return np.asarray(x.compute())


def smooth(block):
    b = np.asarray(block)
    return sum(
        np.roll(np.roll(b, i, 0), j, 1)
        for i in (-1, 0, 1) for j in (-1, 0, 1)
    ) / 9.0


def expected(an, npmode, **kw):
    pe = np.pad(an, 1, mode=npmode, **kw)
    n, m = an.shape
    return sum(
        pe[1 + i:n + 1 + i, 1 + j:m + 1 + j]
        for i in (-1, 0, 1) for j in (-1, 0, 1)
    ) / 9.0


@pytest.mark.parametrize(
    "boundary,npmode,kw",
    [
        ("reflect", "symmetric", {}),
        ("nearest", "edge", {}),
        ("periodic", "wrap", {}),
        (0.0, "constant", {"constant_values": 0.0}),
        (2.5, "constant", {"constant_values": 2.5}),
    ],
)
def test_map_overlap_boundaries(spec, boundary, npmode, kw):
    an = np.random.default_rng(0).standard_normal((40, 40))
    a = ct.from_array(an, chunks=(10, 10), spec=spec)
    got = asnp(ct.map_overlap(smooth, a, depth=1, boundary=boundary))
    np.testing.assert_allclose(got, expected(an, npmode, **kw), atol=1e-12)


def test_map_overlap_depth_forms(spec):
    an = np.random.default_rng(1).standard_normal((24, 18))
    a = ct.from_array(an, chunks=(8, 6), spec=spec)

    def ident(b):
        return np.asarray(b)

    np.testing.assert_allclose(asnp(ct.map_overlap(ident, a, depth=2)), an)
    np.testing.assert_allclose(
        asnp(ct.map_overlap(ident, a, depth={0: 1})), an
    )
    np.testing.assert_allclose(
        asnp(ct.map_overlap(ident, a, depth=(2, 0))), an
    )
    with pytest.raises(ValueError):
        ct.map_overlap(ident, a, depth=-1)
    with pytest.raises(ValueError):
        ct.map_overlap(ident, a, depth=100)
    with pytest.raises(ValueError):
        ct.map_overlap(ident, a, depth=1, boundary="bogus")
    with pytest.raises(IndexError):
        ct.map_overlap(ident, a, depth={2: 1})
    # negative axis keys normalize
    np.testing.assert_allclose(
        asnp(ct.map_overlap(ident, a, depth={-1: 1})), an
    )


def test_map_overlap_ragged_chunks(spec):
    an = np.random.default_rng(2).standard_normal((23, 17))
    a = ct.from_array(an, chunks=(7, 5), spec=spec)
    got = asnp(ct.map_overlap(smooth, a, depth=1))
    np.testing.assert_allclose(got, expected(an, "symmetric"), atol=1e-12)


def test_map_overlap_on_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(3).standard_normal((20, 20))
    a = ct.from_array(an, chunks=(5, 5), spec=spec)
    got = np.asarray(
        ct.map_overlap(smooth, a, depth=1).compute(executor=JaxExecutor())
    )
    np.testing.assert_allclose(got, expected(an, "symmetric"), atol=1e-10)


def test_map_overlap_1d_diffusion_step(spec):
    # heat-equation step: the canonical halo-exchange workload
    an = np.random.default_rng(4).standard_normal(1000)
    a = ct.from_array(an, chunks=(100,), spec=spec)

    def step(b):
        b = np.asarray(b)
        return b + 0.1 * (np.roll(b, 1) - 2 * b + np.roll(b, -1))

    got = asnp(ct.map_overlap(step, a, depth=1, boundary="periodic"))
    expect = an + 0.1 * (np.roll(an, 1) - 2 * an + np.roll(an, -1))
    np.testing.assert_allclose(got, expect, atol=1e-12)
