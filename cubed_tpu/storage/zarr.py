"""Lazy Zarr arrays: metadata creation deferred until the plan-wide
``create-arrays`` op runs. Reference parity: cubed/storage/zarr.py:8-103."""

from __future__ import annotations

from math import prod
from typing import Any, Optional, Sequence

import numpy as np

from ..chunks import blockdims_from_blockshape
from .store import ZarrV2Array, open_zarr_array


class LazyZarrArray:
    """A Zarr array template that has not yet been written to storage.

    Carries shape/dtype/chunks/store so plan construction is pure metadata;
    ``create()`` writes the store-level metadata and ``open()`` returns the
    concrete array (which must have been created first).
    """

    def __init__(
        self,
        store: str,
        shape: Sequence[int],
        dtype: Any,
        chunks: Sequence[int],
        fill_value: Any = None,
        storage_options: Optional[dict] = None,
    ):
        self.store = str(store)
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        self.chunks = tuple(int(c) for c in chunks)
        self.fill_value = fill_value
        self.storage_options = storage_options

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def size(self) -> int:
        return prod(self.shape) if self.shape else 1

    @property
    def nbytes(self) -> int:
        return self.size * self.dtype.itemsize

    def chunkset(self) -> tuple[tuple[int, ...], ...]:
        return blockdims_from_blockshape(self.shape, self.chunks)

    def create(self, mode: str = "w-") -> ZarrV2Array:
        """Write the array metadata to storage and return the open array.

        Uses append-like semantics ("a") during plan execution so resumed runs
        keep previously computed chunks (reference cubed/core/plan.py:430-432).
        """
        return open_zarr_array(
            self.store,
            mode="a" if mode in ("a", "w-") else mode,
            shape=self.shape,
            dtype=self.dtype,
            chunks=self.chunks,
            fill_value=self.fill_value,
            storage_options=self.storage_options,
        )

    def open(self) -> ZarrV2Array:
        return open_zarr_array(self.store, mode="r", storage_options=self.storage_options)

    def __repr__(self) -> str:
        return f"LazyZarrArray<{self.store}, shape={self.shape}, dtype={self.dtype}, chunks={self.chunks}>"


def lazy_empty(
    shape: Sequence[int], *, dtype: Any, chunks: Sequence[int], store: str, **kwargs
) -> LazyZarrArray:
    return LazyZarrArray(store, shape, dtype, chunks, **kwargs)


def lazy_full(
    shape: Sequence[int],
    fill_value: Any,
    *,
    dtype: Any,
    chunks: Sequence[int],
    store: str,
    **kwargs,
) -> LazyZarrArray:
    return LazyZarrArray(store, shape, dtype, chunks, fill_value=fill_value, **kwargs)


def open_if_lazy_zarr_array(array):
    """Resolve a LazyZarrArray to its concrete store; pass others through."""
    if isinstance(array, LazyZarrArray):
        return array.open()
    return array
