"""Array-API linear algebra. matmul/tensordot are blockwise contractions that
keep a size-1 contraction axis then sum over it — each per-block matmul is a
single MXU-shaped ``nxp.matmul``. Reference parity:
cubed/array_api/linear_algebra_functions.py (155 LoC)."""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import blockwise, reduction
from .data_type_functions import result_type
from .dtypes import _numeric_dtypes
from .manipulation_functions import expand_dims, permute_dims


def matmul(x1, x2, /):
    if x1.dtype not in _numeric_dtypes or x2.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in matmul")
    if x1.ndim == 0 or x2.ndim == 0:
        raise ValueError("matmul does not support 0-d arrays")

    x1_is_1d = x1.ndim == 1
    x2_is_1d = x2.ndim == 1
    if x1_is_1d:
        x1 = expand_dims(x1, axis=0)
    if x2_is_1d:
        x2 = expand_dims(x2, axis=x2.ndim)

    if x1.shape[-1] != x2.shape[-2]:
        raise ValueError("arrays must be aligned for matmul")

    dtype = result_type(x1, x2)

    out_ndim = max(x1.ndim, x2.ndim)
    # batch dims broadcast; use symbols: batch..., i, j, k(contracted->size1)
    nb = out_ndim - 2
    batch1 = tuple(range(nb - (x1.ndim - 2), nb))
    batch2 = tuple(range(nb - (x2.ndim - 2), nb))
    i_sym, j_sym, k_sym = nb, nb + 1, nb + 2

    x1_ind = batch1 + (i_sym, k_sym)
    x2_ind = batch2 + (k_sym, j_sym)
    out_ind = tuple(range(nb)) + (i_sym, k_sym, j_sym)  # keep k as size-1 axis

    # contraction temporaries beyond the generic model: the per-block
    # matmul result materializes before the (fusable) k-sum consumes it,
    # and the write path copies it once more — measured at ~2 output
    # blocks over the modelled working set (the measured-RSS suite caught
    # the task peaking ABOVE projected_mem without this); priced at 3
    # blocks so allocator jitter keeps a real margin (measured util 0.94)
    batch_chunk = 1
    for p in range(nb):
        c1 = x1.chunksize[x1.ndim - 3 - p] if x1.ndim - 3 - p >= 0 else 1
        c2 = x2.chunksize[x2.ndim - 3 - p] if x2.ndim - 3 - p >= 0 else 1
        batch_chunk *= max(c1, c2)
    out_block_elems = batch_chunk * x1.chunksize[-2] * x2.chunksize[-1]
    contraction_extra = 3 * out_block_elems * np.dtype(dtype).itemsize

    out = blockwise(
        _matmul_block,
        out_ind,
        x1,
        x1_ind,
        x2,
        x2_ind,
        dtype=dtype,
        adjust_chunks={k_sym: 1},
        extra_projected_mem=contraction_extra,
    )
    # sum over the contraction axis (the size-1-per-block k axis at position nb+1)
    out = _sum_contraction(out, axis=nb + 1)

    if x1_is_1d:
        out = _squeeze_axis(out, out.ndim - 2)
    if x2_is_1d:
        out = _squeeze_axis(out, out.ndim - 1)
    return out


def _squeeze_axis(x, ax):
    from .manipulation_functions import _squeeze_axes

    return _squeeze_axes(x, (ax % x.ndim,))


def _matmul_block(a, b):
    # per-block result is batch+(i, j); insert the size-1 contraction axis
    # between i and j to match out_ind = batch+(i, k, j)
    return nxp.expand_dims(nxp.matmul(a, b), axis=-2)


def _sum_contraction(x, axis):
    return reduction(
        x,
        _sum_keep,
        combine_func=_sum_keep,
        axis=axis,
        intermediate_dtype=x.dtype,
        dtype=x.dtype,
        keepdims=False,
    )


def _sum_keep(a, axis=None, keepdims=True, **kw):
    return nxp.sum(a, axis=axis, keepdims=keepdims)


def matrix_transpose(x, /):
    if x.ndim < 2:
        raise ValueError("x must be at least 2-dimensional")
    axes = tuple(range(x.ndim - 2)) + (x.ndim - 1, x.ndim - 2)
    return permute_dims(x, axes)


def outer(x1, x2, /):
    if x1.ndim != 1 or x2.ndim != 1:
        raise ValueError("outer requires 1-d arrays")
    dtype = result_type(x1, x2)
    return blockwise(
        _outer_block, (0, 1), x1, (0,), x2, (1,), dtype=dtype
    )


def _outer_block(a, b):
    return nxp.multiply(a[:, None], b[None, :])


def tensordot(x1, x2, /, *, axes=2):
    if x1.dtype not in _numeric_dtypes or x2.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in tensordot")
    if isinstance(axes, (int, np.integer)):
        axes = (tuple(range(x1.ndim - axes, x1.ndim)), tuple(range(axes)))
    ax1, ax2 = axes
    if isinstance(ax1, (int, np.integer)):
        ax1 = (ax1,)
    if isinstance(ax2, (int, np.integer)):
        ax2 = (ax2,)
    ax1 = tuple(a % x1.ndim for a in ax1)
    ax2 = tuple(a % x2.ndim for a in ax2)
    if len(ax1) != len(ax2):
        raise ValueError("tensordot axes must have the same length")

    dtype = result_type(x1, x2)

    # symbols: free1..., free2..., contracted...
    free1 = [d for d in range(x1.ndim) if d not in ax1]
    free2 = [d for d in range(x2.ndim) if d not in ax2]
    n_free1, n_free2, n_c = len(free1), len(free2), len(ax1)

    sym = iter(range(x1.ndim + x2.ndim))
    sym1 = {}
    out_syms_1 = []
    for d in free1:
        s = next(sym)
        sym1[d] = s
        out_syms_1.append(s)
    out_syms_2 = []
    sym2 = {}
    for d in free2:
        s = next(sym)
        sym2[d] = s
        out_syms_2.append(s)
    c_syms = []
    for a1, a2 in zip(ax1, ax2):
        s = next(sym)
        sym1[a1] = s
        sym2[a2] = s
        c_syms.append(s)

    x1_ind = tuple(sym1[d] for d in range(x1.ndim))
    x2_ind = tuple(sym2[d] for d in range(x2.ndim))
    # keep contracted axes as size-1 dims, then sum them away
    out_ind = tuple(out_syms_1) + tuple(c_syms) + tuple(out_syms_2)

    adjust = {s: 1 for s in c_syms}

    # same contraction-temporary pricing as matmul (see comment there)
    out_block_elems = 1
    for d in free1:
        out_block_elems *= x1.chunksize[d]
    for d in free2:
        out_block_elems *= x2.chunksize[d]
    contraction_extra = 3 * out_block_elems * np.dtype(dtype).itemsize

    out = blockwise(
        _TensordotBlock(ax1, ax2, n_free1, n_c, n_free2),
        out_ind,
        x1,
        x1_ind,
        x2,
        x2_ind,
        dtype=dtype,
        adjust_chunks=adjust,
        extra_projected_mem=contraction_extra,
    )
    for i in range(n_c):
        out = _sum_contraction(out, axis=n_free1)
    return out


class _TensordotBlock:
    __name__ = "tensordot_block"

    def __init__(self, ax1, ax2, n_free1, n_c, n_free2):
        self.ax1 = ax1
        self.ax2 = ax2
        self.n_free1 = n_free1
        self.n_c = n_c
        self.n_free2 = n_free2

    def __call__(self, a, b):
        out = nxp.tensordot(a, b, axes=(self.ax1, self.ax2))
        # insert size-1 contraction axes between free1 and free2 dims
        for i in range(self.n_c):
            out = nxp.expand_dims(out, axis=self.n_free1)
        return out


def vecdot(x1, x2, /, *, axis=-1):
    from .elementwise_functions import conj, multiply
    from .statistical_functions import sum as _sum

    return _sum(multiply(conj(x1) if np.dtype(x1.dtype).kind == "c" else x1, x2),
                axis=axis)
