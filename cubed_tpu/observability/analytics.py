"""Compute analytics: EXPLAIN for plans, ANALYZE for finished computes.

The paper's promise is a *predicted* bound (projected memory, task counts)
and the stack records rich *measured* reality (clock-aligned task spans,
chunk-graph edges, per-worker series). This module joins the two into the
questions an operator actually asks:

- **EXPLAIN** (:func:`explain`, ``plan.explain()``, ``python -m
  cubed_tpu.explain``) renders the finalized plan *before* execution:
  per-op task counts, projected memory against ``allowed_mem``, predicted
  bytes read/written (how many of those read bytes are peer-eligible —
  reads of intermediate arrays the p2p data plane can serve — and the
  predicted all-to-all shuffle volume of each rechunk stage when p2p is
  armed), the fusion outcome (ops before vs after optimization), and the
  scheduler/barrier decisions the dataflow scheduler would make
  (chunk-structured ops — blockwise AND rechunk — vs conservative
  op-level barriers, chunk-level edge count).

- **ANALYZE** (:func:`analyze`, ``python -m cubed_tpu.diagnose <bundle>
  --analyze``) consumes a flight-recorder bundle (or a live
  ``TraceCollector``) and answers "where did the wall clock go": it walks
  the **critical path** — the dependency-weighted chain of task spans that
  gated the compute's end — using the chunk-level edges the dataflow
  scheduler recorded (``ChunkGraph.edges_by_key``), falling back to the
  op-level dependency skeleton, and decomposes the wall clock into
  attribution buckets::

      kernel | storage_read | storage_write | peer_fetch | shuffle
      | retry | ready_wait | dispatch_overhead | queue_wait
      | straggler_excess | uninstrumented | other

  The decomposition is exact by construction (segments tile the
  ``[compute start, compute end]`` interval), so the buckets always sum to
  the measured wall clock. When a task carries a dispatch ledger (PR 16:
  per-task control-plane stamps on the task-stats channel), the
  pre-start gap splits into ``ready_wait`` (no worker capacity — real
  fleet backpressure) vs ``dispatch_overhead`` (the coordinator itself was
  busy serializing/sending — the scaling cliff); tasks without a ledger
  keep the whole gap in the legacy ``queue_wait`` bucket, so old traces
  analyze unchanged. The report also flags the top-k bottleneck
  tasks on the path and projected-vs-measured divergences (memory
  projections exceeded, wall-clock concentration far above an op's task
  share).

Per-tenant **cost accounting** (task-seconds, store/peer bytes, retry
draw) lives in ``service/service.py`` (``_CostTracker``) and surfaces as
the ``tenant_cost_*`` series family on ``/metrics``, the ``cost`` rows in
``stats_snapshot()``/``/snapshot.json``, and the ``cubed_tpu.top`` COST
panel — see docs/observability.md "Cost attribution & EXPLAIN/ANALYZE".
"""

from __future__ import annotations

import json
import logging
import os
import statistics
from typing import Any, Callable, Dict, List, Optional

from ..utils import memory_repr

logger = logging.getLogger(__name__)

#: sub-span name -> attribution bucket. ``integrity_verify`` folds into
#: ``storage_read`` (it is part of the verified read path);
#: ``retry_sleep``/``recompute_repair`` both count as retry overhead;
#: ``shuffle_fetch`` (peer fetches inside a rechunk task's exchange
#: window — whole-chunk or sub-chunk ranged) gets its own ``shuffle``
#: bucket so the all-to-all's data movement is visible as such instead of
#: blending into generic peer/storage time.
SPAN_BUCKETS = {
    "kernel_apply": "kernel",
    "storage_read": "storage_read",
    "integrity_verify": "storage_read",
    "storage_write": "storage_write",
    "peer_fetch": "peer_fetch",
    "shuffle_fetch": "shuffle",
    "retry_sleep": "retry",
    "recompute_repair": "retry",
    # brownout time: waiting for a breaker IO slot + paced in-place
    # throttle retries (storage/health.py) — kept out of storage_read/
    # write so "the store was slow" and "the store told us to slow down"
    # are distinguishable in the attribution
    "throttle_wait": "throttle_wait",
}

#: every attribution bucket, in render order. ``ready_wait`` /
#: ``dispatch_overhead`` are the ledger-informed split of a task's
#: pre-start gap; ``queue_wait`` remains the undifferentiated gap for
#: tasks that shipped no dispatch ledger (old traces, local executors
#: without stamps)
BUCKETS = (
    "kernel", "storage_read", "storage_write", "peer_fetch", "shuffle",
    "retry", "throttle_wait", "ready_wait", "dispatch_overhead",
    "queue_wait", "straggler_excess", "uninstrumented", "other",
)

#: tasks at or below this duration are resume/cache-satisfied zero-width
#: intervals (chunk-granular resume marks them done without running
#: anything): excluded from op medians and per-op busy statistics, where
#: a flood of zeros would drag the median to ~0 and flag every REAL task
#: a straggler (see tests/observability/test_analytics.py)
_ZERO_WIDTH_S = 1e-6

#: straggler thresholds (match TraceCollector's live-watch defaults)
STRAGGLER_FACTOR = 3.0
STRAGGLER_MIN_S = 0.05

#: plan-row ``peak_measured_mem`` is VmHWM — the WHOLE process footprint,
#: not per-task attribution — so a memory divergence is only flagged when
#: the projection itself clears this floor (same rationale as the
#: aggregator's ``_MEM_OVER_NOISE_FLOOR``); the guard-attributed per-task
#: numbers (``mem_over_projected``) carry their own floor already
MEM_DIVERGENCE_FLOOR = 64 * 1024 * 1024


def _fmt_mem(v) -> str:
    if not isinstance(v, (int, float)) or not v:
        return "-"
    return memory_repr(int(v))


def _save_json(path: str, data: dict) -> str:
    """Atomic JSON dump shared by both report types."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(data, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


# ----------------------------------------------------------------------
# EXPLAIN
# ----------------------------------------------------------------------


class ExplainReport:
    """A finalized plan rendered as predictions: what will run, how much
    memory it is allowed to take, which bytes move where. ``str()`` /
    :meth:`render` give the human view, :meth:`to_dict` the JSON one,
    :meth:`save`/:meth:`load` round-trip it for the
    ``python -m cubed_tpu.explain`` CLI."""

    def __init__(self, data: dict):
        self.data = data

    def to_dict(self) -> dict:
        return self.data

    def save(self, path: str) -> str:
        return _save_json(path, self.data)

    @classmethod
    def load(cls, path: str) -> "ExplainReport":
        with open(path) as f:
            return cls(json.load(f))

    def render(self) -> str:
        return render_explain(self.data)

    def __str__(self) -> str:
        return self.render()


def _op_source_arrays(dag, name: str, nodes: dict) -> list:
    """Array-node predecessors of an op (the arrays its tasks read)."""
    out = []
    for pred in dag.predecessors(name):
        d = nodes[pred]
        if d.get("type") == "array" and d.get("target") is not None:
            out.append((pred, d["target"]))
    return out


def _is_intermediate(dag, array_name: str, nodes: dict) -> bool:
    """True when the array is produced by a real op in this plan — the
    reads the p2p data plane can serve from worker chunk caches."""
    for producer in dag.predecessors(array_name):
        d = nodes[producer]
        if d.get("type") == "op" and d.get("primitive_op") is not None:
            return True
    return False


def explain_finalized(
    finalized, spec=None, ops_before: Optional[int] = None,
) -> ExplainReport:
    """Build an :class:`ExplainReport` from a ``FinalizedPlan``."""
    import networkx as nx

    from ..runtime.dataflow import build_chunk_graph, resolve_scheduler
    from ..runtime.pipeline import iter_op_nodes
    from ..runtime.transfer import resolve_peer_transfer

    dag = finalized.dag
    nodes = dict(dag.nodes(data=True))
    scheduler = resolve_scheduler(spec)
    peer = resolve_peer_transfer(spec)

    graph = None
    try:
        graph = build_chunk_graph(dag)
    except Exception:
        logger.exception("explain: chunk-graph construction failed")
    barrier_ops = set(graph.barrier_ops) if graph is not None else set()
    op_kinds = graph.op_kind if graph is not None else {}
    n_edges = (
        sum(len(d) for d in graph.dependencies.values())
        if graph is not None else None
    )
    try:
        from ..primitive.blockwise import apply_blockwise
    except Exception:  # pragma: no cover - blockwise always importable
        apply_blockwise = None

    rows: List[dict] = []
    total_read = total_written = total_peer = total_shuffle = 0
    for name in nx.topological_sort(dag):
        d = nodes[name]
        if d.get("type") != "op" or d.get("primitive_op") is None:
            continue
        op = d["primitive_op"]
        targets = op.target_arrays or (
            [op.target_array] if op.target_array is not None else []
        )
        bytes_written = sum(
            int(getattr(t, "nbytes", 0) or 0) for t in targets
        )
        bytes_read = peer_eligible = 0
        for arr_name, target in _op_source_arrays(dag, name, nodes):
            nbytes = int(getattr(target, "nbytes", 0) or 0)
            bytes_read += nbytes
            if _is_intermediate(dag, arr_name, nodes):
                peer_eligible += nbytes
        pipeline = op.pipeline
        # the chunk graph's own classification when it built (rechunk is
        # chunk-structured via its shuffle edges); the blockwise check is
        # only the degraded fallback for an unbuildable graph
        kind = op_kinds.get(name)
        if kind is not None:
            structured = kind != "barrier"
        else:
            structured = (
                pipeline is not None
                and apply_blockwise is not None
                and pipeline.function is apply_blockwise
            )
        #: predicted all-to-all exchange volume of a rechunk stage — its
        #: INTERMEDIATE source bytes, i.e. what the peer data plane can
        #: actually route worker-to-worker when armed (a first stage
        #: reading a client-written source array still reads the store,
        #: so counting it would fake a predicted-vs-measured gap)
        shuffle_bytes = (
            peer_eligible
            if peer and kind == "rechunk" else 0
        )
        rows.append({
            "op": name,
            "kind": d.get("op_name") or "",
            "tasks": op.num_tasks,
            "projected_mem": op.projected_mem,
            "allowed_mem": op.allowed_mem,
            "bytes_written": bytes_written,
            "bytes_read": bytes_read,
            "peer_eligible_bytes": peer_eligible if peer else 0,
            "shuffle_bytes": shuffle_bytes,
            "chunk_structured": structured,
            "barrier": name in barrier_ops,
        })
        total_read += bytes_read
        total_written += bytes_written
        total_shuffle += shuffle_bytes
        if peer:
            total_peer += peer_eligible
    n_ops = sum(1 for _ in iter_op_nodes(dag))
    # the create-arrays metadata bootstrap is injected at finalization, so
    # it must not read as "fusion added an op" in the before/after diff
    n_real_ops = sum(
        1 for name, _ in iter_op_nodes(dag) if name != "create-arrays"
    )

    allowed = getattr(spec, "allowed_mem", None)
    if allowed is None:
        allowed = max((r["allowed_mem"] for r in rows), default=0)
    data = {
        "kind": "explain",
        "scheduler": scheduler,
        "peer_transfer": bool(peer),
        "ops": rows,
        "totals": {
            "ops": n_ops,
            "arrays": finalized.num_arrays(),
            "tasks": finalized.num_tasks(),
            "max_projected_mem": finalized.max_projected_mem(),
            "allowed_mem": allowed,
            "bytes_written": total_written,
            "bytes_read": total_read,
            "peer_eligible_bytes": total_peer,
            "predicted_shuffle_bytes": total_shuffle,
        },
        "barriers": {
            "ops": sorted(barrier_ops),
            "chunk_edges": n_edges,
        },
        "fusion": (
            {"ops_before": ops_before, "ops_after": n_real_ops}
            if ops_before is not None else None
        ),
    }
    return ExplainReport(data)


def explain(
    plan, spec=None, optimize_graph: bool = True,
    optimize_function: Optional[Callable] = None,
    array_names: Optional[tuple] = None,
) -> ExplainReport:
    """EXPLAIN a :class:`~cubed_tpu.core.plan.Plan` (or an already
    finalized one): finalize it exactly like ``execute`` would and report
    the predictions — see the module docstring."""
    if hasattr(plan, "_finalize"):
        from ..runtime.pipeline import iter_op_nodes

        ops_before = sum(1 for _ in iter_op_nodes(plan.dag))
        finalized = plan._finalize(
            optimize_graph, optimize_function, array_names
        )
        return explain_finalized(finalized, spec=spec, ops_before=ops_before)
    return explain_finalized(plan, spec=spec)


def render_explain(data: dict) -> str:
    """The human EXPLAIN view (what the CLI prints)."""
    out: List[str] = []
    totals = data.get("totals") or {}
    out.append(
        f"EXPLAIN  {totals.get('ops', '?')} ops / "
        f"{totals.get('arrays', '?')} arrays / "
        f"{totals.get('tasks', '?')} tasks   scheduler="
        f"{data.get('scheduler')}  peer_transfer="
        f"{'on' if data.get('peer_transfer') else 'off'}"
    )
    proj = totals.get("max_projected_mem")
    allowed = totals.get("allowed_mem")
    frac = (
        f" ({proj / allowed:.0%} of allowed_mem)"
        if isinstance(proj, (int, float)) and allowed else ""
    )
    shuffle_total = totals.get("predicted_shuffle_bytes")
    out.append(
        f"projected mem {_fmt_mem(proj)} vs allowed {_fmt_mem(allowed)}"
        f"{frac}; predicted IO: read {_fmt_mem(totals.get('bytes_read'))}, "
        f"write {_fmt_mem(totals.get('bytes_written'))}, peer-eligible "
        f"{_fmt_mem(totals.get('peer_eligible_bytes'))}"
        + (
            f", shuffle {_fmt_mem(shuffle_total)}"
            if shuffle_total else ""
        )
    )
    fusion = data.get("fusion")
    if fusion and fusion.get("ops_before") is not None:
        before, after = fusion["ops_before"], fusion["ops_after"]
        out.append(
            f"fusion: {before} op(s) before optimization -> {after} after"
            + (
                f" ({before - after} fused away)"
                if isinstance(before, int) and isinstance(after, int)
                and before > after else ""
            )
        )
    barriers = data.get("barriers") or {}
    edges = barriers.get("chunk_edges")
    if edges is not None:
        bops = barriers.get("ops") or []
        out.append(
            f"dataflow: {edges} chunk-level edge(s); "
            + (
                f"{len(bops)} op-level barrier(s): {', '.join(bops[:6])}"
                + ("..." if len(bops) > 6 else "")
                if bops else "no op-level barriers"
            )
        )
    out.append("")
    out.append(
        f"{'OP':<30}{'KIND':<16}{'TASKS':>7}{'PROJ MEM':>11}"
        f"{'READ':>11}{'WRITE':>11}  SCHED"
    )
    for r in data.get("ops") or []:
        sched = "barrier" if r.get("barrier") else (
            "chunked" if r.get("chunk_structured") else "op-level"
        )
        out.append(
            f"{r.get('op', '?'):<30}{(r.get('kind') or ''):<16}"
            f"{r.get('tasks', 0):>7}{_fmt_mem(r.get('projected_mem')):>11}"
            f"{_fmt_mem(r.get('bytes_read')):>11}"
            f"{_fmt_mem(r.get('bytes_written')):>11}  {sched}"
        )
    return "\n".join(out) + "\n"


# ----------------------------------------------------------------------
# ANALYZE
# ----------------------------------------------------------------------


class AnalysisReport:
    """Post-compute wall-clock attribution + critical path. ``str()`` /
    :meth:`render` give the human view, :meth:`to_dict` the JSON one."""

    def __init__(self, data: dict):
        self.data = data

    def to_dict(self) -> dict:
        return self.data

    @property
    def wall_clock_s(self) -> Optional[float]:
        return self.data.get("wall_clock_s")

    @property
    def attribution(self) -> dict:
        return self.data.get("attribution") or {}

    @property
    def critical_path(self) -> list:
        return self.data.get("critical_path") or []

    @property
    def bottlenecks(self) -> list:
        return self.data.get("bottlenecks") or []

    def save(self, path: str) -> str:
        return _save_json(path, self.data)

    def render(self) -> str:
        return render_analysis(self.data)

    def __str__(self) -> str:
        return self.render()


def _trace_tables(trace: dict) -> tuple:
    """Parse a chrome trace into (tasks, spans, lanes, bounds).

    Timestamps come back in *seconds* on the trace's own (relative)
    timeline; ``bounds`` is the compute span when present, else the task
    envelope."""
    events = (trace or {}).get("traceEvents") or []
    lanes: Dict[int, str] = {}
    tasks: List[dict] = []
    spans: List[dict] = []
    compute_bounds = None
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            lanes[e.get("tid")] = (e.get("args") or {}).get("name")
            continue
        if e.get("ph") != "X" or e.get("dur") is None:
            continue
        args = e.get("args") or {}
        start = e["ts"] / 1e6
        end = start + e["dur"] / 1e6
        cat = e.get("cat")
        if cat == "compute":
            compute_bounds = (start, end)
        elif cat == "task":
            tasks.append({
                "op": e.get("name"),
                "chunk": args.get("chunk"),
                "start": start,
                "end": end,
                "dur": end - start,
                "tid": e.get("tid"),
                "attempt": args.get("attempt") or 0,
                "error": bool(args.get("error")),
                # the control-plane dispatch ledger, when one rode the
                # task event (collect.merged_tracer attaches it)
                "dispatch": args.get("dispatch"),
            })
        elif cat in (
            "storage", "kernel", "integrity", "retry", "transfer",
            "repair", "span",
        ):
            spans.append({
                "name": e.get("name"),
                "start": start,
                "end": end,
                "tid": e.get("tid"),
                "chunk": args.get("chunk_of_task"),
            })
    if compute_bounds is None and tasks:
        compute_bounds = (
            min(t["start"] for t in tasks), max(t["end"] for t in tasks)
        )
    return tasks, spans, lanes, compute_bounds


def _attach_spans(tasks: List[dict], spans: List[dict]) -> None:
    """Associate sub-spans with their task record: same lane (tid), the
    task's chunk key, and time containment (small epsilon for clock
    granularity). Each task gains a ``"spans"`` list."""
    eps = 2e-3
    index: Dict[tuple, List[dict]] = {}
    for t in tasks:
        t["spans"] = []
        index.setdefault((t["tid"], t["chunk"]), []).append(t)
    for s in spans:
        candidates = index.get((s["tid"], s["chunk"]))
        if not candidates:
            continue
        best = None
        for t in candidates:
            if s["start"] >= t["start"] - eps and s["end"] <= t["end"] + eps:
                if best is None or t["dur"] < best["dur"]:
                    best = t  # smallest containing task (retried chunks)
        if best is not None:
            best["spans"].append(s)


def _op_medians(tasks: List[dict]) -> Dict[str, float]:
    by_op: Dict[str, List[float]] = {}
    for t in tasks:
        if t["dur"] <= _ZERO_WIDTH_S:
            # resume/cache-satisfied zero-width interval: not a real
            # execution — letting it into the median would drag an op's
            # baseline toward zero and mark every genuine task a straggler
            continue
        by_op.setdefault(t["op"], []).append(t["dur"])
    return {
        op: statistics.median(durs) for op, durs in by_op.items() if durs
    }


def _is_straggler(t: dict, medians: Dict[str, float]) -> bool:
    median = medians.get(t["op"])
    if median is None:
        return False
    return t["dur"] > max(STRAGGLER_MIN_S, STRAGGLER_FACTOR * median)


def _interior_buckets(t: dict) -> Dict[str, float]:
    """A task's instrumented interior: seconds per bucket from its
    sub-spans, clipped so their total never exceeds the task duration."""
    out: Dict[str, float] = {}
    for s in t.get("spans") or []:
        bucket = SPAN_BUCKETS.get(s["name"])
        if bucket is None:
            continue
        out[bucket] = out.get(bucket, 0.0) + max(0.0, s["end"] - s["start"])
    total = sum(out.values())
    if total > t["dur"] > 0:
        scale = t["dur"] / total
        out = {k: v * scale for k, v in out.items()}
    return out


def _critical_path(
    tasks: List[dict],
    chunk_edges: Optional[dict],
    op_graph: Optional[dict],
) -> tuple:
    """Walk backwards from the last-finishing task through its gating
    dependencies. Returns ``(chain oldest-first, source)`` where source
    names which edge set drove the walk."""
    completed = [t for t in tasks if not t["error"]]
    if not completed:
        return [], "none"
    # one record per (op, chunk): the FIRST successful completion is the
    # one that released dependents
    by_key: Dict[str, dict] = {}
    for t in completed:
        key = f"{t['op']}\t{t['chunk']}"
        prev = by_key.get(key)
        if prev is None or t["end"] < prev["end"]:
            by_key[key] = t
    by_op: Dict[str, List[dict]] = {}
    for t in by_key.values():
        by_op.setdefault(t["op"], []).append(t)

    source = "heuristic"
    if chunk_edges:
        source = "chunk_graph"
    elif op_graph:
        source = "op_graph"

    def gate_of(t: dict) -> Optional[dict]:
        key = f"{t['op']}\t{t['chunk']}"
        if chunk_edges is not None and key in chunk_edges:
            deps = [
                by_key[k] for k in chunk_edges[key] if k in by_key
            ]
            if deps:
                return max(deps, key=lambda d: d["end"])
            return None  # a source task: the chain head
        if op_graph:
            preds = op_graph.get(t["op"]) or []
            deps = [d for p in preds for d in by_op.get(p, [])]
            if deps:
                return max(deps, key=lambda d: d["end"])
            if t["op"] in op_graph:
                return None  # known source op
        # heuristic: the latest task that finished before this one started
        candidates = [
            c for c in by_key.values()
            if c is not t and c["end"] <= t["start"] + 1e-9
        ]
        if not candidates:
            return None
        return max(candidates, key=lambda c: c["end"])

    last = max(by_key.values(), key=lambda t: t["end"])
    chain = [last]
    seen = {id(last)}
    cur = last
    while True:
        gate = gate_of(cur)
        if gate is None or id(gate) in seen:
            break
        chain.append(gate)
        seen.add(id(gate))
        cur = gate
    chain.reverse()
    return chain, source


def _decompose(
    chain: List[dict], bounds: tuple, medians: Dict[str, float],
) -> tuple:
    """Tile ``[t_start, t_end]`` with the chain's segments and attribute
    each to a bucket. Returns ``(attribution, path_rows)``; the buckets
    sum to the wall clock exactly (segments partition the interval)."""
    t_start, t_end = bounds
    attribution = {b: 0.0 for b in BUCKETS}
    rows: List[dict] = []
    cursor = t_start
    for t in chain:
        queue_wait = max(0.0, t["start"] - cursor)
        # ledger-informed split of the pre-start gap: the coordinator's
        # measured per-task cost (submit_cost_s wraps the whole inline
        # Coordinator.submit; serialize/send/lock-wait are its pieces) is
        # dispatch_overhead, the remainder is ready_wait — genuine fleet
        # backpressure. No ledger -> the whole gap stays queue_wait.
        disp = t.get("dispatch") or None
        dispatch_cost = None
        if disp:
            dispatch_cost = disp.get("submit_cost_s")
            if dispatch_cost is None:
                parts = [
                    disp.get(k)
                    for k in ("serialize_s", "send_s", "lock_wait_s")
                ]
                parts = [
                    p for p in parts if isinstance(p, (int, float))
                ]
                dispatch_cost = sum(parts) if parts else None
        if dispatch_cost is not None:
            dispatch_overhead = min(queue_wait, max(0.0, dispatch_cost))
            ready_wait = queue_wait - dispatch_overhead
            attribution["dispatch_overhead"] += dispatch_overhead
            attribution["ready_wait"] += ready_wait
        else:
            dispatch_overhead = ready_wait = None
            attribution["queue_wait"] += queue_wait
        eff_start = max(t["start"], cursor)
        counted = max(0.0, min(t["end"], t_end) - eff_start)
        scale = (counted / t["dur"]) if t["dur"] > 0 else 0.0
        interior = {
            k: v * scale for k, v in _interior_buckets(t).items()
        }
        uninstrumented = max(0.0, counted - sum(interior.values()))
        buckets = dict(interior)
        buckets["uninstrumented"] = uninstrumented
        straggler = _is_straggler(t, medians)
        excess = 0.0
        if straggler:
            median = medians.get(t["op"]) or 0.0
            excess = min(counted, max(0.0, t["dur"] - median) * scale)
            # carve the excess out of the largest interior buckets — for a
            # sleeping/overloaded task that time sits inside kernel_apply
            # (or uninstrumented), and reporting it as normal kernel time
            # would hide exactly the signal ANALYZE exists to surface
            remaining = excess
            for k in sorted(buckets, key=lambda k: -buckets[k]):
                take = min(buckets[k], remaining)
                buckets[k] -= take
                remaining -= take
                if remaining <= 1e-12:
                    break
            buckets["straggler_excess"] = excess - remaining
        for k, v in buckets.items():
            attribution[k] = attribution.get(k, 0.0) + v
        row = {
            "op": t["op"],
            "chunk": t["chunk"],
            "worker": t.get("worker"),
            "start_s": round(t["start"] - t_start, 6),
            "duration_s": round(t["dur"], 6),
            # queue_wait_s is always the FULL pre-start gap (bottleneck
            # ranking keys on it regardless of whether a ledger split it)
            "queue_wait_s": round(queue_wait, 6),
            "straggler": straggler,
            "straggler_excess_s": round(excess, 6) if straggler else 0.0,
            "buckets": {k: round(v, 6) for k, v in buckets.items() if v},
        }
        if dispatch_overhead is not None:
            row["dispatch_overhead_s"] = round(dispatch_overhead, 6)
            row["ready_wait_s"] = round(ready_wait, 6)
        rows.append(row)
        cursor = max(cursor, t["end"])
    attribution["other"] += max(0.0, t_end - cursor)
    return {k: round(v, 6) for k, v in attribution.items()}, rows


def _per_op_rows(
    tasks: List[dict], medians: Dict[str, float], manifest: dict,
) -> Dict[str, dict]:
    """Busy-time attribution over ALL completed tasks, per op (the
    whole-fleet view beside the critical path's wall-clock view)."""
    per_op: Dict[str, dict] = {}
    op_wall = manifest.get("op_wall_clock") or {}
    for t in tasks:
        if t["error"] or t["dur"] <= _ZERO_WIDTH_S:
            # zero-width (resume-satisfied) intervals carry no busy time
            # and no spans: keep them out of the bucket statistics
            continue
        row = per_op.setdefault(t["op"], {
            "tasks": 0, "busy_s": 0.0, "stragglers": 0,
            "buckets": {},
        })
        row["tasks"] += 1
        row["busy_s"] += t["dur"]
        if _is_straggler(t, medians):
            row["stragglers"] += 1
        for k, v in _interior_buckets(t).items():
            row["buckets"][k] = row["buckets"].get(k, 0.0) + v
    for op, row in per_op.items():
        interior = sum(row["buckets"].values())
        row["buckets"]["uninstrumented"] = max(
            0.0, row["busy_s"] - interior
        )
        row["buckets"] = {
            k: round(v, 6) for k, v in row["buckets"].items() if v
        }
        row["busy_s"] = round(row["busy_s"], 6)
        row["wall_clock_s"] = op_wall.get(op)
    return per_op


def _divergences(
    manifest: dict, per_op: Dict[str, dict], explain_data: Optional[dict],
) -> List[dict]:
    """Projected-vs-measured gaps worth a look."""
    out: List[dict] = []
    stats = manifest.get("executor_stats") or {}
    stats_per_op = stats.get("per_op") or {}
    for row in manifest.get("plan") or []:
        name = row.get("array_name")
        util = row.get("projected_mem_utilization")
        projected = row.get("projected_mem") or 0
        if (
            isinstance(util, (int, float)) and util > 1.0
            and projected > MEM_DIVERGENCE_FLOOR
        ):
            out.append({
                "op": name,
                "kind": "memory",
                "note": (
                    f"measured peak {_fmt_mem(row.get('peak_measured_mem'))}"
                    f" exceeded projection "
                    f"{_fmt_mem(row.get('projected_mem'))} "
                    f"({util:.0%} utilization)"
                ),
            })
    for name, row in stats_per_op.items():
        if row.get("mem_over_projected"):
            out.append({
                "op": name,
                "kind": "memory",
                "note": (
                    f"guard-attributed peak "
                    f"{_fmt_mem(row.get('guard_peak_mem'))} over projection "
                    f"{_fmt_mem(row.get('projected_mem'))}"
                ),
            })
    total_busy = sum(r["busy_s"] for r in per_op.values()) or 0.0
    total_tasks = sum(r["tasks"] for r in per_op.values()) or 0
    if total_busy and total_tasks:
        for name, row in per_op.items():
            busy_share = row["busy_s"] / total_busy
            task_share = row["tasks"] / total_tasks
            if busy_share > 2.0 * task_share and row["busy_s"] > 0.5:
                out.append({
                    "op": name,
                    "kind": "wall_clock",
                    "note": (
                        f"{busy_share:.0%} of busy time from "
                        f"{task_share:.0%} of tasks"
                        + (
                            f" ({row['stragglers']} straggler(s))"
                            if row["stragglers"] else ""
                        )
                    ),
                })
    if explain_data:
        predicted = {
            r["op"]: r for r in (explain_data.get("ops") or [])
        }
        for name, row in stats_per_op.items():
            pred = predicted.get(name)
            if not pred:
                continue
            pb, mb = pred.get("bytes_written"), row.get("bytes_written")
            if pb and mb and (mb > 2 * pb or mb * 2 < pb):
                out.append({
                    "op": name,
                    "kind": "bytes",
                    "note": (
                        f"measured write {_fmt_mem(mb)} vs predicted "
                        f"{_fmt_mem(pb)}"
                    ),
                })
    return out


def _looks_like_bundle(obj: Any) -> bool:
    return isinstance(obj, dict) and "manifest" in obj


def _collector_bundle(collector) -> dict:
    """An in-memory bundle from a live ``TraceCollector`` (or subclass):
    ANALYZE without ever touching disk."""
    if hasattr(collector, "manifest"):
        manifest = collector.manifest()
    else:
        from .collect import decisions_since

        manifest = {
            "compute_id": collector.compute_id,
            "status": (
                "failed" if collector.error is not None else "succeeded"
            ),
            "wall_clock_s": (
                collector.end_tstamp - collector.start_tstamp
                if collector.end_tstamp and collector.start_tstamp
                else None
            ),
            "op_wall_clock": {
                name: t.wall_clock
                for name, t in collector.op_timings.items()
            },
            "plan": collector.projected_vs_measured(),
            "executor_stats": collector.executor_stats,
            "stragglers": collector.stragglers(),
            "op_graph": collector.op_graph(),
            "chunk_graph": collector.chunk_graph(),
            "decisions": decisions_since(collector._t0),
        }
    return {
        "manifest": manifest,
        "trace": {
            "traceEvents": collector.merged_tracer().chrome_events()
        },
    }


def _resolve_target(target, bundle_dir: Optional[str]) -> dict:
    """Turn any accepted ``analyze`` target into a bundle dict."""
    from .flightrecorder import FLIGHT_RECORDER_ENV_VAR, load_bundle

    if _looks_like_bundle(target):
        return target
    if hasattr(target, "merged_tracer"):
        return _collector_bundle(target)
    if isinstance(target, str):
        if os.path.exists(target):
            return load_bundle(target)
        # a compute id: find its bundle under bundle_dir / the operator's
        # flight-recorder dir / the conventional default
        for base in (
            bundle_dir,
            os.environ.get(FLIGHT_RECORDER_ENV_VAR),
            "flight-recorder",
        ):
            if not base:
                continue
            candidate = os.path.join(base, f"bundle-{target}")
            if os.path.exists(candidate):
                return load_bundle(candidate)
        raise FileNotFoundError(
            f"no bundle found for {target!r} (looked for a path and for "
            f"bundle-{target} under the flight-recorder directories)"
        )
    raise TypeError(
        f"analyze() expects a bundle dir/path, a compute id, a loaded "
        f"bundle dict, or a TraceCollector — got {type(target).__name__}"
    )


def analyze(
    target,
    bundle_dir: Optional[str] = None,
    explain_report: Optional[ExplainReport] = None,
    top_k: int = 5,
    baseline=None,
) -> AnalysisReport:
    """ANALYZE a finished compute: critical path + wall-clock attribution.

    ``target`` may be a flight-recorder bundle directory (or its
    ``manifest.json``), a compute id (searched under ``bundle_dir``, the
    ``CUBED_TPU_FLIGHT_RECORDER`` directory, then ``./flight-recorder``),
    an already-loaded bundle dict, or a live
    :class:`~cubed_tpu.observability.collect.TraceCollector` /
    ``FlightRecorder``. Pass the plan's :class:`ExplainReport` as
    ``explain_report`` to also diff predicted bytes against measured.

    ``baseline`` (a run-history compute record from
    :func:`~cubed_tpu.observability.runhistory.load_runs` /
    ``find_baseline``, or a prior :class:`AnalysisReport` / its data
    dict) adds a ``regression`` section: the bucket-by-bucket and per-op
    diff against that earlier run of the same plan
    (:func:`regression_diff`).
    """
    bundle = _resolve_target(target, bundle_dir)
    manifest = bundle.get("manifest") or {}
    trace = bundle.get("trace")
    if not trace or not (trace.get("traceEvents") or []):
        raise ValueError(
            "bundle has no trace (trace.json missing or empty) — ANALYZE "
            "needs the merged task spans; attach a TraceCollector or "
            "FlightRecorder to the compute"
        )
    tasks, spans, lanes, bounds = _trace_tables(trace)
    if not tasks or bounds is None:
        raise ValueError("trace contains no task spans to analyze")
    for t in tasks:
        lane = lanes.get(t["tid"]) or ""
        t["worker"] = lane.replace("worker ", "") if lane.startswith(
            "worker "
        ) else None
    _attach_spans(tasks, spans)
    medians = _op_medians([t for t in tasks if not t["error"]])

    chunk_edges = manifest.get("chunk_graph") or None
    op_graph = manifest.get("op_graph") or None
    chain, source = _critical_path(tasks, chunk_edges, op_graph)
    attribution, path_rows = _decompose(chain, bounds, medians)
    wall = bounds[1] - bounds[0]
    covered = sum(attribution.values())
    per_op = _per_op_rows(tasks, medians, manifest)
    bottlenecks = sorted(
        path_rows,
        key=lambda r: -(r["queue_wait_s"] + r["duration_s"]),
    )[:top_k]

    data = {
        "kind": "analysis",
        "compute_id": manifest.get("compute_id"),
        "status": manifest.get("status"),
        "wall_clock_s": round(wall, 6),
        "attribution": attribution,
        "attribution_coverage": round(covered / wall, 4) if wall else None,
        "critical_path": path_rows,
        "critical_path_source": source,
        "bottlenecks": bottlenecks,
        "per_op": per_op,
        "divergences": _divergences(
            manifest, per_op,
            explain_report.to_dict() if explain_report else None,
        ),
        "stragglers": manifest.get("stragglers") or [],
        "tasks_analyzed": len(tasks),
    }
    if baseline is not None:
        data["regression"] = regression_diff(baseline, data)
    return AnalysisReport(data)


# ----------------------------------------------------------------------
# cross-run regression attribution
# ----------------------------------------------------------------------

#: a run is only called regressed when it is at least this much slower
#: than its baseline — sub-10% wall-clock wiggle is scheduling noise on
#: small computes, not a regression worth naming
REGRESSION_RATIO = 1.10


def _normalize_run(obj) -> Dict[str, Any]:
    """One shape for both comparands: ``{compute_id, ts, wall_clock_s,
    buckets, per_op}``. Accepts a run-history compute record (``buckets``
    / ``per_op`` keys), an :class:`AnalysisReport`, or its data dict
    (``attribution`` / ``per_op`` keys)."""
    if isinstance(obj, AnalysisReport):
        obj = obj.to_dict()
    if not isinstance(obj, dict):
        raise TypeError(
            "regression comparand must be a run-history record, an "
            f"AnalysisReport, or its data dict — got {type(obj).__name__}"
        )
    buckets = obj.get("buckets")
    if buckets is None:
        buckets = obj.get("attribution") or {}
    per_op = {}
    for name, row in (obj.get("per_op") or {}).items():
        if isinstance(row, dict):
            per_op[name] = {
                "busy_s": float(row.get("busy_s") or 0.0),
                "buckets": {
                    k: float(v)
                    for k, v in (row.get("buckets") or {}).items()
                    if isinstance(v, (int, float))
                },
            }
    return {
        "compute_id": obj.get("compute_id"),
        "ts": obj.get("ts"),
        "wall_clock_s": obj.get("wall_clock_s"),
        "buckets": {
            k: float(v) for k, v in buckets.items()
            if isinstance(v, (int, float))
        },
        "per_op": per_op,
        "stragglers": obj.get("stragglers") or [],
    }


def regression_diff(baseline, current) -> Dict[str, Any]:
    """Name what got slower: the bucket-by-bucket / per-op diff between
    two runs of the same plan.

    Both arguments go through :func:`_normalize_run` (archive records
    and live ``analyze()`` data are interchangeable). Each bucket/op row
    carries its absolute delta and its share of the total slowdown;
    ``culprits`` ranks the buckets that account for the wall-clock
    growth, and worker names ride along from the current run's straggler
    digest so "which bucket" can often be narrowed to "which worker"."""
    base = _normalize_run(baseline)
    cur = _normalize_run(current)
    base_wall = base.get("wall_clock_s")
    cur_wall = cur.get("wall_clock_s")
    delta_wall = (
        cur_wall - base_wall
        if isinstance(base_wall, (int, float))
        and isinstance(cur_wall, (int, float)) else None
    )
    ratio = (
        cur_wall / base_wall
        if isinstance(delta_wall, (int, float)) and base_wall else None
    )

    bucket_rows = []
    names = [b for b in BUCKETS if b in base["buckets"] or b in cur["buckets"]]
    names += sorted(
        (set(base["buckets"]) | set(cur["buckets"])) - set(names)
    )
    slowdown = delta_wall if isinstance(delta_wall, (int, float)) else None
    for name in names:
        b = base["buckets"].get(name, 0.0)
        c = cur["buckets"].get(name, 0.0)
        d = c - b
        row = {
            "bucket": name,
            "baseline_s": round(b, 6),
            "current_s": round(c, 6),
            "delta_s": round(d, 6),
        }
        if slowdown and slowdown > 0 and d > 0:
            row["share_of_slowdown"] = round(min(d / slowdown, 1.0), 4)
        bucket_rows.append(row)
    bucket_rows.sort(key=lambda r: -r["delta_s"])

    op_rows = []
    for name in set(base["per_op"]) | set(cur["per_op"]):
        b = base["per_op"].get(name, {"busy_s": 0.0, "buckets": {}})
        c = cur["per_op"].get(name, {"busy_s": 0.0, "buckets": {}})
        d = c["busy_s"] - b["busy_s"]
        deltas = {
            k: c["buckets"].get(k, 0.0) - b["buckets"].get(k, 0.0)
            for k in set(b["buckets"]) | set(c["buckets"])
        }
        grew = max(deltas.items(), key=lambda kv: kv[1])[0] if deltas else None
        op_rows.append({
            "op": name,
            "baseline_busy_s": round(b["busy_s"], 6),
            "current_busy_s": round(c["busy_s"], 6),
            "delta_s": round(d, 6),
            "grew_bucket": grew if deltas and deltas[grew] > 1e-6 else None,
        })
    op_rows.sort(key=lambda r: -r["delta_s"])

    culprits = [
        r["bucket"] for r in bucket_rows
        if r["delta_s"] > 1e-6 and (
            slowdown is None or slowdown <= 0
            or r["delta_s"] >= 0.05 * slowdown
        )
    ][:3]
    workers = sorted({
        s.get("worker") for s in cur["stragglers"]
        if isinstance(s, dict) and s.get("worker")
    })
    return {
        "baseline_compute_id": base.get("compute_id"),
        "baseline_ts": base.get("ts"),
        "current_compute_id": cur.get("compute_id"),
        "wall_clock": {
            "baseline_s": base_wall,
            "current_s": cur_wall,
            "delta_s": (
                round(delta_wall, 6)
                if isinstance(delta_wall, (int, float)) else None
            ),
            "ratio": round(ratio, 4) if ratio is not None else None,
        },
        "regressed": bool(ratio is not None and ratio >= REGRESSION_RATIO),
        "buckets": bucket_rows,
        "ops": op_rows,
        "culprits": culprits,
        "straggler_workers": workers,
    }


def render_regression(reg: dict) -> str:
    """The human regression view (``python -m cubed_tpu.regress`` and
    ``diagnose --analyze`` print this)."""
    out: List[str] = []
    wc = reg.get("wall_clock") or {}
    ratio = wc.get("ratio")
    verdict = (
        "REGRESSED" if reg.get("regressed")
        else "no regression" if ratio is not None else "incomparable"
    )
    out.append(
        f"REGRESSION  {reg.get('current_compute_id')} vs baseline "
        f"{reg.get('baseline_compute_id')}  [{verdict}]"
    )
    b, c = wc.get("baseline_s"), wc.get("current_s")
    if isinstance(b, (int, float)) and isinstance(c, (int, float)):
        out.append(
            f"  wall clock {b:.3f}s -> {c:.3f}s  "
            f"({'+' if c >= b else ''}{c - b:.3f}s, "
            f"{ratio:.2f}x)" if ratio is not None
            else f"  wall clock {b:.3f}s -> {c:.3f}s"
        )
    rows = [
        r for r in (reg.get("buckets") or []) if abs(r["delta_s"]) > 1e-6
    ]
    if rows:
        out.append("  bucket deltas (current - baseline):")
        for r in rows[:8]:
            share = r.get("share_of_slowdown")
            share_s = f"  {share:>5.0%} of slowdown" if share else ""
            out.append(
                f"    {r['bucket']:<18}{r['baseline_s']:>9.3f}s ->"
                f"{r['current_s']:>9.3f}s  "
                f"{'+' if r['delta_s'] >= 0 else ''}"
                f"{r['delta_s']:.3f}s{share_s}"
            )
    culprits = reg.get("culprits") or []
    if culprits:
        out.append(f"  culprit bucket(s): {', '.join(culprits)}")
    ops = [
        r for r in (reg.get("ops") or []) if abs(r["delta_s"]) > 1e-6
    ]
    if ops:
        out.append("  op deltas (busy time):")
        for r in ops[:6]:
            grew = f"  [{r['grew_bucket']}]" if r.get("grew_bucket") else ""
            out.append(
                f"    {r['op']:<28}{r['baseline_busy_s']:>9.3f}s ->"
                f"{r['current_busy_s']:>9.3f}s  "
                f"{'+' if r['delta_s'] >= 0 else ''}"
                f"{r['delta_s']:.3f}s{grew}"
            )
    workers = reg.get("straggler_workers") or []
    if workers:
        out.append(f"  straggling worker(s): {', '.join(map(str, workers))}")
    return "\n".join(out) + "\n"


def render_analysis(data: dict, path_limit: int = 12) -> str:
    """The human ANALYZE view (``diagnose --analyze`` prints this)."""
    out: List[str] = []
    wall = data.get("wall_clock_s")
    out.append(
        f"ANALYZE  compute {data.get('compute_id')}  "
        f"[{data.get('status')}]  wall clock "
        f"{wall:.3f}s" if isinstance(wall, (int, float))
        else f"ANALYZE  compute {data.get('compute_id')}"
    )
    attribution = data.get("attribution") or {}
    if attribution and isinstance(wall, (int, float)) and wall:
        out.append("")
        out.append("wall-clock attribution (critical-path decomposition):")
        for bucket in BUCKETS:
            v = attribution.get(bucket) or 0.0
            if v < 1e-6:
                continue
            bar = "#" * max(1, int(round(30 * v / wall)))
            out.append(
                f"  {bucket:<18}{v:>9.3f}s {v / wall:>5.0%}  {bar}"
            )
    path = data.get("critical_path") or []
    if path:
        out.append("")
        out.append(
            f"critical path ({len(path)} task(s), source="
            f"{data.get('critical_path_source')}):"
        )
        shown = path if len(path) <= path_limit else (
            path[: path_limit // 2] + [None] + path[-path_limit // 2:]
        )
        for r in shown:
            if r is None:
                out.append(f"  ... {len(path) - path_limit} more ...")
                continue
            flag = "  STRAGGLER" if r.get("straggler") else ""
            out.append(
                f"  +{r['start_s']:8.3f}s {r['op']:<28} "
                f"chunk={str(r.get('chunk'))[:28]:<30} "
                f"wait {r['queue_wait_s']:6.3f}s  run "
                f"{r['duration_s']:6.3f}s{flag}"
            )
    bottlenecks = data.get("bottlenecks") or []
    if bottlenecks:
        out.append("")
        out.append("top bottleneck tasks (path contribution):")
        for r in bottlenecks:
            contrib = r["queue_wait_s"] + r["duration_s"]
            out.append(
                f"  {r['op']:<28} chunk={str(r.get('chunk'))[:28]:<30} "
                f"{contrib:6.3f}s"
                + (" STRAGGLER" if r.get("straggler") else "")
            )
    per_op = data.get("per_op") or {}
    if per_op:
        out.append("")
        out.append("per-op busy-time attribution (all workers):")
        ranked = sorted(
            per_op.items(), key=lambda kv: -kv[1]["busy_s"]
        )
        for name, row in ranked[:10]:
            top = sorted(
                row["buckets"].items(), key=lambda kv: -kv[1]
            )[:3]
            top_s = ", ".join(f"{k} {v:.3f}s" for k, v in top)
            out.append(
                f"  {name:<28} tasks={row['tasks']:<6} busy "
                f"{row['busy_s']:8.3f}s  [{top_s}]"
            )
    divergences = data.get("divergences") or []
    if divergences:
        out.append("")
        out.append("projected-vs-measured divergences:")
        for d in divergences:
            out.append(f"  [{d.get('kind')}] {d.get('op')}: {d.get('note')}")
    reg = data.get("regression")
    if reg:
        out.append("")
        out.append(render_regression(reg).rstrip("\n"))
    return "\n".join(out) + "\n"
