"""Device object for the Array API surface.

The plan executes on whatever the Spec's executor targets (CPU oracle or the
TPU mesh); the API-level device is a single logical placeholder, like the
reference's ``device='cpu'`` (cubed/array_api/array_object.py).
"""


class Device:
    def __init__(self, name: str):
        self.name = name

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other) -> bool:
        return isinstance(other, Device) and other.name == self.name or other == self.name

    def __hash__(self):
        return hash(self.name)


device = Device("cubed-tpu")
