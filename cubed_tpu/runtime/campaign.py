"""Composed-failure chaos campaigns: seeded schedules, shrinking, repros.

The chaos suites prove each failure domain in isolation; real incidents
happen at domain *intersections*. This module turns the existing
:class:`~cubed_tpu.runtime.faults.FaultConfig` knobs plus lifecycle
events into one declarative, seeded :class:`FaultSchedule`, runs it over
a small workload matrix, and verifies the outcome twice: bitwise output
equality AND a clean :class:`~cubed_tpu.runtime.audit.InvariantAuditor`
report over the run's durable artifacts. When a schedule fails either
check, :class:`CampaignRunner.shrink` reduces it to a minimal reproducing
subset (greedy delta-debugging over fault atoms) and writes a replayable
repro file:

    python -m cubed_tpu.chaos --seed 7          # one generated schedule
    python -m cubed_tpu.chaos --campaign 25     # seeded soak over seeds
    python -m cubed_tpu.chaos --repro repro-7.json   # replay a repro

Determinism: the injector hashes ``seed:site:key:n`` where chunk keys
embed gensym'd plan names, so each run pins the process-global sym
counter (the established bench/brownout idiom) — the same schedule rolls
the same decisions every run, which is what makes both the tier-1
fixed-seed proof and repro replay meaningful.

Two execution modes:

- **in-process** (default): threaded or in-process-fleet executors.
  Schedules must not contain *process faults* (coordinator SIGKILL /
  client SIGKILL) — those hard-exit the calling process by design.
  ``generate()`` therefore only emits them when
  ``allow_process_faults=True``.
- **subprocess** (``--campaign`` soak / process-fault schedules): the
  compute runs in a child interpreter (the test_failover harness shape),
  the parent kills/adopts per the schedule's events, and the auditor
  runs over the artifacts the child left behind.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import random
import shutil
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

from .audit import AuditReport, InvariantAuditor

#: knobs that only make sense together form one shrink "atom": removing a
#: rate without its companion duration/names would leave dead weight and
#: make "minimal" ambiguous. seed is never an atom (always kept).
KNOB_ATOMS = (
    ("storage_read_failure_rate",),
    ("storage_write_failure_rate", "storage_write_leaves_tmp"),
    ("storage_throttle_rate",),
    ("storage_corrupt_rate",),
    ("task_failure_rate",),
    ("task_fatal_rate",),
    ("task_fatal_chunk_keys",),
    ("straggler_rate", "straggler_delay_s"),
    ("task_mem_spike_rate", "task_mem_spike_bytes"),
    ("worker_crash_names", "worker_crash_after_tasks"),
    ("worker_hang_names", "worker_hang_after_tasks", "worker_hang_s"),
    ("worker_preempt_rate", "worker_preempt_after_tasks",
     "preempt_notice_s"),
    ("net_msg_drop_rate",),
    ("net_msg_dup_rate",),
    ("net_msg_delay_rate", "net_msg_delay_s"),
    ("net_reset_rate",),
    ("partition_worker_names", "partition_after_tasks",
     "partition_duration_s", "partition_direction"),
    ("peer_drop_rate",),
    ("peer_delay_rate", "peer_delay_s"),
    ("peer_corrupt_rate",),
    ("peer_reset_rate",),
    ("coordinator_crash_after_dispatches",),
    ("coordinator_takeover_crash_after_dispatches",),
)

#: knob -> failure domain, for the ≥3-domains-composed acceptance check
#: and for generate()'s domain sampling
KNOB_DOMAINS = {
    "storage_read_failure_rate": "storage",
    "storage_write_failure_rate": "storage",
    "storage_write_leaves_tmp": "storage",
    "storage_throttle_rate": "storage",
    "storage_corrupt_rate": "integrity",
    "task_failure_rate": "task",
    # poison-task knobs: the WORKLOAD is the fault (a request whose chunks
    # kill their worker every attempt). Deliberately absent from
    # _DOMAIN_TEMPLATES — a generated campaign expects bitwise success,
    # and a poison chunk is *supposed* to fail (with PoisonTaskError);
    # explicit schedules and tests/service/test_overload.py exercise it
    "task_fatal_rate": "workload",
    "task_fatal_chunk_keys": "workload",
    "straggler_rate": "task",
    "straggler_delay_s": "task",
    "task_mem_spike_rate": "memory",
    "task_mem_spike_bytes": "memory",
    "worker_crash_names": "worker_loss",
    "worker_crash_after_tasks": "worker_loss",
    "worker_hang_names": "worker_loss",
    "worker_hang_after_tasks": "worker_loss",
    "worker_hang_s": "worker_loss",
    "worker_preempt_rate": "elasticity",
    "worker_preempt_after_tasks": "elasticity",
    "preempt_notice_s": "elasticity",
    "net_msg_drop_rate": "partition",
    "net_msg_dup_rate": "partition",
    "net_msg_delay_rate": "partition",
    "net_msg_delay_s": "partition",
    "net_reset_rate": "partition",
    "partition_worker_names": "partition",
    "partition_after_tasks": "partition",
    "partition_duration_s": "partition",
    "partition_direction": "partition",
    "peer_drop_rate": "partition",
    "peer_delay_rate": "partition",
    "peer_delay_s": "partition",
    "peer_corrupt_rate": "partition",
    "peer_reset_rate": "partition",
    "coordinator_crash_after_dispatches": "coordinator",
    "coordinator_takeover_crash_after_dispatches": "coordinator",
}

EVENT_DOMAINS = {
    "cancel": "cancellation",
    "client_kill": "client_loss",
}

#: fleet-side knobs force the distributed in-process fleet (the threaded
#: executor has no workers to crash, partition, or preempt — and a
#: poison task kills a WORKER process, so "workload" is fleet-side too)
FLEET_KNOBS = frozenset(
    k for k, d in KNOB_DOMAINS.items()
    if d in ("worker_loss", "elasticity", "partition", "coordinator",
             "workload")
)

#: knobs/events that hard-exit the CURRENT process (coordinator crash
#: injection calls os._exit; client_kill SIGKILLs the driver) — only
#: legal in subprocess mode
PROCESS_FAULT_KNOBS = frozenset({
    "coordinator_crash_after_dispatches",
    "coordinator_takeover_crash_after_dispatches",
})
PROCESS_FAULT_EVENTS = frozenset({"client_kill"})


@dataclass
class FaultSchedule:
    """One declarative, seeded timeline of composed faults.

    ``faults`` is a plain FaultConfig-knob dict (validated on run via
    ``FaultConfig.from_dict`` — unknown knobs are a schedule bug, not a
    silent no-op); ``events`` are lifecycle actions the runner itself
    performs (``{"kind": "cancel", "after_completes": n}``,
    ``{"kind": "client_kill", "after_completes": n}``)."""

    seed: int
    workload: str
    faults: dict = field(default_factory=dict)
    events: list = field(default_factory=list)

    @property
    def domains(self) -> set:
        out = {
            KNOB_DOMAINS[k] for k in self.faults
            if k in KNOB_DOMAINS
        }
        out |= {
            EVENT_DOMAINS[e.get("kind")] for e in self.events
            if e.get("kind") in EVENT_DOMAINS
        }
        return out

    @property
    def needs_subprocess(self) -> bool:
        return bool(PROCESS_FAULT_KNOBS & set(self.faults)) or any(
            e.get("kind") in PROCESS_FAULT_EVENTS for e in self.events
        )

    @property
    def needs_fleet(self) -> bool:
        return bool(FLEET_KNOBS & set(self.faults)) or self.needs_subprocess

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "seed": self.seed,
            "workload": self.workload,
            "faults": dict(self.faults),
            "events": [dict(e) for e in self.events],
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "FaultSchedule":
        return cls(
            seed=int(doc["seed"]),
            workload=str(doc["workload"]),
            faults=dict(doc.get("faults") or {}),
            events=[dict(e) for e in doc.get("events") or []],
        )

    def save(self, path: str) -> None:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> "FaultSchedule":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def describe(self) -> str:
        doms = ",".join(sorted(self.domains)) or "none"
        evs = ",".join(e.get("kind", "?") for e in self.events) or "-"
        return (
            f"schedule(seed={self.seed}, workload={self.workload}, "
            f"domains=[{doms}], knobs={len(self.faults)}, events={evs})"
        )


# -- workload matrix ------------------------------------------------------


def _wl_blockwise_chain(ct, xp, spec):
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    lazy = (a * 2.0 + 1.0) * 0.5
    return [("chain", lazy, (an * 2.0 + 1.0) * 0.5)]


def _wl_tree_reduce(ct, xp, spec):
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    lazy = xp.sum(a + 1.0, axis=0)
    return [("reduce", lazy, (an + 1.0).sum(axis=0))]


def _wl_rechunk(ct, xp, spec):
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    lazy = (a + 3.0).rechunk((8, 2)) * 2.0
    return [("rechunk", lazy, (an + 3.0) * 2.0)]


def _wl_overload_flood(ct, xp, spec):
    """A 2x-overload shape: one tenant floods many small computes while a
    victim tenant runs one normal reduce — the overload/poison chaos
    surface. Under a plain campaign every compute must still land
    bitwise; seeding ``task_fatal_*`` on top of it (explicit schedules,
    tests/service/test_overload.py) turns a flood chunk into a poison
    task whose *request* fails while the fleet and the victim survive."""
    pairs = []
    for i in range(5):
        an = np.arange(36, dtype=np.float64).reshape(6, 6) + i
        a = ct.from_array(an, chunks=(3, 3), spec=spec)
        pairs.append((f"flood-{i}", a * 2.0 + float(i), an * 2.0 + float(i)))
    vn = np.arange(144, dtype=np.float64).reshape(12, 12)
    v = ct.from_array(vn, chunks=(4, 4), spec=spec)
    pairs.append(("victim", xp.sum(v + 1.0, axis=0), (vn + 1.0).sum(axis=0)))
    return pairs


def _wl_multi_tenant(ct, xp, spec):
    """Two tenants' requests through one runtime, the shape the service
    layer serves — each must land bitwise in spite of the other's load."""
    an = np.arange(144, dtype=np.float64).reshape(12, 12)
    bn = np.arange(144, dtype=np.float64).reshape(12, 12) * 3.0
    a = ct.from_array(an, chunks=(3, 3), spec=spec)
    b = ct.from_array(bn, chunks=(4, 4), spec=spec)
    return [
        ("tenant-a", a * 2.0, an * 2.0),
        ("tenant-b", xp.sum(b, axis=1), bn.sum(axis=1)),
    ]


WORKLOADS = {
    "blockwise_chain": _wl_blockwise_chain,
    "tree_reduce": _wl_tree_reduce,
    "rechunk": _wl_rechunk,
    "multi_tenant": _wl_multi_tenant,
    "overload_flood": _wl_overload_flood,
}


# -- generation -----------------------------------------------------------

#: knob templates per domain generate() samples from: moderate rates that
#: a 6-retry policy should absorb (campaigns hunt invariant breaks, not
#: guaranteed-fatal outages)
_DOMAIN_TEMPLATES = {
    "storage": [
        {"storage_read_failure_rate": 0.1},
        {"storage_write_failure_rate": 0.1,
         "storage_write_leaves_tmp": True},
        {"storage_throttle_rate": 0.15},
    ],
    "task": [
        {"task_failure_rate": 0.08},
        {"straggler_rate": 0.2, "straggler_delay_s": 0.1},
    ],
    "memory": [
        {"task_mem_spike_rate": 0.1, "task_mem_spike_bytes": 1 << 20},
    ],
    "elasticity": [
        {"worker_preempt_rate": 0.3, "worker_preempt_after_tasks": 2,
         "preempt_notice_s": 0.5},
    ],
    "partition": [
        {"net_msg_delay_rate": 0.2, "net_msg_delay_s": 0.05},
        {"net_msg_dup_rate": 0.15},
        {"partition_worker_names": ("local-1",), "partition_after_tasks": 2,
         "partition_duration_s": 1.0, "partition_direction": "both"},
    ],
    "cancellation": [
        {"__event__": {"kind": "cancel", "after_completes": 3}},
    ],
    # subprocess-only domains (gated on allow_process_faults)
    "coordinator": [
        {"coordinator_crash_after_dispatches": 10},
    ],
    "client_loss": [
        {"__event__": {"kind": "client_kill", "after_completes": 8}},
    ],
}


@dataclass
class CampaignResult:
    """Outcome of running one schedule."""

    schedule: FaultSchedule
    ok: bool
    stage: str  # "ok" | "compute" | "bitwise" | "audit"
    error: Optional[str] = None
    report: Optional[AuditReport] = None
    wall_s: float = 0.0
    stats: dict = field(default_factory=dict)

    @property
    def signature(self) -> tuple:
        """What shrink preserves: the failing stage and error class —
        'the same failure', not 'any failure'."""
        etype = (self.error or "").split(":", 1)[0]
        return (self.stage, etype)

    def render(self) -> str:
        head = (
            f"{'PASS' if self.ok else 'FAIL'} [{self.stage}] "
            f"{self.schedule.describe()} wall={self.wall_s:.2f}s"
        )
        lines = [head]
        if self.error:
            lines.append(f"  error: {self.error}")
        if self.report is not None and not self.report.ok:
            lines.extend(
                "  " + v.render() for v in self.report.violations
            )
        return "\n".join(lines)


class CampaignRunner:
    """Generate, run, shrink, and replay composed-failure schedules."""

    def __init__(
        self,
        base_dir: str,
        retries: int = 6,
        allowed_mem: str = "500MB",
        gensym_base: int = 20_000,
    ):
        self.base_dir = str(base_dir)
        self.retries = retries
        self.allowed_mem = allowed_mem
        self.gensym_base = gensym_base
        self._runs = 0

    # -- generation --------------------------------------------------------

    def generate(
        self,
        seed: int,
        n_domains: int = 3,
        allow_process_faults: bool = False,
    ) -> FaultSchedule:
        """A random schedule from a seed: pick a workload and compose
        knobs from ``n_domains`` (or more) distinct failure domains."""
        rng = random.Random(seed)
        workload = rng.choice(sorted(WORKLOADS))
        pool = [
            d for d in sorted(_DOMAIN_TEMPLATES)
            if allow_process_faults or d not in ("coordinator", "client_loss")
        ]
        n = min(max(n_domains, 3), len(pool))
        domains = rng.sample(pool, n)
        faults: dict = {"seed": seed}
        events: list = []
        for d in domains:
            tmpl = rng.choice(_DOMAIN_TEMPLATES[d])
            for k, v in tmpl.items():
                if k == "__event__":
                    events.append(dict(v))
                else:
                    faults[k] = v
        return FaultSchedule(
            seed=seed, workload=workload, faults=faults, events=events
        )

    # -- running -----------------------------------------------------------

    def run(self, schedule: FaultSchedule) -> CampaignResult:
        if schedule.needs_subprocess:
            return self._run_subprocess(schedule)
        return self._run_inprocess(schedule)

    def _scratch(self, schedule: FaultSchedule) -> str:
        self._runs += 1
        d = os.path.join(
            self.base_dir,
            f"campaign-{schedule.seed}-{self._runs:03d}",
        )
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d, exist_ok=True)
        return d

    def _run_inprocess(self, schedule: FaultSchedule) -> CampaignResult:
        import cubed_tpu as ct
        import cubed_tpu.array_api as xp
        from cubed_tpu import utils as ct_utils
        from cubed_tpu.observability.metrics import get_registry

        from .faults import FaultConfig
        from .resilience import RetryPolicy

        t0 = time.monotonic()
        scratch = self._scratch(schedule)
        journal = os.path.join(scratch, "compute.journal")
        work_dir = os.path.join(scratch, "work")
        control_dir = os.path.join(scratch, "control")

        faults = dict(schedule.faults)

        # pin plan names so this schedule's injector decisions replay
        # identically run over run (bench/brownout idiom)
        resume_at = next(ct_utils.sym_counter)
        ct_utils.sym_counter = itertools.count(self.gensym_base)
        stage, error, report = "ok", None, None
        delta: dict = {}
        try:
            # schedule bugs (unknown knobs) must fail loudly as a campaign
            # verdict, not inject nothing
            FaultConfig.from_dict(faults)
            spec = ct.Spec(
                work_dir=work_dir,
                allowed_mem=self.allowed_mem,
                fault_injection=faults or None,
                journal=journal,
                integrity="verify" if faults.get(
                    "storage_corrupt_rate"
                ) else None,
            )
            pairs = WORKLOADS[schedule.workload](ct, xp, spec)
            policy = RetryPolicy(
                retries=self.retries, backoff_base=0.01, seed=0
            )
            before = get_registry().snapshot()
            if schedule.needs_fleet:
                from .executors.distributed import DistributedDagExecutor

                ex = DistributedDagExecutor(
                    n_local_workers=2,
                    control_dir=control_dir,
                    retry_policy=policy,
                )
            else:
                from .executors.python_async import AsyncPythonDagExecutor

                ex = AsyncPythonDagExecutor(retry_policy=policy)
            try:
                for name, lazy, expected in pairs:
                    result = self._compute_one(
                        lazy, ex, schedule, journal
                    )
                    if not np.array_equal(np.asarray(result), expected):
                        stage, error = "bitwise", (
                            f"BitwiseMismatch: workload "
                            f"{schedule.workload}/{name} diverged"
                        )
                        break
            finally:
                close = getattr(ex, "close", None)
                if close:
                    close()
            delta = get_registry().snapshot_delta(before)
        except Exception as e:  # noqa: BLE001 — the verdict IS the product
            stage = "compute"
            error = f"{type(e).__name__}: {e}"
        finally:
            used = next(ct_utils.sym_counter) - self.gensym_base
            ct_utils.sym_counter = itertools.count(resume_at + used)

        if stage == "ok":
            report = InvariantAuditor(
                journal=journal,
                control_dir=control_dir if schedule.needs_fleet else None,
                work_dir=work_dir,
                metrics=delta,
                expect_success=True,
            ).audit()
            if not report.ok:
                stage = "audit"
                error = "; ".join(
                    sorted({v.invariant for v in report.violations})
                )
        ok = stage == "ok"
        if ok:
            shutil.rmtree(scratch, ignore_errors=True)
        return CampaignResult(
            schedule=schedule, ok=ok, stage=stage, error=error,
            report=report, wall_s=time.monotonic() - t0,
            stats={
                k: delta[k] for k in (
                    "faults_injected", "task_retries",
                    "worker_loss_requeues", "cancellations",
                    "tasks_skipped_resume", "chunks_quarantined",
                ) if delta.get(k)
            },
        )

    def _compute_one(self, lazy, ex, schedule: FaultSchedule, journal: str):
        """One workload compute, applying in-process lifecycle events
        (mid-compute cancel + journal resume)."""
        from .cancellation import CancellationToken, ComputeCancelledError

        cancel_ev = next(
            (e for e in schedule.events if e.get("kind") == "cancel"), None
        )
        if cancel_ev is None:
            return lazy.compute(executor=ex)

        tok = CancellationToken()
        after = int(cancel_ev.get("after_completes", 3))

        class _CancelAfter:
            seen = 0

            def on_task_end(self, event):
                self.seen += 1
                if self.seen == after and not tok.cancelled:
                    tok.cancel("campaign cancel event")

        try:
            result = lazy.compute(
                executor=ex, cancellation=tok, callbacks=[_CancelAfter()]
            )
            # compute finished before the event fired (tiny workloads can
            # legally outrun the trigger) — still a valid run
            return result
        except ComputeCancelledError:
            # the event fired: the resumed compute must land bitwise,
            # proving cancel composed with the other domains lost nothing
            return lazy.compute(executor=ex, resume_from_journal=journal)

    # -- subprocess mode ---------------------------------------------------

    _CHILD_SCRIPT = r"""
import json, sys
from cubed_tpu.runtime.campaign import CampaignRunner, FaultSchedule

doc = json.load(open(sys.argv[1]))
sched = FaultSchedule.from_dict(doc["schedule"])
# the child runs the schedule minus the process-fault events the PARENT
# performs (client_kill) — coordinator-crash knobs stay: they kill the
# child, which is the point
sched.events = [
    e for e in sched.events if e.get("kind") != "client_kill"
]
runner = CampaignRunner(doc["base_dir"], gensym_base=doc["gensym_base"])
res = runner._run_inprocess(sched)
print(json.dumps({"ok": res.ok, "stage": res.stage, "error": res.error}))
"""

    def _run_subprocess(self, schedule: FaultSchedule) -> CampaignResult:
        """Run a process-fault schedule in a child interpreter.

        Coordinator-crash knobs hard-exit the child (exit 137 shape);
        ``client_kill`` events SIGKILL it from here. Either way the
        parent audits the artifacts the child left and, for a killed
        child, re-runs in a fresh child WITHOUT the process faults to
        prove the journal/control artifacts support recovery."""
        import signal
        import subprocess
        import sys

        t0 = time.monotonic()
        scratch = self._scratch(schedule)
        plan_path = os.path.join(scratch, "child-plan.json")
        child_base = os.path.join(scratch, "child")
        with open(plan_path, "w") as f:
            json.dump({
                "schedule": schedule.to_dict(),
                "base_dir": child_base,
                "gensym_base": self.gensym_base,
            }, f)

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")

        kill_ev = next(
            (e for e in schedule.events if e.get("kind") == "client_kill"),
            None,
        )
        proc = subprocess.Popen(
            [sys.executable, "-c", self._CHILD_SCRIPT, plan_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        killed = False
        if kill_ev is not None:
            delay = float(kill_ev.get("after_s", 2.0))
            try:
                proc.wait(timeout=delay)
            except subprocess.TimeoutExpired:
                os.kill(proc.pid, signal.SIGKILL)
                killed = True
        out, err = proc.communicate(timeout=600)
        rc = proc.returncode

        stage, error = "ok", None
        if killed or rc != 0:
            # the process fault fired; a clean replay (faults stripped)
            # must now succeed from the same seed
            clean = FaultSchedule(
                seed=schedule.seed, workload=schedule.workload,
                faults={
                    k: v for k, v in schedule.faults.items()
                    if k not in PROCESS_FAULT_KNOBS
                },
                events=[
                    e for e in schedule.events
                    if e.get("kind") not in PROCESS_FAULT_EVENTS
                ],
            )
            res2 = self._run_inprocess(clean)
            if not res2.ok:
                stage, error = res2.stage, res2.error
            report = res2.report
        else:
            try:
                verdict = json.loads(out.strip().splitlines()[-1])
            except (ValueError, IndexError):
                verdict = {"ok": False, "stage": "compute",
                           "error": f"child rc={rc}: {err[-500:]}"}
            if not verdict.get("ok"):
                stage = verdict.get("stage", "compute")
                error = verdict.get("error")
            report = None
        ok = stage == "ok"
        if ok:
            shutil.rmtree(scratch, ignore_errors=True)
        return CampaignResult(
            schedule=schedule, ok=ok, stage=stage, error=error,
            report=report, wall_s=time.monotonic() - t0,
            stats={"child_rc": rc, "child_killed": killed},
        )

    # -- shrinking ---------------------------------------------------------

    def _atoms(self, schedule: FaultSchedule) -> list:
        """The removable units of a schedule: knob groups + events."""
        atoms = []
        present = set(schedule.faults)
        for group in KNOB_ATOMS:
            if present & set(group):
                atoms.append(("knobs", group))
        for i, _e in enumerate(schedule.events):
            atoms.append(("event", i))
        return atoms

    @staticmethod
    def _without(schedule: FaultSchedule, atom) -> FaultSchedule:
        kind, spec = atom
        if kind == "knobs":
            faults = {
                k: v for k, v in schedule.faults.items() if k not in spec
            }
            return FaultSchedule(
                seed=schedule.seed, workload=schedule.workload,
                faults=faults, events=[dict(e) for e in schedule.events],
            )
        events = [
            dict(e) for i, e in enumerate(schedule.events) if i != spec
        ]
        return FaultSchedule(
            seed=schedule.seed, workload=schedule.workload,
            faults=dict(schedule.faults), events=events,
        )

    def shrink(
        self,
        schedule: FaultSchedule,
        signature: Optional[tuple] = None,
        check: Optional[Callable[[FaultSchedule], bool]] = None,
        log: Optional[Callable[[str], None]] = None,
    ) -> FaultSchedule:
        """Greedy delta-debugging: repeatedly drop any fault atom whose
        removal still reproduces the failure (same stage + error class),
        until no single atom can be removed. Returns the minimal
        schedule (the input itself if already minimal)."""
        say = log or (lambda _m: None)
        if check is None:
            want = signature
            if want is None:
                first = self.run(schedule)
                if first.ok:
                    raise ValueError(
                        "cannot shrink a passing schedule: "
                        + schedule.describe()
                    )
                want = first.signature

            def check(s: FaultSchedule) -> bool:
                return self.run(s).signature == want

        current = schedule
        progress = True
        while progress:
            progress = False
            for atom in self._atoms(current):
                candidate = self._without(current, atom)
                say(f"shrink: trying without {atom[1]}")
                if check(candidate):
                    say(f"shrink: dropped {atom[1]}")
                    current = candidate
                    progress = True
                    break
        return current

    # -- repro files -------------------------------------------------------

    def write_repro(
        self, schedule: FaultSchedule, result: CampaignResult,
        path: Optional[str] = None,
    ) -> str:
        path = path or os.path.join(
            self.base_dir, f"repro-{schedule.seed}.json"
        )
        doc = schedule.to_dict()
        doc["failure"] = {
            "stage": result.stage,
            "error": result.error,
            "violations": [
                {"invariant": v.invariant, "message": v.message,
                 "context": v.context}
                for v in (result.report.violations if result.report else [])
            ],
        }
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path

    def replay(self, repro_path: str) -> CampaignResult:
        return self.run(FaultSchedule.load(repro_path))

    # -- campaign loop -----------------------------------------------------

    def run_campaign(
        self,
        seeds,
        n_domains: int = 3,
        allow_process_faults: bool = False,
        log: Optional[Callable[[str], None]] = None,
    ) -> dict:
        """Generate + run a schedule per seed; shrink and write a repro
        for every failure. Returns a summary dict."""
        say = log or (lambda _m: None)
        passed, failures = 0, []
        for seed in seeds:
            sched = self.generate(
                seed, n_domains=n_domains,
                allow_process_faults=allow_process_faults,
            )
            say(f"seed {seed}: {sched.describe()}")
            res = self.run(sched)
            say("  " + res.render().splitlines()[0])
            if res.ok:
                passed += 1
                continue
            say("  shrinking to a minimal reproducing subset ...")
            minimal = self.shrink(sched, signature=res.signature, log=say)
            repro = self.write_repro(minimal, self.run(minimal))
            say(f"  repro written: {repro}")
            failures.append({
                "seed": seed, "stage": res.stage, "error": res.error,
                "repro": repro, "minimal": minimal.to_dict(),
            })
        return {
            "total": passed + len(failures),
            "passed": passed,
            "failures": failures,
        }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cubed_tpu.chaos",
        description="Composed-failure chaos campaigns: run seeded "
        "schedules over the workload matrix, shrink failures, replay "
        "repro files.",
    )
    parser.add_argument(
        "--seed", type=int, help="run the one schedule generated from "
        "this seed"
    )
    parser.add_argument(
        "--campaign", type=int, metavar="N",
        help="soak: run schedules for seeds 0..N-1",
    )
    parser.add_argument(
        "--repro", metavar="FILE", help="replay a repro schedule file"
    )
    parser.add_argument(
        "--base-dir", default="chaos-campaigns",
        help="scratch + repro output directory (default: %(default)s)",
    )
    parser.add_argument(
        "--domains", type=int, default=3,
        help="failure domains composed per generated schedule "
        "(default: %(default)s)",
    )
    parser.add_argument(
        "--allow-process-faults", action="store_true",
        help="let generated schedules include coordinator/client kills "
        "(subprocess mode)",
    )
    args = parser.parse_args(argv)
    modes = [m for m in (args.seed is not None, args.campaign is not None,
                         args.repro) if m]
    if len(modes) != 1:
        parser.error("pass exactly one of --seed, --campaign, --repro")

    runner = CampaignRunner(args.base_dir)
    if args.repro:
        res = runner.replay(args.repro)
        print(res.render())
        return 0 if res.ok else 1
    if args.seed is not None:
        sched = runner.generate(
            args.seed, n_domains=args.domains,
            allow_process_faults=args.allow_process_faults,
        )
        print(sched.describe())
        res = runner.run(sched)
        print(res.render())
        if not res.ok:
            minimal = runner.shrink(
                sched, signature=res.signature, log=print
            )
            repro = runner.write_repro(minimal, runner.run(minimal))
            print(f"repro written: {repro}")
        return 0 if res.ok else 1
    summary = runner.run_campaign(
        range(args.campaign), n_domains=args.domains,
        allow_process_faults=args.allow_process_faults, log=print,
    )
    print(json.dumps(
        {k: v for k, v in summary.items() if k != "failures"}
    ))
    for f in summary["failures"]:
        print(f"FAIL seed={f['seed']} stage={f['stage']}: {f['repro']}")
    return 0 if not summary["failures"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
