"""DAG traversal helpers shared by all executors.

Reference parity: cubed/runtime/pipeline.py:8-57.
"""

from __future__ import annotations

from typing import Iterator

import networkx as nx


def already_computed(name, dag, nodes: dict, resume: bool | None) -> bool:
    """True if this node's computation can be skipped.

    Nodes without a pipeline (array nodes) are always skipped. With
    ``resume=True`` an op is skipped when every successor array's store reports
    all chunks initialized (the op-granularity checkpoint).
    """
    pipeline = nodes[name].get("primitive_op", None)
    if pipeline is None:
        return True
    if resume:
        for succ in dag.successors(name):
            target = nodes[succ].get("target", None)
            if target is None:
                return False
            try:
                arr = target.open() if hasattr(target, "open") else target
                if arr.nchunks_initialized != arr.nchunks:
                    return False
            except FileNotFoundError:
                return False
        return True
    return False


def iter_op_nodes(dag) -> Iterator[tuple[str, dict]]:
    """Yield (name, node-data) for every op node carrying a primitive_op —
    the one predicate for 'this node represents real work', shared by the
    observability callbacks and anything else scanning the plan."""
    for name, d in dag.nodes(data=True):
        if d.get("type") == "op" and d.get("primitive_op") is not None:
            yield name, d


def visit_nodes(dag, resume: bool | None = None) -> Iterator[tuple[str, dict]]:
    """Yield (name, node-data) for op nodes in topological order."""
    nodes = dict(dag.nodes(data=True))
    for name in nx.topological_sort(dag):
        if already_computed(name, dag, nodes, resume):
            continue
        yield name, nodes[name]


def visit_node_generations(dag, resume: bool | None = None) -> Iterator[list]:
    """Yield lists of (name, node-data) for ops in the same topological generation."""
    nodes = dict(dag.nodes(data=True))
    for generation in nx.topological_generations(dag):
        gen = [
            (name, nodes[name])
            for name in generation
            if not already_computed(name, dag, nodes, resume)
        ]
        if gen:
            yield gen
