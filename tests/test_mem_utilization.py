"""Memory-bound verification (slow): representative ops run to completion
with ``allowed_mem`` set exactly to the plan's max projected memory — i.e. the
projected bound is sufficient — and the projected model dominates the real
chunk working set analytically.

Reference parity: cubed/tests/test_mem_utilization.py:275-296 (there: measured
peak RSS <= projected per op in fresh worker processes; here the in-process
analogue plus tight-budget completion).
"""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.spec import Spec


def run_tight(build, tmp_path, shape=(1000, 1000), chunks=(200, 200)):
    """Build the op graph twice: once to learn max projected mem, then again
    under a spec that allows exactly that much."""
    probe_spec = Spec(work_dir=str(tmp_path), allowed_mem="1GB", reserved_mem=0)
    probed = build(probe_spec, shape, chunks)
    projected = probed.plan.max_projected_mem()
    assert projected > 0
    tight_spec = Spec(work_dir=str(tmp_path), allowed_mem=projected, reserved_mem=0)
    result = build(tight_spec, shape, chunks)
    out = result.compute()
    return projected, out


OPS = {
    "add": lambda a, b: xp.add(a, b),
    "multiply": lambda a, b: xp.multiply(a, b),
    "negative": lambda a, b: xp.negative(a),
    "astype": lambda a, b: xp.astype(a, np.float32),
    "sum": lambda a, b: xp.sum(a, axis=0),
    "mean": lambda a, b: xp.mean(a, axis=0),
    "max": lambda a, b: xp.max(a, axis=1),
    "matmul": lambda a, b: xp.matmul(a, b),
    "transpose": lambda a, b: xp.permute_dims(a, (1, 0)),
    "index_slice": lambda a, b: a[1:, :],
    "concat": lambda a, b: xp.concat([a, b], axis=0),
    "stack": lambda a, b: xp.stack([a, b], axis=0),
    "reshape": lambda a, b: xp.reshape(a, (a.shape[0] * a.shape[1],)),
    "sort_axis": lambda a, b: xp.sort(a, axis=1),
    "qr_q": lambda a, b: xp.linalg.qr(a).Q,
    "svdvals": lambda a, b: xp.linalg.svdvals(a),
    "fft_abs": lambda a, b: xp.abs(xp.fft.fft(a, axis=1)),
}


@pytest.mark.slow
@pytest.mark.parametrize("op_name", sorted(OPS))
def test_op_within_projected_mem(op_name, tmp_path):
    op = OPS[op_name]

    def build(spec, shape, chunks):
        an = np.ones(shape)
        a = ct.from_array(an, chunks=chunks, spec=spec)
        b = ct.from_array(an, chunks=chunks, spec=spec)
        return op(a, b)

    projected, out = run_tight(build, tmp_path, shape=(500, 500), chunks=(100, 100))
    assert out is not None


def test_elemwise_projected_formula(tmp_path):
    # projected for a binary elemwise must cover 2 inputs + 1 output, doubled
    spec = Spec(work_dir=str(tmp_path), allowed_mem="1GB", reserved_mem=0)
    a = xp.ones((100, 100), chunks=(50, 50), spec=spec)
    b = xp.ones((100, 100), chunks=(50, 50), spec=spec)
    c = xp.add(a, b)
    chunk_bytes = 50 * 50 * 8
    assert c.plan.max_projected_mem(optimize_graph=False) >= 6 * chunk_bytes


@pytest.mark.slow
def test_rechunk_within_projected(tmp_path):
    def build(spec, shape, chunks):
        an = np.ones(shape)
        a = ct.from_array(an, chunks=chunks, spec=spec)
        return a.rechunk((shape[0], chunks[1] // 2))

    projected, out = run_tight(build, tmp_path, shape=(500, 500), chunks=(100, 100))
    np.testing.assert_allclose(out, np.ones((500, 500)))


# ---------------------------------------------------------------------------
# MEASURED memory bounds (reference: cubed/tests/test_mem_utilization.py:275-296
# asserts peak_measured_mem / projected_mem <= 1.0 in real worker processes)
# ---------------------------------------------------------------------------

_MEASURE_SCRIPT = r"""
import json, os, sys, tempfile
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.runtime.executors.multiprocess import MultiprocessDagExecutor
from cubed_tpu.runtime.types import Callback

work_dir = {work_dir!r}

def executor():
    return MultiprocessDagExecutor(max_workers=2)

reserved = ct.measure_reserved_mem(executor=executor(), work_dir=work_dir)

class PeakCapture(Callback):
    def __init__(self):
        self.peak = 0
    def on_task_end(self, event):
        if event.peak_measured_mem_end:
            self.peak = max(self.peak, event.peak_measured_mem_end)

ALL_OPS = {{
    "add": lambda a, b: xp.add(a, b),
    "negative": lambda a, b: xp.negative(a),
    "sum": lambda a, b: xp.sum(a, axis=0),
    "mean": lambda a, b: xp.mean(a, axis=0),
    "transpose": lambda a, b: xp.permute_dims(a, (1, 0)),
    "matmul": lambda a, b: xp.matmul(a, b),
    "rechunk": lambda a, b: a.rechunk((SHAPE[0], CHUNKS[1] // 2)),
}}
OP_NAMES = {op_names!r}
SHAPE = {shape!r}
CHUNKS = {chunks!r}

results = {{}}
for name in OP_NAMES:
    op = ALL_OPS[name]
    spec = ct.Spec(work_dir=work_dir, allowed_mem="2GB", reserved_mem=reserved)
    # virtual (never-materialized) inputs: nothing ships in task closures, so
    # worker RSS reflects ONLY per-task chunk traffic + the measured baseline
    a = xp.ones(SHAPE, chunks=CHUNKS, spec=spec)
    b = xp.ones(SHAPE, chunks=CHUNKS, spec=spec)
    out = op(a, b)
    projected = out.plan.max_projected_mem()
    cap = PeakCapture()
    out.compute(executor=executor(), callbacks=[cap], optimize_graph=False)
    results[name] = {{
        "projected": int(projected),
        "peak_measured": int(cap.peak),
        "utilization": round(cap.peak / projected, 3) if projected else None,
    }}

print(json.dumps({{"reserved": int(reserved), "ops": results}}))
"""


def _run_measured_rss(tmp_path, *, op_names, shape, chunks, timeout=600):
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))
    }
    env["CUBED_TPU_BACKEND"] = "numpy"
    env["JAX_PLATFORMS"] = "cpu"
    script = _MEASURE_SCRIPT.format(
        repo=repo, work_dir=str(tmp_path), op_names=list(op_names),
        shape=tuple(shape), chunks=tuple(chunks),
    )
    out = subprocess.run(
        [sys.executable, "-c", script],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    data = json.loads(out.stdout.strip().splitlines()[-1])
    assert data["reserved"] > 0
    bad = {
        name: r
        for name, r in data["ops"].items()
        if r["utilization"] is None or r["utilization"] > 1.0
    }
    assert not bad, f"ops exceeding projected_mem: {bad} (all: {data['ops']})"
    # the measurement must be real: every op reports a worker-process peak
    # (interpreter baseline is tens of MB at minimum)
    assert all(r["peak_measured"] > 30 * 2**20 for r in data["ops"].values()), data
    return data


def test_measured_worker_peak_rss_fast(tmp_path):
    """Fast-mode slice of the flagship guarantee, in the DEFAULT suite: a
    real fresh-worker-process RSS measurement for two representative ops
    must stay within projected_mem — a memory-model regression can't land
    without failing a plain ``pytest tests/`` (VERDICT r3 #10).

    One retry: the idle margins are healthy (utilization ~0.70/0.78 for
    add/sum via VmHWM), but the measurement runs real subprocesses that
    heavy machine load can make RSS-spiky or slow — a genuine model
    regression fails both attempts deterministically."""
    import subprocess

    for attempt in range(2):
        try:
            _run_measured_rss(
                tmp_path, op_names=["add", "sum"], shape=(2000, 2000),
                chunks=(1000, 1000), timeout=300,
            )
            return
        except (AssertionError, subprocess.TimeoutExpired):
            if attempt == 1:
                raise


@pytest.mark.slow
def test_measured_worker_peak_rss_within_projected(tmp_path):
    """Per-op worker peak RSS (getrusage in the worker process) must stay
    within the plan-time projected_mem bound — the projected model's upper
    bound validated against real processes, on the numpy backend where the
    per-chunk working set is exactly what the model prices."""
    data = _run_measured_rss(
        tmp_path,
        op_names=["add", "negative", "sum", "mean", "transpose", "matmul",
                  "rechunk"],
        shape=(4000, 4000), chunks=(1000, 1000),
    )
    # at least one op lands near its bound so a trivially-loose model
    # still gets caught
    assert any(r["utilization"] > 0.5 for r in data["ops"].values()), data


@pytest.mark.slow
def test_jax_segment_hbm_footprint_within_budget(tmp_path):
    """XLA's own memory analysis of the fused segment program (args + outputs
    + temps) must fit the executor's residency budget — the HBM analogue of
    the worker-RSS bound."""
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = Spec(work_dir=str(tmp_path), allowed_mem="2GB", reserved_mem=0)
    a = xp.ones((2000, 2000), chunks=(500, 500), spec=spec)
    b = xp.ones((2000, 2000), chunks=(500, 500), spec=spec)
    out = xp.mean(xp.add(xp.multiply(a, 2.0), b))
    budget = 512 * 2**20
    ex = JaxExecutor(device_mem=budget)
    val = float(out.compute(executor=ex))
    assert np.isclose(val, 3.0)
    assert ex.stats["segments_traced"] == 1
    footprint = ex.stats.get("segment_hbm_footprint")
    if footprint:  # analysis available on this backend
        assert footprint <= budget, (footprint, budget)
