"""Plan visualization: DAG -> graphviz DOT -> svg/png, with per-op tooltips
(projected mem, task counts, caller lines, user variable names).

Reference parity: cubed/core/plan.py:249-404. Falls back to writing plain DOT
when no graphviz renderer is installed.
"""

from __future__ import annotations

from typing import Optional

from ..utils import memory_repr

_OP_COLORS = {
    "blockwise": "#dcbeff",
    "rechunk": "#aaffc3",
    "create-arrays": "#ffd8b1",
}


def _escape(s: str) -> str:
    return str(s).replace('"', "'").replace("\n", "\\n")


def build_dot(dag, rankdir="TB", show_hidden=False) -> str:
    lines = [
        "digraph {",
        f'  rankdir="{rankdir}";',
        '  node [fontname="helvetica", shape=box, fontsize=10];',
    ]
    for name, d in dag.nodes(data=True):
        if d.get("hidden") and not show_hidden:
            continue
        if d.get("type") == "op":
            op = d.get("primitive_op")
            label = d.get("op_display_name", name)
            tooltip_parts = [f"name: {name}"]
            if op is not None:
                tooltip_parts.append(f"tasks: {op.num_tasks}")
                tooltip_parts.append(f"projected memory: {memory_repr(op.projected_mem)}")
            for ss in d.get("stack_summaries") or []:
                if not ss.is_cubed():
                    tooltip_parts.append(f"calls: {ss.name} ({ss.filename}:{ss.lineno})")
            color = _OP_COLORS.get(d.get("op_name", ""), "#ffffff")
            lines.append(
                f'  "{name}" [label="{_escape(label)}", style=filled, '
                f'fillcolor="{color}", tooltip="{_escape(chr(10).join(tooltip_parts))}"];'
            )
        else:
            target = d.get("target")
            shape_info = ""
            if target is not None and hasattr(target, "shape"):
                shape_info = f"\\nshape: {target.shape}\\nchunks: {getattr(target, 'chunks', '?')}"
            # map internal names to user variable names via stack summaries of
            # the producing op
            var_name = None
            for pred in dag.predecessors(name):
                for ss in dag.nodes[pred].get("stack_summaries") or []:
                    if name in ss.array_names_to_variable_names:
                        var_name = ss.array_names_to_variable_names[name]
            label = f"{name}" + (f" ({var_name})" if var_name else "") + shape_info
            lines.append(
                f'  "{name}" [label="{_escape(label)}", shape=ellipse];'
            )
    for u, v in dag.edges():
        du, dv = dag.nodes[u], dag.nodes[v]
        if (du.get("hidden") or dv.get("hidden")) and not show_hidden:
            continue
        lines.append(f'  "{u}" -> "{v}";')
    lines.append("}")
    return "\n".join(lines)


def visualize_dag(
    dag,
    filename: str = "cubed",
    format: Optional[str] = None,
    rankdir: str = "TB",
    show_hidden: bool = False,
):
    dot = build_dot(dag, rankdir=rankdir, show_hidden=show_hidden)
    fmt = format or "svg"
    dot_path = f"{filename}.dot"
    with open(dot_path, "w") as f:
        f.write(dot)
    try:
        import subprocess

        out_path = f"{filename}.{fmt}"
        subprocess.run(
            ["dot", f"-T{fmt}", dot_path, "-o", out_path],
            check=True,
            capture_output=True,
            timeout=60,
        )
        return out_path
    except Exception:
        # graphviz binary unavailable: the DOT file is the artifact
        return dot_path
