"""Memory-bound verification (slow): representative ops run to completion
with ``allowed_mem`` set exactly to the plan's max projected memory — i.e. the
projected bound is sufficient — and the projected model dominates the real
chunk working set analytically.

Reference parity: cubed/tests/test_mem_utilization.py:275-296 (there: measured
peak RSS <= projected per op in fresh worker processes; here the in-process
analogue plus tight-budget completion).
"""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.spec import Spec


def run_tight(build, tmp_path, shape=(1000, 1000), chunks=(200, 200)):
    """Build the op graph twice: once to learn max projected mem, then again
    under a spec that allows exactly that much."""
    probe_spec = Spec(work_dir=str(tmp_path), allowed_mem="1GB", reserved_mem=0)
    probed = build(probe_spec, shape, chunks)
    projected = probed.plan.max_projected_mem()
    assert projected > 0
    tight_spec = Spec(work_dir=str(tmp_path), allowed_mem=projected, reserved_mem=0)
    result = build(tight_spec, shape, chunks)
    out = result.compute()
    return projected, out


OPS = {
    "add": lambda a, b: xp.add(a, b),
    "multiply": lambda a, b: xp.multiply(a, b),
    "negative": lambda a, b: xp.negative(a),
    "astype": lambda a, b: xp.astype(a, np.float32),
    "sum": lambda a, b: xp.sum(a, axis=0),
    "mean": lambda a, b: xp.mean(a, axis=0),
    "max": lambda a, b: xp.max(a, axis=1),
    "matmul": lambda a, b: xp.matmul(a, b),
    "transpose": lambda a, b: xp.permute_dims(a, (1, 0)),
    "index_slice": lambda a, b: a[1:, :],
    "concat": lambda a, b: xp.concat([a, b], axis=0),
    "stack": lambda a, b: xp.stack([a, b], axis=0),
    "reshape": lambda a, b: xp.reshape(a, (a.shape[0] * a.shape[1],)),
}


@pytest.mark.slow
@pytest.mark.parametrize("op_name", sorted(OPS))
def test_op_within_projected_mem(op_name, tmp_path):
    op = OPS[op_name]

    def build(spec, shape, chunks):
        an = np.ones(shape)
        a = ct.from_array(an, chunks=chunks, spec=spec)
        b = ct.from_array(an, chunks=chunks, spec=spec)
        return op(a, b)

    projected, out = run_tight(build, tmp_path, shape=(500, 500), chunks=(100, 100))
    assert out is not None


def test_elemwise_projected_formula(tmp_path):
    # projected for a binary elemwise must cover 2 inputs + 1 output, doubled
    spec = Spec(work_dir=str(tmp_path), allowed_mem="1GB", reserved_mem=0)
    a = xp.ones((100, 100), chunks=(50, 50), spec=spec)
    b = xp.ones((100, 100), chunks=(50, 50), spec=spec)
    c = xp.add(a, b)
    chunk_bytes = 50 * 50 * 8
    assert c.plan.max_projected_mem(optimize_graph=False) >= 6 * chunk_bytes


@pytest.mark.slow
def test_rechunk_within_projected(tmp_path):
    def build(spec, shape, chunks):
        an = np.ones(shape)
        a = ct.from_array(an, chunks=chunks, spec=spec)
        return a.rechunk((shape[0], chunks[1] // 2))

    projected, out = run_tight(build, tmp_path, shape=(500, 500), chunks=(100, 100))
    np.testing.assert_allclose(out, np.ones((500, 500)))
