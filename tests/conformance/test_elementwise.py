"""Elementwise conformance: every implemented elementwise function against
the numpy oracle over generated arrays, including broadcasting and promotion.

Parity role: array-api-tests test_operators_and_elementwise_functions.py.
"""

from __future__ import annotations

import numpy as np
import pytest
from hypothesis import given
from hypothesis import strategies as st
from hypothesis.extra import numpy as hnp

import cubed_tpu.array_api as xp

from .harness import (
    ALL_DTYPES,
    BOOL_DTYPE,
    INT_DTYPES,
    NUMERIC_DTYPES,
    REAL_FLOAT_DTYPES,
    UINT_DTYPES,
    arrays,
    assert_matches,
    run,
    wrap,
)

# name -> (dtype pool, element strategy override or None). All bounds are
# exactly representable in float32 (hypothesis requires it at width=32).
# allow_subnormal=False everywhere: XLA flushes subnormals to zero, which
# ratio-sensitive functions (atan2) amplify to O(1) errors (SKIPS.txt)
_SMALL = st.floats(min_value=-8, max_value=8, allow_nan=False,
                   allow_subnormal=False, width=32)
_POS = st.floats(min_value=2**-10, max_value=1e6, allow_nan=False,
                 allow_subnormal=False, width=32)
_UNIT = st.floats(min_value=-1.0, max_value=1.0, allow_nan=False,
                  allow_subnormal=False, width=32)
_GE1 = st.floats(min_value=1.0, max_value=1e6, allow_nan=False,
                 allow_subnormal=False, width=32)
_OPEN_UNIT = st.floats(min_value=-0.984375, max_value=0.984375,
                       allow_nan=False, allow_subnormal=False, width=32)
_GT_NEG1 = st.floats(min_value=-0.984375, max_value=1e6, allow_nan=False,
                     allow_subnormal=False, width=32)

UNARY = {
    "abs": (NUMERIC_DTYPES, None),
    "acos": (REAL_FLOAT_DTYPES, _UNIT),
    "acosh": (REAL_FLOAT_DTYPES, _GE1),
    "asin": (REAL_FLOAT_DTYPES, _UNIT),
    "asinh": (REAL_FLOAT_DTYPES, None),
    "atan": (REAL_FLOAT_DTYPES, None),
    "atanh": (REAL_FLOAT_DTYPES, _OPEN_UNIT),
    "ceil": (REAL_FLOAT_DTYPES + INT_DTYPES, None),
    "cos": (REAL_FLOAT_DTYPES, _SMALL),
    "signbit": (REAL_FLOAT_DTYPES, _SMALL),
    "cosh": (REAL_FLOAT_DTYPES, _SMALL),
    "exp": (REAL_FLOAT_DTYPES, _SMALL),
    "expm1": (REAL_FLOAT_DTYPES, _SMALL),
    "floor": (REAL_FLOAT_DTYPES + INT_DTYPES, None),
    "isfinite": (NUMERIC_DTYPES, None),
    "isinf": (NUMERIC_DTYPES, None),
    "isnan": (NUMERIC_DTYPES, None),
    "log": (REAL_FLOAT_DTYPES, _POS),
    "log10": (REAL_FLOAT_DTYPES, _POS),
    "log1p": (REAL_FLOAT_DTYPES, _GT_NEG1),
    "log2": (REAL_FLOAT_DTYPES, _POS),
    "logical_not": (BOOL_DTYPE, None),
    "negative": (REAL_FLOAT_DTYPES + INT_DTYPES, None),
    "positive": (NUMERIC_DTYPES, None),
    "round": (REAL_FLOAT_DTYPES, None),
    "sign": (REAL_FLOAT_DTYPES + INT_DTYPES, None),
    "sin": (REAL_FLOAT_DTYPES, _SMALL),
    "sinh": (REAL_FLOAT_DTYPES, _SMALL),
    "sqrt": (REAL_FLOAT_DTYPES, _POS),
    "square": (REAL_FLOAT_DTYPES, None),
    "tan": (REAL_FLOAT_DTYPES, _UNIT),
    "tanh": (REAL_FLOAT_DTYPES, None),
    "trunc": (REAL_FLOAT_DTYPES + INT_DTYPES, None),
    "bitwise_invert": (INT_DTYPES + UINT_DTYPES + BOOL_DTYPE, None),
}

# wide-enough int pools to avoid implementation-defined overflow wrap
_MUL_DTYPES = REAL_FLOAT_DTYPES + (np.int16, np.int32, np.int64, np.uint16, np.uint32)

BINARY = {
    "add": (NUMERIC_DTYPES, None),
    "subtract": (REAL_FLOAT_DTYPES + INT_DTYPES, None),
    "multiply": (_MUL_DTYPES, None),
    # bounded magnitudes: XLA's atan2 loses ~1e-4 near the pi/2 asymptote for
    # operand ratios ~1e300 (pinned in SKIPS.txt)
    "atan2": (REAL_FLOAT_DTYPES, _SMALL),
    "logaddexp": (REAL_FLOAT_DTYPES, _SMALL),
    # 2023.12 additions
    "maximum": (NUMERIC_DTYPES, None),
    "minimum": (NUMERIC_DTYPES, None),
    "hypot": (REAL_FLOAT_DTYPES, _SMALL),
    "copysign": (REAL_FLOAT_DTYPES, _SMALL),
    "bitwise_and": (INT_DTYPES + UINT_DTYPES + BOOL_DTYPE, None),
    "bitwise_or": (INT_DTYPES + UINT_DTYPES + BOOL_DTYPE, None),
    "bitwise_xor": (INT_DTYPES + UINT_DTYPES + BOOL_DTYPE, None),
    "equal": (ALL_DTYPES, None),
    "not_equal": (ALL_DTYPES, None),
    "greater": (NUMERIC_DTYPES, None),
    "greater_equal": (NUMERIC_DTYPES, None),
    "less": (NUMERIC_DTYPES, None),
    "less_equal": (NUMERIC_DTYPES, None),
    "logical_and": (BOOL_DTYPE, None),
    "logical_or": (BOOL_DTYPE, None),
    "logical_xor": (BOOL_DTYPE, None),
}


@pytest.mark.parametrize("name", sorted(UNARY))
@given(data=st.data())
def test_unary(name, data, spec):
    dtypes, elements = UNARY[name]
    an = data.draw(arrays(dtypes=dtypes, elements=elements))
    got = run(getattr(xp, name)(wrap(an, spec)))
    if name in ("ceil", "floor", "trunc") and an.dtype.kind in "iu":
        expect = an  # spec: integer input returned as-is (numpy promotes)
    else:
        expect = getattr(np, {"bitwise_invert": "invert"}.get(name, name))(an)
    assert_matches(got, expect)


@pytest.mark.parametrize("name", sorted(BINARY))
@given(data=st.data())
def test_binary_same_dtype(name, data, spec):
    dtypes, elements = BINARY[name]
    dt = data.draw(st.sampled_from(dtypes))
    shape = data.draw(hnp.array_shapes(min_dims=1, max_dims=3, min_side=1, max_side=6))
    an = data.draw(arrays(dtypes=(dt,), shape=shape, elements=elements))
    bn = data.draw(arrays(dtypes=(dt,), shape=shape, elements=elements))
    got = run(getattr(xp, name)(wrap(an, spec), wrap(bn, spec)))
    expect = getattr(np, name)(an, bn)
    assert_matches(got, expect)


@given(data=st.data())
def test_divide(data, spec):
    dt = data.draw(st.sampled_from(REAL_FLOAT_DTYPES))
    shape = data.draw(hnp.array_shapes(min_dims=1, max_dims=3, min_side=1, max_side=6))
    nonzero = st.floats(min_value=0.125, max_value=1000.0, allow_nan=False, width=32)
    an = data.draw(arrays(dtypes=(dt,), shape=shape))
    bn = data.draw(arrays(dtypes=(dt,), shape=shape, elements=nonzero))
    got = run(xp.divide(wrap(an, spec), wrap(bn, spec)))
    assert_matches(got, np.divide(an, bn))


@given(data=st.data())
def test_pow_float(data, spec):
    shape = data.draw(hnp.array_shapes(min_dims=1, max_dims=2, min_side=1, max_side=5))
    base = data.draw(arrays(dtypes=(np.float64,), shape=shape, elements=_POS))
    expo = data.draw(arrays(dtypes=(np.float64,), shape=shape, elements=_SMALL))
    got = run(xp.pow(wrap(base, spec), wrap(expo, spec)))
    assert_matches(got, np.pow(base, expo))


@given(data=st.data())
def test_binary_broadcasting(data, spec):
    """Broadcast semantics across distinct but compatible shapes."""
    sh = data.draw(
        hnp.mutually_broadcastable_shapes(num_shapes=2, min_dims=1, max_dims=3, max_side=5)
    )
    an = data.draw(arrays(dtypes=(np.float64,), shape=sh.input_shapes[0]))
    bn = data.draw(arrays(dtypes=(np.float64,), shape=sh.input_shapes[1]))
    got = run(xp.add(wrap(an, spec), wrap(bn, spec)))
    assert_matches(got, np.add(an, bn))


@given(data=st.data())
def test_same_kind_promotion(data, spec):
    """Mixed dtypes within a kind promote per the spec (numpy 2.x oracle)."""
    kind = data.draw(st.sampled_from([REAL_FLOAT_DTYPES, INT_DTYPES, UINT_DTYPES]))
    dt1 = data.draw(st.sampled_from(kind))
    dt2 = data.draw(st.sampled_from(kind))
    shape = (3, 4)
    an = data.draw(arrays(dtypes=(dt1,), shape=shape))
    bn = data.draw(arrays(dtypes=(dt2,), shape=shape))
    got = run(xp.add(wrap(an, spec), wrap(bn, spec)))
    assert_matches(got, np.add(an, bn))


@given(data=st.data())
def test_python_scalar_promotion(data, spec):
    """array <op> python scalar keeps the array dtype (spec rule)."""
    an = data.draw(arrays(dtypes=REAL_FLOAT_DTYPES))
    scalar = data.draw(st.floats(min_value=-100, max_value=100, allow_nan=False))
    a = wrap(an, spec)
    got = run(a * scalar + 1.0)
    expect = (an * np.asarray(scalar, dtype=an.dtype)) + np.asarray(1.0, dtype=an.dtype)
    assert_matches(got, expect.astype(an.dtype))


@pytest.mark.parametrize("op", ["__add__", "__mul__", "__sub__", "__truediv__", "__pow__"])
@given(data=st.data())
def test_reflected_operators(op, data, spec):
    # bounded away from 0 and small: keeps 2.0**x and 2.0/x finite and quiet
    elems = st.floats(min_value=0.125, max_value=8.0, allow_nan=False, width=32)
    an = data.draw(arrays(dtypes=(np.float64,), elements=elems))
    a = wrap(an, spec)
    rop = op.replace("__", "__r", 1)
    got = run(getattr(a, rop)(2.0))
    expect = getattr(np, {"__add__": "add", "__mul__": "multiply", "__sub__": "subtract",
                          "__truediv__": "divide", "__pow__": "power"}[op])(
        np.float64(2.0), an
    )
    assert_matches(got, expect)


@given(data=st.data())
def test_clip_property(data, spec):
    an = data.draw(arrays(dtypes=REAL_FLOAT_DTYPES))
    lo = data.draw(st.one_of(st.none(), st.floats(-100, 50)))
    hi = data.draw(st.one_of(st.none(), st.floats(50, 200)))
    got = run(xp.clip(wrap(an, spec), min=lo, max=hi))
    expect = an if lo is None and hi is None else np.clip(an, lo, hi)
    assert_matches(got, expect.astype(an.dtype))
