"""Live fleet telemetry: a bounded time-series pipeline over the metrics.

Everything the observability stack produced so far — metrics snapshots
(PR 1), traces and flight-recorder bundles (PR 5) — is post-hoc: readable
after the compute ends. This module is the *live* layer the service front
door and the auto-tuning loop read from:

- :class:`TimeSeriesStore` — a bounded ring of ``(timestamp, value)``
  points per ``(metric, labels)`` series. Fixed memory: ``capacity``
  points per series, ``max_series`` series (at the cap the stalest
  series is evicted for the new one, counted in
  ``timeseries_series_evicted`` — never silent).

- :class:`TelemetrySampler` — a ~1s daemon thread that samples the merged
  fleet view into the store: the process metrics registry (counters ride
  as cumulative values; ``rate()`` derives per-second rates on read),
  per-worker rows from every registered :class:`Coordinator` (RSS, load,
  connectivity, peer-cache footprint — fed by the worker heartbeats,
  which since this PR also piggyback bounded ``snapshot_delta`` payloads
  so worker-side counters reach the coordinator continuously), and
  per-compute progress (tasks done/total) from
  :class:`ComputeProgressCallback`. Each tick also evaluates the alert
  engine (``observability/alerts.py``).

The HTTP endpoints over this store (``/metrics``, ``/healthz``,
``/snapshot.json``) and the arming precedence live in
``observability/export.py``; the terminal dashboard is
``python -m cubed_tpu.top``.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Dict, Optional, Tuple

from ..runtime.types import Callback
from .metrics import get_registry

logger = logging.getLogger(__name__)

#: points retained per series (~10 minutes at the 1s default interval)
DEFAULT_CAPACITY = 600
#: distinct (name, labels) series retained; overflow is counted
DEFAULT_MAX_SERIES = 2048

#: bound on how many numeric metric keys one sampler tick records from a
#: registry snapshot — a runaway metric namespace must not grow the store
MAX_SAMPLED_METRICS = 512


def _label_key(labels: Optional[dict]) -> Tuple:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class TimeSeriesStore:
    """Bounded in-memory time series: ``(name, labels) -> ring of points``.

    Thread-safe; writers are the sampler and the coordinator heartbeat
    path, readers are the HTTP endpoints, the alert engine, the dashboard
    and the flight recorder.
    """

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        max_series: int = DEFAULT_MAX_SERIES,
    ):
        self.capacity = int(capacity)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        #: (name, label_key) -> (labels dict, deque[(ts, value)])
        self._series: "OrderedDict[Tuple, Tuple[dict, deque]]" = OrderedDict()
        self.series_evicted = 0

    # -- writing -------------------------------------------------------

    def record(
        self, name: str, value, ts: Optional[float] = None,
        labels: Optional[dict] = None,
    ) -> None:
        """Append one point. Non-numeric values are ignored (the sampler
        feeds raw snapshots; histogram dicts are decomposed by the caller).

        At the series cap the STALEST series (oldest last point) is
        evicted to admit the new one — a long-lived service endpoint
        churns labelled dimensions forever (per-compute progress,
        autoscaler-churned worker names), and dropping the NEW series
        would starve exactly the live computes/workers an operator is
        watching. Evictions are counted (``timeseries_series_evicted``),
        never silent."""
        if isinstance(value, bool):
            value = int(value)
        elif not isinstance(value, (int, float)):
            return
        if ts is None:
            ts = time.time()
        key = (name, _label_key(labels))
        evicted = False
        with self._lock:
            entry = self._series.get(key)
            if entry is None:
                if len(self._series) >= self.max_series:
                    stalest = min(
                        self._series,
                        key=lambda k: (
                            self._series[k][1][-1][0]
                            if self._series[k][1] else 0.0
                        ),
                    )
                    del self._series[stalest]
                    self.series_evicted += 1
                    evicted = True
                entry = (dict(labels or {}), deque(maxlen=self.capacity))
                self._series[key] = entry
            entry[1].append((float(ts), float(value)))
        if evicted:
            get_registry().counter("timeseries_series_evicted").inc()
            if self.series_evicted == 1:
                logger.warning(
                    "time-series store reached its %d-series bound; "
                    "stalest series are evicted for new ones (counted in "
                    "timeseries_series_evicted)", self.max_series,
                )

    def forget(self, name: str, labels: Optional[dict] = None) -> None:
        """Drop one series (e.g. a finished compute's progress gauges)."""
        with self._lock:
            self._series.pop((name, _label_key(labels)), None)

    # -- reading -------------------------------------------------------

    def latest(self, name: str, labels: Optional[dict] = None):
        """The most recent value of a series, or None."""
        pt = self.latest_point(name, labels=labels)
        return None if pt is None else pt[1]

    def latest_point(self, name: str, labels: Optional[dict] = None):
        """The most recent ``(ts, value)`` of a series, or None — the
        timestamp lets alert rules treat a FROZEN series (its writer is
        gone) as no-data instead of evaluating a stale reading forever."""
        with self._lock:
            entry = self._series.get((name, _label_key(labels)))
            if entry is None or not entry[1]:
                return None
            return entry[1][-1]

    def window(
        self, name: str, seconds: float, labels: Optional[dict] = None,
        now: Optional[float] = None,
    ) -> list:
        """Points of one series within the trailing window, oldest first."""
        if now is None:
            now = time.time()
        t0 = now - seconds
        with self._lock:
            entry = self._series.get((name, _label_key(labels)))
            if entry is None:
                return []
            return [(ts, v) for ts, v in entry[1] if ts >= t0]

    def rate(
        self, name: str, seconds: float, labels: Optional[dict] = None,
        now: Optional[float] = None,
    ) -> Optional[float]:
        """Per-second increase of a cumulative counter series over the
        trailing window (clamped at 0 — a process restart resets counters,
        which must read as "no progress", not a negative rate). None with
        fewer than two points in the window."""
        pts = self.window(name, seconds, labels=labels, now=now)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts[0], pts[-1]
        if t1 <= t0:
            return None
        return max(0.0, (v1 - v0) / (t1 - t0))

    def series(self) -> list:
        """``[(name, labels, n_points), ...]`` for every retained series."""
        with self._lock:
            return [
                (name, dict(entry[0]), len(entry[1]))
                for (name, _k), entry in self._series.items()
            ]

    def labelled_latest(self) -> list:
        """``[(name, labels, latest_value), ...]`` for every LABELLED
        series (per-worker / per-compute dimensions) — what the Prometheus
        exposition exports beside the registry's unlabelled metrics."""
        return [row for row in self.latest_series() if row[1]]

    def latest_series(self) -> list:
        """``[(name, labels, latest_value), ...]`` for every series —
        labels empty for unlabelled ones (fleet aggregates like
        ``fleet_pressured_fraction``, which exist only here, not in the
        registry)."""
        out = []
        with self._lock:
            for (name, _k), (labels, ring) in self._series.items():
                if ring:
                    out.append((name, dict(labels), ring[-1][1]))
        return out

    def to_dict(
        self, window_s: Optional[float] = None, max_points: int = 240,
        now: Optional[float] = None,
    ) -> list:
        """JSON-serializable dump: one ``{name, labels, points}`` row per
        series, each series bounded to its trailing ``max_points`` (within
        ``window_s`` when given) — what ``/snapshot.json`` and the
        flight-recorder bundle embed."""
        if now is None:
            now = time.time()
        t0 = None if window_s is None else now - window_s
        out = []
        with self._lock:
            items = list(self._series.items())
        for (name, _k), (labels, ring) in items:
            pts = list(ring)
            if t0 is not None:
                pts = [p for p in pts if p[0] >= t0]
            pts = pts[-max_points:]
            if not pts:
                continue
            out.append({
                "name": name,
                "labels": dict(labels),
                "points": [[round(ts, 3), v] for ts, v in pts],
            })
        return out


# ----------------------------------------------------------------------
# fleet + compute registration (what the sampler samples)
# ----------------------------------------------------------------------

#: live Coordinators (weak: a closed/garbage fleet must never pin itself
#: into the telemetry loop); registered by Coordinator.__init__
_fleets: "weakref.WeakSet" = weakref.WeakSet()
_fleets_lock = threading.Lock()


def register_fleet(coordinator) -> None:
    with _fleets_lock:
        _fleets.add(coordinator)


def unregister_fleet(coordinator) -> None:
    with _fleets_lock:
        _fleets.discard(coordinator)


def live_fleets() -> list:
    with _fleets_lock:
        return [c for c in _fleets if not c._closed.is_set()]


#: live ComputeServices (weak, like fleets); registered by
#: ComputeService.start — the sampler derives the per-tenant series
#: (tenant_queued/tenant_running/tenant_completed, labelled by tenant)
#: and /snapshot.json's "service" section from these
_services: "weakref.WeakSet" = weakref.WeakSet()
_services_lock = threading.Lock()


def register_service(service) -> None:
    with _services_lock:
        _services.add(service)


def unregister_service(service) -> None:
    with _services_lock:
        _services.discard(service)


def live_services() -> list:
    with _services_lock:
        return [s for s in _services if not s.closed]


def service_view() -> Optional[dict]:
    """Merged per-tenant service table for ``/snapshot.json`` and the
    dashboard; None while no service is live."""
    views = []
    for svc in live_services():
        try:
            views.append(svc.stats_snapshot())
        except Exception:
            continue
    if not views:
        return None
    if len(views) == 1:
        return views[0]
    # 2+ live services: the tenant/queue aggregates still merge (the
    # TENANTS panel reads one table), but the per-service identity —
    # service_dir, cache stats, SLO board — must NOT be nulled away the
    # moment a second service starts: each view keeps its own row under
    # "services", and the slo boards merge per tenant (tenant names are
    # already the services' own namespaces)
    merged = {
        "tenants": {}, "queue_depth": 0, "running": 0, "slots": 0,
        "throttling": any(v.get("throttling") for v in views),
        "durable": any(v.get("durable") for v in views),
        "slo": {},
        "services": [
            {
                "service_dir": v.get("service_dir"),
                "durable": v.get("durable"),
                "plan_cache": v.get("plan_cache"),
                "result_cache": v.get("result_cache"),
                "queue_depth": v.get("queue_depth"),
                "running": v.get("running"),
                "slots": v.get("slots"),
                "throttling": v.get("throttling"),
            }
            for v in views
        ],
    }
    for v in views:
        merged["tenants"].update(v.get("tenants") or {})
        merged["queue_depth"] += v.get("queue_depth") or 0
        merged["running"] += v.get("running") or 0
        merged["slots"] += v.get("slots") or 0
        merged["slo"].update(v.get("slo") or {})
    if not merged["slo"]:
        merged["slo"] = None
    return merged


#: active (and a few recent) computes: compute_id -> progress dict
_computes_lock = threading.Lock()
_computes: "OrderedDict[str, dict]" = OrderedDict()
MAX_TRACKED_COMPUTES = 16


def compute_progress() -> list:
    """Progress rows for the dashboard/endpoints, newest last."""
    with _computes_lock:
        return [dict(row) for row in _computes.values()]


class ComputeProgressCallback(Callback):
    """Tracks one compute's tasks done/total for the live endpoints.

    Attached by ``Plan.execute`` whenever telemetry is armed; the sampler
    turns the numbers into ``compute_tasks_done`` / ``compute_tasks_total``
    series (labelled by compute id) from which the dashboard derives task
    rate and ETA."""

    def __init__(self):
        self._compute_id: Optional[str] = None

    def on_compute_start(self, event) -> None:
        from ..runtime.pipeline import iter_op_nodes

        cid = getattr(event, "compute_id", None) or "unknown"
        self._compute_id = cid
        total = 0
        try:
            total = sum(
                d["primitive_op"].num_tasks
                for _, d in iter_op_nodes(event.dag)
            )
        except Exception:  # introspection must never fail a compute
            pass
        with _computes_lock:
            _computes[cid] = {
                "compute_id": cid,
                "started_at": time.time(),
                "tasks_done": 0,
                "tasks_total": total,
                "status": "running",
                "ended_at": None,
            }
            while len(_computes) > MAX_TRACKED_COMPUTES:
                _computes.popitem(last=False)

    def on_task_end(self, event) -> None:
        cid = self._compute_id
        if cid is None:
            return
        # some executors (jax) emit ONE event covering an op's whole task
        # batch — num_tasks carries the real count (cf. the metrics
        # callback's tasks_completed fold)
        n = getattr(event, "num_tasks", 1) or 1
        with _computes_lock:
            row = _computes.get(cid)
            if row is not None:
                row["tasks_done"] += n

    def on_compute_end(self, event) -> None:
        cid = self._compute_id
        if cid is None:
            return
        failed = getattr(event, "error", None) is not None
        with _computes_lock:
            row = _computes.get(cid)
            if row is not None:
                row["status"] = "failed" if failed else "succeeded"
                row["ended_at"] = time.time()
        self._compute_id = None
        # release the finished compute's progress series promptly: the
        # dashboard only reads series for RUNNING computes, and a
        # long-lived endpoint must not let per-compute labels accumulate
        # toward the store's series cap
        from .export import get_runtime

        runtime = get_runtime()
        if runtime is not None:
            labels = {"compute": cid}
            runtime.store.forget("compute_tasks_done", labels=labels)
            runtime.store.forget("compute_tasks_total", labels=labels)


# ----------------------------------------------------------------------
# the sampler
# ----------------------------------------------------------------------


class TelemetrySampler:
    """~1s daemon loop: registry + fleet + compute progress -> the store.

    Counters are recorded cumulatively (rates derive on read), gauges as
    readings, histograms as ``<name>_count`` / ``<name>_sum`` plus their
    estimated quantiles. Per-worker dimensions come from every registered
    coordinator's worker table (heartbeat-fed); per-compute dimensions
    from :class:`ComputeProgressCallback`. Each tick ends by evaluating
    the alert engine, so alert latency is one sampling interval."""

    def __init__(
        self,
        store: TimeSeriesStore,
        interval_s: float = 1.0,
        alert_engine=None,
    ):
        self.store = store
        self.interval_s = max(0.05, float(interval_s))
        self.alert_engine = alert_engine
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.last_sample_ts: Optional[float] = None
        self._skip_logged = False
        #: once any fleet registered, the aggregate series keep recording
        #: (as zeros) after it closes — stale non-zero readings must decay
        self._saw_fleet = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # a stopped sampler must be restartable
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-sampler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                # the telemetry loop must never die of one bad tick
                logger.exception("telemetry sampler tick failed")

    # -- one tick ------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> None:
        """One sampling tick (public so tests and the sampler share one
        code path; the thread just calls this every interval)."""
        if now is None:
            now = time.time()
        reg = get_registry()
        self._sample_registry(reg, now)
        self._sample_fleets(now)
        self._sample_computes(now)
        self._sample_services(now)
        reg.counter("telemetry_samples").inc()
        self.last_sample_ts = now
        if self.alert_engine is not None:
            try:
                self.alert_engine.tick(now=now)
            except Exception:
                logger.exception("alert engine tick failed")

    def _sample_registry(self, reg, now: float) -> None:
        snap = reg.snapshot()
        recorded = 0
        skipped = 0
        for k in sorted(snap):
            if recorded >= MAX_SAMPLED_METRICS:
                # deterministic starvation of the alphabetically-late tail
                # — counted like every other bound in this layer, so a
                # metric silently missing from the series store has a
                # visible cause
                skipped += 1
                continue
            v = snap[k]
            if isinstance(v, dict):  # histogram summary
                self.store.record(f"{k}_count", v.get("count"), ts=now)
                self.store.record(f"{k}_sum", v.get("sum"), ts=now)
                recorded += 2
                for label in ("p50", "p95", "p99"):
                    if v.get(label) is not None:
                        self.store.record(f"{k}_{label}", v[label], ts=now)
                        recorded += 1
            elif k.endswith("_max"):
                continue  # lifetime high-water marks: not a time series
            elif isinstance(v, (int, float)):
                self.store.record(k, v, ts=now)
                recorded += 1
        if skipped:
            reg.counter("telemetry_metrics_skipped").inc(skipped)
            if not self._skip_logged:
                self._skip_logged = True
                logger.warning(
                    "telemetry sampler: registry namespace exceeds the "
                    "%d-metric per-tick budget; %d metric(s) skipped "
                    "(counted in telemetry_metrics_skipped)",
                    MAX_SAMPLED_METRICS, skipped,
                )

    def _sample_fleets(self, now: float) -> None:
        live = pressured = queue = 0
        n_fleets = 0
        for coord in live_fleets():
            n_fleets += 1
            try:
                rows = coord.load_view()
                workers = coord.stats_snapshot().get("workers") or {}
            except Exception:
                continue
            for row in rows:
                live += 1
                if row.get("pressured"):
                    pressured += 1
                queue += row.get("outstanding") or 0
                labels = {"worker": row["name"]}
                self.store.record(
                    "worker_outstanding", row.get("outstanding"), ts=now,
                    labels=labels,
                )
                self.store.record(
                    "worker_connected", 1 if row.get("connected") else 0,
                    ts=now, labels=labels,
                )
                self.store.record(
                    "worker_pressured", 1 if row.get("pressured") else 0,
                    ts=now, labels=labels,
                )
                wrow = workers.get(row["name"]) or {}
                if wrow.get("rss") is not None:
                    self.store.record(
                        "worker_rss_bytes", wrow["rss"], ts=now,
                        labels=labels,
                    )
                cache = wrow.get("peer_cache")
                if isinstance(cache, dict):
                    self.store.record(
                        "worker_peer_cache_bytes", cache.get("bytes"),
                        ts=now, labels=labels,
                    )
                metrics = wrow.get("metrics")
                if isinstance(metrics, dict):
                    # per-worker cumulative counters accumulated from the
                    # heartbeat snapshot_delta payloads: the ones the
                    # dashboard reads per worker (counted where the work
                    # ran — runtime/distributed.py folds them into each
                    # worker's registry)
                    for k in (
                        "worker_tasks_executed", "worker_task_errors",
                        "peer_hits", "peer_misses", "peer_chunks_served",
                    ):
                        if isinstance(metrics.get(k), (int, float)):
                            self.store.record(
                                f"fleet_{k}", metrics[k], ts=now,
                                labels=labels,
                            )
        if n_fleets:
            self._saw_fleet = True
        if self._saw_fleet:
            # keep recording (real zeros) after the last fleet closes: a
            # frozen last-known reading >=0.5 would hold a pressure alert
            # active forever in the long-lived telemetry singleton
            self.store.record("fleet_workers_live", live, ts=now)
            self.store.record("fleet_workers_pressured", pressured, ts=now)
            self.store.record(
                "fleet_pressured_fraction",
                (pressured / live) if live else 0.0, ts=now,
            )
            self.store.record("fleet_queue_depth", queue, ts=now)

    def _sample_services(self, now: float) -> None:
        """Per-tenant series from every live ComputeService: queue depth
        and running count as gauges, completions as a cumulative counter —
        what the ``tenant_starvation`` alert rule and the dashboard's
        TENANTS panel read."""
        for svc in live_services():
            try:
                snap = svc.stats_snapshot()
            except Exception:
                continue
            for tenant, row in (snap.get("tenants") or {}).items():
                labels = {"tenant": tenant}
                self.store.record(
                    "tenant_queued", row.get("queued"), ts=now, labels=labels,
                )
                self.store.record(
                    "tenant_running", row.get("running"), ts=now,
                    labels=labels,
                )
                self.store.record(
                    "tenant_completed", row.get("completed"), ts=now,
                    labels=labels,
                )
                self.store.record(
                    "tenant_throttled_total", row.get("throttled"), ts=now,
                    labels=labels,
                )
                # the tenant_cost_* family: cumulative consumption per
                # tenant (task-seconds, store/peer bytes, retry draw) from
                # the service's _CostTracker fold — what a quota/billing
                # story reads off /metrics
                cost = row.get("cost") or {}
                self.store.record(
                    "tenant_cost_task_seconds", cost.get("task_seconds"),
                    ts=now, labels=labels,
                )
                self.store.record(
                    "tenant_cost_bytes_read", cost.get("bytes_read"),
                    ts=now, labels=labels,
                )
                self.store.record(
                    "tenant_cost_bytes_written", cost.get("bytes_written"),
                    ts=now, labels=labels,
                )
                self.store.record(
                    "tenant_cost_peer_bytes", cost.get("peer_bytes"),
                    ts=now, labels=labels,
                )
                self.store.record(
                    "tenant_cost_retries", cost.get("retries"),
                    ts=now, labels=labels,
                )
            # the slo_* family: per-tenant board rows (burn rate per
            # window, budget remaining, SLI counts, latency quantiles) —
            # what the slo_fast_burn / slo_slow_burn rules watch and the
            # summary-convention /metrics quantile export reads
            for tenant, row in (snap.get("slo") or {}).items():
                labels = {"tenant": tenant}
                burn = row.get("burn") or {}
                for wlabel in ("5m", "1h", "6h", "3d"):
                    self.store.record(
                        f"slo_burn_{wlabel}", burn.get(wlabel), ts=now,
                        labels=labels,
                    )
                self.store.record(
                    "slo_budget_remaining", row.get("budget_remaining"),
                    ts=now, labels=labels,
                )
                self.store.record(
                    "slo_events_total", row.get("events"), ts=now,
                    labels=labels,
                )
                self.store.record(
                    "slo_bad_total", row.get("bad"), ts=now, labels=labels,
                )
                lat = row.get("latency") or {}
                for q in ("p50", "p95", "p99"):
                    self.store.record(
                        f"slo_request_latency_{q}", lat.get(f"{q}_s"),
                        ts=now, labels=labels,
                    )

    def _sample_computes(self, now: float) -> None:
        for row in compute_progress():
            if row.get("status") != "running":
                continue
            labels = {"compute": row["compute_id"]}
            self.store.record(
                "compute_tasks_done", row["tasks_done"], ts=now,
                labels=labels,
            )
            self.store.record(
                "compute_tasks_total", row["tasks_total"], ts=now,
                labels=labels,
            )


def fleet_view() -> dict:
    """Point-in-time fleet table for ``/snapshot.json`` / ``/healthz`` /
    the dashboard: per-worker rows from every live coordinator, plus the
    aggregate counts the health verdict is made of."""
    workers: Dict[str, dict] = {}
    live = pressured = disconnected = 0
    epoch = 0
    for coord in live_fleets():
        try:
            snap = coord.stats_snapshot()
        except Exception:
            continue
        epoch = max(epoch, int(snap.get("epoch") or 0))
        for name, row in (snap.get("workers") or {}).items():
            if not row.get("alive"):
                continue
            live += 1
            if row.get("pressured"):
                pressured += 1
            if not row.get("connected", True):
                disconnected += 1
            workers[name] = row
    return {
        "workers": workers,
        "workers_live": live,
        "workers_pressured": pressured,
        "workers_disconnected": disconnected,
        # the control-plane epoch (max across fleets): bumps on every
        # coordinator takeover, so a dashboard reading 1+ knows this
        # fleet was adopted by a successor at least once
        "epoch": epoch,
        "fleets": len(live_fleets()),
    }


def dispatch_view() -> Optional[dict]:
    """Point-in-time control-plane view for ``/snapshot.json`` and the
    ``cubed_tpu.top`` DISPATCH panel: the dispatch loop's self-accounted
    utilization/capacity gauges (registry) plus per-message-type frame
    and byte counts from every live coordinator's link. None when
    nothing dispatch-shaped has been recorded yet."""
    from .metrics import get_registry

    snap = get_registry().snapshot()
    out: dict = {}
    for key in (
        "dispatch_utilization", "dispatch_capacity_estimate",
        "dispatch_submit_s", "dispatch_serialize_s", "dispatch_send_s",
        "dispatch_unpickle_s", "dispatch_release_s",
        "dispatch_lock_wait_s", "dispatch_sched_hook_s",
        "coord_frames_sent", "coord_frames_recv",
        "coord_frame_bytes_sent", "coord_frame_bytes_recv",
    ):
        if key in snap:
            out[key] = snap[key]
    frames: Dict[str, dict] = {}
    for coord in live_fleets():
        try:
            fsnap = coord.stats_snapshot().get("frames") or {}
        except Exception:
            continue
        for direction, rows in fsnap.items():
            agg = frames.setdefault(direction, {})
            for mtype, (count, nbytes) in rows.items():
                cur = agg.setdefault(mtype, [0, 0])
                cur[0] += count
                cur[1] += nbytes
    if frames:
        out["frames"] = frames
    return out or None
