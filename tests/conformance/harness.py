"""Shared machinery for the hypothesis conformance suite.

Role parity: the official ``data-apis/array-api-tests`` hypothesis suite the
reference runs in CI (/root/reference/.github/workflows/array-api-tests.yml:
28-112). That package cannot be installed here (no network egress), so this
suite reimplements its approach — property tests driving the namespace-under-
test against an oracle over generated inputs — with numpy 2.x (Array-API-
aligned) as the oracle. Known divergences are pinned in SKIPS.txt.
"""

from __future__ import annotations

import numpy as np
from hypothesis import strategies as st
from hypothesis.extra import numpy as hnp

import cubed_tpu as ct

#: dtype pools per Array API category
REAL_FLOAT_DTYPES = (np.float32, np.float64)
INT_DTYPES = (np.int8, np.int16, np.int32, np.int64)
UINT_DTYPES = (np.uint8, np.uint16, np.uint32, np.uint64)
NUMERIC_DTYPES = REAL_FLOAT_DTYPES + INT_DTYPES + UINT_DTYPES
BOOL_DTYPE = (np.bool_,)
ALL_DTYPES = NUMERIC_DTYPES + BOOL_DTYPE


def shapes(min_dims=1, max_dims=3, max_side=7):
    return hnp.array_shapes(
        min_dims=min_dims, max_dims=max_dims, min_side=1, max_side=max_side
    )


def arrays(dtypes=REAL_FLOAT_DTYPES, shape=None, elements=None, min_dims=1):
    """Strategy for a numpy array with finite, kernel-safe elements."""

    def elems(dt):
        dt = np.dtype(dt)
        if elements is not None:
            return elements
        if dt.kind == "f":
            # no subnormals: XLA flushes them to zero (pinned in SKIPS.txt)
            return st.floats(
                min_value=-1e6,
                max_value=1e6,
                allow_nan=False,
                allow_infinity=False,
                allow_subnormal=False,
                width=dt.itemsize * 8,
            )
        if dt.kind == "u":
            return st.integers(min_value=0, max_value=100)
        if dt.kind == "i":
            return st.integers(min_value=-100, max_value=100)
        return st.booleans()

    dtype_st = st.sampled_from(dtypes)
    shape_st = shapes(min_dims=min_dims) if shape is None else st.just(shape)
    return dtype_st.flatmap(
        lambda dt: shape_st.flatmap(
            lambda sh: hnp.arrays(dtype=dt, shape=sh, elements=elems(dt))
        )
    )


def chunks_for(shape):
    """A ragged-ish chunking: exercises edge chunks on most shapes."""
    return tuple(max(1, (s + 1) // 2) for s in shape)


def wrap(an, spec):
    return ct.from_array(an, chunks=chunks_for(an.shape), spec=spec)


def run(arr):
    return np.asarray(arr.compute())


def assert_matches(got: np.ndarray, expect: np.ndarray, *, exact=False, atol=None):
    """Result comparison with spec-level tolerance per dtype.

    ``atol`` overrides the near-zero absolute floor — reductions over
    reorderable sums need a magnitude-aware one (see summation_atol)."""
    assert got.shape == tuple(expect.shape), (got.shape, expect.shape)
    assert got.dtype == expect.dtype, (got.dtype, expect.dtype)
    if exact or expect.dtype.kind in "biu":
        np.testing.assert_array_equal(got, expect)
    else:
        rtol = 1e-4 if expect.dtype.itemsize <= 4 else 1e-9
        np.testing.assert_allclose(
            got, expect, rtol=rtol, atol=1e-30 if atol is None else atol,
            equal_nan=True,
        )


def summation_atol(an: np.ndarray, axis=None, *, mean=False) -> float:
    """Absolute tolerance for a reordered (chunk-tree) float summation.

    The spec leaves summation order unspecified; chunked tree-sums and
    numpy's pairwise sums legitimately diverge under catastrophic
    cancellation, where RELATIVE error is unbounded (found by the
    conformance fuzzer at 120-example depth on f32). The standard bound
    for a depth-d summation tree is ``|err| <= d * eps * sum(|a|)`` per
    output element; both orderings here are trees of depth
    O(log2(k) + chunks), so the tolerance tracks the worst per-output
    ``sum(|a|)`` times a depth factor — far tighter in k than the former
    ``k * max|a| * eps`` sequential-order bound, which admitted absolute
    errors no real tree-sum produces for large k. For ``mean`` the bound
    divides by k (the mean divides the sum)."""
    if an.size == 0 or an.dtype.kind not in "fc":
        return 1e-30
    finite_abs = np.abs(np.where(np.isfinite(an), an, 0.0))
    if axis is None:
        axes = tuple(range(an.ndim))
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        axes = tuple(ax % an.ndim for ax in axes)
    k = 1
    for ax in set(axes):
        k *= an.shape[ax]
    k = max(k, 1)
    per_output_abssum = np.sum(finite_abs, axis=axes)
    scale = float(np.max(per_output_abssum)) if per_output_abssum.size else 0.0
    # depth slack: numpy's pairwise summation is sequential within blocks
    # of up to 128 adds (its base case), so the effective tree depth is
    # min(k, 128) sequential steps + log2(k/128) pairwise levels — a pure
    # log2(k) model under-bounds mid-size k (~256..1e5), where an
    # adversarial draw can legitimately exceed it; + a constant for the
    # chunk-boundary reorder between the two trees (chunkings are <=2/axis)
    depth = min(float(k), 128.0) + np.log2(max(1.0, k / 128.0)) + 8.0
    bound = 4.0 * depth * scale * float(np.finfo(an.dtype).eps)
    if mean:
        bound /= k
    return max(1e-30, bound)
