"""The blockwise primitive: apply a function to corresponding blocks of inputs,
producing one output block per task.

Front-end ``blockwise`` compiles dask-style index notation into a *block
function* mapping an output chunk key to the input chunk keys it consumes
(implemented from scratch — no dask machinery). Back-end ``general_blockwise``
wires read/write proxies, computes the plan-time projected memory and raises if
it exceeds ``allowed_mem`` — the bounded-memory guarantee.

Fusion composes block functions and chunk functions so a fused chain becomes a
single per-chunk kernel — on the TPU executor this compiles to ONE XLA program
whose intermediates never leave registers/HBM.

Reference parity: cubed/primitive/blockwise.py (behavioral; clean-room).
"""

from __future__ import annotations

import inspect
import itertools
import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..backend_array_api import (
    backend_array_to_numpy_array,
    numpy_array_to_backend_array,
)
from ..chunks import numblocks as chunks_to_numblocks
from ..chunks import blockdims_from_blockshape
from ..storage.zarr import lazy_empty
from ..utils import (  # noqa: F401  (gensym re-exported for rechunk/tests)
    chunk_memory,
    gensym,
    get_item,
    map_nested,
    memory_repr,
    split_into,
    to_chunksize,
)
from .types import (
    CubedArrayProxy,
    CubedPipeline,
    MemoryModeller,
    PrimitiveOperation,
)


# ---------------------------------------------------------------------------
# BlockwiseSpec and the task body
# ---------------------------------------------------------------------------


@dataclass
class BlockwiseSpec:
    """Specification of how to compute one output block of a blockwise op.

    ``block_function`` maps an output chunk key ``(name, i, j, ...)`` to a tuple
    with one entry per function argument; each entry is an input chunk key, a
    (possibly nested) list of keys (contracted dims), or an iterator of keys
    (streaming reads for tree reductions).
    ``function`` consumes the chunks in the same structure and returns the
    output chunk (an array, or a dict of arrays for structured intermediates).

    Multi-output ops (``writes_rest`` non-empty) return a TUPLE of arrays,
    one per output, all sharing the block grid of the primary output; each
    is written to the corresponding target. One kernel evaluation feeds N
    arrays — e.g. a sort-network round emits (values, indices) from a
    single merge instead of running the merge once per output.
    """

    block_function: Callable[..., Any]
    function: Callable[..., Any]
    function_nargs: int
    num_input_blocks: tuple[int, ...]
    reads_map: Dict[str, CubedArrayProxy]
    write: CubedArrayProxy
    #: True when ``function`` commutes with chunking (pure elementwise /
    #: broadcasting kernels): applying it to whole arrays equals applying it
    #: per chunk. The TPU executor uses this to run the entire (fused) kernel
    #: as ONE XLA program over HBM-resident arrays.
    shape_invariant: bool = False
    #: additional output proxies for multi-output ops (empty for the
    #: ordinary single-output case)
    writes_rest: tuple = ()

    @property
    def writes(self) -> tuple:
        """All output proxies, primary first."""
        return (self.write, *self.writes_rest)


def get_chunk(arr, chunkset, block_idx: tuple[int, ...]):
    """Read one chunk of an opened array as a backend (jax) array."""
    sel = get_item(chunkset, block_idx)
    chunk = arr[sel]
    return numpy_array_to_backend_array(chunk)


def _read_keys(structure, config: BlockwiseSpec):
    """Resolve a (nested / lazy) structure of chunk keys into chunk arrays."""
    if isinstance(structure, PredKeys):
        return PredArgs([_read_keys(item, config) for item in structure])
    if isinstance(structure, (list, tuple)) and not _is_key(structure):
        return [_read_keys(item, config) for item in structure]
    if isinstance(structure, Iterator):
        return (_read_keys(item, config) for item in structure)
    # a single key: (name, i, j, ...)
    name, block_idx = structure[0], tuple(structure[1:])
    proxy = config.reads_map[name]
    arr = proxy.open()
    chunkset = blockdims_from_blockshape(arr.shape, proxy.chunks) if arr.shape else ()
    return get_chunk(arr, chunkset, block_idx)


def _is_key(obj) -> bool:
    return (
        isinstance(obj, tuple)
        and len(obj) >= 1
        and isinstance(obj[0], str)
        and all(isinstance(i, (int, np.integer)) for i in obj[1:])
    )


def apply_blockwise(out_key: tuple, *, config: BlockwiseSpec) -> None:
    """Task body: read input chunks, apply the (fused) kernel, write the result."""
    from ..observability.accounting import scope_span

    out_name, out_coords = out_key[0], tuple(out_key[1:])
    args_structure = config.block_function(out_key)
    args = [_read_keys(entry, config) for entry in args_structure]
    # the kernel itself gets its own span (vs the storage spans around it),
    # so a merged trace separates compute time from IO time per task
    with scope_span("kernel_apply", cat="kernel", op=out_name):
        if getattr(config.function, "needs_block_id", False):
            result = config.function(*args, block_id=out_coords)
        else:
            result = config.function(*args)

    if config.writes_rest:
        writes = config.writes
        if not isinstance(result, (tuple, list)) or len(result) != len(writes):
            raise ValueError(
                f"multi-output kernel must return {len(writes)} arrays, "
                f"got {type(result).__name__}"
            )
        for proxy, res in zip(writes, result):
            _write_chunk(proxy, out_coords, res)
    else:
        _write_chunk(config.write, out_coords, result)


def _write_chunk(write: CubedArrayProxy, out_coords: tuple, result) -> None:
    """Write one output chunk through a proxy (plain or structured dtype)."""
    target = write.open()
    chunkset = (
        blockdims_from_blockshape(target.shape, write.chunks)
        if target.shape
        else ()
    )
    out_sel = get_item(chunkset, out_coords) if target.shape else ()
    if isinstance(result, dict):
        # structured (pytree) intermediates: write each field of a structured dtype
        fields = {k: backend_array_to_numpy_array(v) for k, v in result.items()}
        names = target.dtype.names
        shape = next(iter(fields.values())).shape
        rec = np.empty(shape, dtype=target.dtype)
        for k in names:
            rec[k] = fields[k]
        target[out_sel] = rec
    else:
        target[out_sel] = backend_array_to_numpy_array(result)


# ---------------------------------------------------------------------------
# Index-notation compiler (replaces the dask machinery the reference vendors)
# ---------------------------------------------------------------------------


def make_blockwise_function(
    out_name: str,
    out_ind: Sequence,
    argpairs: Sequence[tuple[str, Sequence]],
    numblocks: Dict[str, tuple[int, ...]],
    new_axes: Optional[Dict] = None,
) -> Callable[[tuple], tuple]:
    """Compile index notation into a block function.

    For each output key, every argument gets the input key(s) with coordinates
    matched by index symbol. Symbols appearing in arguments but not in the
    output ("contracted" symbols) expand to nested lists over all their blocks,
    nested in the order the symbols appear in that argument's indices.
    Arguments with a single block along a dim broadcast (coordinate clamps to 0).
    """
    new_axes = new_axes or {}
    # number of blocks per symbol
    dims: Dict[Any, int] = {}
    for name, ind in argpairs:
        if ind is None:
            continue
        for sym, nb in zip(ind, numblocks[name]):
            if sym in dims:
                dims[sym] = max(dims[sym], nb)
            else:
                dims[sym] = nb
    for sym in out_ind:
        if sym not in dims:
            dims[sym] = 1  # new axis symbols

    def block_function(out_key: tuple) -> tuple:
        out_coords = dict(zip(out_ind, out_key[1:]))
        entries = []
        for name, ind in argpairs:
            if ind is None:
                entries.append(None)
                continue
            contracted = [s for s in ind if s not in out_coords]
            # dedupe, preserving order
            seen = set()
            contracted = [s for s in contracted if not (s in seen or seen.add(s))]

            def build(sym_values: Dict, rem: List):
                if not rem:
                    coords = []
                    for axis, s in enumerate(ind):
                        c = out_coords.get(s, sym_values.get(s, 0))
                        if numblocks[name][axis] == 1:
                            c = 0
                        coords.append(int(c))
                    return (name, *coords)
                sym = rem[0]
                return [
                    build({**sym_values, sym: v}, rem[1:]) for v in range(dims[sym])
                ]

            entries.append(build({}, contracted))
        return tuple(entries)

    return block_function


# ---------------------------------------------------------------------------
# Primitive constructors
# ---------------------------------------------------------------------------


def blockwise(
    func: Callable,
    out_ind: Sequence,
    *args: Any,  # pairs of (array, indices)
    allowed_mem: int,
    reserved_mem: int,
    target_store: str,
    shape: tuple[int, ...],
    dtype: Any,
    chunks: tuple,  # tuple-of-tuples (normalized)
    new_axes: Optional[Dict] = None,
    in_names: Optional[List[str]] = None,
    out_name: Optional[str] = None,
    extra_projected_mem: int = 0,
    extra_func_kwargs: Optional[Dict] = None,
    fusable: bool = True,
    shape_invariant: bool = False,
    storage_options: Optional[dict] = None,
    **kwargs,
) -> PrimitiveOperation:
    """Apply *func* across blocks of inputs matched by index notation."""
    arrays = args[0::2]
    inds = args[1::2]
    if in_names is None:
        in_names = [f"in_{i}" for i in range(len(arrays))]
    numblocks: Dict[str, tuple[int, ...]] = {}
    for name, arr in zip(in_names, arrays):
        cs = _array_chunkset(arr)
        numblocks[name] = chunks_to_numblocks(cs)

    argpairs = list(zip(in_names, inds))
    block_function = make_blockwise_function(
        out_name or "out", out_ind, argpairs, numblocks, new_axes
    )

    func_kwargs = {**(extra_func_kwargs or {}), **kwargs}
    if func_kwargs:

        def function(*chunk_args):
            return func(*chunk_args, **func_kwargs)

        function.__name__ = getattr(func, "__name__", "function")
    else:
        function = func

    return general_blockwise(
        function,
        block_function,
        *arrays,
        allowed_mem=allowed_mem,
        reserved_mem=reserved_mem,
        target_store=target_store,
        shape=shape,
        dtype=dtype,
        chunks=chunks,
        in_names=in_names,
        out_name=out_name,
        extra_projected_mem=extra_projected_mem,
        fusable=fusable,
        shape_invariant=shape_invariant,
        storage_options=storage_options,
    )


def _array_chunkset(arr) -> tuple[tuple[int, ...], ...]:
    """Chunks of any array-like in tuple-of-tuples form."""
    if hasattr(arr, "chunkset"):
        return arr.chunkset()
    chunks = arr.chunks
    if chunks and isinstance(chunks[0], tuple):
        return chunks
    return blockdims_from_blockshape(arr.shape, chunks)


def general_blockwise(
    function: Callable,
    block_function: Callable,
    *arrays: Any,
    allowed_mem: int,
    reserved_mem: int,
    target_store: Any,
    shape: Any,
    dtype: Any,
    chunks: tuple,  # tuple-of-tuples
    in_names: Optional[List[str]] = None,
    out_name: Any = None,
    extra_projected_mem: int = 0,
    num_input_blocks: Optional[tuple[int, ...]] = None,
    fusable: bool = True,
    shape_invariant: bool = False,
    storage_options: Optional[dict] = None,
) -> PrimitiveOperation:
    """Build a PrimitiveOperation for an explicit block function.

    Multi-output: pass ``dtype`` (and ``target_store``/``out_name``, and
    optionally ``shape``) as LISTS — one entry per output, all outputs on
    ONE shared block grid. ``function`` then returns a tuple of arrays,
    one per output, and the returned op carries ``target_arrays``.
    Outputs may have distinct chunk SIZES (pass ``chunks`` as a list of
    per-output normalized chunks) as long as every output's numblocks
    agree — e.g. TSQR's per-row-block (Q, R) pair, where Q blocks are
    ``(c, n)`` and R blocks ``(n, n)`` on the same grid.
    """
    multi = isinstance(dtype, (list, tuple))
    if multi:
        n_out = len(dtype)
        # the core layer owns shape replication; the primitive requires
        # explicit per-output lists so a plain string/tuple can't be
        # silently iterated into nonsense
        if not (
            isinstance(shape, (list, tuple))
            and shape
            and isinstance(shape[0], (list, tuple))
        ):
            raise TypeError(
                "multi-output general_blockwise requires shape to be a "
                "list of per-output shapes"
            )
        if not isinstance(target_store, (list, tuple)) or not isinstance(
            out_name, (list, tuple)
        ):
            raise TypeError(
                "multi-output general_blockwise requires list-valued "
                "target_store and out_name"
            )
        shapes = [tuple(s) for s in shape]
        stores = list(target_store)
        out_names = list(out_name)
        dtypes = list(dtype)
        if not (len(shapes) == len(stores) == len(out_names) == n_out):
            raise ValueError("multi-output lists must have equal length")
        if isinstance(chunks, list):  # per-output chunks
            if len(chunks) != n_out:
                raise ValueError(
                    "per-output chunks list must have one entry per output"
                )
            chunks_list = [tuple(c) for c in chunks]
        else:
            chunks_list = [chunks] * n_out
        chunksizes = [
            to_chunksize(c) if s else ()
            for c, s in zip(chunks_list, shapes)
        ]
        nbs = {
            chunks_to_numblocks(blockdims_from_blockshape(s, cs))
            for s, cs in zip(shapes, chunksizes)
        }
        if len(nbs) != 1:
            raise ValueError(
                "multi-output arrays must share one block grid; got "
                f"numblocks {sorted(nbs)}"
            )
        chunks = chunks_list[0]  # output 0 defines the mappable grid
    else:
        shapes = [tuple(shape)]
        stores = [target_store]
        out_names = [out_name or gensym("array")]
        dtypes = [dtype]
        chunksizes = [to_chunksize(chunks) if shapes[0] else ()]
    if in_names is None:
        in_names = [f"in_{i}" for i in range(len(arrays))]

    chunksize = chunksizes[0]
    target_arrays = [
        lazy_empty(
            s, dtype=dt, chunks=cs, store=st,
            storage_options=storage_options,
        )
        for s, dt, cs, st in zip(shapes, dtypes, chunksizes, stores)
    ]

    reads_map = {
        name: CubedArrayProxy(arr, _proxy_chunks(arr))
        for name, arr in zip(in_names, arrays)
    }
    writes = [
        CubedArrayProxy(t, cs) for t, cs in zip(target_arrays, chunksizes)
    ]

    # --- plan-time memory bound -------------------------------------------
    # Each input chunk is counted twice (storage-side buffer + backend array)
    # and each output twice (backend result + write buffer); this deliberately
    # keeps the reference's conservative factor even though raw (uncompressed)
    # storage could drop one copy. Reference: cubed/primitive/blockwise.py:282-300.
    projected_mem = reserved_mem + extra_projected_mem
    for name, arr in zip(in_names, arrays):
        projected_mem += 2 * chunk_memory(arr.dtype, reads_map[name].chunks)
    for dt, cs in zip(dtypes, chunksizes):
        projected_mem += 2 * chunk_memory(dt, cs)

    if projected_mem > allowed_mem:
        raise ValueError(
            f"Projected blockwise memory ({memory_repr(projected_mem)}) exceeds "
            f"allowed_mem ({memory_repr(allowed_mem)}), including "
            f"reserved_mem ({memory_repr(reserved_mem)})"
        )

    nb_out = chunks_to_numblocks(chunks)
    mappable = [(out_names[0], *idx) for idx in itertools.product(*(range(n) for n in nb_out))]
    if not mappable:
        mappable = [(out_names[0],)]

    spec = BlockwiseSpec(
        block_function=block_function,
        function=function,
        function_nargs=len(arrays),
        num_input_blocks=num_input_blocks or (1,) * len(arrays),
        reads_map=reads_map,
        write=writes[0],
        shape_invariant=shape_invariant,
        writes_rest=tuple(writes[1:]),
    )
    pipeline = CubedPipeline(apply_blockwise, gensym("blockwise"), mappable, spec)
    return PrimitiveOperation(
        pipeline=pipeline,
        source_array_names=list(in_names),
        target_array=target_arrays[0],
        projected_mem=projected_mem,
        allowed_mem=allowed_mem,
        reserved_mem=reserved_mem,
        num_tasks=len(mappable),
        fusable=fusable,
        write_chunks=chunksize,
        target_arrays=target_arrays if multi else None,
    )


def _proxy_chunks(arr) -> tuple[int, ...]:
    chunks = arr.chunks
    if chunks and isinstance(chunks[0], tuple):
        return to_chunksize(chunks)
    return tuple(chunks)


# ---------------------------------------------------------------------------
# Fusion
# ---------------------------------------------------------------------------


class PredKeys(list):
    """Marks the key-structure of a fused predecessor's argument list.

    When a fused chain's block function substitutes a predecessor's block
    function in place of a chunk key, the resulting per-arg key structure is
    wrapped in this type so the read path and the fused kernel can tell it
    apart from a plain contraction list.
    """


class PredArgs(list):
    """The resolved-chunk counterpart of :class:`PredKeys`."""


def is_fuse_candidate(op: PrimitiveOperation) -> bool:
    """An op is fusable iff its task body is ``apply_blockwise``."""
    return op.pipeline.function is apply_blockwise


def can_fuse_pipelines(op1: PrimitiveOperation, op2: PrimitiveOperation) -> bool:
    if op1.target_arrays is not None:
        # a multi-output predecessor can't fuse away into one consumer: its
        # other outputs still need writing (consumers CAN be multi-output;
        # on the TPU executor unfused ops still trace into one segment
        # program, so nothing is lost on the primary path)
        return False
    if is_fuse_candidate(op1) and is_fuse_candidate(op2):
        return op1.fusable and op2.fusable and op1.num_tasks == op2.num_tasks
    return False


def _substitute(entry, pred_spec: BlockwiseSpec):
    """Replace every chunk key in *entry* with the predecessor's key structure."""
    if isinstance(entry, list):
        return [_substitute(e, pred_spec) for e in entry]
    if isinstance(entry, Iterator):
        return (_substitute(e, pred_spec) for e in entry)
    # a single key of the predecessor's output
    return PredKeys(pred_spec.block_function(entry))


def _evaluate(arg, pred_function: Callable):
    """Apply the predecessor kernel wherever reads were substituted."""
    if isinstance(arg, PredArgs):
        return pred_function(*arg)
    if isinstance(arg, list):
        return [_evaluate(a, pred_function) for a in arg]
    if isinstance(arg, Iterator):
        return (_evaluate(a, pred_function) for a in arg)
    return arg


def fuse(op1: PrimitiveOperation, op2: PrimitiveOperation) -> PrimitiveOperation:
    """Fuse a linear op1 -> (array) -> op2 chain into one op.

    The composed chunk function applies op1's kernel to each chunk read and
    feeds the results to op2's kernel — one jittable body whose intermediate
    never exists in storage (and, under the TPU executor, never leaves HBM).
    """
    assert op1.num_tasks == op2.num_tasks
    return fuse_multiple(op2, *( [op1] * op2.pipeline.config.function_nargs ))


def fuse_multiple(
    op: PrimitiveOperation,
    *predecessor_ops: Optional[PrimitiveOperation],
) -> PrimitiveOperation:
    """Fuse op with any subset of its argument-producing predecessors.

    ``predecessor_ops[i]`` produces op's i-th argument, or None to leave that
    argument as a plain read. Reference parity: cubed/primitive/blockwise.py:420-508.
    """
    spec: BlockwiseSpec = op.pipeline.config
    preds = list(predecessor_ops) + [None] * (spec.function_nargs - len(predecessor_ops))
    pred_specs: list[Optional[BlockwiseSpec]] = [
        p.pipeline.config if p is not None else None for p in preds
    ]
    pred_functions = [ps.function if ps is not None else None for ps in pred_specs]

    def fused_block_function(out_key):
        structure = spec.block_function(out_key)
        return tuple(
            entry if pspec is None else _substitute(entry, pspec)
            for entry, pspec in zip(structure, pred_specs)
        )

    def fused_function(*args, **kw):
        evaluated = [
            arg if pf is None else _evaluate(arg, pf)
            for arg, pf in zip(args, pred_functions)
        ]
        return spec.function(*evaluated, **kw)

    # executor routing hints survive fusion: a fused kernel is host-bound if
    # any component is. Every offsets-reading kernel carries either
    # host_block_id or traced_offsets, so "some component reads offsets
    # traced and none reads them on the host" means all offsets reads in the
    # fused body are trace-safe.
    components = [spec.function] + [pf for pf in pred_functions if pf is not None]
    fused_function.host_block_id = any(
        getattr(f, "host_block_id", False) for f in components
    )
    fused_function.host_data_nbytes = sum(
        getattr(f, "host_data_nbytes", 0) for f in components
    )
    fused_function.traced_offsets = (
        any(getattr(f, "traced_offsets", False) for f in components)
        and not fused_function.host_block_id
    )
    if getattr(spec.function, "needs_block_id", False):
        fused_function.needs_block_id = True

    # reads: union of unfused own reads and all fused predecessors' reads
    fused_outputs = {id(p.target_array) for p in preds if p is not None}
    reads_map: Dict[str, CubedArrayProxy] = {}
    source_names: list[str] = []
    for name, proxy in spec.reads_map.items():
        if id(proxy.array) not in fused_outputs:
            reads_map[name] = proxy
            source_names.append(name)
    seen_preds = set()
    num_input_blocks: list[int] = []
    for i, (p, pspec) in enumerate(zip(preds, pred_specs)):
        if pspec is None:
            if i < len(spec.num_input_blocks):
                num_input_blocks.append(spec.num_input_blocks[i])
            continue
        if id(p) in seen_preds:
            continue
        seen_preds.add(id(p))
        reads_map.update(pspec.reads_map)
        source_names.extend(p.source_array_names)
        nib = spec.num_input_blocks[i] if i < len(spec.num_input_blocks) else 1
        num_input_blocks.extend(n * nib for n in pspec.num_input_blocks)

    # memory model: predecessors execute one after another inside the fused
    # task; each holds its own projected working set while running, and leaves
    # its output chunk live until the consuming kernel runs.
    modeller = MemoryModeller()
    unique_preds = []
    seen = set()
    for p in preds:
        if p is not None and id(p) not in seen:
            seen.add(id(p))
            unique_preds.append(p)
    for p in unique_preds:
        working = p.projected_mem - p.reserved_mem
        retained = 2 * chunk_memory(p.target_array.dtype, p.write_chunks or ())
        modeller.allocate(working)
        modeller.free(working - retained)
    modeller.allocate(op.projected_mem - op.reserved_mem)
    projected_mem = op.reserved_mem + modeller.peak_mem

    fused_spec = BlockwiseSpec(
        block_function=fused_block_function,
        function=fused_function,
        function_nargs=spec.function_nargs,
        num_input_blocks=tuple(num_input_blocks) or spec.num_input_blocks,
        reads_map=reads_map,
        write=spec.write,
        shape_invariant=spec.shape_invariant
        and all(ps is None or ps.shape_invariant for ps in pred_specs),
        writes_rest=spec.writes_rest,
    )
    pipeline = CubedPipeline(
        apply_blockwise, gensym("fused"), op.pipeline.mappable, fused_spec
    )
    return PrimitiveOperation(
        pipeline=pipeline,
        source_array_names=source_names,
        target_array=op.target_array,
        projected_mem=projected_mem,
        allowed_mem=op.allowed_mem,
        reserved_mem=op.reserved_mem,
        num_tasks=op.num_tasks,
        fusable=True,
        write_chunks=op.write_chunks,
        target_arrays=op.target_arrays,
    )


def peak_projected_mem(ops: Sequence[PrimitiveOperation]) -> int:
    """Peak projected memory of running *ops* sequentially, retaining outputs."""
    modeller = MemoryModeller()
    for p in ops:
        working = p.projected_mem - p.reserved_mem
        retained = 2 * chunk_memory(p.target_array.dtype, p.write_chunks or ())
        modeller.allocate(working)
        modeller.free(working - retained)
    return modeller.peak_mem
