"""The Array API namespace (v2022.12 standard surface plus extensions).

Reference parity: cubed/array_api/__init__.py:1-254.
"""

__array_api_version__ = "2022.12"

from .array_object import Array  # noqa: F401

from .constants import e, inf, nan, newaxis, pi  # noqa: F401

from .creation_functions import (  # noqa: F401
    arange,
    asarray,
    empty,
    empty_like,
    empty_virtual_array,
    eye,
    full,
    full_like,
    linspace,
    meshgrid,
    ones,
    ones_like,
    tril,
    triu,
    zeros,
    zeros_like,
)

from .data_type_functions import (  # noqa: F401
    astype,
    can_cast,
    finfo,
    iinfo,
    isdtype,
    result_type,
)

from .dtypes import (  # noqa: F401
    bool,
    complex64,
    complex128,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
    uint16,
    uint32,
    uint64,
)

from .elementwise_functions import (  # noqa: F401
    abs,
    acos,
    acosh,
    add,
    asin,
    asinh,
    atan,
    atan2,
    atanh,
    bitwise_and,
    bitwise_invert,
    bitwise_left_shift,
    bitwise_or,
    bitwise_right_shift,
    bitwise_xor,
    ceil,
    clip,
    conj,
    copysign,
    cos,
    cosh,
    divide,
    equal,
    exp,
    expm1,
    floor,
    floor_divide,
    greater,
    greater_equal,
    hypot,
    imag,
    isfinite,
    isinf,
    isnan,
    less,
    less_equal,
    log,
    log10,
    log1p,
    log2,
    logaddexp,
    logical_and,
    logical_not,
    logical_or,
    logical_xor,
    maximum,
    minimum,
    multiply,
    negative,
    not_equal,
    positive,
    pow,
    real,
    remainder,
    round,
    sign,
    signbit,
    nextafter,
    reciprocal,
    sin,
    sinh,
    sqrt,
    square,
    subtract,
    tan,
    tanh,
    trunc,
)

from .indexing_functions import take, take_along_axis  # noqa: F401

from .linear_algebra_functions import (  # noqa: F401
    matmul,
    matrix_transpose,
    outer,
    tensordot,
    vecdot,
)

from .manipulation_functions import (  # noqa: F401
    broadcast_arrays,
    broadcast_to,
    concat,
    expand_dims,
    flatten,
    flip,
    moveaxis,
    permute_dims,
    repeat,
    reshape,
    roll,
    squeeze,
    stack,
    tile,
    unstack,
)

from .searching_functions import argmax, argmin, count_nonzero, where  # noqa: F401
from .sorting_functions import argsort, searchsorted, sort  # noqa: F401

from .statistical_functions import (  # noqa: F401
    cumulative_prod,
    cumulative_sum,
    max,
    mean,
    min,
    prod,
    std,
    sum,
    var,
)

from .utility_functions import all, any, diff  # noqa: F401

from . import fft  # noqa: F401  (extension namespace, beyond reference)
from . import linalg  # noqa: F401  (extension namespace, beyond reference)
from .searching_functions import nonzero  # noqa: F401  (loud rejection)
from .set_functions import (  # noqa: F401  (loud rejections)
    unique_all,
    unique_counts,
    unique_inverse,
    unique_values,
)
from .creation_functions import from_dlpack  # noqa: F401
from .einsum_functions import einsum  # noqa: F401  (beyond-standard extension)
from .statistical_functions import median, quantile  # noqa: F401  (beyond-standard)
from .statistical_functions import corrcoef, cov, histogram  # noqa: F401  (beyond-standard)
from .manipulation_functions import pad  # noqa: F401  (beyond-standard)
from .statistical_functions import nanmedian, nanquantile  # noqa: F401  (beyond-standard)
from .sorting_functions import argtopk, topk  # noqa: F401  (beyond-standard)
