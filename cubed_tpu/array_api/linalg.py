"""Array-API ``linalg`` extension namespace — beyond the reference.

The reference implements only the five core linear-algebra functions and no
``linalg`` extension (cubed/array_api/linear_algebra_functions.py); this
module adds the 2022.12 extension surface on chunked arrays.

TPU-first design:

- ``qr`` / ``svd`` / ``svdvals`` on 2-d row-chunked arrays run **TSQR**
  (tall-skinny QR): stage 1 is ONE multi-output blockwise op emitting
  per-panel Q blocks *and* the stacked R factors — two outputs with
  different chunk sizes ((c, n) and (n, n)) on one block grid, which is
  exactly what per-output-chunks multi-output ops exist for. Stage 2 QRs
  the stacked R in a single task; stage 3 forms Q by pairing each panel
  with its slice of the inner Q (a traced-offset kernel, so the whole
  factorization jits/vmaps and joins fused segments). Rows may exceed
  ``allowed_mem``; panels never do.
- Square per-matrix ops (``cholesky``, ``inv``, ``solve``, ``det``,
  ``slogdet``, ``eigh``, …) rechunk the core (last two) dims to a single
  chunk and run as gufuncs over the batch grid — each task is one
  ``nxp.linalg`` call under jit, batched across matrices by vmap on the
  TPU executor. ``slogdet`` uses a multi-output gufunc (one LU per task
  feeds both outputs).
- Norms, ``trace``, ``diagonal``, ``cross``, ``matrix_power`` compose
  existing chunked primitives (reductions, elementwise, matmul) and
  inherit their fusion/memory bounds.
"""

from __future__ import annotations

import math
from collections import namedtuple

import numpy as np

from ..backend_array_api import nxp
from ..core.gufunc import apply_gufunc
from ..core.ops import (
    _offsets_array_for,
    block_index_from_offset,
    general_blockwise,
    rechunk,
)
from .creation_functions import eye
from .data_type_functions import astype, result_type
from .dtypes import _floating_dtypes, _numeric_dtypes, float64, int64
from .elementwise_functions import (
    _float_of,
    abs as xp_abs,
    greater,
    multiply,
    pow as xp_pow,
    sqrt,
    square,
    subtract,
)
from .linear_algebra_functions import (  # noqa: F401  (re-exported per spec)
    matmul,
    matrix_transpose,
    outer,
    tensordot,
    vecdot,
)
from .manipulation_functions import expand_dims, moveaxis, squeeze, stack
from .statistical_functions import max as xp_max, min as xp_min, sum as xp_sum

__all__ = [
    "cholesky", "cross", "det", "diagonal", "eigh", "eigvalsh", "inv",
    "matmul", "matrix_norm", "matrix_power", "matrix_rank",
    "matrix_transpose", "outer", "pinv", "qr", "slogdet", "solve", "svd",
    "svdvals", "tensordot", "trace", "vecdot", "vector_norm",
]

QRResult = namedtuple("QRResult", ["Q", "R"])
SVDResult = namedtuple("SVDResult", ["U", "S", "Vh"])
EighResult = namedtuple("EighResult", ["eigenvalues", "eigenvectors"])
SlogdetResult = namedtuple("SlogdetResult", ["sign", "logabsdet"])


def _require_floating(x, fname):
    if x.dtype not in _floating_dtypes:
        raise TypeError(f"Only floating-point dtypes are allowed in {fname}")


def _require_square(x, fname):
    if x.ndim < 2 or x.shape[-1] != x.shape[-2]:
        raise ValueError(
            f"{fname} requires square matrices in the last two dimensions; "
            f"got shape {x.shape}"
        )


def _single_chunk_core(x, ncore=2):
    """Rechunk so the last ``ncore`` dims are each one chunk (gufunc core)."""
    target = {ax: x.shape[ax] for ax in range(x.ndim - ncore, x.ndim)}
    return rechunk(x, target)


# ---------------------------------------------------------------------------
# TSQR (qr / svd / svdvals)
# ---------------------------------------------------------------------------


def _tsqr_row_chunks(x, n):
    """Row-rechunk x so every row block has >= n rows and stage 2 (the
    (b·n, n) stacked-R QR in one task) fits the memory budget; returns the
    rechunked array."""
    m = x.shape[0]
    itemsize = x.dtype.itemsize
    allowed = x.spec.allowed_mem or (2**63)
    # stage-2 task holds the stacked R plus Q2/R outputs; keep its
    # footprint well under the budget
    b_mem_cap = max(1, int(allowed // (8 * n * n * itemsize)))
    if all(c >= n for c in x.chunks[0]) and len(x.chunks[0]) <= b_mem_cap:
        return x
    for b in range(min(m // max(n, 1), b_mem_cap) or 1, 0, -1):
        c = math.ceil(m / b)
        last = m - (b - 1) * c
        if last >= n or b == 1:
            return rechunk(x, {0: c})
    return rechunk(x, {0: m})


def _per_matrix_multi(x, kernel, shapes, chunks, op_name, dtypes=None):
    """One multi-output blockwise op applying ``kernel`` to each core block
    of a single-chunk-core array over the batch grid — the decomposition
    runs ONCE per matrix and feeds every output (vs one gufunc per output
    re-running it). All outputs must share the batch grid; pad a missing
    core dim to size-1 and squeeze at the call site."""
    x_name = x.name

    def bf(out_key):
        return ((x_name, *out_key[1:]),)

    return general_blockwise(
        kernel, bf, x,
        shape=shapes,
        dtype=list(dtypes) if dtypes else [x.dtype] * len(shapes),
        chunks=chunks,
        op_name=op_name,
    )


def _batch_chunks(x, *core):
    """chunks tuple: x's batch-dim chunks + the given core-dim sizes."""
    return tuple(x.chunks[:-2]) + tuple((c,) for c in core)


def _tsqr_r(x):
    """R factor only (single-output TSQR): skips forming/writing the m×n Q
    panels entirely — for consumers like svdvals that discard Q."""
    m, n = x.shape
    dt = x.dtype
    if len(x.chunks[1]) > 1:
        x = rechunk(x, {1: n})
    x = _tsqr_row_chunks(x, n)
    b = len(x.chunks[0])
    x_name = x.name

    def bf_panel(out_key):
        i = out_key[1]
        return ((x_name, i, 0),)

    r1 = general_blockwise(
        lambda a: nxp.linalg.qr(a)[1], bf_panel, x,
        shape=(b * n, n),
        dtype=dt,
        chunks=((n,) * b, (n,)),
        op_name="tsqr_panel_r",
    )
    if b == 1:
        return r1
    r1_name = r1.name

    def bf_reduce(out_key):
        return ([(r1_name, i, 0) for i in range(b)],)

    return general_blockwise(
        lambda rs: nxp.linalg.qr(nxp.concatenate(list(rs), axis=0))[1],
        bf_reduce, r1,
        shape=(n, n),
        dtype=dt,
        chunks=((n,), (n,)),
        num_input_blocks=(b,),
        extra_projected_mem=2 * (b - 1) * n * n * dt.itemsize,
        op_name="tsqr_reduce_r",
    )


def qr(x, /, *, mode="reduced"):
    """Reduced QR of a 2-d array via TSQR (rows may be chunked; columns are
    gathered to one chunk). Panels QR independently, the stacked R factors
    QR once, and Q re-forms blockwise — three ops total, two of them
    multi-output."""
    _require_floating(x, "qr")
    if mode != "reduced":
        raise NotImplementedError("qr currently supports mode='reduced' only")
    if x.ndim != 2:
        if x.ndim < 2:
            raise ValueError("qr requires at least 2 dimensions")
        mm, nn = x.shape[-2], x.shape[-1]
        k = min(mm, nn)
        xc = _single_chunk_core(x)
        batch = x.shape[:-2]
        q, r = _per_matrix_multi(
            xc, lambda a: nxp.linalg.qr(a),
            shapes=[(*batch, mm, k), (*batch, k, nn)],
            chunks=[_batch_chunks(xc, mm, k), _batch_chunks(xc, k, nn)],
            op_name="qr_batched",
        )
        return QRResult(q, r)

    m, n = x.shape
    dt = x.dtype
    if len(x.chunks[1]) > 1:
        x = rechunk(x, {1: n})

    if m < n:
        # wide: single-block QR (Q (m, m), R (m, n)) as one multi-output op
        x1 = rechunk(x, {0: m})

        def bf_single(out_key):
            return (((x1.name, 0, 0)),)

        def _qr_block(a):
            q, r = nxp.linalg.qr(a)
            return q, r

        q, r = general_blockwise(
            _qr_block, bf_single, x1,
            shape=[(m, m), (m, n)],
            dtype=[dt, dt],
            chunks=[((m,), (m,)), ((m,), (n,))],
            op_name="qr_single",
        )
        return QRResult(q, r)

    x = _tsqr_row_chunks(x, n)
    row_chunks = x.chunks[0]
    b = len(row_chunks)
    x_name = x.name

    # ---- stage 1: panel QR — ONE op, two outputs on one (b, 1) grid ----
    def bf_panel(out_key):
        i = out_key[1]
        return ((x_name, i, 0),)

    def _panel_qr(a):
        q, r = nxp.linalg.qr(a)
        return q, r

    q1, r1 = general_blockwise(
        _panel_qr, bf_panel, x,
        shape=[(m, n), (b * n, n)],
        dtype=[dt, dt],
        chunks=[(row_chunks, (n,)), ((n,) * b, (n,))],
        op_name="tsqr_panel",
    )
    if b == 1:
        return QRResult(q1, r1)

    # ---- stage 2: QR of the stacked R factors, one task ----
    r1_name = r1.name

    def bf_reduce(out_key):
        return ([(r1_name, i, 0) for i in range(b)],)

    def _stack_qr(rs):
        q, r = nxp.linalg.qr(nxp.concatenate(list(rs), axis=0))
        return q, r

    q2, r = general_blockwise(
        _stack_qr, bf_reduce, r1,
        shape=[(b * n, n), (n, n)],
        dtype=[dt, dt],
        chunks=[((b * n,), (n,)), ((n,), (n,))],
        num_input_blocks=(b,),
        extra_projected_mem=2 * (b - 1) * n * n * dt.itemsize,
        op_name="tsqr_reduce",
    )

    # ---- stage 3: Q_i = Q1_i @ Q2[i*n:(i+1)*n] (traced offset slice) ----
    offsets = _offsets_array_for(q1)
    q1_name, q2_name, off_name = q1.name, q2.name, offsets.name

    def bf_apply(out_key):
        i = out_key[1]
        return ((q1_name, i, 0), (q2_name, 0, 0), (off_name, i, 0))

    def _apply_q(panel, q2_full, off):
        bi = block_index_from_offset(off, 0, (b, 1))
        rows = bi * n + nxp.arange(n)
        return nxp.matmul(panel, nxp.take(q2_full, rows, axis=0))

    _apply_q.traced_offsets = True

    q = general_blockwise(
        _apply_q, bf_apply, q1, q2, offsets,
        shape=(m, n),
        dtype=dt,
        chunks=(row_chunks, (n,)),
        op_name="tsqr_apply_q",
    )
    return QRResult(q, r)


def svd(x, /, *, full_matrices=True):
    """Thin SVD. 2-d arrays factor via TSQR then one small SVD of R;
    batched inputs run per-matrix gufuncs."""
    _require_floating(x, "svd")
    if full_matrices:
        raise NotImplementedError(
            "svd currently computes the thin factorization only; pass "
            "full_matrices=False"
        )
    if x.ndim < 2:
        raise ValueError("svd requires at least 2 dimensions")
    k = min(x.shape[-2], x.shape[-1])
    if x.ndim > 2:
        mm, nn = x.shape[-2], x.shape[-1]
        xc = _single_chunk_core(x)
        batch = x.shape[:-2]

        def _svd_all(a):
            u, s, vh = nxp.linalg.svd(a, full_matrices=False)
            return u, s[..., None, :], vh

        u, s2d, vh = _per_matrix_multi(
            xc, _svd_all,
            shapes=[(*batch, mm, k), (*batch, 1, k), (*batch, k, nn)],
            chunks=[
                _batch_chunks(xc, mm, k),
                _batch_chunks(xc, 1, k),
                _batch_chunks(xc, k, nn),
            ],
            op_name="svd_batched",
            dtypes=[x.dtype, _float_of(x.dtype), x.dtype],
        )
        return SVDResult(u, squeeze(s2d, axis=-2), vh)

    m, n = x.shape
    dt = x.dtype
    if m >= n:
        q, r = qr(x)
        r_name = r.name

        def bf_svd(out_key):
            return ((r_name, 0, 0),)

        def _svd_r(a):
            u, s, vh = nxp.linalg.svd(a, full_matrices=False)
            return u, nxp.reshape(s, (1, -1)), vh

        u_r, s2d, vh = general_blockwise(
            _svd_r, bf_svd, r,
            shape=[(n, n), (1, n), (n, n)],
            dtype=[dt, _float_of(dt), dt],
            chunks=[((n,), (n,)), ((1,), (n,)), ((n,), (n,))],
            op_name="svd_of_r",
        )
        return SVDResult(matmul(q, u_r), squeeze(s2d, axis=0), vh)

    # wide: one single-block SVD
    x1 = rechunk(x, {0: m, 1: n})
    x1_name = x1.name

    def bf_wide(out_key):
        return ((x1_name, 0, 0),)

    def _svd_block(a):
        u, s, vh = nxp.linalg.svd(a, full_matrices=False)
        return u, nxp.reshape(s, (1, -1)), vh

    u, s2d, vh = general_blockwise(
        _svd_block, bf_wide, x1,
        shape=[(m, k), (1, k), (k, n)],
        dtype=[dt, _float_of(dt), dt],
        chunks=[((m,), (k,)), ((1,), (k,)), ((k,), (n,))],
        op_name="svd_single",
    )
    return SVDResult(u, squeeze(s2d, axis=0), vh)


def svdvals(x, /):
    _require_floating(x, "svdvals")
    if x.ndim < 2:
        raise ValueError("svdvals requires at least 2 dimensions")
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    if x.ndim == 2 and m >= n:
        # R-only TSQR: singular values of x == singular values of R, and
        # the Q panels are never formed or written
        target = _tsqr_r(x)
    else:
        target = _single_chunk_core(x)
    return apply_gufunc(
        lambda a: nxp.linalg.svd(a, compute_uv=False),
        "(i,j)->(k)", target, output_dtypes=_float_of(x.dtype),
        output_sizes={"k": k},
    )


# ---------------------------------------------------------------------------
# Square per-matrix ops (gufunc over the batch grid)
# ---------------------------------------------------------------------------


def cholesky(x, /, *, upper=False):
    """Cholesky factorization. Stacks (and 2-d matrices that fit one task)
    run a per-matrix gufunc; a 2-d SPD matrix too large for one task runs
    a **blocked right-looking factorization over the chunk grid** — a
    sequential plan of nb panel steps whose every task touches only
    block-sized operands, so ``n`` may exceed `allowed_mem`."""
    _require_floating(x, "cholesky")
    _require_square(x, "cholesky")

    if x.ndim == 2:
        n = x.shape[-1]
        itemsize = np.dtype(x.dtype).itemsize
        allowed = x.spec.allowed_mem or (2**63)
        # the gufunc path gathers the full matrix into one task (~2 input
        # + 2 output chunk copies); route to the blocked factorization
        # when that cannot fit
        if 5 * n * n * itemsize > allowed:
            lo = _blocked_cholesky(x)
            if not upper:
                return lo
            up = matrix_transpose(lo)
            if np.dtype(x.dtype).kind == "c":
                from .elementwise_functions import conj

                up = conj(up)
            return up

    def _chol(a):
        lo = nxp.linalg.cholesky(a)
        if upper:
            return nxp.conj(nxp.swapaxes(lo, -1, -2))
        return lo

    return apply_gufunc(
        _chol, "(i,j)->(i,j)", _single_chunk_core(x), output_dtypes=x.dtype
    )


def _blocked_cholesky(x):
    """Right-looking blocked Cholesky on the chunk grid (lower factor).

    Classic panel algorithm, expressed entirely in chunked ops over
    single-block panels:

        for k:  L[k][k] = chol( A[k][k] - Σ_j L[k][j] L[k][j]^T )
                L[i][k] = ( A[i][k] - Σ_j L[i][j] L[k][j]^T )
                          · solve(L[k][k]^T)          for i > k

    The plan has O(nb^3) small matmul nodes with a sequential depth of nb
    panel steps — each task holds only (c, c) blocks, so the matrix may
    exceed ``allowed_mem``. Solves use ``nxp.linalg.solve`` on the (c, c)
    diagonal factor (no explicit inverse); complex Hermitian inputs use
    the conjugate transpose throughout (A = L L^H). The final factor
    assembles in ONE map_direct write (each task reads exactly one L
    block or emits zeros) — no intermediate row concatenation."""
    from ..core.ops import map_direct
    from .elementwise_functions import conj

    n = x.shape[0]
    itemsize = np.dtype(x.dtype).itemsize
    allowed = x.spec.allowed_mem or (2**63)
    # block size: keep the existing square chunking when its blocks fit
    # the per-task budget (no rechunk at all); otherwise pick the largest
    # (c, c) that does and rechunk once
    cur = x.chunksize
    if cur[0] == cur[1] and 16 * cur[0] * cur[0] * itemsize <= allowed:
        c = cur[0]
    else:
        c = max(
            1,
            min(n, int(math.isqrt(max(1, int(allowed // (16 * itemsize)))))),
        )
    nb = math.ceil(n / c)
    if x.chunksize != (c, c):
        x = rechunk(x, {0: c, 1: c})
    bounds = [min(n, i * c) for i in range(nb + 1)]

    is_complex = np.dtype(x.dtype).kind == "c"

    def ct_(a):
        # conjugate transpose for the Hermitian update (plain transpose
        # for real dtypes — conj would be a no-op graph node)
        t = matrix_transpose(a)
        return conj(t) if is_complex else t

    def block(arr, i, j):
        return arr[bounds[i]:bounds[i + 1], bounds[j]:bounds[j + 1]]

    def chol_block(a):
        return apply_gufunc(
            lambda m: nxp.linalg.cholesky(m), "(i,j)->(i,j)", a,
            output_dtypes=a.dtype,
        )

    L: dict = {}
    for k in range(nb):
        s = block(x, k, k)
        for j in range(k):
            s = subtract(s, matmul(L[k, j], ct_(L[k, j])))
        L[k, k] = chol_block(s)
        for i in range(k + 1, nb):
            t = block(x, i, k)
            for j in range(k):
                t = subtract(t, matmul(L[i, j], ct_(L[k, j])))
            # L[i][k] = t @ L[k][k]^-H  ==  (solve(L[k][k], t^H))^H
            L[i, k] = ct_(solve(L[k, k], ct_(t)))

    if nb == 1:
        return L[0, 0]

    # single-write assembly: output block (i, j) copies its L block or
    # emits zeros; side-input reads are one block per task
    ordered = sorted(L)  # (i, j) -> positional side-input index
    index_of = {ij: p for p, ij in enumerate(ordered)}
    axis_chunks = tuple(bounds[i + 1] - bounds[i] for i in range(nb))
    out_dtype = np.dtype(x.dtype)

    def _assemble_block(out_chunk, *zarrs, block_id=None):
        i, j = block_id
        if j > i:
            return np.zeros(
                (axis_chunks[i], axis_chunks[j]), dtype=out_dtype
            )
        return np.asarray(zarrs[index_of[(i, j)]][:, :])

    block_bytes = max(axis_chunks) ** 2 * out_dtype.itemsize
    return map_direct(
        _assemble_block,
        *[L[ij] for ij in ordered],
        shape=(n, n),
        dtype=out_dtype,
        chunks=(axis_chunks, axis_chunks),
        extra_projected_mem=2 * block_bytes,
        spec=x.spec,
    )


def det(x, /):
    _require_floating(x, "det")
    _require_square(x, "det")
    return apply_gufunc(
        lambda a: nxp.linalg.det(a), "(i,j)->()", _single_chunk_core(x),
        output_dtypes=x.dtype,
    )


def slogdet(x, /):
    _require_floating(x, "slogdet")
    _require_square(x, "slogdet")

    def _slogdet(a):
        sign, logabs = nxp.linalg.slogdet(a)
        return sign, logabs

    sign, logabs = apply_gufunc(
        _slogdet, "(i,j)->(),()", _single_chunk_core(x),
        output_dtypes=[x.dtype, _float_of(x.dtype)],
    )
    return SlogdetResult(sign, logabs)


def inv(x, /):
    _require_floating(x, "inv")
    _require_square(x, "inv")
    return apply_gufunc(
        lambda a: nxp.linalg.inv(a), "(i,j)->(i,j)", _single_chunk_core(x),
        output_dtypes=x.dtype,
    )


def solve(x1, x2, /):
    _require_floating(x1, "solve")
    _require_square(x1, "solve")
    vector = x2.ndim == 1
    if vector:
        x2 = expand_dims(x2, axis=-1)
    dt = result_type(x1, x2)
    out = apply_gufunc(
        lambda a, b: nxp.linalg.solve(a, b), "(i,j),(j,k)->(i,k)",
        _single_chunk_core(x1), _single_chunk_core(x2), output_dtypes=dt,
    )
    return squeeze(out, axis=-1) if vector else out


def eigh(x, /):
    _require_floating(x, "eigh")
    _require_square(x, "eigh")
    n = x.shape[-1]
    xc = _single_chunk_core(x)
    batch = x.shape[:-2]

    def _eigh_all(a):
        vals, vecs = nxp.linalg.eigh(a)
        return vals[..., None, :], vecs

    vals2d, vecs = _per_matrix_multi(
        xc, _eigh_all,
        shapes=[(*batch, 1, n), (*batch, n, n)],
        chunks=[_batch_chunks(xc, 1, n), _batch_chunks(xc, n, n)],
        op_name="eigh",
        dtypes=[_float_of(x.dtype), x.dtype],
    )
    return EighResult(squeeze(vals2d, axis=-2), vecs)


def eigvalsh(x, /):
    _require_floating(x, "eigvalsh")
    _require_square(x, "eigvalsh")
    return apply_gufunc(
        lambda a: nxp.linalg.eigvalsh(a), "(i,j)->(i)",
        _single_chunk_core(x), output_dtypes=_float_of(x.dtype),
    )


# ---------------------------------------------------------------------------
# Composites over chunked primitives
# ---------------------------------------------------------------------------


def matrix_power(x, n, /):
    _require_floating(x, "matrix_power")
    _require_square(x, "matrix_power")
    if n == 0:
        mask = eye(x.shape[-1], dtype=x.dtype, spec=x.spec,
                   chunks=(x.chunks[-2], x.chunks[-1]))
        if x.ndim == 2:
            return mask
        from .creation_functions import ones_like

        return multiply(mask, ones_like(x))
    if n < 0:
        x = inv(x)
        n = -n
    result = None
    power = x
    while n:
        if n & 1:
            result = power if result is None else matmul(result, power)
        n >>= 1
        if n:
            power = matmul(power, power)
    return result


def diagonal(x, /, *, offset=0):
    """Diagonal of the last two dims via a virtual eye mask + row reduction
    (O(n·m) reads, fully chunked/fused — no gather op needed). ``where``
    rather than multiply-by-mask so inf/nan off-diagonal entries cannot
    poison the row sums."""
    if x.ndim < 2:
        raise ValueError("diagonal requires at least 2 dimensions")
    n, m = x.shape[-2], x.shape[-1]
    # out-of-range offsets yield an empty diagonal (numpy convention —
    # trace of such an offset is then 0, not an error)
    d = max(0, min(n, m - offset) if offset >= 0 else min(n + offset, m))
    from .creation_functions import asarray
    from .dtypes import bool as xp_bool
    from .searching_functions import where

    mask = eye(n, m, k=offset, dtype=xp_bool, spec=x.spec,
               chunks=(x.chunks[-2], x.chunks[-1]))
    if x.dtype == xp_bool:
        from .elementwise_functions import logical_and
        from .utility_functions import any as xp_any

        v = xp_any(logical_and(x, mask), axis=-1)
    else:
        zero = asarray(0, dtype=x.dtype, spec=x.spec)
        # v[..., i] = x[..., i, i+offset]
        v = xp_sum(where(mask, x, zero), axis=-1, dtype=x.dtype)
    start = max(0, -offset)
    return v[(Ellipsis, slice(start, start + d))]


def trace(x, /, *, offset=0, dtype=None):
    if x.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in trace")
    return xp_sum(diagonal(x, offset=offset), axis=-1, dtype=dtype)


def cross(x1, x2, /, *, axis=-1):
    if x1.dtype not in _numeric_dtypes or x2.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in cross")
    if x1.shape[axis] != 3 or x2.shape[axis] != 3:
        raise ValueError("cross requires the axis to have size 3")
    a = moveaxis(x1, axis, -1)
    b = moveaxis(x2, axis, -1)

    def comp(i, j):
        return subtract(
            multiply(a[..., i], b[..., j]), multiply(a[..., j], b[..., i])
        )

    c = stack([comp(1, 2), comp(2, 0), comp(0, 1)], axis=-1)
    return moveaxis(c, -1, axis)


def matrix_norm(x, /, *, keepdims=False, ord="fro"):
    _require_floating(x, "matrix_norm")
    if x.ndim < 2:
        raise ValueError("matrix_norm requires at least 2 dimensions")
    if ord == "fro":
        return sqrt(
            xp_sum(square(xp_abs(x)), axis=(-2, -1), keepdims=keepdims)
        )
    if ord in (1, -1, np.inf, -np.inf):
        sum_axis, pick_axis = (-2, -1) if ord in (1, -1) else (-1, -2)
        sums = xp_sum(xp_abs(x), axis=sum_axis, keepdims=True)
        pick = xp_max if ord in (1, np.inf) else xp_min
        out = pick(sums, axis=pick_axis, keepdims=True)
        return out if keepdims else squeeze(out, axis=(-2, -1))
    if ord in (2, -2, "nuc"):
        s = svdvals(x)
        if ord == 2:
            out = xp_max(s, axis=-1)
        elif ord == -2:
            out = xp_min(s, axis=-1)
        else:
            out = xp_sum(s, axis=-1)
        if keepdims:
            out = expand_dims(expand_dims(out, axis=-1), axis=-1)
        return out
    raise ValueError(f"unsupported matrix norm order: {ord!r}")


def vector_norm(x, /, *, axis=None, keepdims=False, ord=2):
    _require_floating(x, "vector_norm")
    if ord == np.inf:
        return xp_max(xp_abs(x), axis=axis, keepdims=keepdims)
    if ord == -np.inf:
        return xp_min(xp_abs(x), axis=axis, keepdims=keepdims)
    if ord == 0:
        from .searching_functions import count_nonzero

        return astype(
            count_nonzero(x, axis=axis, keepdims=keepdims),
            _float_of(x.dtype),
        )
    if ord == 2:
        return sqrt(xp_sum(square(xp_abs(x)), axis=axis, keepdims=keepdims))
    p = float(ord)
    from .creation_functions import asarray

    # exponents carry the REAL counterpart dtype: abs() already demoted
    # complex input, and a complex-dtyped constant would promote the whole
    # chain back to complex
    rd = _float_of(x.dtype)
    powed = xp_pow(xp_abs(x), asarray(p, dtype=rd, spec=x.spec))
    return xp_pow(
        xp_sum(powed, axis=axis, keepdims=keepdims),
        asarray(1.0 / p, dtype=rd, spec=x.spec),
    )


def matrix_rank(x, /, *, rtol=None):
    _require_floating(x, "matrix_rank")
    if x.ndim < 2:
        raise ValueError("matrix_rank requires at least 2 dimensions")
    s = svdvals(x)
    if rtol is None:
        rtol = max(x.shape[-2], x.shape[-1]) * np.finfo(
            np.dtype(x.dtype)
        ).eps
    smax = xp_max(s, axis=-1, keepdims=True)
    from .creation_functions import asarray

    tol = multiply(smax, asarray(float(rtol), dtype=s.dtype, spec=x.spec))
    return xp_sum(astype(greater(s, tol), int64), axis=-1)


def pinv(x, /, *, rtol=None):
    _require_floating(x, "pinv")
    if x.ndim < 2:
        raise ValueError("pinv requires at least 2 dimensions")
    u, s, vh = svd(x, full_matrices=False)
    if rtol is None:
        rtol = max(x.shape[-2], x.shape[-1]) * np.finfo(
            np.dtype(x.dtype)
        ).eps
    from .creation_functions import asarray
    from .searching_functions import where

    smax = xp_max(s, axis=-1, keepdims=True)
    cutoff = multiply(smax, asarray(float(rtol), dtype=s.dtype, spec=x.spec))
    zero = asarray(0.0, dtype=s.dtype, spec=x.spec)
    sinv = where(greater(s, cutoff), xp_pow(s, asarray(-1.0, dtype=s.dtype, spec=x.spec)), zero)
    # pinv = V @ diag(sinv) @ U^H  ==  (V * sinv[..., None, :]) @ U^H
    v = matrix_transpose(vh)
    return matmul(multiply(v, expand_dims(sinv, axis=-2)), matrix_transpose(u))
