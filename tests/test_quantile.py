"""Exact quantile/median over chunked axes (beyond-standard extension;
dask only approximates multi-chunk quantiles — here the axis rides the
scale-out sort network and the result is two static slices)."""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp


def asnp(x):
    return np.asarray(x.compute())


@pytest.mark.parametrize("q", [0.0, 0.25, 0.5, 0.9, 1.0])
def test_quantile_matches_numpy(spec, q):
    an = np.random.default_rng(0).standard_normal((6, 101))
    a = ct.from_array(an, chunks=(2, 25), spec=spec)
    np.testing.assert_allclose(
        asnp(xp.quantile(a, q, axis=1)), np.quantile(an, q, axis=1),
        atol=1e-12,
    )


@pytest.mark.parametrize("method", ["lower", "higher", "nearest"])
def test_quantile_methods(spec, method):
    an = np.random.default_rng(1).standard_normal(53)
    a = ct.from_array(an, chunks=(10,), spec=spec)
    np.testing.assert_allclose(
        float(xp.quantile(a, 0.37, axis=0, method=method).compute()),
        np.quantile(an, 0.37, method=method),
        atol=1e-12,
    )


def test_median_axis_none_and_keepdims(spec):
    an = np.random.default_rng(2).standard_normal((5, 8))
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    np.testing.assert_allclose(
        float(xp.median(a).compute()), np.median(an), atol=1e-12
    )
    out = xp.median(a, axis=1, keepdims=True)
    assert out.shape == (5, 1)
    np.testing.assert_allclose(
        asnp(out), np.median(an, axis=1, keepdims=True), atol=1e-12
    )
    out0 = xp.quantile(a, 0.5, keepdims=True)
    assert out0.shape == (1, 1)


@pytest.mark.slow
def test_quantile_axis_larger_than_memory(tmp_path):
    # the sorted axis exceeds allowed_mem: the sort network carries it
    an = np.random.default_rng(3).standard_normal(120_000)
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem=400_000)
    a = ct.from_array(an, chunks=(10_000,), spec=spec)
    np.testing.assert_allclose(
        float(xp.quantile(a, 0.75, axis=0).compute()),
        np.quantile(an, 0.75),
        atol=1e-12,
    )


def test_quantile_on_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(4).standard_normal((4, 64))
    a = ct.from_array(an, chunks=(2, 16), spec=spec)
    got = np.asarray(
        xp.quantile(a, 0.5, axis=1).compute(executor=JaxExecutor())
    )
    np.testing.assert_allclose(got, np.quantile(an, 0.5, axis=1), atol=1e-10)


def test_quantile_validation(spec):
    a = ct.from_array(np.ones(5), chunks=(5,), spec=spec)
    with pytest.raises(ValueError):
        xp.quantile(a, 1.5)
    with pytest.raises(TypeError):
        xp.quantile(a, [0.5])
    with pytest.raises(ValueError):
        xp.quantile(a, 0.5, method="bogus")
    ai = ct.from_array(np.ones(5, dtype=np.int32), chunks=(5,), spec=spec)
    with pytest.raises(TypeError):
        xp.quantile(ai, 0.5)


def test_quantile_nan_propagates(spec):
    an = np.array([1.0, np.nan, 3.0, 2.0, 5.0])
    a = ct.from_array(an, chunks=(2,), spec=spec)
    assert np.isnan(float(xp.quantile(a, 0.5, axis=0).compute()))
    # rows without NaN stay exact
    bn = np.array([[1.0, np.nan, 3.0], [4.0, 5.0, 6.0]])
    b = ct.from_array(bn, chunks=(1, 2), spec=spec)
    got = np.asarray(xp.median(b, axis=1).compute())
    assert np.isnan(got[0]) and got[1] == 5.0
    with pytest.raises(IndexError):
        xp.quantile(b, 0.5, axis=5)


def test_nanquantile_matches_numpy(spec):
    import warnings

    rng = np.random.default_rng(5)
    an = rng.standard_normal((6, 60))
    an[an > 1.2] = np.nan
    an[3] = np.nan  # all-NaN row
    a = ct.from_array(an, chunks=(2, 15), spec=spec)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for q in (0.0, 0.3, 0.9, 1.0):
            np.testing.assert_allclose(
                asnp(xp.nanquantile(a, q, axis=1)),
                np.nanquantile(an, q, axis=1),
                atol=1e-12, equal_nan=True,
            )
        np.testing.assert_allclose(
            asnp(xp.nanmedian(a, axis=0)), np.nanmedian(an, axis=0),
            atol=1e-12, equal_nan=True,
        )
        got = float(xp.nanmedian(a).compute())
        assert np.isclose(got, np.nanmedian(an))
    out = xp.nanquantile(a, 0.5, axis=1, keepdims=True)
    assert out.shape == (6, 1)


def test_nanquantile_on_jax_executor(spec):
    import warnings

    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(6).standard_normal((4, 32))
    an[0, :5] = np.nan
    a = ct.from_array(an, chunks=(2, 8), spec=spec)
    got = np.asarray(
        xp.nanquantile(a, 0.5, axis=1).compute(executor=JaxExecutor())
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        np.testing.assert_allclose(
            got, np.nanquantile(an, 0.5, axis=1), atol=1e-10, equal_nan=True
        )


def test_topk_argtopk(spec):
    rng = np.random.default_rng(7)
    an = rng.standard_normal((5, 40))
    a = ct.from_array(an, chunks=(2, 10), spec=spec)
    got = asnp(xp.topk(a, 3, axis=1))
    np.testing.assert_allclose(got, -np.sort(-an, axis=1)[:, :3])
    got_small = asnp(xp.topk(a, -2, axis=1))
    np.testing.assert_allclose(got_small, np.sort(an, axis=1)[:, :2])
    gi = asnp(xp.argtopk(a, 3, axis=1))
    np.testing.assert_allclose(
        np.take_along_axis(an, gi, axis=1), -np.sort(-an, axis=1)[:, :3]
    )
    with pytest.raises(ValueError):
        xp.topk(a, 0)
    with pytest.raises(ValueError):
        xp.topk(a, 99, axis=1)


@pytest.mark.parametrize(
    "dtype",
    [np.uint8, np.uint16, np.uint64, np.int8, np.int64, np.float32],
)
def test_topk_argtopk_descending_integer_dtypes(spec, dtype):
    """Regression (ROADMAP item 5): descending top-k used key negation,
    which WRAPS for unsigned dtypes (-1 -> UINT_MAX) and for INT_MIN —
    silently wrong results, worst exactly at the extremes a top-k is asked
    to find. The fix orders via flip-identity/native-descending argsort
    and pads short blocks with dtype-aware sentinels (±inf doesn't exist
    for ints)."""
    dt = np.dtype(dtype)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        pool = np.array(
            [info.min, info.max, info.min + 1, info.max - 1, 0, 1, 2, 7],
            dtype=dt,
        )
    else:
        pool = np.array([-3.0, -1.5, 0.0, 1.0, 2.5, 7.0, -8.0, 9.0], dtype=dt)
    rng = np.random.default_rng(11)
    an = pool[rng.integers(0, len(pool), size=(4, 24))]
    a = ct.from_array(an, chunks=(2, 6), spec=spec)  # multi-chunk axis

    # k > 0: the LARGEST k, descending — the wrap-bug case
    got = asnp(xp.topk(a, 3, axis=1))
    want = np.flip(np.sort(an, axis=1), axis=1)[:, :3]
    np.testing.assert_array_equal(got, want)
    # k < 0: the SMALLEST |k|, ascending
    got_small = asnp(xp.topk(a, -3, axis=1))
    np.testing.assert_array_equal(got_small, np.sort(an, axis=1)[:, :3])
    # argtopk indices must point at genuinely-largest values
    gi = asnp(xp.argtopk(a, 3, axis=1))
    np.testing.assert_array_equal(np.take_along_axis(an, gi, axis=1), want)


def test_topk_short_blocks_pad_with_integer_sentinels(spec):
    """Blocks shorter than k force sentinel padding; with an unsigned
    dtype the old ±inf fill is unrepresentable (and the negated sort order
    wrong). Extremes must still win."""
    an = np.array([[250, 255, 0, 3, 128, 2, 254, 1, 127, 129]], dtype=np.uint8)
    a = ct.from_array(an, chunks=(1, 3), spec=spec)  # last block is ragged
    got = asnp(xp.topk(a, 4, axis=1))  # k > several block lengths
    np.testing.assert_array_equal(
        got, np.flip(np.sort(an, axis=1), axis=1)[:, :4]
    )
    got_small = asnp(xp.topk(a, -4, axis=1))
    np.testing.assert_array_equal(got_small, np.sort(an, axis=1)[:, :4])


def test_topk_one_pass_engine(tmp_path):
    # k << n with a tight budget: the one-pass path must fire (the full
    # sort network would also work, but the plan should carry topk ops)
    rng = np.random.default_rng(8)
    an = rng.standard_normal(200_000)
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem=600_000)
    a = ct.from_array(an, chunks=(10_000,), spec=spec)
    t = xp.topk(a, 5)
    ops = [d.get("op_name", "") for _, d in t.plan.dag.nodes(data=True)]
    assert any("topk_local" in o for o in ops), ops
    np.testing.assert_allclose(asnp(t), -np.sort(-an)[:5])
    gi = asnp(xp.argtopk(a, 5))
    np.testing.assert_allclose(an[gi], -np.sort(-an)[:5])


def test_topk_ragged_and_jax(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(9).standard_normal((3, 23))  # ragged last
    a = ct.from_array(an, chunks=(2, 5), spec=spec)
    got = np.asarray(xp.topk(a, 4, axis=1).compute(executor=JaxExecutor()))
    np.testing.assert_allclose(got, -np.sort(-an, axis=1)[:, :4])
    with pytest.raises(IndexError):
        xp.topk(a, 2, axis=5)
