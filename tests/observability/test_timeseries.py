"""Time-series store + telemetry sampler unit tests."""

from __future__ import annotations

import time

from cubed_tpu.observability.metrics import MetricsRegistry, get_registry
from cubed_tpu.observability.timeseries import (
    ComputeProgressCallback,
    TelemetrySampler,
    TimeSeriesStore,
    _computes,
    _computes_lock,
    compute_progress,
    fleet_view,
    live_fleets,
    register_fleet,
    unregister_fleet,
)


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def test_store_record_latest_and_window():
    s = TimeSeriesStore()
    t0 = 1000.0
    for i in range(5):
        s.record("m", i, ts=t0 + i)
    assert s.latest("m") == 4
    # trailing 2.5s window from t0+4 holds the last 3 points
    pts = s.window("m", 2.5, now=t0 + 4)
    assert [v for _, v in pts] == [2, 3, 4]
    assert s.window("missing", 10, now=t0) == []
    assert s.latest("missing") is None


def test_store_labels_are_distinct_series():
    s = TimeSeriesStore()
    s.record("rss", 1, ts=1.0, labels={"worker": "a"})
    s.record("rss", 2, ts=1.0, labels={"worker": "b"})
    assert s.latest("rss", labels={"worker": "a"}) == 1
    assert s.latest("rss", labels={"worker": "b"}) == 2
    # labelled series surface for the Prometheus exposition
    labelled = {
        (name, labels["worker"]): v
        for name, labels, v in s.labelled_latest()
    }
    assert labelled[("rss", "a")] == 1 and labelled[("rss", "b")] == 2


def test_store_ring_is_bounded_per_series():
    s = TimeSeriesStore(capacity=10)
    for i in range(100):
        s.record("m", i, ts=float(i))
    pts = s.window("m", 1e9, now=100.0)
    assert len(pts) == 10
    assert pts[-1][1] == 99  # newest kept, oldest evicted


def test_store_series_cap_evicts_stalest_for_new():
    reg = get_registry()
    before = reg.snapshot()
    s = TimeSeriesStore(max_series=3)
    # stalest-last-point series make way for new ones (a long-lived
    # endpoint churns compute/worker labels forever; dropping the NEW
    # series would starve exactly what the operator is watching)
    for i in range(6):
        s.record("m", 1, ts=float(i), labels={"worker": f"w{i}"})
    assert len(s.series()) == 3
    kept = {labels["worker"] for _, labels, _ in s.latest_series()}
    assert kept == {"w3", "w4", "w5"}  # the freshest survive
    assert s.series_evicted == 3
    delta = reg.snapshot_delta(before)
    assert delta.get("timeseries_series_evicted", 0) >= 3


def test_store_rate_from_cumulative_counter():
    s = TimeSeriesStore()
    s.record("c", 10, ts=100.0)
    s.record("c", 30, ts=110.0)
    assert s.rate("c", 60, now=110.0) == 2.0
    # counter reset (process restart) must clamp to zero, not go negative
    s.record("c", 0, ts=120.0)
    assert s.rate("c", 60, now=120.0) == 0.0
    # a single point has no rate
    s2 = TimeSeriesStore()
    s2.record("c", 1, ts=1.0)
    assert s2.rate("c", 60, now=1.0) is None


def test_store_ignores_non_numeric_values():
    s = TimeSeriesStore()
    s.record("m", "not-a-number", ts=1.0)
    s.record("m", None, ts=1.0)
    s.record("m", True, ts=2.0)  # bools coerce to 0/1
    assert s.latest("m") == 1


def test_store_to_dict_windows_and_bounds():
    s = TimeSeriesStore()
    for i in range(50):
        s.record("m", i, ts=1000.0 + i, labels={"worker": "a"})
    rows = s.to_dict(window_s=20.0, max_points=5, now=1049.0)
    assert len(rows) == 1
    row = rows[0]
    assert row["name"] == "m" and row["labels"] == {"worker": "a"}
    assert len(row["points"]) == 5
    assert row["points"][-1][1] == 49


# ---------------------------------------------------------------------------
# sampler
# ---------------------------------------------------------------------------


def test_sampler_records_registry_counters_gauges_histograms(monkeypatch):
    reg = MetricsRegistry()
    reg.counter("tasks_completed").inc(7)
    reg.gauge("queue_depth").set(3)
    reg.histogram("op_wall_clock_s").observe(0.5)
    monkeypatch.setattr(
        "cubed_tpu.observability.timeseries.get_registry", lambda: reg
    )
    store = TimeSeriesStore()
    sampler = TelemetrySampler(store)
    sampler.sample_once(now=100.0)
    assert store.latest("tasks_completed") == 7
    assert store.latest("queue_depth") == 3
    assert store.latest("op_wall_clock_s_count") == 1
    assert store.latest("op_wall_clock_s_sum") == 0.5
    assert store.latest("op_wall_clock_s_p50") == 0.5
    # the tick itself is counted (on the patched registry)
    assert reg.snapshot().get("telemetry_samples") == 1
    assert sampler.last_sample_ts == 100.0


class _FakeCoordinator:
    """The minimal coordinator surface the sampler/fleet_view read."""

    def __init__(self, rows, workers):
        self._rows = rows
        self._workers = workers
        import threading

        self._closed = threading.Event()

    def load_view(self):
        return self._rows

    def stats_snapshot(self):
        return {"workers": self._workers}


def _fake_fleet():
    return _FakeCoordinator(
        rows=[
            {"name": "w0", "draining": False, "pressured": True,
             "connected": True, "outstanding": 2, "nthreads": 1},
            {"name": "w1", "draining": False, "pressured": False,
             "connected": True, "outstanding": 1, "nthreads": 1},
        ],
        workers={
            "w0": {"alive": True, "connected": True, "pressured": True,
                   "rss": 1024, "peer_cache": {"bytes": 10},
                   "metrics": {"worker_tasks_executed": 5}},
            "w1": {"alive": True, "connected": True, "pressured": False,
                   "rss": 2048, "peer_cache": None, "metrics": None},
        },
    )


def test_sampler_records_fleet_series_per_worker_and_aggregate():
    coord = _fake_fleet()
    register_fleet(coord)
    try:
        store = TimeSeriesStore()
        TelemetrySampler(store).sample_once(now=50.0)
        assert store.latest("fleet_workers_live") == 2
        assert store.latest("fleet_workers_pressured") == 1
        assert store.latest("fleet_pressured_fraction") == 0.5
        assert store.latest("fleet_queue_depth") == 3
        assert store.latest(
            "worker_rss_bytes", labels={"worker": "w0"}
        ) == 1024
        assert store.latest(
            "worker_outstanding", labels={"worker": "w1"}
        ) == 1
        assert store.latest(
            "fleet_worker_tasks_executed", labels={"worker": "w0"}
        ) == 5
        view = fleet_view()
        assert view["workers_live"] == 2
        assert view["workers_pressured"] == 1
        assert "w0" in view["workers"]
    finally:
        unregister_fleet(coord)


def test_fleet_registration_is_weak_and_close_aware():
    coord = _fake_fleet()
    register_fleet(coord)
    assert coord in live_fleets()
    coord._closed.set()
    assert coord not in live_fleets()
    unregister_fleet(coord)
    # a dropped reference disappears from the registry on its own
    coord2 = _fake_fleet()
    register_fleet(coord2)
    del coord2
    import gc

    gc.collect()
    assert all(c is not None for c in live_fleets())


# ---------------------------------------------------------------------------
# compute progress
# ---------------------------------------------------------------------------


class _Event:
    def __init__(self, **kw):
        self.__dict__.update(kw)


def _fake_dag(num_tasks=4):
    import networkx as nx

    class _Op:
        def __init__(self, n):
            self.num_tasks = n

    dag = nx.MultiDiGraph()
    dag.add_node("op-a", type="op", primitive_op=_Op(num_tasks))
    return dag


def test_compute_progress_callback_tracks_done_total_and_status():
    with _computes_lock:
        _computes.clear()
    cb = ComputeProgressCallback()
    cb.on_compute_start(_Event(compute_id="c-test", dag=_fake_dag(3)))
    rows = compute_progress()
    assert rows[-1]["compute_id"] == "c-test"
    assert rows[-1]["tasks_total"] == 3
    assert rows[-1]["status"] == "running"
    for _ in range(2):
        cb.on_task_end(_Event())
    assert compute_progress()[-1]["tasks_done"] == 2
    cb.on_compute_end(_Event(error=None))
    row = compute_progress()[-1]
    assert row["status"] == "succeeded" and row["ended_at"] is not None
    # a failed compute reads as failed
    cb2 = ComputeProgressCallback()
    cb2.on_compute_start(_Event(compute_id="c-fail", dag=_fake_dag(1)))
    cb2.on_compute_end(_Event(error=RuntimeError("boom")))
    assert compute_progress()[-1]["status"] == "failed"


def test_compute_progress_feeds_sampler_series():
    with _computes_lock:
        _computes.clear()
    cb = ComputeProgressCallback()
    cb.on_compute_start(_Event(compute_id="c-live", dag=_fake_dag(10)))
    cb.on_task_end(_Event())
    store = TimeSeriesStore()
    TelemetrySampler(store).sample_once(now=10.0)
    assert store.latest(
        "compute_tasks_done", labels={"compute": "c-live"}
    ) == 1
    assert store.latest(
        "compute_tasks_total", labels={"compute": "c-live"}
    ) == 10
    cb.on_compute_end(_Event(error=None))
    # finished computes stop being sampled (series freezes)
    TelemetrySampler(store).sample_once(now=11.0)
    pts = store.window("compute_tasks_done", 100, labels={"compute": "c-live"}, now=11.0)
    assert len(pts) == 1


def test_sampler_thread_lifecycle():
    store = TimeSeriesStore()
    sampler = TelemetrySampler(store, interval_s=0.05)
    sampler.start()
    try:
        deadline = time.monotonic() + 5.0
        while sampler.last_sample_ts is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sampler.last_sample_ts is not None
        assert sampler.alive
    finally:
        sampler.stop()
    assert not sampler.alive
    # a stopped sampler restarts cleanly (stop() must not poison start())
    sampler.last_sample_ts = None
    sampler.start()
    try:
        deadline = time.monotonic() + 5.0
        while sampler.last_sample_ts is None and time.monotonic() < deadline:
            time.sleep(0.02)
        assert sampler.alive and sampler.last_sample_ts is not None
    finally:
        sampler.stop()


def test_fleet_aggregates_decay_to_zero_after_fleet_closes():
    """A closed fleet's last pressured reading must not freeze: the
    aggregates keep recording real zeros so a pressure alert clears."""
    coord = _fake_fleet()
    register_fleet(coord)
    store = TimeSeriesStore()
    sampler = TelemetrySampler(store)
    try:
        sampler.sample_once(now=50.0)
        assert store.latest("fleet_pressured_fraction") == 0.5
    finally:
        coord._closed.set()
        unregister_fleet(coord)
    sampler.sample_once(now=51.0)
    assert store.latest("fleet_pressured_fraction") == 0.0
    assert store.latest("fleet_workers_live") == 0
