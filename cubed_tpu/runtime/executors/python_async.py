"""Threaded async executor: completion-ordered fan-out with classified
retries, exponential backoff, speculative straggler backups, and optional
batched submission.

Reference parity: cubed/runtime/executors/python_async.py and the generic
async_map_unordered core (cubed/runtime/executors/asyncio.py:11-102),
reimplemented on concurrent.futures without aiostream. Failure handling
goes beyond the reference's flat immediate retries: exceptions are
classified (``runtime/resilience.py``) — programming errors fail fast with
exactly one attempt, transient errors resubmit after an exponential-backoff
delay (scheduled, never blocking the completion loop), worker loss requeues
for free — and every consumed retry draws from a compute-wide budget so a
systemic outage aborts promptly.
"""

from __future__ import annotations

import concurrent.futures
import heapq
import itertools
import logging
import time
from collections import deque
from typing import Callable, Dict, Iterable, Optional

from ...observability.collect import record_decision, record_failed_task
from ...observability.metrics import get_registry
from ..backup import should_launch_backup
from ..dataflow import (
    DataflowScheduler,
    effective_scheduler,
    record_scheduler_mode,
)
from ..memory import (
    AdmissionController,
    count_resource_failure,
    pressure_level,
    resource_abort_error,
)
from ..pipeline import (
    RecomputeResolver,
    ResumeState,
    pending_mappable,
    visit_node_generations,
    visit_nodes,
)
from ..resilience import (
    DEFAULT_RETRIES,
    Classification,
    PoisonTaskError,
    RetryBudget,
    RetryPolicy,
    budget_exhausted_error,
    compute_retry_budget,  # noqa: F401  (re-export for the other executors)
    integrity_payload,
    resolve_policy,
)
from ..types import (
    DagExecutor,
    OperationEndEvent,
    OperationStartEvent,
    callbacks_on,
)
from ..utils import (
    chunk_key,
    end_generation,
    execute_with_stats,
    fire_task_start,
    handle_callbacks,
    merge_generation,
)

logger = logging.getLogger(__name__)


def _count_integrity_failure(metrics, exc) -> None:
    """Count a surfaced chunk-integrity failure client-side.

    The detecting task's scope (where the raising site recorded its counts)
    is discarded when the task fails, so detection/quarantine are counted
    here — once per failure reaching the completion loop, for every
    executor (local raise, pickled from a pool worker, or a RemoteTaskError
    off the fleet wire). A ``checksum``-kind failure quarantined its file;
    a ``missing``-kind one found it already gone."""
    metrics.counter("chunks_corrupt_detected").inc()
    payload = integrity_payload(exc)
    if payload and payload.get("kind") == "checksum":
        metrics.counter("chunks_quarantined").inc()
        record_decision(
            "quarantine", store=str(payload.get("store", "")),
            chunk=payload.get("chunk_key"),
        )


def _clean_worker_loss(exc: BaseException) -> bool:
    """True when a REQUEUE-classified failure was a CLEAN worker exit
    (drain/preemption — ``WorkerDrainedError``): the worker announced its
    departure and handed tasks back unexecuted, so it is evidence about
    the INFRASTRUCTURE, never about the task. Matched by MRO name so this
    pure-local module never imports the distributed machinery."""
    return any(
        c.__name__ == "WorkerDrainedError" for c in type(exc).__mro__
    )


def _overload_sheds_optional() -> bool:
    """True while any live service OverloadController is at L1 or above:
    speculative backups are pure extra load, shed first. Late import —
    the ladder lives in the service layer, and executors must work
    without it."""
    try:
        from ...service.overload import sheds_optional_work

        return sheds_optional_work()
    except Exception:
        return False


def map_unordered(
    executor: concurrent.futures.Executor,
    function: Callable,
    inputs: Iterable,
    retries: int = DEFAULT_RETRIES,
    use_backups: bool = False,
    batch_size: Optional[int] = None,
    callbacks=None,
    array_name: Optional[str] = None,
    array_names: Optional[list] = None,
    executor_name: Optional[str] = None,
    retry_policy: Optional[RetryPolicy] = None,
    retry_budget: Optional[RetryBudget] = None,
    recompute_resolver=None,
    admission: Optional[AdmissionController] = None,
    dependencies: Optional[Dict[int, set]] = None,
    on_input_submit: Optional[Callable[[int], None]] = None,
    on_input_done: Optional[Callable[[int], None]] = None,
    completed_inputs: Optional[set] = None,
    cancellation=None,
    **kwargs,
) -> None:
    """Run function over inputs, handling completion order, retries, backups.

    ``array_names`` (parallel to inputs) attributes each task's end event to
    its own op when tasks of several ops are interleaved in one map.

    With ``batch_size`` set and no ``array_names``, inputs are consumed
    lazily batch by batch — large task grids never materialize in memory
    (that bounded-submission streaming is what ``batch_size`` is for).

    ``retry_policy`` governs failure classification and backoff; when absent
    a default policy is built around the ``retries`` int (which an explicit
    policy overrides). ``retry_budget`` shares one circuit-breaker allowance
    across several maps (a whole compute); when absent each batch gets its
    own, sized to its task count.

    ``recompute_resolver`` (a ``pipeline.RecomputeResolver``) handles
    RECOMPUTE-classified failures — a task that read a corrupt (now
    quarantined) input chunk: the resolver's thunk re-runs the producing
    op's task for exactly that chunk, then the reader resubmits. Each
    repair consumes one retry and one budget unit, so corruption storms
    abort promptly instead of looping.

    ``admission`` (a ``memory.AdmissionController``, shared across one
    compute's maps like the budget) bounds tasks in flight under memory
    pressure: unbounded — today's exact behavior — until a
    RESOURCE-classified failure or a hard host-pressure watermark halves
    it, after which submissions queue (``tasks_throttled``) until
    completions free slots or a pressure-free success window restores the
    limit multiplicatively. A task that fails RESOURCE even when admitted
    at concurrency 1 aborts the compute with an actionable
    measured-vs-allowed error instead of burning the budget.

    ``dependencies`` (the chunk-granular dataflow scheduler,
    ``runtime/dataflow.py``) maps an input index to the set of input
    indices that must COMPLETE before it may be submitted: blocked inputs
    are held back and released the moment their last dependency lands, so
    tasks of a downstream op dispatch while the upstream op is still
    running. Requires the un-batched path (one index space).
    ``on_input_submit``/``on_input_done`` are per-index hooks the dataflow
    scheduler uses for operation lifecycle events and overlap metrics.
    ``completed_inputs`` (indices, read once at entry) marks inputs done
    before anything dispatches — a crash-recovery re-run over the same
    index space (the multiprocess pool rebuild) resumes from where the
    previous attempt died instead of re-running the whole map; their
    dependents' edges count as satisfied.

    ``cancellation`` (a ``runtime.cancellation.CancellationToken``) bounds
    TIME the way ``admission`` bounds memory: the dispatch loop checks it
    every iteration — a tripped token (explicit cancel or deadline) stops
    new submissions, cancels pending futures, and raises the typed
    ``ComputeCancelledError``/``ComputeDeadlineExceededError``. A
    CANCELLED-classified task failure (a worker aborted cooperatively)
    does the same, drawing zero retry budget either way.
    """
    policy = resolve_policy(retry_policy, retries)
    if admission is None:
        admission = AdmissionController()
    if dependencies and batch_size is not None:
        raise ValueError(
            "dependencies (dataflow scheduling) and batch_size are mutually "
            "exclusive: batching would split the dependency index space"
        )
    if array_names is not None:
        inputs = list(inputs)
        assert len(array_names) == len(inputs)
    if batch_size is None:
        _map_unordered_batch(
            executor, function, list(inputs), policy, retry_budget,
            use_backups, callbacks, array_name, array_names, executor_name,
            recompute_resolver, admission,
            dependencies=dependencies,
            on_input_submit=on_input_submit,
            on_input_done=on_input_done,
            completed_inputs=completed_inputs,
            cancellation=cancellation,
            **kwargs,
        )
    elif array_names is None:
        it = iter(inputs)
        while True:
            batch = list(itertools.islice(it, batch_size))
            if not batch:
                break
            _map_unordered_batch(
                executor, function, batch, policy, retry_budget,
                use_backups, callbacks, array_name, None, executor_name,
                recompute_resolver, admission,
                cancellation=cancellation,
                **kwargs,
            )
    else:
        for start in range(0, len(inputs), batch_size):
            _map_unordered_batch(
                executor,
                function,
                inputs[start : start + batch_size],
                policy,
                retry_budget,
                use_backups,
                callbacks,
                array_name,
                array_names[start : start + batch_size],
                executor_name,
                recompute_resolver,
                admission,
                cancellation=cancellation,
                **kwargs,
            )


def _map_unordered_batch(
    executor,
    function,
    inputs: list,
    policy: RetryPolicy,
    budget: Optional[RetryBudget],
    use_backups: bool,
    callbacks,
    array_name,
    array_names: Optional[list] = None,
    executor_name: Optional[str] = None,
    recompute_resolver=None,
    admission: Optional[AdmissionController] = None,
    dependencies: Optional[Dict[int, set]] = None,
    on_input_submit: Optional[Callable[[int], None]] = None,
    on_input_done: Optional[Callable[[int], None]] = None,
    completed_inputs: Optional[set] = None,
    cancellation=None,
    **kwargs,
) -> None:
    metrics = get_registry()
    retries = policy.retries
    if budget is None:
        budget = policy.new_budget(len(inputs))
    if admission is None:
        admission = AdmissionController()
    attempts: Dict[int, int] = {i: 0 for i in range(len(inputs))}
    #: free worker-loss reroutes consumed per input (capped by the policy)
    requeues: Dict[int, int] = {}
    #: ABRUPT worker deaths per input (lease expiry / verified hard exit —
    #: never clean drains): the poison-request evidence. One input taking
    #: out max_requeues + 1 hosts in a row is quarantined with a
    #: PoisonTaskError instead of burning retries and workers fleet-wide
    fatal_strikes: Dict[int, int] = {}
    #: min-heap of (due time, input index) retries awaiting their backoff
    delayed: list[tuple[float, int]] = []
    #: inputs ready to run but waiting for an admission slot (memory
    #: pressure stepped the in-flight limit down)
    admit_queue: deque[int] = deque()
    #: input -> (floor failures so far, done_inputs size at the last one):
    #: a RESOURCE failure of a task admitted ALONE (limit 1) is only fatal
    #: on repetition with NO other task completing in between — one solo
    #: failure can still be residual pressure draining (or, under
    #: multi-process chaos, a per-process injector decision repeating);
    #: zero progress between two solo failures proves degradation is spent
    floor_strikes: Dict[int, tuple[int, int]] = {}
    start_times: Dict[object, float] = {}
    end_times: Dict[object, float] = {}
    create_times: Dict[int, float] = {}
    #: dispatch ledger, loop side: input -> when it became dispatchable
    #: (deps met / admitted), and future -> the loop's per-submit stamps
    #: (submitted_tstamp + the wall time the dispatch loop spent inside
    #: the submit call — serialize+send on the distributed executor)
    ready_times: Dict[int, float] = {}
    submit_meta: Dict[concurrent.futures.Future, dict] = {}
    #: trailing window of per-task submit cost: the basis of the
    #: dispatch_capacity_estimate gauge (tasks/sec the dispatch path could
    #: sustain if it did nothing else)
    dispatch_costs: deque = deque(maxlen=64)
    # future -> (input index, is_backup, attempt number it was submitted
    # as, admission limit at submit time — None = unbounded; a RESOURCE
    # failure of a task admitted at limit 1 is fatal, degradation is spent)
    pending: Dict[concurrent.futures.Future, tuple[int, bool, int, Optional[int]]] = {}
    backups: Dict[int, list[concurrent.futures.Future]] = {}
    done_inputs: set[int] = set()
    #: input index -> in-flight upstream repair (RECOMPUTE): repairs run on
    #: a small side pool so a full producing-task re-run never stalls the
    #: completion loop (the same never-block rule backoff retries follow)
    repairing: Dict[int, concurrent.futures.Future] = {}
    repair_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None

    # crash-recovery resume: indices a previous attempt over this same
    # input list already completed (snapshotted once at entry) start out
    # done — never resubmitted, and never blocking their dependents
    if completed_inputs:
        done_inputs.update(
            i for i in completed_inputs if 0 <= i < len(inputs)
        )

    #: dataflow gating: input -> still-unmet dependency indices, and the
    #: reverse map releasing dependents the moment an input completes
    blocked: Dict[int, set] = {}
    dependents: Dict[int, list] = {}
    if dependencies:
        for i, deps in dependencies.items():
            if i in done_inputs:
                continue
            rem = {
                d for d in deps
                if d != i and 0 <= d < len(inputs) and d not in done_inputs
            }
            if rem:
                blocked[i] = set(rem)
                for d in rem:
                    dependents.setdefault(d, []).append(i)

    key_cache: Dict[int, str] = {}

    def op_of(i: int) -> str:
        return array_names[i] if array_names is not None else array_name

    def key_of(i: int) -> str:
        # one str() per input, shared by the start event, retries/backups,
        # and the end event — chunk keys are stable per task
        key = key_cache.get(i)
        if key is None:
            # interleaved-generation items are (op_name, task_input) pairs
            m = inputs[i][1] if array_names is not None else inputs[i]
            key = key_cache[i] = chunk_key(m)
        return key

    def submit(i: int, is_backup: bool = False):
        # the dispatch ledger's "dequeued -> sent" window: everything from
        # here through executor.submit runs ON the dispatch loop (for the
        # distributed executor, Coordinator.submit — pickle + socket send —
        # is inline in that call), so its duration IS per-task coordinator
        # cost, distinct from waiting on a free worker
        t_dispatch = time.perf_counter()
        if on_input_submit is not None:
            on_input_submit(i)
        submitted_ts = time.time()
        create_times.setdefault(i, submitted_ts)
        ready_times.setdefault(i, submitted_ts)
        fire_task_start(
            callbacks, op_of(i), key_fn=lambda: key_of(i),
            attempt=attempts[i], backup=is_backup,
        )
        fut = executor.submit(execute_with_stats, function, inputs[i], **kwargs)
        cost = time.perf_counter() - t_dispatch
        submit_meta[fut] = {
            "ready_tstamp": ready_times.get(i, submitted_ts),
            "submitted_tstamp": submitted_ts,
            "submit_cost_s": cost,
        }
        dispatch_costs.append(cost)
        metrics.counter("dispatch_submit_s").inc(cost)
        start_times[fut] = time.time()
        # the submit-time attempt rides with the future so the end event
        # reports the attempt that actually produced the result (a backup
        # submitted as attempt 0 can win after the original fails and bumps
        # attempts[i])
        pending[fut] = (i, is_backup, attempts[i], admission.limit)
        if is_backup:
            backups.setdefault(i, []).append(fut)
        return fut

    def cancel_pending() -> None:
        for f in pending:
            f.cancel()

    def resubmit(i: int) -> None:
        # a raising submit (e.g. NoWorkersError from a dead fleet) must not
        # leave the rest of the map running detached
        try:
            submit(i)
        except Exception:
            cancel_pending()
            raise

    def admit(i: int) -> None:
        """Submit *i* now, or queue it when the admission limit is hit.

        With the controller unbounded (no memory pressure ever seen) every
        input submits immediately — exactly the pre-guard behavior."""
        # deps-ready stamp: the input is dispatchable from here on, whether
        # it submits now or queues for an admission slot — the interval to
        # the submit stamp is real backpressure, not coordinator cost
        now_ts = time.time()
        create_times.setdefault(i, now_ts)
        ready_times.setdefault(i, now_ts)
        if not admit_queue and admission.has_slot(len(pending)):
            resubmit(i)
            return
        metrics.counter("tasks_throttled").inc()
        admit_queue.append(i)

    def drain_admit_queue() -> None:
        while admit_queue and admission.has_slot(len(pending)):
            i = admit_queue.popleft()
            if i not in done_inputs:
                resubmit(i)

    def release_dependents(i_done: int) -> None:
        """Unblock tasks whose last dependency just completed: they admit
        immediately — the whole point of the dataflow scheduler."""
        for j in dependents.get(i_done, ()):
            rem = blocked.get(j)
            if rem is None:
                continue
            rem.discard(i_done)
            if not rem:
                del blocked[j]
                if j not in done_inputs:
                    admit(j)

    for i in range(len(inputs)):
        if i not in blocked and i not in done_inputs:
            admit(i)

    #: dispatch-loop busy-vs-idle self-accounting: time spent blocked in
    #: the completion waits / backoff sleeps below is idle; everything else
    #: the loop does (submit, classify, release) is busy. Folded into the
    #: dispatch_utilization gauge each ~0.5s window — utilization pegged at
    #: ~1.0 while queue_depth grows is the dispatch-saturation signature
    #: (the dispatch_saturation alert watches exactly that pair)
    util_t0 = time.time()
    util_idle_s = 0.0

    try:
        while pending or delayed or repairing or admit_queue or blocked:
            now = time.time()
            # cooperative cancellation / deadline: the dispatch loop is
            # the first enforcement point — stop submitting, cancel
            # pending futures, raise the typed error (counted + recorded
            # + fleet-broadcast via cancellation.abort)
            if cancellation is not None and cancellation.cancelled:
                from ..cancellation import abort as _cancel_abort

                cancel_pending()
                raise _cancel_abort(cancellation)
            # launch retries whose backoff has elapsed
            while delayed and delayed[0][0] <= now:
                _, i = heapq.heappop(delayed)
                if i not in done_inputs:
                    admit(i)
            # hard host pressure (RSS watermark / MemAvailable floor) steps
            # concurrency down even before any task actually dies of it
            if pressure_level() == "hard":
                admission.on_pressure(len(pending))
            drain_admit_queue()
            # resubmit readers whose upstream repair finished; a failed
            # repair falls back to a backoff retry (next attempt re-triggers
            # the repair — bounded, since each drew retries/budget already)
            for ri, rfut in [(k, f) for k, f in repairing.items() if f.done()]:
                del repairing[ri]
                if ri in done_inputs:
                    continue
                rexc = rfut.exception()
                if rexc is None:
                    admit(ri)
                else:
                    rdelay = policy.backoff_delay(attempts[ri])
                    logger.warning(
                        "upstream recompute for input %s failed (%r); "
                        "retrying the reader in %.3fs", ri, rexc, rdelay,
                    )
                    heapq.heappush(delayed, (now + rdelay, ri))
            metrics.gauge("queue_depth").set(len(pending))
            now_util = time.time()
            if now_util - util_t0 >= 0.5:
                elapsed = now_util - util_t0
                metrics.gauge("dispatch_utilization").set(
                    max(0.0, min(1.0, 1.0 - util_idle_s / elapsed))
                )
                if dispatch_costs:
                    mean_cost = sum(dispatch_costs) / len(dispatch_costs)
                    if mean_cost > 0:
                        metrics.gauge("dispatch_capacity_estimate").set(
                            1.0 / mean_cost
                        )
                util_t0 = now_util
                util_idle_s = 0.0
            if not pending:
                # nothing in flight: sleep until the next retry is due or
                # an in-flight repair completes
                if delayed:
                    t_idle = time.perf_counter()
                    time.sleep(max(0.0, min(delayed[0][0] - time.time(), 0.25)))
                    util_idle_s += time.perf_counter() - t_idle
                elif repairing:
                    t_idle = time.perf_counter()
                    concurrent.futures.wait(
                        list(repairing.values()), timeout=0.25
                    )
                    util_idle_s += time.perf_counter() - t_idle
                elif admit_queue:
                    # throttled to zero in flight: keep draining
                    continue
                elif blocked:
                    # nothing runs, nothing is scheduled to run, yet tasks
                    # still wait on dependencies: a cyclic or miswired
                    # chunk graph — fail loudly instead of spinning
                    raise RuntimeError(
                        f"dataflow deadlock: {len(blocked)} task(s) blocked "
                        "on dependencies that can no longer complete "
                        "(first blocked inputs: "
                        f"{sorted(blocked)[:5]})"
                    )
                continue
            timeout = 2.0
            if cancellation is not None:
                # notice a cancel/deadline within a fraction of a second,
                # not a whole wait quantum (the 2s worker-abort bound);
                # an armed deadline also never oversleeps its own expiry
                timeout = 0.25
                rem = cancellation.remaining()
                if rem is not None:
                    timeout = max(0.01, min(timeout, rem))
            if delayed:
                timeout = max(0.01, min(timeout, delayed[0][0] - now))
            if repairing:
                timeout = min(timeout, 0.05)  # notice repair completions fast
            t_idle = time.perf_counter()
            done, _ = concurrent.futures.wait(
                list(pending), timeout=timeout,
                return_when=concurrent.futures.FIRST_COMPLETED,
            )
            util_idle_s += time.perf_counter() - t_idle
            now = time.time()
            for fut in done:
                entry = pending.pop(fut, None)
                meta = submit_meta.pop(fut, None)
                if entry is None:
                    # a twin that completed in the same wait batch as its
                    # winner: the winner's cancel loop already removed it
                    continue
                i, is_backup, attempt, limit_at_submit = entry
                end_times[fut] = now
                if i in done_inputs:
                    continue  # a twin already won
                exc = fut.exception()
                if exc is not None:
                    twins = [f for f in pending if pending[f][0] == i]
                    cls = policy.classify(exc)
                    # the failure timeline: every observed task failure,
                    # with its classification, for traces/bundles
                    record_decision(
                        "task_failed",
                        op=op_of(i), chunk=key_of(i), attempt=attempt,
                        error_type=type(exc).__name__,
                        error=str(exc)[:200],
                        classification=cls.name.lower(),
                    )
                    # the failed attempt's span buffer rides the exception
                    # (locally, pickled off a pool, or on the fleet error
                    # frame): land it on the merged trace
                    record_failed_task(op_of(i), key_of(i), attempt, exc)
                    if (
                        cls is Classification.REQUEUE
                        and not _clean_worker_loss(exc)
                        and getattr(exc, "was_executing", True)
                    ):
                        # an ABRUPT worker death with THIS task EXECUTING
                        # (was_executing False marks tasks that were only
                        # queued on the corpse — innocents, no strike):
                        # one strike toward the poison verdict. K =
                        # max_requeues + 1 consecutive worker-fatal
                        # attempts convicts the task — the workers keep
                        # dying wherever it lands, so rerouting further
                        # only feeds it hosts
                        fatal_strikes[i] = fatal_strikes.get(i, 0) + 1
                        if fatal_strikes[i] > policy.max_requeues:
                            metrics.counter("poison_quarantined").inc()
                            record_decision(
                                "poison_quarantine", op=op_of(i),
                                chunk=key_of(i),
                                attempts=fatal_strikes[i],
                            )
                            cancel_pending()
                            raise PoisonTaskError(
                                op_of(i), key_of(i), fatal_strikes[i]
                            ) from exc
                    if (
                        cls is Classification.REQUEUE
                        and requeues.get(i, 0) < policy.max_requeues
                    ):
                        # the worker died, not the task: reroute to a
                        # survivor without consuming a user-visible retry
                        requeues[i] = requeues.get(i, 0) + 1
                        metrics.counter("worker_loss_requeues").inc()
                        record_decision(
                            "requeue", op=op_of(i), chunk=key_of(i),
                            requeue=requeues[i],
                        )
                        logger.info(
                            "requeueing input %s after worker loss "
                            "(requeue %d/%d)", i, requeues[i],
                            policy.max_requeues,
                        )
                        if not twins:
                            admit(i)
                        continue
                    if cls is Classification.CANCELLED:
                        # the task aborted because the COMPUTE was
                        # cancelled (worker-side cooperative abort, or
                        # the deadline fired in the task body): not a
                        # task failure — abort the whole map with the
                        # typed error, zero retries, zero budget draw
                        cancel_pending()
                        if cancellation is not None:
                            from ..cancellation import abort as _cancel_abort

                            raise _cancel_abort(cancellation) from exc
                        from ..cancellation import (
                            ComputeDeadlineExceededError,
                        )

                        metrics.counter(
                            "deadline_aborts"
                            if isinstance(exc, ComputeDeadlineExceededError)
                            or getattr(exc, "remote_type", None)
                            == "ComputeDeadlineExceededError"
                            else "cancellations"
                        ).inc()
                        raise exc
                    attempts[i] += 1
                    if cls is Classification.RESOURCE:
                        # BEFORE twin suppression — memory pressure is
                        # real whether or not a backup twin is still
                        # running, and deferring the step-down until the
                        # twin also dies would keep everything at full
                        # concurrency for one extra OOM-pressure round.
                        # The task (or its worker) ran out of memory:
                        # blind full-concurrency retries recreate the
                        # pressure, so halve the admission limit first —
                        # and if the task was already admitted ALONE
                        # (limit 1), degradation is spent: abort with the
                        # actionable measured-vs-allowed error
                        count_resource_failure(metrics, exc)
                        if limit_at_submit == 1:
                            strikes, done_at = floor_strikes.get(i, (0, -1))
                            if strikes >= 1 and done_at == len(done_inputs):
                                cancel_pending()
                                raise resource_abort_error(
                                    op_of(i), exc
                                ) from exc
                            floor_strikes[i] = (strikes + 1, len(done_inputs))
                        admission.step_down(len(pending) + 1)
                    # suppress if a backup twin is still running
                    if twins:
                        continue
                    if cls is Classification.RECOMPUTE:
                        # counted after twin suppression, so a backup pair
                        # failing on one corrupt chunk reports one defect
                        _count_integrity_failure(metrics, exc)
                    if cls is Classification.FAIL_FAST:
                        # deterministic programming error: retrying cannot
                        # change the outcome — one attempt, no backoff
                        metrics.counter("task_failfast").inc()
                        cancel_pending()
                        raise exc
                    if attempts[i] > retries:
                        cancel_pending()
                        if cls is Classification.RESOURCE:
                            # retries exhausted on memory: surface the
                            # actionable form, not a bare MemoryError
                            raise resource_abort_error(
                                op_of(i), exc, at_floor=False
                            ) from exc
                        raise exc
                    if not budget.consume():
                        cancel_pending()
                        raise budget_exhausted_error(exc, budget) from exc
                    if cls is Classification.RECOMPUTE:
                        repair = (
                            recompute_resolver.resolve(integrity_payload(exc))
                            if recompute_resolver is not None
                            else None
                        )
                        if repair is not None:
                            # re-run the producing task for the corrupt
                            # chunk on the side pool; the reader resubmits
                            # when the repair lands (no extra backoff — the
                            # repair itself costs the wall clock one would)
                            payload = integrity_payload(exc) or {}
                            record_decision(
                                "recompute", op=op_of(i), chunk=key_of(i),
                                store=str(payload.get("store", "")),
                                corrupt_chunk=payload.get("chunk_key"),
                            )
                            if repair_pool is None:
                                repair_pool = (
                                    concurrent.futures.ThreadPoolExecutor(
                                        max_workers=2,
                                        thread_name_prefix="chunk-repair",
                                    )
                                )
                            repairing[i] = repair_pool.submit(repair)
                            continue
                        logger.warning(
                            "corrupt chunk with no recompute path "
                            "(input %s): retrying blind — will fail "
                            "loudly if the corruption cannot heal", i,
                        )
                    delay = policy.backoff_delay(attempts[i])
                    if cls is Classification.THROTTLE:
                        # a store throttle escaped the breaker's in-place
                        # pacing (or the breaker is off): count it here —
                        # the failing attempt's scope counters were
                        # discarded with the attempt — and floor the
                        # backoff so the retry doesn't hammer a store
                        # that just said SlowDown
                        metrics.counter("store_throttled").inc()
                        delay = max(delay, 0.2)
                    logger.info(
                        "retrying input %s (attempt %d) in %.3fs",
                        i, attempts[i] + 1, delay,
                    )
                    metrics.counter("task_retries").inc()
                    metrics.histogram("retry_backoff_s").observe(delay)
                    record_decision(
                        "retry", op=op_of(i), chunk=key_of(i),
                        attempt=attempts[i], delay_s=round(delay, 4),
                    )
                    if delay <= 0:
                        admit(i)
                    else:
                        heapq.heappush(delayed, (now + delay, i))
                    continue
                _, stats = fut.result()
                done_inputs.add(i)
                admission.on_success(pressure_level() == "ok")
                # cancel the losing twin(s)
                for f in list(pending):
                    if pending[f][0] == i:
                        f.cancel()
                        del pending[f]
                        submit_meta.pop(f, None)
                # the dispatch ledger: the loop's own stamps (deps-ready /
                # dequeued / submit cost) merged with whatever the
                # coordinator injected into the stats channel (serialize/
                # send/lock-wait/result-unpickle, distributed executor
                # only) — the keys are disjoint by construction
                stats = dict(stats)
                disp = stats.pop("dispatch", None) or {}
                if meta:
                    disp = dict(disp, **meta)
                handle_callbacks(
                    callbacks,
                    dict(
                        stats,
                        array_name=op_of(i),
                        task_create_tstamp=create_times[i],
                        chunk_key=key_of(i),
                        attempt=attempt,
                        executor=executor_name,
                        dispatch=disp or None,
                    ),
                )
                # dataflow hooks and dependent release fire AFTER the task
                # end event: observers see a completion before any of its
                # consequences (an op's end event still follows its last
                # task's end event), and a callback mutating storage for
                # chaos tests cannot race the released consumer's read
                t_release = time.perf_counter()
                if on_input_done is not None:
                    on_input_done(i)
                release_dependents(i)
                # dependents-released: fan-out time is dispatch cost too
                # (it includes the submits it triggers, which also count
                # under dispatch_submit_s — the ledger, not these coarse
                # counters, is the double-count-free view)
                metrics.counter("dispatch_release_s").inc(
                    time.perf_counter() - t_release
                )
            if (
                use_backups
                and not admission.throttling
                and not _overload_sheds_optional()
            ):
                # no speculative duplicates while degraded for memory (or
                # while the service overload ladder is shedding optional
                # work at L1+): a backup twin is pure extra footprint
                for fut, (i, is_backup, _attempt, _lim) in list(pending.items()):
                    if is_backup or i in done_inputs or i in backups:
                        continue
                    if should_launch_backup(fut, now, start_times, end_times):
                        logger.info("launching backup for input %s", i)
                        metrics.counter("speculative_backups").inc()
                        record_decision(
                            "backup", op=op_of(i), chunk=key_of(i),
                        )
                        submit(i, is_backup=True)
    finally:
        # reset even when retries are exhausted mid-loop: a stale nonzero
        # queue_depth would read as phantom in-flight tasks forever after
        # (likewise a pegged utilization with no loop running)
        metrics.gauge("queue_depth").set(0)
        metrics.gauge("dispatch_utilization").set(0.0)
        if repair_pool is not None:
            repair_pool.shutdown(wait=False, cancel_futures=True)


class AsyncPythonDagExecutor(DagExecutor):
    """ThreadPool executor with classified retries, backups and generation
    parallelism."""

    def __init__(
        self,
        max_workers: Optional[int] = None,
        retries: int = DEFAULT_RETRIES,
        use_backups: bool = False,
        batch_size: Optional[int] = None,
        compute_arrays_in_parallel: bool = False,
        retry_policy: Optional[RetryPolicy] = None,
        **kwargs,
    ):
        self.max_workers = max_workers
        self.retries = retries
        self.use_backups = use_backups
        self.batch_size = batch_size
        self.compute_arrays_in_parallel = compute_arrays_in_parallel
        self.retry_policy = retry_policy
        self.kwargs = kwargs

    @property
    def name(self) -> str:
        return "threads"

    def execute_dag(
        self,
        dag,
        callbacks=None,
        array_names=None,
        resume=None,
        spec=None,
        retries: Optional[int] = None,
        use_backups: Optional[bool] = None,
        batch_size: Optional[int] = None,
        compute_arrays_in_parallel: Optional[bool] = None,
        retry_policy: Optional[RetryPolicy] = None,
        journal=None,
        cancellation=None,
        **kwargs,
    ) -> None:
        retries = self.retries if retries is None else retries
        use_backups = self.use_backups if use_backups is None else use_backups
        batch_size = self.batch_size if batch_size is None else batch_size
        if compute_arrays_in_parallel is None:
            compute_arrays_in_parallel = self.compute_arrays_in_parallel
        policy = resolve_policy(retry_policy or self.retry_policy, retries)
        budget = compute_retry_budget(policy, dag)
        # one admission controller per compute (like the budget): a memory
        # step-down discovered in one op carries into the next instead of
        # rediscovering the pressure op by op
        admission = AdmissionController()
        # chunk-granular resume: one checksum-verified scan per store, shared
        # by the op-level and task-level skips; corrupt chunks found by the
        # scan are quarantined so their tasks re-run. A loaded compute
        # journal (resume_from_journal) narrows the skip set to its
        # completed-task frontier ∩ the integrity scan
        state = (
            ResumeState(quarantine=True, journal=journal) if resume else None
        )
        resolver = RecomputeResolver(dag)
        # a defaulted dataflow yields to an explicit batch_size (the rule
        # lives in dataflow.effective_scheduler); explicit requests win
        # and warn below
        scheduler = effective_scheduler(spec, batch_size)
        record_scheduler_mode(scheduler, executor=self.name)

        with concurrent.futures.ThreadPoolExecutor(
            max_workers=self.max_workers
        ) as pool:
            if scheduler == "dataflow":
                # chunk-granular dataflow: the whole DAG becomes ONE map
                # whose dependencies gate each task on its own input
                # chunks — subsumes generation interleaving (batch_size
                # does not apply: one dependency index space)
                if batch_size:
                    logger.warning(
                        "batch_size=%s is ignored under scheduler="
                        "\"dataflow\" (the whole DAG is one dependency-"
                        "gated map); use admission control / max_workers "
                        "to bound in-flight tasks", batch_size,
                    )
                sched = DataflowScheduler(
                    dag, resume=resume, state=state, callbacks=callbacks
                )
                sched.start()
                try:
                    self._run_tasks(
                        pool, sched.items, sched.pipelines, policy, budget,
                        use_backups, None, callbacks, resolver, admission,
                        dependencies=sched.dependencies,
                        on_input_submit=sched.on_submit,
                        on_input_done=sched.on_done,
                        cancellation=cancellation,
                    )
                finally:
                    sched.finish()
            elif compute_arrays_in_parallel:
                # ops in the same topological generation interleave their tasks
                for generation in visit_node_generations(
                    dag, resume=resume, state=state
                ):
                    merged, pipelines = merge_generation(
                        generation, callbacks, resume=resume, resume_state=state
                    )
                    self._run_tasks(
                        pool, merged, pipelines, policy, budget, use_backups,
                        batch_size, callbacks, resolver, admission,
                        cancellation=cancellation,
                    )
                    end_generation(generation, callbacks)
            else:
                for name, node in visit_nodes(dag, resume=resume, state=state):
                    primitive_op = node["primitive_op"]
                    pipeline = primitive_op.pipeline
                    callbacks_on(
                        callbacks, "on_operation_start",
                        OperationStartEvent(name, primitive_op.num_tasks),
                    )
                    mappable, _ = pending_mappable(name, node, resume, state)
                    map_unordered(
                        pool,
                        pipeline.function,
                        mappable,
                        retry_policy=policy,
                        retry_budget=budget,
                        use_backups=use_backups,
                        batch_size=batch_size,
                        callbacks=callbacks,
                        array_name=name,
                        executor_name=self.name,
                        recompute_resolver=resolver,
                        admission=admission,
                        cancellation=cancellation,
                        config=pipeline.config,
                    )
                    callbacks_on(
                        callbacks, "on_operation_end",
                        OperationEndEvent(name, primitive_op.num_tasks),
                    )

    def _run_tasks(
        self, pool, merged, pipelines, policy, budget, use_backups,
        batch_size, callbacks, recompute_resolver=None, admission=None,
        **dataflow_kwargs,
    ):
        def fn(item):
            name, m = item
            pipeline = pipelines[name]
            return pipeline.function(m, config=pipeline.config)

        map_unordered(
            pool,
            fn,
            merged,
            retry_policy=policy,
            retry_budget=budget,
            use_backups=use_backups,
            batch_size=batch_size,
            callbacks=callbacks,
            array_names=[name for name, _ in merged],
            executor_name=self.name,
            recompute_resolver=recompute_resolver,
            admission=admission,
            **dataflow_kwargs,
        )
