"""Coordination-free distributed random arrays.

The reference keys a Philox generator by ``root_seed + linear block offset``
(cubed/random.py:13-36); the TPU-native equivalent is the jax threefry PRNG
with ``jax.random.fold_in(key, block_offset)`` — the same per-block
determinism contract (reproducible regardless of which worker/chip computes
which block), expressed with the native counter-based PRNG.
"""

from __future__ import annotations

import random as pyrandom

import numpy as np

from .backend_array_api import BACKEND, nxp
from .chunks import normalize_chunks
from .core.ops import map_blocks
from .array_api.creation_functions import empty
from .utils import block_id_to_offset


def random(size, *, diagnostics=None, chunks=None, spec=None):
    """Uniform [0, 1) float64 array with per-block reproducible randomness."""
    shape = (size,) if isinstance(size, int) else tuple(size)
    dtype = np.float64
    chunks = normalize_chunks(chunks, shape, dtype=dtype)
    numblocks = tuple(len(c) for c in chunks)
    root_seed = pyrandom.getrandbits(32)

    return map_blocks(
        _RandomBlock(root_seed, numblocks),
        empty(shape, dtype=dtype, chunks=chunks, spec=spec),
        dtype=dtype,
    )


class _RandomBlock:
    __name__ = "random_block"

    def __init__(self, root_seed: int, numblocks):
        self.root_seed = root_seed
        self.numblocks = numblocks

    def __call__(self, chunk, block_id=None):
        offset = block_id_to_offset(block_id, self.numblocks) if block_id else 0
        if BACKEND == "jax":
            import jax

            key = jax.random.fold_in(jax.random.key(self.root_seed), offset)
            return jax.random.uniform(key, chunk.shape, dtype=np.float64)
        rng = np.random.Generator(np.random.Philox(seed=self.root_seed + offset))
        return rng.random(chunk.shape, dtype=np.float64)
