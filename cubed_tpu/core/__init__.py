from .plan import Plan  # noqa: F401
