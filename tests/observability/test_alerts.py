"""Alert-rule engine tests: rule units, engine edge/cooldown semantics,
and the chaos proof — a seeded retry burn fires the matching rule with the
firing visible in the decision ring, the flight-recorder bundle, and
``python -m cubed_tpu.diagnose`` output."""

from __future__ import annotations

import tempfile

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.diagnose import render_report
from cubed_tpu.observability.alerts import (
    AlertEngine,
    BurnRateRule,
    StallRule,
    ThresholdRule,
    default_rules,
)
from cubed_tpu.observability.collect import decisions_since
from cubed_tpu.observability.flightrecorder import FlightRecorder, load_bundle
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.observability.timeseries import TimeSeriesStore

# ---------------------------------------------------------------------------
# rule units
# ---------------------------------------------------------------------------


def test_threshold_rule_latest_value():
    store = TimeSeriesStore()
    rule = ThresholdRule("mem", metric="fleet_pressured_fraction", threshold=0.5)
    assert rule.evaluate(store, 100.0) is None  # no data = healthy
    store.record("fleet_pressured_fraction", 0.25, ts=99.0)
    assert rule.evaluate(store, 100.0) is None
    store.record("fleet_pressured_fraction", 0.5, ts=100.0)
    details = rule.evaluate(store, 100.0)
    assert details is not None
    assert details["value"] == 0.5 and details["threshold"] == 0.5


def test_threshold_rule_rate_mode():
    store = TimeSeriesStore()
    rule = ThresholdRule(
        "stragglers", metric="stragglers_detected", rate=True,
        threshold=0.2, window_s=30.0,
    )
    store.record("stragglers_detected", 0, ts=70.0)
    store.record("stragglers_detected", 1, ts=80.0)
    # 1 in 10s = 0.1/s < 0.2 threshold
    assert rule.evaluate(store, 80.0) is None
    store.record("stragglers_detected", 7, ts=90.0)
    details = rule.evaluate(store, 90.0)
    assert details is not None and details["value"] >= 0.2


def test_threshold_rule_ignores_frozen_series():
    """A latest-value reading whose writer is gone (no samples for longer
    than the staleness bound) is no-data, not a standing alert — the
    long-lived telemetry singleton must not re-fire on a closed fleet's
    fossil reading every cooldown forever."""
    store = TimeSeriesStore()
    rule = ThresholdRule("mem", metric="fleet_pressured_fraction", threshold=0.5)
    store.record("fleet_pressured_fraction", 0.9, ts=100.0)
    assert rule.evaluate(store, 105.0) is not None  # fresh: fires
    assert rule.evaluate(store, 100.0 + rule.stale_after_s + 1) is None


def test_threshold_rule_rejects_bad_comparison():
    with pytest.raises(ValueError):
        ThresholdRule("x", metric="m", threshold=1, comparison="==")


def test_burn_rate_rule():
    store = TimeSeriesStore()
    rule = BurnRateRule(
        "retry_burn", counter="task_retries", budget=100,
        burn_frac=0.1, window_s=60.0,
    )
    store.record("task_retries", 0, ts=0.0)
    store.record("task_retries", 5, ts=30.0)
    assert rule.evaluate(store, 30.0) is None  # 5 < 10% of 100
    store.record("task_retries", 12, ts=40.0)
    details = rule.evaluate(store, 40.0)
    assert details is not None
    assert details["value"] == 12 and details["threshold"] == 10.0


def test_stall_rule_fires_only_on_sustained_stall():
    store = TimeSeriesStore()
    rule = StallRule("stall", window_s=30.0)
    # queued work, completions advancing: healthy
    for t in range(0, 40, 5):
        store.record("queue_depth", 4, ts=float(t))
        store.record("tasks_completed", t, ts=float(t))
    assert rule.evaluate(store, 39.0) is None
    # queued work, completions frozen across the whole window: stalled
    store2 = TimeSeriesStore()
    for t in range(0, 40, 5):
        store2.record("queue_depth", 4, ts=float(t))
        store2.record("tasks_completed", 7, ts=float(t))
    details = rule.evaluate(store2, 39.0)
    assert details is not None and details["value"] == 4
    # a fleet wedged before the FIRST task ever completes never creates
    # the tasks_completed series at all — missing progress is zero
    # progress, not health (the depth series proves sampler coverage)
    store2b = TimeSeriesStore()
    for t in range(0, 40, 5):
        store2b.record("queue_depth", 4, ts=float(t))
    assert rule.evaluate(store2b, 39.0) is not None
    # a queue that only JUST filled is starting, not stalled
    store3 = TimeSeriesStore()
    store3.record("queue_depth", 4, ts=38.0)
    store3.record("queue_depth", 4, ts=39.0)
    store3.record("tasks_completed", 7, ts=38.0)
    store3.record("tasks_completed", 7, ts=39.0)
    assert rule.evaluate(store3, 39.0) is None
    # an empty queue is never a stall
    assert rule.evaluate(TimeSeriesStore(), 39.0) is None


def test_default_rules_cover_the_documented_shapes():
    names = {r.name for r in default_rules()}
    assert names == {
        "retry_budget_burn", "fleet_memory_pressure", "straggler_rate",
        "queue_depth_stall", "peer_fetch_fallback_spike",
        "tenant_starvation", "store_brownout", "dispatch_saturation",
        "overload_shedding", "tenant_breaker_open",
        "slo_fast_burn", "slo_slow_burn",
    }


def test_tenant_starvation_rule_fires_per_tenant():
    """Queued work for a whole window with zero completions fires, naming
    the starving tenant(s); a progressing tenant does not."""
    from cubed_tpu.observability.alerts import TenantStarvationRule

    now = 1000.0
    store = TimeSeriesStore()
    for i in range(40):
        ts = now - 40 + i
        # starved: constant queue, frozen completion counter
        store.record("tenant_queued", 3, ts=ts, labels={"tenant": "starved"})
        store.record(
            "tenant_completed", 7, ts=ts, labels={"tenant": "starved"}
        )
        # busy: constant queue but completions increasing
        store.record("tenant_queued", 5, ts=ts, labels={"tenant": "busy"})
        store.record(
            "tenant_completed", i, ts=ts, labels={"tenant": "busy"}
        )
    rule = TenantStarvationRule(window_s=30.0)
    firing = rule.evaluate(store, now)
    assert firing is not None
    assert firing["tenants"] == ["starved"]
    assert firing["metric"] == "tenant_queued"


def test_tenant_starvation_needs_the_whole_window():
    """A queue that just filled is starting, not starved — and a tenant
    whose completion series is missing entirely IS starving (a service
    wedged before its first completion never writes the counter)."""
    from cubed_tpu.observability.alerts import TenantStarvationRule

    now = 1000.0
    rule = TenantStarvationRule(window_s=30.0)
    fresh = TimeSeriesStore()
    for i in range(5):  # only the last 5s of the window
        fresh.record(
            "tenant_queued", 4, ts=now - 5 + i, labels={"tenant": "new"}
        )
    assert rule.evaluate(fresh, now) is None

    wedged = TimeSeriesStore()
    for i in range(40):
        wedged.record(
            "tenant_queued", 4, ts=now - 40 + i, labels={"tenant": "wedged"}
        )
    firing = rule.evaluate(wedged, now)
    assert firing is not None and firing["tenants"] == ["wedged"]


# ---------------------------------------------------------------------------
# engine semantics
# ---------------------------------------------------------------------------


def _pressure_store(frac: float, ts: float = 100.0) -> TimeSeriesStore:
    store = TimeSeriesStore()
    store.record("fleet_pressured_fraction", frac, ts=ts)
    return store


def test_engine_fires_on_rising_edge_and_counts():
    store = _pressure_store(0.75)
    engine = AlertEngine(
        store,
        rules=[ThresholdRule("mem", metric="fleet_pressured_fraction",
                             threshold=0.5)],
    )
    reg = get_registry()
    before = reg.snapshot()
    t0 = 100.0
    fired = engine.tick(now=t0)
    assert len(fired) == 1
    firing = fired[0]
    assert firing["rule"] == "mem" and firing["value"] == 0.75
    assert engine.active() == ["mem"]
    # visible in the counter AND the decision ring
    assert reg.snapshot_delta(before).get("alerts_fired") == 1
    ring = [d for d in decisions_since(0) if d["kind"] == "alert_fired"]
    assert ring and ring[-1]["rule"] == "mem"
    # the firing ring serves the dashboard
    assert engine.recent()[-1]["rule"] == "mem"


def test_engine_cooldown_suppresses_sustained_condition():
    store = _pressure_store(0.9, ts=100.0)
    engine = AlertEngine(
        store, cooldown_s=60.0,
        rules=[ThresholdRule("mem", metric="fleet_pressured_fraction",
                             threshold=0.5)],
    )
    assert len(engine.tick(now=100.0)) == 1
    store.record("fleet_pressured_fraction", 0.9, ts=101.0)
    assert engine.tick(now=101.0) == []  # still active, inside cooldown
    store.record("fleet_pressured_fraction", 0.9, ts=161.0)
    assert len(engine.tick(now=161.0)) == 1  # re-fires after cooldown
    # condition clears, then returns: rising edge fires immediately
    store.record("fleet_pressured_fraction", 0.1, ts=162.0)
    assert engine.tick(now=162.0) == []
    assert engine.active() == []
    store.record("fleet_pressured_fraction", 0.9, ts=163.0)
    assert len(engine.tick(now=163.0)) == 1


def test_engine_survives_a_broken_rule():
    class _Broken(ThresholdRule):
        def evaluate(self, store, now):
            raise RuntimeError("boom")

    store = _pressure_store(0.9)
    engine = AlertEngine(
        store,
        rules=[
            _Broken("broken", metric="x", threshold=1),
            ThresholdRule("mem", metric="fleet_pressured_fraction",
                          threshold=0.5),
        ],
    )
    fired = engine.tick(now=100.0)
    assert [f["rule"] for f in fired] == ["mem"]


# ---------------------------------------------------------------------------
# chaos: a seeded retry burn fires retry_budget_burn, visible in the
# decision ring, the flight-recorder bundle, and diagnose output
# ---------------------------------------------------------------------------


def test_chaos_retry_burn_fires_alert_into_ring_bundle_and_diagnose(
    tmp_path, monkeypatch,
):
    pytest.importorskip("jax")
    from cubed_tpu.observability import export

    export.shutdown()
    monkeypatch.delenv(export.TELEMETRY_PORT_ENV_VAR, raising=False)
    spec = ct.Spec(
        work_dir=str(tmp_path / "work"), allowed_mem="500MB",
        telemetry_port=0,
        # seeded storage flakiness: every retry draws task_retries up —
        # the same deterministic chaos shape test_chaos proves correctness
        # under; here it exists to burn the retry budget visibly
        fault_injection={"storage_read_failure_rate": 0.25, "seed": 7},
    )
    fr = FlightRecorder(bundle_dir=str(tmp_path / "bundles"), always=True)
    an = np.arange(144.0).reshape(12, 12)
    a = ct.from_array(an, chunks=(3, 3), spec=spec)
    r = ct.map_blocks(lambda x: x + 2.0, a, dtype=np.float64)
    retries_before = get_registry().snapshot().get("task_retries", 0)
    try:
        from cubed_tpu.runtime.executors.python_async import (
            AsyncPythonDagExecutor,
        )

        result = np.asarray(
            r.compute(callbacks=[fr], executor=AsyncPythonDagExecutor())
        )
        np.testing.assert_array_equal(result, an + 2.0)
        rt = export.get_runtime()
        assert rt is not None, "telemetry never armed"
        # a tight burn rule over the live series (the default 20%-of-50
        # allowance would need a bigger storm than a unit test wants)
        rt.alert_engine.rules = [
            BurnRateRule(
                "retry_budget_burn", counter="task_retries", budget=10,
                burn_frac=0.1, window_s=300.0,
            ),
        ]
        rt.alert_engine._state = {
            "retry_budget_burn": {"active": False, "last_fired": 0.0}
        }
        retries = get_registry().snapshot().get("task_retries", 0)
        assert retries - retries_before > 0, (
            "seeded flakiness produced no retries"
        )
        # bracket the burn deterministically: the pre-compute baseline
        # (the tick the 1s sampler would have taken had the compute not
        # armed telemetry itself) plus one live tick at the current value
        import time as _time

        rt.store.record(
            "task_retries", retries_before, ts=_time.time() - 30.0
        )
        # the sampler tick runs the engine itself — exactly the live path
        rt.sampler.sample_once()
        fired = rt.alert_engine.recent()
        assert [f["rule"] for f in fired] == ["retry_budget_burn"]
        assert rt.alert_engine.active() == ["retry_budget_burn"]
        # 1) the decision ring carries the firing
        ring = [
            d for d in decisions_since(0) if d["kind"] == "alert_fired"
            and d["rule"] == "retry_budget_burn"
        ]
        assert ring, "alert firing missing from the decision ring"
        # 2) the flight-recorder bundle carries the alert timeline and the
        #    time-series dump
        bundle_path = fr.dump()
        bundle = load_bundle(bundle_path)
        manifest = bundle["manifest"]
        alerts = manifest.get("alerts") or []
        assert any(a.get("rule") == "retry_budget_burn" for a in alerts), (
            manifest.get("alerts")
        )
        series = manifest.get("timeseries") or []
        assert any(s["name"] == "task_retries" for s in series), (
            [s["name"] for s in series][:10]
        )
        # 3) diagnose renders the alerts section
        report = render_report(bundle)
        assert "alerts" in report
        assert "retry_budget_burn" in report
        assert "timeseries:" in report
    finally:
        export.shutdown()
