"""apply_gufunc tests. Reference parity: cubed/tests/test_gufunc.py."""

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.backend_array_api import nxp


def test_elementwise_gufunc(spec):
    an = np.arange(12.0).reshape(3, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    r = ct.apply_gufunc(nxp.negative, "()->()", a, output_dtypes=a.dtype)
    np.testing.assert_allclose(r.compute(), -an)


def test_core_dim_reduction(spec):
    an = np.arange(24.0).reshape(4, 6)
    # core dim must be single-chunk
    a = ct.from_array(an, chunks=(2, 6), spec=spec)

    def last_mean(x):
        return nxp.mean(x, axis=-1)

    r = ct.apply_gufunc(last_mean, "(i)->()", a, output_dtypes=a.dtype)
    np.testing.assert_allclose(r.compute(), an.mean(axis=-1))


def test_matvec_gufunc(spec):
    rng = np.random.default_rng(0)
    mats = rng.random((3, 4, 5))
    vecs = rng.random((3, 5))
    a = ct.from_array(mats, chunks=(1, 4, 5), spec=spec)
    b = ct.from_array(vecs, chunks=(1, 5), spec=spec)

    def matvec(m, v):
        return nxp.einsum("...ij,...j->...i", m, v)

    r = ct.apply_gufunc(matvec, "(i,j),(j)->(i)", a, b, output_dtypes=mats.dtype)
    np.testing.assert_allclose(r.compute(), np.einsum("bij,bj->bi", mats, vecs),
                               rtol=1e-12)


def test_chunked_core_dim_raises(spec):
    an = np.arange(24.0).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)  # core dim chunked
    with pytest.raises(ValueError, match="core dimension"):
        ct.apply_gufunc(lambda x: nxp.sum(x, axis=-1), "(i)->()", a,
                        output_dtypes=a.dtype)


def test_vectorize(spec):
    an = np.arange(6.0)
    a = ct.from_array(an, chunks=3, spec=spec)

    def add_one_scalar(x):
        return x + 1

    r = ct.apply_gufunc(
        add_one_scalar, "()->()", a, output_dtypes=a.dtype, vectorize=True
    )
    np.testing.assert_allclose(r.compute(), an + 1)


def test_bad_signature(spec):
    a = ct.from_array(np.zeros(3), chunks=3, spec=spec)
    with pytest.raises(ValueError, match="valid gufunc signature"):
        ct.apply_gufunc(lambda x: x, "bad sig", a, output_dtypes=np.float64)


def test_apply_gufunc_multiple_outputs(spec):
    """Same-core-dim multi-output signatures run as ONE multi-output op
    (the reference rejects all multi-output gufuncs)."""
    an = np.random.default_rng(0).random((8, 6))

    def mean_and_ptp(a):
        return a.mean(axis=-1), a.max(axis=-1) - a.min(axis=-1)

    a = ct.from_array(an, chunks=(2, 6), spec=spec)
    m, p = ct.apply_gufunc(
        mean_and_ptp, "(i)->(),()", a,
        output_dtypes=[np.float64, np.float64],
    )
    np.testing.assert_allclose(np.asarray(m.compute()), an.mean(axis=1))
    np.testing.assert_allclose(
        np.asarray(p.compute()), an.max(axis=1) - an.min(axis=1)
    )
    # one op feeds both outputs
    dag = m.plan.dag
    multi = [
        d["primitive_op"]
        for _, d in dag.nodes(data=True)
        if d.get("type") == "op"
        and d.get("primitive_op") is not None
        and d["primitive_op"].target_arrays is not None
    ]
    assert len(multi) == 1 and len(multi[0].target_arrays) == 2


def test_apply_gufunc_multiple_outputs_vectorized_divmod(spec):
    an = (np.random.default_rng(1).random(24) * 100).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    q, r = ct.apply_gufunc(
        lambda v: (v // 7.0, v % 7.0), "()->(),()", a,
        output_dtypes=[np.float64, np.float64], vectorize=True,
    )
    np.testing.assert_allclose(np.asarray(q.compute()), an // 7.0)
    np.testing.assert_allclose(np.asarray(r.compute()), an % 7.0)


def test_apply_gufunc_differing_output_core_dims_rejected(spec):
    a = ct.from_array(np.zeros((4, 4)), chunks=(2, 4), spec=spec)
    with pytest.raises(NotImplementedError, match="same core dimensions"):
        ct.apply_gufunc(
            lambda v: (v, v.sum()), "(i)->(i),()", a,
            output_dtypes=[np.float64, np.float64],
        )
