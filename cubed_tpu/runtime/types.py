"""Executor protocol and observability event types.

Reference parity: cubed/runtime/types.py:9-88.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence


class DagExecutor:
    """Protocol for plan executors: map each op's task function over its tasks."""

    @property
    def name(self) -> str:
        raise NotImplementedError

    def execute_dag(self, dag, callbacks=None, array_names=None, resume=None, spec=None, **kwargs) -> None:
        raise NotImplementedError


Executor = DagExecutor


@dataclass
class TaskEndEvent:
    """Metrics for a completed task."""

    array_name: str
    num_tasks: int = 1
    task_create_tstamp: Optional[float] = None
    function_start_tstamp: Optional[float] = None
    function_end_tstamp: Optional[float] = None
    task_result_tstamp: Optional[float] = None
    peak_measured_mem_start: Optional[int] = None
    peak_measured_mem_end: Optional[int] = None


class Callback:
    """Observer protocol for compute lifecycle events."""

    def on_compute_start(self, event) -> None:
        """Called when the computation is about to start; event has .dag, .resume."""

    def on_compute_end(self, event) -> None:
        """Called when the computation has finished; event has .dag."""

    def on_operation_start(self, event) -> None:
        """Called when an op begins; event has .name and .num_tasks."""

    def on_task_end(self, event: TaskEndEvent) -> None:
        """Called when one or more tasks of an op finish."""


@dataclass
class ComputeStartEvent:
    dag: object
    resume: Optional[bool] = None


@dataclass
class ComputeEndEvent:
    dag: object
    #: execution-path counters from the executor (e.g. segments traced,
    #: batched dispatches, eager fallbacks) — None if it reports none
    executor_stats: Optional[dict] = None


@dataclass
class OperationStartEvent:
    name: str
    num_tasks: int = 0


def callbacks_on(callbacks: Optional[Sequence[Callback]], method: str, event) -> None:
    if callbacks:
        for cb in callbacks:
            getattr(cb, method, lambda e: None)(event)
