#!/bin/bash
# Probe-and-fire loop (round 5): retry the gap-first device session on a
# ~15-minute cadence until every pending device measurement is recorded.
# Each attempt self-probes (appending to TUNNEL_LOG.jsonl) and exits fast
# when the tunnel is dead, so a dead tunnel costs one probe per cycle.
cd "$(dirname "$0")/.." || exit 1
for i in $(seq 1 40); do
  echo "=== gap_loop iteration $i $(date -u +%FT%TZ) ===" >> benchmarks/gap_loop.log
  python benchmarks/device_gap_session.py >> benchmarks/gap_loop.log 2>&1
  if grep -q "gaps=\[\] raw_gaps=\[\] threefry=\[\] mxu_sat_pending=False tsqr_pending=False" <(tail -40 benchmarks/gap_loop.log); then
    echo "all gaps filled $(date -u +%FT%TZ)" >> benchmarks/gap_loop.log
    exit 0
  fi
  sleep 900
done
