"""Plan optimization: blockwise fusion rewrites on the DAG.

Fusing op chains serves two goals: fewer storage round-trips (the reference's
motivation) and — central here — larger single XLA programs, since the TPU
executor jit-compiles each op's fused chunk kernel once and XLA fuses the whole
chain into registers/HBM.

Reference parity: cubed/core/optimization.py (behavioral; clean-room).
"""

from __future__ import annotations

import logging
from typing import Iterator, Optional

import networkx as nx

from ..primitive.blockwise import (
    BlockwiseSpec,
    can_fuse_pipelines,
    fuse_multiple,
    is_fuse_candidate,
)

logger = logging.getLogger(__name__)

#: reference default: do not fuse ops whose combined source-array count
#: exceeds this (cubed/core/optimization.py:98-209)
DEFAULT_MAX_TOTAL_SOURCE_ARRAYS = 4


def _op_nodes(dag) -> Iterator[str]:
    for name in list(nx.topological_sort(dag)):
        if name in dag and dag.nodes[name].get("type") == "op":
            yield name


def _producer_op(dag, array_name: str) -> Optional[str]:
    preds = list(dag.predecessors(array_name))
    if len(preds) == 1 and dag.nodes[preds[0]].get("type") == "op":
        return preds[0]
    return None


def _arg_source_names(primitive_op) -> Optional[list[str]]:
    """Per-argument input array names, derived by probing the block function."""
    spec: BlockwiseSpec = primitive_op.pipeline.config
    try:
        sample = next(iter(primitive_op.pipeline.mappable))
    except StopIteration:
        return None
    try:
        structure = spec.block_function(sample)
    except Exception:
        return None
    names = []
    for entry in structure:
        key = _first_key(entry)
        if key is None:
            return None
        names.append(key[0])
    return names


def _first_key(entry):
    if isinstance(entry, tuple) and entry and isinstance(entry[0], str):
        return entry
    if isinstance(entry, (list, tuple)):
        for item in entry:
            k = _first_key(item)
            if k is not None:
                return k
    return None


def can_fuse_predecessors(
    dag,
    op_name: str,
    array_names: Optional[tuple] = None,
    max_total_source_arrays: int = DEFAULT_MAX_TOTAL_SOURCE_ARRAYS,
    max_total_num_input_blocks: Optional[int] = None,
    always_fuse: Optional[set] = None,
    never_fuse: Optional[set] = None,
    require_unary: bool = False,
):
    """Decide whether op_name's predecessors can fuse into it.

    Returns (arg_names, predecessor_map) or None. predecessor_map maps an input
    array name to its producing op node when that producer will be fused.
    """
    nodes = dag.nodes
    op = nodes[op_name].get("primitive_op")
    if op is None or not is_fuse_candidate(op):
        return None
    if never_fuse and op_name in never_fuse:
        return None
    arg_names = _arg_source_names(op)
    if arg_names is None:
        return None

    input_arrays = list(dict.fromkeys(arg_names))
    if require_unary and len(input_arrays) != 1:
        return None

    forced = always_fuse is not None and op_name in always_fuse
    predecessor_map: dict[str, str] = {}
    total_sources = 0
    total_input_blocks = 0
    spec: BlockwiseSpec = op.pipeline.config
    for arr_name in input_arrays:
        if arr_name not in dag:
            return None
        producer = _producer_op(dag, arr_name)
        fusable_here = producer is not None
        if fusable_here:
            p_op = nodes[producer].get("primitive_op")
            fusable_here = (
                p_op is not None
                and can_fuse_pipelines(p_op, op)
                and (never_fuse is None or producer not in never_fuse)
                # the intermediate must have no other consumers and must not be
                # a requested output
                and dag.out_degree(arr_name) == _edges_to(dag, arr_name, op_name)
                and (array_names is None or arr_name not in array_names)
            )
        if fusable_here:
            predecessor_map[arr_name] = producer
            total_sources += len(p_op.source_array_names) or 1
            total_input_blocks += sum(p_op.pipeline.config.num_input_blocks)
        else:
            total_sources += 1
            total_input_blocks += 1

    if not predecessor_map:
        return None
    if not forced:
        if total_sources > max_total_source_arrays:
            logger.debug(
                "not fusing %s: total source arrays %d > %d",
                op_name, total_sources, max_total_source_arrays,
            )
            return None
        if (
            max_total_num_input_blocks is not None
            and total_input_blocks > max_total_num_input_blocks
        ):
            return None
    return arg_names, predecessor_map


def _edges_to(dag, u: str, v: str) -> int:
    return dag.number_of_edges(u, v)


def fuse_predecessors(
    dag,
    op_name: str,
    arg_names: list[str],
    predecessor_map: dict[str, str],
) -> bool:
    """Rewrite the graph fusing the given predecessor ops into op_name.

    Returns False (graph unchanged) if the fused op would exceed allowed_mem.
    """
    nodes = dag.nodes
    op = nodes[op_name]["primitive_op"]
    predecessor_ops = []
    for arr_name in arg_names:
        producer = predecessor_map.get(arr_name)
        predecessor_ops.append(
            nodes[producer]["primitive_op"] if producer is not None else None
        )

    fused = fuse_multiple(op, *predecessor_ops)
    if fused.projected_mem > op.allowed_mem > 0:
        logger.debug(
            "not fusing %s: projected mem %d > allowed %d",
            op_name, fused.projected_mem, op.allowed_mem,
        )
        return False

    nodes[op_name]["primitive_op"] = fused
    nodes[op_name]["pipeline"] = fused.pipeline

    for arr_name, producer in predecessor_map.items():
        # rewire: sources of the fused producer now feed op_name directly
        for src in list(dag.predecessors(producer)):
            dag.add_edge(src, op_name)
        dag.remove_node(arr_name)
        dag.remove_node(producer)
    return True


def simple_optimize_dag(dag, array_names: Optional[tuple] = None):
    """Linear map-fusion of op1 -> array -> op2 chains (unary only)."""
    dag = dag.copy()
    for op_name in list(_op_nodes(dag)):
        if op_name not in dag:
            continue
        result = can_fuse_predecessors(
            dag, op_name, array_names=array_names, require_unary=True
        )
        if result is None:
            continue
        arg_names, predecessor_map = result
        fuse_predecessors(dag, op_name, arg_names, predecessor_map)
    return dag


def multiple_inputs_optimize_dag(
    dag,
    array_names: Optional[tuple] = None,
    max_total_source_arrays: int = DEFAULT_MAX_TOTAL_SOURCE_ARRAYS,
    max_total_num_input_blocks: Optional[int] = None,
    always_fuse: Optional[set] = None,
    never_fuse: Optional[set] = None,
):
    """N-ary predecessor fusion in topological order (the default optimizer)."""
    dag = dag.copy()
    for op_name in list(_op_nodes(dag)):
        if op_name not in dag:
            continue
        result = can_fuse_predecessors(
            dag,
            op_name,
            array_names=array_names,
            max_total_source_arrays=max_total_source_arrays,
            max_total_num_input_blocks=max_total_num_input_blocks,
            always_fuse=always_fuse,
            never_fuse=never_fuse,
        )
        if result is None:
            continue
        arg_names, predecessor_map = result
        fuse_predecessors(dag, op_name, arg_names, predecessor_map)
    return dag


def fuse_all_optimize_dag(dag, array_names: Optional[tuple] = None):
    """Test helper: fuse as aggressively as possible."""
    all_ops = {n for n, d in dag.nodes(data=True) if d.get("type") == "op"}
    return multiple_inputs_optimize_dag(
        dag,
        array_names=array_names,
        max_total_source_arrays=10**9,
        max_total_num_input_blocks=10**9,
        always_fuse=all_ops,
    )


def fuse_only_optimize_dag(
    dag, array_names: Optional[tuple] = None, only_fuse: Optional[set] = None
):
    """Test helper: fuse only the named ops."""
    all_ops = {n for n, d in dag.nodes(data=True) if d.get("type") == "op"}
    never = all_ops - set(only_fuse or ())
    return multiple_inputs_optimize_dag(
        dag,
        array_names=array_names,
        always_fuse=set(only_fuse or ()),
        never_fuse=never,
        max_total_source_arrays=10**9,
        max_total_num_input_blocks=10**9,
    )
