from .types import (  # noqa: F401
    CubedArrayProxy,
    CubedCopySpec,
    CubedPipeline,
    MemoryModeller,
    PrimitiveOperation,
)
