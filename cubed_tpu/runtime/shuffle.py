"""All-to-all rechunk on the peer data plane: the shuffle layer.

The paper's execution model forbids inter-worker communication, so a
rechunk is two things at once: a full write+read round-trip through the
Zarr store, and — because its copy regions were opaque to the chunk graph
— a conservative op-level barrier in the dataflow scheduler. Both are
killable with machinery that already exists, and this module is the glue:

- **Chunk-level shuffle edges.** A rechunk task's mappable item is a
  slice-region over the write grid; which source chunks it overlaps and
  which target chunks it covers are pure index computations
  (:func:`rechunk_task_reads` / :func:`rechunk_task_writes`, same shape as
  blockwise key walking). ``build_chunk_graph`` (``runtime/dataflow.py``)
  uses them to give every rechunk task its exact dependency set, so
  rechunk stops being a barrier: a target-chunk task dispatches the moment
  the source chunks it overlaps are written, overlapping with both its
  producers and its consumers in the dataflow frontier.

- **Peer-routed exchange.** The same read set feeds the coordinator's
  locality-aware placement (put a target task on the worker holding the
  most overlapping source bytes) and the task body's reads ride the PR 9
  peer data plane. Because a target task often touches only a fraction of
  each source chunk, :func:`byte_ranges` turns the needed sub-region of a
  C-order chunk into coalesced byte ranges for the sub-chunk fetch
  protocol (``runtime/transfer.py``) — a transpose-ish shuffle moves the
  bytes it needs, not whole chunks it barely touches.

- **The fallback contract is inherited, not re-implemented.** Zarr stays
  the durable write-through tier; any peer miss, death, timeout, or
  checksum mismatch degrades to the store read inside
  ``ZarrV2Array`` — so resume, the journal, and integrity manifests are
  untouched, and a mid-shuffle worker loss costs store reads, never
  correctness or retry budget.

:func:`exchange_scope` marks the rechunk task body's read window so the
observability layer can attribute peer time during a shuffle to its own
``shuffle`` bucket (span ``shuffle_fetch``) instead of folding it into
generic peer/storage time — see ``observability/analytics.py``.
"""

from __future__ import annotations

import itertools
import math
import threading
from typing import Iterator, List, Optional, Tuple

#: bound on byte ranges per sub-chunk fetch: past this, the per-range
#: bookkeeping costs more than the bytes it saves — fetch the whole chunk
MAX_FETCH_RANGES = 512

#: a region covering at least this fraction of the chunk fetches the whole
#: chunk instead (one range, and the cache entry verifies against the
#: manifest end to end)
RANGE_FETCH_MAX_FRACTION = 0.75


# ----------------------------------------------------------------------
# recognizing rechunk pipelines and naming their tasks
# ----------------------------------------------------------------------


def is_rechunk_pipeline(pipeline) -> bool:
    """True for a rechunk copy stage (task body reads one source region
    and writes it to the target — ``primitive/rechunk.copy_read_to_write``)."""
    from ..primitive.rechunk import copy_read_to_write

    return getattr(pipeline, "function", None) is copy_read_to_write


def is_region_item(m) -> bool:
    """True for a rechunk mappable item: a tuple of slices over the write
    grid (blockwise items are ``(out_name, i, j, ...)`` tuples instead)."""
    return (
        isinstance(m, tuple)
        and len(m) > 0
        and all(isinstance(s, slice) for s in m)
    )


def region_identity(m) -> str:
    """A compact, stable identity for a slice-region mappable item —
    ``"0:4,8:16"`` — used wherever blockwise items use their dotted chunk
    key (locality hints; NOT the trace join key, which stays
    ``utils.chunk_key``)."""
    return ",".join(f"{s.start}:{s.stop}" for s in m)


def chunk_key_str(idx: Tuple[int, ...]) -> str:
    """The store's dotted chunk file name for a chunk index tuple —
    THE dotted-key format contract: ``ZarrV2Array._chunk_key`` (the file
    names on disk) and ``pipeline._task_chunk_key`` (the out-key side)
    both delegate here, so the three users of the format cannot drift
    apart (a drift would silently degrade every rechunk edge to an
    op-level barrier and break chunk-granular resume matching)."""
    return ".".join(str(i) for i in idx) if idx else "0"


# ----------------------------------------------------------------------
# region <-> chunk-grid index math (the shuffle edge computation)
# ----------------------------------------------------------------------


def chunks_overlapping_region(
    region: Tuple[slice, ...], chunks: Tuple[int, ...],
) -> Iterator[Tuple[int, ...]]:
    """Chunk index tuples of a ``chunks``-gridded array that a slice-region
    overlaps. The pure index computation both shuffle edge directions are
    built from: with the *source* chunking these are the chunks a rechunk
    task reads; with the *target* chunking, the chunks it writes."""
    if not region:
        yield ()
        return
    ranges = []
    for s, c in zip(region, chunks):
        c = max(1, int(c))
        start = int(s.start or 0)
        stop = int(s.stop if s.stop is not None else start)
        first = start // c
        last = max(first, (max(stop - 1, start)) // c)
        ranges.append(range(first, last + 1))
    yield from itertools.product(*ranges)


def region_chunk_keys(
    region: Tuple[slice, ...], chunks: Tuple[int, ...],
) -> List[str]:
    """Dotted chunk keys overlapped by a region (see
    :func:`chunks_overlapping_region`)."""
    return [chunk_key_str(i) for i in chunks_overlapping_region(region, chunks)]


def rechunk_task_reads(m, config) -> List[tuple]:
    """``[(source store, source chunk key), ...]`` a rechunk task reads:
    the source chunks its region overlaps. Feeds both the dataflow edges
    and the coordinator's locality placement (shuffle fan-in lands on the
    worker holding the most of these bytes)."""
    src = config.read.array
    store = str(getattr(src, "store", "") or "")
    chunks = tuple(config.read.chunks)
    return [(store, chunk_key_str(i)) for i in chunks_overlapping_region(m, chunks)]


def rechunk_task_writes(m, config) -> List[str]:
    """Dotted target chunk keys a rechunk task's region covers. Write
    regions are aligned to the target chunk grid (the planner keeps
    consolidated write chunks exact multiples of the target chunks), so
    every target chunk is covered by exactly one task."""
    chunks = tuple(config.write.chunks)
    return region_chunk_keys(m, chunks)


# ----------------------------------------------------------------------
# sub-chunk byte ranges (the wire format of a partial-chunk fetch)
# ----------------------------------------------------------------------


def byte_ranges(
    chunk_shape: Tuple[int, ...],
    itemsize: int,
    inner_sel: Tuple[slice, ...],
) -> Optional[List[Tuple[int, int]]]:
    """Coalesced ``(offset, nbytes)`` ranges of a C-order chunk covering
    ``inner_sel`` (unit-step slices within the chunk), enumerated in the
    region's own C order — so the concatenated payload IS the selected
    sub-array's C-order buffer. Returns None when a range read is not
    worth it (full coverage, strided selection, too many ranges, or the
    region is nearly the whole chunk — see :data:`MAX_FETCH_RANGES` /
    :data:`RANGE_FETCH_MAX_FRACTION`); the caller then fetches the whole
    chunk."""
    if not chunk_shape:
        return None
    sel = []
    region_elems = 1
    for s, extent in zip(inner_sel, chunk_shape):
        step = s.step or 1
        if step != 1:
            return None
        start = int(s.start or 0)
        stop = min(int(s.stop if s.stop is not None else extent), extent)
        if stop <= start:
            return None
        sel.append((start, stop))
        region_elems *= stop - start
    chunk_elems = math.prod(chunk_shape)
    if region_elems >= chunk_elems:
        return None  # full chunk: the whole-chunk path verifies end to end
    if region_elems * itemsize > RANGE_FETCH_MAX_FRACTION * chunk_elems * itemsize:
        return None

    # the largest suffix of axes fully covered: runs are contiguous across
    # it, anchored at the last partially-covered axis
    ndim = len(chunk_shape)
    full_from = ndim
    for ax in reversed(range(ndim)):
        if sel[ax] == (0, chunk_shape[ax]):
            full_from = ax
        else:
            break
    # strides in elements, C order
    strides = [1] * ndim
    for ax in reversed(range(ndim - 1)):
        strides[ax] = strides[ax + 1] * chunk_shape[ax + 1]
    run_axis = full_from - 1  # the contiguous-run axis (last partial one)
    if run_axis < 0:
        return None  # fully covered (caught above, but belt and braces)
    run_elems = (sel[run_axis][1] - sel[run_axis][0]) * strides[run_axis]
    lead_counts = [sel[ax][1] - sel[ax][0] for ax in range(run_axis)]
    n_ranges = math.prod(lead_counts) if lead_counts else 1
    if n_ranges > MAX_FETCH_RANGES:
        return None
    ranges: List[Tuple[int, int]] = []
    base = sum(sel[ax][0] * strides[ax] for ax in range(run_axis + 1))
    for combo in itertools.product(*(range(n) for n in lead_counts)):
        off = base
        for ax, i in enumerate(combo):
            off += i * strides[ax]
        ranges.append((off * itemsize, run_elems * itemsize))
    return ranges


# ----------------------------------------------------------------------
# the exchange scope (observability: shuffle time gets its own bucket)
# ----------------------------------------------------------------------

_tls = threading.local()


class exchange_scope:
    """Marks the current thread as inside a rechunk task's read window, so
    peer fetches issued under it record ``shuffle_fetch`` spans (the
    ``shuffle`` attribution bucket) and count ``shuffle_bytes_peer``
    instead of blending into generic peer-fetch time."""

    def __enter__(self):
        self._prev = getattr(_tls, "exchange", False)
        _tls.exchange = True
        return self

    def __exit__(self, *exc) -> None:
        _tls.exchange = self._prev


def in_exchange() -> bool:
    return bool(getattr(_tls, "exchange", False))


