"""Correlated structured logging: every log line attributable to its task.

Three contextvars — ``compute_id``, ``op``, ``chunk`` — are set where the
work actually happens (``Plan.execute`` around a compute, task bodies in
``execute_with_stats``), so a log record emitted anywhere under them can be
joined back to the compute/op/chunk that produced it, in the client, a
multiprocess pool worker (the compute id crosses the spawn boundary via
``CUBED_TPU_COMPUTE_ID``), or a fleet worker (every task message carries
the client's compute id).

Pieces:

- :class:`ContextFilter` — a ``logging.Filter`` injecting
  ``record.compute_id`` / ``record.op`` / ``record.chunk`` so any format
  string (or the JSON formatter below) can reference them.
- :class:`StructuredFormatter` — one JSON object per line (ts, level,
  logger, message, compute_id, op, chunk, pid), greppable and
  machine-joinable against the merged trace.
- :class:`RecentRecordsHandler` — a bounded ring of the last N structured
  records, installed once per process on the ``cubed_tpu`` logger; the
  flight recorder snapshots it into every post-mortem bundle.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import Optional

#: how the compute id crosses the spawn boundary into pool workers
COMPUTE_ID_ENV_VAR = "CUBED_TPU_COMPUTE_ID"

compute_id_var: contextvars.ContextVar = contextvars.ContextVar(
    "cubed_tpu_compute_id", default=None
)
op_var: contextvars.ContextVar = contextvars.ContextVar(
    "cubed_tpu_op", default=None
)
chunk_var: contextvars.ContextVar = contextvars.ContextVar(
    "cubed_tpu_chunk", default=None
)


def current_compute_id() -> Optional[str]:
    """The active compute id: contextvar first, spawn-time env second."""
    cid = compute_id_var.get()
    if cid is not None:
        return cid
    return os.environ.get(COMPUTE_ID_ENV_VAR) or None


#: serializes env-var export/restore across concurrently running computes
_env_export_lock = threading.Lock()
#: compute ids currently exported by a LIVE scope in this process — so an
#: exiting scope can tell a live sibling's id (restore it) from a dead
#: one (drop it) when exits happen out of order
_live_exports: set = set()
#: every id any scope in this process ever exported — a "previous" value
#: NOT in here came from outside (an operator/parent-process pin) and is
#: always restorable
_ever_exported: set = set()


@contextmanager
def compute_scope(compute_id: str, export_env: bool = False):
    """Bind the compute id for a block (and, with ``export_env``, for every
    child process spawned inside it — how pool workers inherit it).

    The contextvar is per-thread, so concurrent computes on different
    threads (the multi-tenant service) see only their own id. The env
    export is inherently process-global: concurrent exporters are
    last-writer-wins (children spawned meanwhile inherit whichever id is
    current), but exit is guarded two ways — a scope only touches the
    variable if it still holds ITS OWN id, and it only restores the
    previous value when that value is still a live scope's export (or an
    external pin); a finished sibling's id is dropped, never resurrected.
    """
    token = compute_id_var.set(compute_id)
    with _env_export_lock:
        prev_env = os.environ.get(COMPUTE_ID_ENV_VAR)
        if export_env:
            os.environ[COMPUTE_ID_ENV_VAR] = compute_id
            _live_exports.add(compute_id)
            if len(_ever_exported) >= 4096:
                # bounded: after a reset, an out-of-order exit degrades to
                # the old restore-the-previous behavior at worst
                _ever_exported.clear()
                _ever_exported.update(_live_exports)
            _ever_exported.add(compute_id)
    try:
        yield
    finally:
        compute_id_var.reset(token)
        if export_env:
            with _env_export_lock:
                _live_exports.discard(compute_id)
                if os.environ.get(COMPUTE_ID_ENV_VAR) == compute_id:
                    restorable = prev_env is not None and (
                        prev_env in _live_exports
                        or prev_env not in _ever_exported
                    )
                    if restorable:
                        os.environ[COMPUTE_ID_ENV_VAR] = prev_env
                    else:
                        os.environ.pop(COMPUTE_ID_ENV_VAR, None)


@contextmanager
def task_context(op: Optional[str] = None, chunk: Optional[str] = None,
                 compute_id: Optional[str] = None):
    """Bind op/chunk (and optionally compute id) around one task body."""
    tokens = []
    if compute_id is not None:
        tokens.append((compute_id_var, compute_id_var.set(compute_id)))
    if op is not None:
        tokens.append((op_var, op_var.set(op)))
    if chunk is not None:
        tokens.append((chunk_var, chunk_var.set(chunk)))
    try:
        yield
    finally:
        for var, token in reversed(tokens):
            var.reset(token)


class ContextFilter(logging.Filter):
    """Inject the correlation contextvars into every record that passes."""

    def filter(self, record: logging.LogRecord) -> bool:
        record.compute_id = current_compute_id() or "-"
        record.op = op_var.get() or "-"
        record.chunk = chunk_var.get() or "-"
        return True


class StructuredFormatter(logging.Formatter):
    """One JSON object per line; joinable against the merged trace."""

    def format(self, record: logging.LogRecord) -> str:
        return json.dumps(_record_to_dict(record), default=str)


def _record_to_dict(record: logging.LogRecord) -> dict:
    out = {
        "ts": record.created,
        "level": record.levelname,
        "logger": record.name,
        "message": record.getMessage(),
        "compute_id": getattr(record, "compute_id", None)
        or current_compute_id() or "-",
        "op": getattr(record, "op", None) or op_var.get() or "-",
        "chunk": getattr(record, "chunk", None) or chunk_var.get() or "-",
        "pid": record.process,
    }
    if record.exc_info and record.exc_info[0] is not None:
        out["exc_type"] = record.exc_info[0].__name__
    return out


class RecentRecordsHandler(logging.Handler):
    """Bounded ring buffer of structured records (the flight recorder's
    last-N log window). Never raises into the logging call."""

    def __init__(self, capacity: int = 500):
        super().__init__()
        self._records: deque = deque(maxlen=capacity)
        self.addFilter(ContextFilter())

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._records.append(_record_to_dict(record))
        except Exception:
            pass  # an observer must never fail the caller

    def records(self, n: Optional[int] = None) -> list:
        items = list(self._records)
        return items if n is None else items[-n:]


_install_lock = threading.Lock()
_ring: Optional[RecentRecordsHandler] = None


def install(capacity: int = 500) -> RecentRecordsHandler:
    """Attach the ring handler to the ``cubed_tpu`` logger (idempotent).

    Records from every ``cubed_tpu.*`` module logger propagate here, so
    the ring sees retry warnings, straggler alerts, quarantine notices —
    regardless of how the application configured its own handlers.
    """
    global _ring
    with _install_lock:
        if _ring is None:
            _ring = RecentRecordsHandler(capacity=capacity)
            logging.getLogger("cubed_tpu").addHandler(_ring)
        return _ring


def recent_records(n: Optional[int] = None) -> list:
    """The last structured records captured in this process ([] before
    :func:`install` has run)."""
    return _ring.records(n) if _ring is not None else []


def basic_structured_config(level: int = logging.INFO) -> None:
    """Convenience: root handler emitting JSON lines with correlation ids
    (what the fleet worker entry point uses with ``--log-json``)."""
    handler = logging.StreamHandler()
    handler.setFormatter(StructuredFormatter())
    handler.addFilter(ContextFilter())
    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(level)
