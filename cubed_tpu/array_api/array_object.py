"""The Array object: CoreArray plus the full Array-API operator set.

Every operator lowers to ``elemwise(nxp.<op>)`` with spec-conformant dtype
checking and scalar promotion. Reference parity:
cubed/array_api/array_object.py (446 LoC).
"""

from __future__ import annotations

import builtins
from typing import Optional

import numpy as np

from ..backend_array_api import nxp
from ..core.array import CoreArray
from ..core.ops import elemwise
from .dtypes import (
    _boolean_dtypes,
    _complex_floating_dtypes,
    _dtype_categories,
    _floating_dtypes,
    _integer_dtypes,
    _integer_or_boolean_dtypes,
    _numeric_dtypes,
    _real_floating_dtypes,
    float32,
    float64,
    complex64,
    complex128,
    int8,
    int16,
    int32,
    int64,
    uint8,
    uint16,
    uint32,
    uint64,
    promote_types,
)


class Array(CoreArray):
    """A chunked, lazily-computed N-dimensional array (Array API standard)."""

    # make numpy defer to us for arr <op> Array
    __array_priority__ = 100

    # -- conversion protocols ---------------------------------------------

    def __array__(self, dtype=None) -> np.ndarray:
        x = self.compute()
        if dtype is not None and x.dtype != dtype:
            x = x.astype(dtype)
        return np.asarray(x)

    def __bool__(self) -> builtins.bool:
        self._check_0d("bool")
        return builtins.bool(self.compute())

    def __float__(self) -> float:
        self._check_0d("float")
        return float(self.compute())

    def __int__(self) -> int:
        self._check_0d("int")
        return int(self.compute())

    def __index__(self) -> int:
        if self.dtype not in _integer_dtypes:
            raise TypeError("Only integer arrays can be used as an index")
        self._check_0d("index")
        return int(self.compute())

    def __complex__(self) -> complex:
        self._check_0d("complex")
        return complex(self.compute())

    def _check_0d(self, name):
        if self.ndim != 0:
            raise TypeError(f"{name}() of non-0d array")

    # -- attributes --------------------------------------------------------

    def __array_namespace__(self, *, api_version=None):
        if api_version is not None and api_version not in ("2021.12", "2022.12"):
            raise ValueError(f"Unrecognized array API version: {api_version!r}")
        import cubed_tpu.array_api

        return cubed_tpu.array_api

    def to_device(self, device, /, *, stream=None):
        if stream is not None:
            raise ValueError("stream is not supported")
        return self

    @property
    def device(self):
        from .device import device as _device

        return _device

    @property
    def mT(self):
        from .linear_algebra_functions import matrix_transpose

        return matrix_transpose(self)

    @property
    def T(self):
        if self.ndim != 2:
            raise ValueError("x.T requires x to have 2 dimensions")
        from .linear_algebra_functions import matrix_transpose

        return matrix_transpose(self)

    def __repr__(self) -> str:
        return f"cubed_tpu.Array<{self.name}, shape={self.shape}, dtype={self.dtype}, chunks={self.chunks}>"

    def _repr_html_(self):
        try:
            from .html_repr import array_html_repr

            return array_html_repr(self)
        except Exception:
            return f"<pre>{self!r}</pre>"

    # -- scalar promotion --------------------------------------------------

    def _promote_scalar(self, scalar) -> Optional["Array"]:
        """Convert a Python scalar to a 0-d array of this array's kind,
        per the spec's scalar-promotion rules."""
        from .creation_functions import asarray

        if isinstance(scalar, builtins.bool):
            if self.dtype not in _boolean_dtypes:
                raise TypeError("Python bool not allowed with non-boolean arrays")
        elif isinstance(scalar, int):
            if self.dtype in _boolean_dtypes:
                raise TypeError("Python int not allowed with boolean arrays")
        elif isinstance(scalar, float):
            if self.dtype not in _floating_dtypes:
                raise TypeError("Python float not allowed with integer/boolean arrays")
        elif isinstance(scalar, complex):
            if self.dtype not in _complex_floating_dtypes:
                raise TypeError("Python complex not allowed with non-complex arrays")
        else:
            return None
        return asarray(scalar, dtype=self.dtype, spec=self.spec)

    def _check_op_dtypes(self, other, category, op):
        if self.dtype not in _dtype_categories[category]:
            raise TypeError(f"Only {category} dtypes are allowed in {op}")
        if isinstance(other, (int, float, complex, builtins.bool)):
            other = self._promote_scalar(other)
        elif isinstance(other, CoreArray):
            if other.dtype not in _dtype_categories[category]:
                raise TypeError(f"Only {category} dtypes are allowed in {op}")
        else:
            return NotImplemented
        return other

    # -- arithmetic --------------------------------------------------------

    def _binop(self, other, nxp_func, category, op, reflected=False):
        other = self._check_op_dtypes(other, category, op)
        if other is NotImplemented:
            return NotImplemented
        a, b = (other, self) if reflected else (self, other)
        if op in _COMPARISON_OPS:
            dtype = np.dtype(np.bool_)
        elif op in _TRUEDIV_OPS:
            dtype = promote_types(a.dtype, b.dtype)
            if dtype in _integer_or_boolean_dtypes:
                dtype = np.dtype(np.float64)
        else:
            dtype = promote_types(a.dtype, b.dtype)
        return elemwise(nxp_func, a, b, dtype=dtype)

    def __add__(self, other):
        return self._binop(other, nxp.add, "numeric", "__add__")

    def __radd__(self, other):
        return self._binop(other, nxp.add, "numeric", "__radd__", reflected=True)

    def __sub__(self, other):
        return self._binop(other, nxp.subtract, "numeric", "__sub__")

    def __rsub__(self, other):
        return self._binop(other, nxp.subtract, "numeric", "__rsub__", reflected=True)

    def __mul__(self, other):
        return self._binop(other, nxp.multiply, "numeric", "__mul__")

    def __rmul__(self, other):
        return self._binop(other, nxp.multiply, "numeric", "__rmul__", reflected=True)

    def __truediv__(self, other):
        return self._binop(other, nxp.divide, "floating-point", "__truediv__")

    def __rtruediv__(self, other):
        return self._binop(other, nxp.divide, "floating-point", "__rtruediv__", reflected=True)

    def __floordiv__(self, other):
        return self._binop(other, nxp.floor_divide, "real numeric", "__floordiv__")

    def __rfloordiv__(self, other):
        return self._binop(other, nxp.floor_divide, "real numeric", "__rfloordiv__", reflected=True)

    def __mod__(self, other):
        return self._binop(other, nxp.remainder, "real numeric", "__mod__")

    def __rmod__(self, other):
        return self._binop(other, nxp.remainder, "real numeric", "__rmod__", reflected=True)

    def __pow__(self, other):
        return self._binop(other, nxp.pow, "numeric", "__pow__")

    def __rpow__(self, other):
        return self._binop(other, nxp.pow, "numeric", "__rpow__", reflected=True)

    def __matmul__(self, other):
        from .linear_algebra_functions import matmul

        if not isinstance(other, CoreArray):
            return NotImplemented
        return matmul(self, other)

    def __rmatmul__(self, other):
        from .linear_algebra_functions import matmul

        if not isinstance(other, CoreArray):
            return NotImplemented
        return matmul(other, self)

    def __neg__(self):
        if self.dtype not in _numeric_dtypes:
            raise TypeError("Only numeric dtypes are allowed in __neg__")
        return elemwise(nxp.negative, self, dtype=self.dtype)

    def __pos__(self):
        if self.dtype not in _numeric_dtypes:
            raise TypeError("Only numeric dtypes are allowed in __pos__")
        return elemwise(nxp.positive, self, dtype=self.dtype)

    def __abs__(self):
        if self.dtype not in _numeric_dtypes:
            raise TypeError("Only numeric dtypes are allowed in __abs__")
        dtype = self.dtype
        if dtype == complex64:
            dtype = float32
        elif dtype == complex128:
            dtype = float64
        return elemwise(nxp.abs, self, dtype=dtype)

    # -- bitwise -----------------------------------------------------------

    def __and__(self, other):
        return self._binop(other, nxp.bitwise_and, "integer or boolean", "__and__")

    def __rand__(self, other):
        return self._binop(other, nxp.bitwise_and, "integer or boolean", "__rand__", reflected=True)

    def __or__(self, other):
        return self._binop(other, nxp.bitwise_or, "integer or boolean", "__or__")

    def __ror__(self, other):
        return self._binop(other, nxp.bitwise_or, "integer or boolean", "__ror__", reflected=True)

    def __xor__(self, other):
        return self._binop(other, nxp.bitwise_xor, "integer or boolean", "__xor__")

    def __rxor__(self, other):
        return self._binop(other, nxp.bitwise_xor, "integer or boolean", "__rxor__", reflected=True)

    def __lshift__(self, other):
        return self._binop(other, nxp.bitwise_left_shift, "integer", "__lshift__")

    def __rlshift__(self, other):
        return self._binop(other, nxp.bitwise_left_shift, "integer", "__rlshift__", reflected=True)

    def __rshift__(self, other):
        return self._binop(other, nxp.bitwise_right_shift, "integer", "__rshift__")

    def __rrshift__(self, other):
        return self._binop(other, nxp.bitwise_right_shift, "integer", "__rrshift__", reflected=True)

    def __invert__(self):
        if self.dtype not in _integer_or_boolean_dtypes:
            raise TypeError("Only integer or boolean dtypes are allowed in __invert__")
        return elemwise(nxp.bitwise_invert, self, dtype=self.dtype)

    # -- comparison --------------------------------------------------------

    def __eq__(self, other):  # type: ignore[override]
        return self._binop(other, nxp.equal, "all", "__eq__")

    def __ne__(self, other):  # type: ignore[override]
        return self._binop(other, nxp.not_equal, "all", "__ne__")

    def __lt__(self, other):
        return self._binop(other, nxp.less, "real numeric", "__lt__")

    def __le__(self, other):
        return self._binop(other, nxp.less_equal, "real numeric", "__le__")

    def __gt__(self, other):
        return self._binop(other, nxp.greater, "real numeric", "__gt__")

    def __ge__(self, other):
        return self._binop(other, nxp.greater_equal, "real numeric", "__ge__")

    __hash__ = None  # type: ignore[assignment]


_COMPARISON_OPS = {
    "__eq__", "__ne__", "__lt__", "__le__", "__gt__", "__ge__",
    "__req__", "__rne__",
}
_TRUEDIV_OPS = {"__truediv__", "__rtruediv__"}
