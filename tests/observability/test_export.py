"""Telemetry endpoint tests: Prometheus text-format conformance, the
stdlib-HTTP endpoints, arming precedence, and a live-fleet scrape."""

from __future__ import annotations

import json
import re
import tempfile
import threading
import time
import urllib.request

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability import export
from cubed_tpu.observability.export import (
    TELEMETRY_PORT_ENV_VAR,
    TelemetryRuntime,
    escape_label_value,
    prometheus_text,
    resolve_port,
    sanitize_metric_name,
)
from cubed_tpu.observability.metrics import MetricsRegistry
from cubed_tpu.observability.timeseries import TimeSeriesStore

# ---------------------------------------------------------------------------
# exposition-format conformance
# ---------------------------------------------------------------------------

#: one sample line of text exposition format 0.0.4:
#: name{labels} value [timestamp]
_SAMPLE_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"               # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\})?"
    r" -?[0-9.eE+naif]+$"                      # value (incl. nan/inf)
)


def parse_exposition(text: str) -> dict:
    """Strict parse of the exposition text: every line must be a comment
    or a valid sample; returns {sample_name_with_labels: float}."""
    samples = {}
    types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert kind in ("counter", "gauge", "summary", "histogram"), line
            types[name] = kind
        else:
            assert _SAMPLE_LINE.match(line), f"malformed sample line: {line!r}"
            key, _, value = line.rpartition(" ")
            samples[key] = float(value)
    # every sample belongs to a family that declared a TYPE
    for key in samples:
        base = key.split("{")[0]
        family_ok = any(
            base == name or base.startswith(name + "_")
            or name.startswith(base)
            for name in types
        )
        assert family_ok, f"sample {key!r} has no TYPE line"
    return samples


def test_metric_name_sanitization():
    assert sanitize_metric_name("foo.bar-baz") == "foo_bar_baz"
    assert sanitize_metric_name("a b/c") == "a_b_c"
    assert sanitize_metric_name("9lives") == "_9lives"
    assert sanitize_metric_name("already_fine:total") == "already_fine:total"


def test_label_value_escaping():
    assert escape_label_value('say "hi"') == 'say \\"hi\\"'
    assert escape_label_value("back\\slash") == "back\\\\slash"
    assert escape_label_value("two\nlines") == "two\\nlines"


def test_prometheus_text_help_type_and_values():
    reg = MetricsRegistry()
    reg.counter("tasks_completed").inc(12)
    reg.gauge("queue_depth").set(4)
    reg.histogram("op_wall_clock_s").observe(0.5)
    reg.histogram("op_wall_clock_s").observe(1.5)
    text = prometheus_text(registry=reg)
    samples = parse_exposition(text)
    assert samples["cubed_tpu_tasks_completed"] == 12
    assert samples["cubed_tpu_queue_depth"] == 4
    assert samples["cubed_tpu_queue_depth_max"] == 4
    assert samples["cubed_tpu_op_wall_clock_s_count"] == 2
    assert samples["cubed_tpu_op_wall_clock_s_sum"] == 2.0
    assert 'cubed_tpu_op_wall_clock_s{quantile="0.5"}' in samples
    assert 'cubed_tpu_op_wall_clock_s{quantile="0.99"}' in samples
    assert "# HELP cubed_tpu_tasks_completed" in text
    assert "# TYPE cubed_tpu_tasks_completed counter" in text
    assert "# TYPE cubed_tpu_queue_depth gauge" in text
    assert "# TYPE cubed_tpu_op_wall_clock_s summary" in text


def test_prometheus_text_sanitizes_weird_names_and_labels():
    reg = MetricsRegistry()
    reg.counter("weird.name-with/stuff").inc(1)
    store = TimeSeriesStore()
    store.record(
        "worker_rss_bytes", 7,
        labels={"worker": 'host:1 "quoted"\nnewline'},
    )
    text = prometheus_text(registry=reg, store=store)
    samples = parse_exposition(text)
    assert samples["cubed_tpu_weird_name_with_stuff"] == 1
    labelled = [k for k in samples if k.startswith("cubed_tpu_worker_rss_bytes{")]
    assert labelled, text
    assert '\\"quoted\\"' in labelled[0] and "\\n" in labelled[0]


def test_scrape_twice_counters_are_monotonic():
    reg = MetricsRegistry()
    reg.counter("tasks_completed").inc(3)
    reg.counter("task_retries").inc(1)
    first = parse_exposition(prometheus_text(registry=reg))
    reg.counter("tasks_completed").inc(5)
    second = parse_exposition(prometheus_text(registry=reg))
    kinds = reg.kinds()
    for name, kind in kinds.items():
        if kind != "counter":
            continue
        key = f"cubed_tpu_{name}"
        assert second[key] >= first[key], (
            f"counter {name} went backwards between scrapes"
        )
    assert second["cubed_tpu_tasks_completed"] == 8


def test_labelled_store_series_export_latest_sample():
    reg = MetricsRegistry()
    store = TimeSeriesStore()
    store.record("worker_outstanding", 1, ts=1.0, labels={"worker": "w0"})
    store.record("worker_outstanding", 4, ts=2.0, labels={"worker": "w0"})
    store.record("compute_tasks_done", 9, ts=2.0, labels={"compute": "c-1"})
    samples = parse_exposition(prometheus_text(registry=reg, store=store))
    assert samples['cubed_tpu_worker_outstanding{worker="w0"}'] == 4
    assert samples['cubed_tpu_compute_tasks_done{compute="c-1"}'] == 9


def test_fleet_aggregates_export_and_families_stay_unique():
    """Store-only series (the sampler's fleet aggregates) must appear on
    /metrics — they are what the documented alert thresholds read — and
    labelled samples must merge into an existing registry family instead
    of re-declaring it (one TYPE line per family, per the exposition
    spec). Registry-mirrored and histogram-derived unlabelled series must
    NOT duplicate their families."""
    reg = MetricsRegistry()
    reg.gauge("worker_rss_bytes").set(111)
    reg.counter("tasks_completed").inc(5)
    reg.histogram("op_wall_clock_s").observe(0.5)
    store = TimeSeriesStore()
    store.record("fleet_pressured_fraction", 0.5)
    store.record("fleet_workers_live", 4)
    # registry mirror + histogram-derived mirror: already exported
    store.record("tasks_completed", 5)
    store.record("op_wall_clock_s_count", 1)
    # labelled samples of a registry gauge: same family, extra samples
    store.record("worker_rss_bytes", 222, labels={"worker": "w0"})
    text = prometheus_text(registry=reg, store=store)
    samples = parse_exposition(text)
    assert samples["cubed_tpu_fleet_pressured_fraction"] == 0.5
    assert samples["cubed_tpu_fleet_workers_live"] == 4
    assert samples["cubed_tpu_worker_rss_bytes"] == 111
    assert samples['cubed_tpu_worker_rss_bytes{worker="w0"}'] == 222
    type_lines = [ln for ln in text.splitlines() if ln.startswith("# TYPE ")]
    assert len(type_lines) == len(set(type_lines)), (
        "duplicate TYPE declarations:\n" + "\n".join(type_lines)
    )
    # the unlabelled mirrors did not add second families
    assert type_lines.count("# TYPE cubed_tpu_tasks_completed counter") == 1
    assert not any("op_wall_clock_s_count" in ln for ln in type_lines)


# ---------------------------------------------------------------------------
# arming precedence: env (operator) > Spec > off
# ---------------------------------------------------------------------------


def test_resolve_port_precedence(monkeypatch):
    monkeypatch.delenv(TELEMETRY_PORT_ENV_VAR, raising=False)
    assert resolve_port(None) is None
    spec = ct.Spec(telemetry_port=9100)
    assert resolve_port(spec) == 9100
    # env wins over Spec
    monkeypatch.setenv(TELEMETRY_PORT_ENV_VAR, "9200")
    assert resolve_port(spec) == 9200
    # the operator can force telemetry OFF even when a Spec arms it
    monkeypatch.setenv(TELEMETRY_PORT_ENV_VAR, "off")
    assert resolve_port(spec) is None
    monkeypatch.setenv(TELEMETRY_PORT_ENV_VAR, "")
    assert resolve_port(spec) is None
    # malformed env values stay loud
    monkeypatch.setenv(TELEMETRY_PORT_ENV_VAR, "not-a-port")
    with pytest.raises(ValueError):
        resolve_port(spec)
    monkeypatch.setenv(TELEMETRY_PORT_ENV_VAR, "70000")
    with pytest.raises(ValueError):
        resolve_port(spec)


def test_spec_validates_telemetry_port():
    assert ct.Spec(telemetry_port=0).telemetry_port == 0
    assert ct.Spec().telemetry_port is None
    with pytest.raises(ValueError):
        ct.Spec(telemetry_port=-1)
    with pytest.raises(ValueError):
        ct.Spec(telemetry_port=99999)


# ---------------------------------------------------------------------------
# the HTTP endpoints
# ---------------------------------------------------------------------------


def _get(port: int, path: str):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:  # non-200 still carries a body
        return e.code, e.read().decode(), dict(e.headers)


@pytest.fixture
def runtime():
    rt = TelemetryRuntime(port=0)
    rt.start()
    try:
        yield rt
    finally:
        rt.stop()


def test_endpoints_serve_metrics_healthz_snapshot(runtime):
    runtime.sampler.sample_once()
    code, body, headers = _get(runtime.port, "/metrics")
    assert code == 200
    assert headers["Content-Type"].startswith("text/plain")
    parse_exposition(body)  # must be valid exposition text
    assert "cubed_tpu_telemetry_samples" in body

    code, body, _ = _get(runtime.port, "/healthz")
    assert code == 200
    health = json.loads(body)
    assert health["status"] in ("ok", "degraded")
    assert health["sampler_alive"] in (True, False)
    assert health["last_sample_age_s"] is not None

    code, body, _ = _get(runtime.port, "/snapshot.json")
    assert code == 200
    snap = json.loads(body)
    for key in ("ts", "metrics", "fleet", "computes", "alerts", "series"):
        assert key in snap

    code, _, _ = _get(runtime.port, "/nope")
    assert code == 404


def test_healthz_reports_stale_sampler_as_503():
    rt = TelemetryRuntime(port=0)
    rt.start()
    try:
        rt.sampler.stop()
        rt.sampler.last_sample_ts = time.time() - 60.0
        code, body, _ = _get(rt.port, "/healthz")
        assert code == 503
        assert json.loads(body)["status"] == "stale"
    finally:
        rt.stop()


def test_bind_host_env_knob(monkeypatch):
    from cubed_tpu.observability.export import TELEMETRY_HOST_ENV_VAR

    monkeypatch.setenv(TELEMETRY_HOST_ENV_VAR, "127.0.0.1")
    rt = TelemetryRuntime(port=0)
    rt.start()
    try:
        assert rt.server.server_address[0] == "127.0.0.1"
        code, _, _ = _get(rt.port, "/healthz")
        assert code in (200, 503)
    finally:
        rt.stop()


def test_ensure_started_is_idempotent_singleton(monkeypatch):
    export.shutdown()
    try:
        rt1 = export.ensure_started(0)
        rt2 = export.ensure_started(0)
        assert rt1 is rt2
        assert export.get_runtime() is rt1
        # a conflicting port request is logged and ignored, not a rebind
        rt3 = export.ensure_started(12345)
        assert rt3 is rt1
    finally:
        export.shutdown()
    assert export.get_runtime() is None


# ---------------------------------------------------------------------------
# live fleet scrape: /metrics + /healthz answered DURING a distributed
# compute (fleet workers are real subprocesses)
# ---------------------------------------------------------------------------


def test_live_fleet_compute_serves_metrics_and_healthz(tmp_path):
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor
    from tests.utils import SlowAdd

    export.shutdown()
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", telemetry_port=0
    )
    an = np.arange(64.0).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    r = ct.map_blocks(SlowAdd(0.15), a, dtype=np.float64)
    ex = DistributedDagExecutor(n_local_workers=2)
    result_box: dict = {}

    def compute():
        try:
            result_box["value"] = np.asarray(r.compute(executor=ex))
        except BaseException as e:  # surfaced by the main thread
            result_box["error"] = e

    t = threading.Thread(target=compute)
    try:
        ex._ensure_fleet()
        t.start()
        # wait for the compute to arm telemetry, then scrape it LIVE
        deadline = time.monotonic() + 30
        rt = None
        while rt is None and time.monotonic() < deadline:
            rt = export.get_runtime()
            time.sleep(0.02)
        assert rt is not None, "telemetry never armed"
        code, metrics_body, _ = _get(rt.port, "/metrics")
        assert code == 200
        parse_exposition(metrics_body)
        code, health_body, _ = _get(rt.port, "/healthz")
        health = json.loads(health_body)
        assert code in (200, 503)  # first sample may still be pending
        t.join(timeout=120)
        assert not t.is_alive()
        assert "error" not in result_box, result_box.get("error")
        np.testing.assert_array_equal(result_box["value"], an + 1.0)
        # after the compute: the fleet was visible and metrics flowed
        rt.sampler.sample_once()
        code, body, _ = _get(rt.port, "/metrics")
        samples = parse_exposition(body)
        assert samples.get("cubed_tpu_tasks_completed", 0) >= 16
        code, body, _ = _get(rt.port, "/healthz")
        health = json.loads(body)
        assert health["workers_live"] == 2
        snap = json.loads(_get(rt.port, "/snapshot.json")[1])
        assert any(
            c.get("status") == "succeeded" and c.get("tasks_done") ==
            c.get("tasks_total") for c in snap["computes"]
        ), snap["computes"]
        # the dashboard renders a frame from the same compute's endpoint
        from cubed_tpu import top

        frame = top.render(top.fetch_snapshot(f"127.0.0.1:{rt.port}"))
        assert "local-0" in frame and "local-1" in frame
        assert "succeeded" in frame
    finally:
        ex.close()
        export.shutdown()
