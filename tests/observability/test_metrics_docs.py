"""Docs-rot guard: every metric registered in the codebase, every
decision-ring kind recorded, and every default alert-rule name must appear
in the canonical tables in docs/observability.md.

Greps literal ``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")``
/ ``record_scoped_counter("...")`` registrations and
``record_decision("...")`` call sites out of ``cubed_tpu/``, imports the
default alert-rule set, and fails naming anything the docs don't mention —
so adding a metric, a decision kind, or an alert rule without documenting
it breaks tier-1, not a future reader's trust.
"""

from __future__ import annotations

import re
from pathlib import Path

REPO = Path(__file__).resolve().parents[2]

_PATTERNS = [
    re.compile(r'\.counter\(\s*"([a-z0-9_]+)"'),
    re.compile(r'\.gauge\(\s*"([a-z0-9_]+)"'),
    re.compile(r'\.histogram\(\s*"([a-z0-9_]+)"'),
    re.compile(r'record_scoped_counter\(\s*\n?\s*"([a-z0-9_]+)"'),
]

#: decision-ring kinds: the first (string-literal) argument of every
#: record_decision call site; the docstring mention in alerts.py matches
#: too, harmlessly — it names a real kind
_DECISION_PATTERN = re.compile(r'record_decision\(\s*\n?\s*"([a-z0-9_]+)"')


def _sources() -> list:
    return [
        p for p in (REPO / "cubed_tpu").rglob("*.py")
    ]


def registered_metric_names() -> set:
    names: set = set()
    for path in _sources():
        src = path.read_text(encoding="utf-8")
        for pat in _PATTERNS:
            names.update(pat.findall(src))
    return names


def recorded_decision_kinds() -> set:
    kinds: set = set()
    for path in _sources():
        kinds.update(_DECISION_PATTERN.findall(path.read_text(encoding="utf-8")))
    return kinds


def _doc() -> str:
    return (REPO / "docs" / "observability.md").read_text(encoding="utf-8")


def test_metric_registrations_are_found():
    # the grep itself must keep working: if a refactor renames the
    # registry methods this test must fail loudly, not pass vacuously
    names = registered_metric_names()
    assert "tasks_completed" in names
    assert "queue_depth" in names
    assert "op_wall_clock_s" in names
    assert len(names) >= 30


def test_every_registered_metric_is_documented():
    doc = _doc()
    missing = sorted(n for n in registered_metric_names() if n not in doc)
    assert not missing, (
        "metrics registered in cubed_tpu/ but missing from the "
        f"docs/observability.md metrics table: {missing} — add each to the "
        "canonical inventory (kind + source) so the metrics docs can't rot"
    )


def test_decision_kind_grep_is_found():
    kinds = recorded_decision_kinds()
    assert "retry" in kinds
    assert "straggler" in kinds
    assert "alert_fired" in kinds
    assert len(kinds) >= 25


def test_every_decision_kind_is_documented():
    doc = _doc()
    missing = sorted(k for k in recorded_decision_kinds() if k not in doc)
    assert not missing, (
        "decision kinds recorded in cubed_tpu/ but missing from the "
        f"docs/observability.md decision-ring table: {missing} — add each "
        "to the canonical kinds inventory so the decision docs can't rot"
    )


def test_every_fault_knob_is_documented_in_reliability_docs():
    """The same rot-guard for chaos: every FaultConfig knob must appear in
    docs/reliability.md's fault-injection knob table — campaigns compose
    ALL knobs, so an undocumented knob is an unreviewable schedule."""
    from dataclasses import fields

    from cubed_tpu.runtime.faults import FaultConfig

    doc = (REPO / "docs" / "reliability.md").read_text(encoding="utf-8")
    knobs = sorted(f.name for f in fields(FaultConfig))
    assert len(knobs) >= 30  # the introspection keeps finding the knobs
    missing = sorted(k for k in knobs if k not in doc)
    assert not missing, (
        "FaultConfig knobs missing from the docs/reliability.md chaos-knob "
        f"table: {missing} — document each knob (what it injects, where it "
        "fires) so chaos schedules stay reviewable"
    )


def test_every_default_alert_rule_is_documented():
    from cubed_tpu.observability.alerts import default_rules

    doc = _doc()
    names = [r.name for r in default_rules()]
    assert len(names) >= 5  # the grep-equivalent sanity: rules exist
    missing = sorted(n for n in names if n not in doc)
    assert not missing, (
        "default alert rules missing from the docs/observability.md "
        f"alert-rule table: {missing} — document the rule (kind, fires "
        "when, default) so the alert docs can't rot"
    )
