"""Rechunk primitive unit tests. Reference parity:
cubed/tests/primitive/test_rechunk.py."""

import numpy as np
import pytest

from cubed_tpu.primitive.rechunk import rechunk, rechunking_plan
from cubed_tpu.storage.store import open_zarr_array

from ..utils import execute_pipeline


def make_zarr(tmp_path, name, arr, chunks):
    store = str(tmp_path / name)
    z = open_zarr_array(store, mode="w", shape=arr.shape, dtype=arr.dtype, chunks=chunks)
    z[...] = arr
    return z


def test_rechunk_direct(tmp_path):
    an = np.arange(100.0).reshape(10, 10)
    src = make_zarr(tmp_path, "src.zarr", an, (2, 10))
    ops = rechunk(
        src,
        source_chunks=(2, 10),
        target_chunks=(10, 2),
        allowed_mem=10**7,
        reserved_mem=0,
        target_store=str(tmp_path / "dst.zarr"),
        temp_store=str(tmp_path / "tmp.zarr"),
    )
    assert len(ops) == 1
    execute_pipeline(ops[0])
    out = ops[0].target_array.open()
    np.testing.assert_array_equal(out[...], an)
    assert out.chunks == (10, 2)


def test_rechunk_staged(tmp_path):
    an = np.arange(900.0).reshape(30, 30)
    src = make_zarr(tmp_path, "src.zarr", an, (30, 2))
    # tight budget: covering region of a (2,30) write chunk is the whole array
    ops = rechunk(
        src,
        source_chunks=(30, 2),
        target_chunks=(2, 30),
        allowed_mem=20000,
        reserved_mem=0,
        target_store=str(tmp_path / "dst.zarr"),
        temp_store=str(tmp_path / "tmp.zarr"),
    )
    assert len(ops) == 2
    execute_pipeline(ops[0])
    execute_pipeline(ops[1])
    out = ops[1].target_array.open()
    np.testing.assert_array_equal(out[...], an)
    assert out.chunks == (2, 30)
    # both stages respect the memory budget
    for op in ops:
        assert op.projected_mem <= 20000


def test_rechunk_allowed_mem_exceeded(tmp_path):
    an = np.zeros((100, 100))
    src = make_zarr(tmp_path, "src.zarr", an, (100, 1))
    with pytest.raises(ValueError, match="exceeds allowed_mem"):
        rechunk(
            src,
            source_chunks=(100, 1),
            target_chunks=(1, 100),
            allowed_mem=2000,  # cannot even hold one min-chunk copy
            reserved_mem=0,
            target_store=str(tmp_path / "dst.zarr"),
            temp_store=str(tmp_path / "tmp.zarr"),
        )


def test_rechunking_plan_direct_when_fits():
    read, inter, write = rechunking_plan(
        shape=(100, 100),
        source_chunks=(10, 100),
        target_chunks=(100, 10),
        itemsize=8,
        max_mem=10**7,
    )
    assert inter is None


def test_rechunking_plan_staged_when_tight():
    read, inter, write = rechunking_plan(
        shape=(1000, 1000),
        source_chunks=(1000, 1),
        target_chunks=(1, 1000),
        itemsize=8,
        max_mem=100_000,
    )
    assert inter == (1, 1)


def test_rechunk_ragged(tmp_path):
    an = np.arange(35.0).reshape(7, 5)
    src = make_zarr(tmp_path, "src.zarr", an, (3, 2))
    ops = rechunk(
        src,
        source_chunks=(3, 2),
        target_chunks=(2, 4),
        allowed_mem=10**6,
        reserved_mem=0,
        target_store=str(tmp_path / "dst.zarr"),
        temp_store=str(tmp_path / "tmp.zarr"),
    )
    for op in ops:
        execute_pipeline(op)
    out = ops[-1].target_array.open()
    np.testing.assert_array_equal(out[...], an)
