"""Blockwise primitive unit tests: projected-mem assertions, allowed-mem
errors, block-function behavior, fusion. Reference parity:
cubed/tests/primitive/test_blockwise.py."""

import numpy as np
import pytest

from cubed_tpu.backend_array_api import nxp
from cubed_tpu.chunks import normalize_chunks
from cubed_tpu.primitive.blockwise import (
    blockwise,
    can_fuse_pipelines,
    fuse,
    fuse_multiple,
    general_blockwise,
    make_blockwise_function,
)
from cubed_tpu.storage.store import open_zarr_array

from ..utils import execute_pipeline


def make_zarr(tmp_path, name, arr, chunks):
    store = str(tmp_path / name)
    z = open_zarr_array(store, mode="w", shape=arr.shape, dtype=arr.dtype, chunks=chunks)
    z[...] = arr
    return z


def test_blockwise_add(tmp_path):
    an = np.arange(20.0).reshape(4, 5)
    a = make_zarr(tmp_path, "a.zarr", an, (2, 3))
    b = make_zarr(tmp_path, "b.zarr", an, (2, 3))
    op = blockwise(
        nxp.add,
        ("i", "j"),
        a,
        ("i", "j"),
        b,
        ("i", "j"),
        allowed_mem=10**7,
        reserved_mem=0,
        target_store=str(tmp_path / "out.zarr"),
        shape=(4, 5),
        dtype=np.float64,
        chunks=normalize_chunks((2, 3), (4, 5), dtype=np.float64),
        in_names=["a", "b"],
        out_name="out",
    )
    assert op.num_tasks == 4
    execute_pipeline(op)
    out = op.target_array.open()
    np.testing.assert_array_equal(out[...], an + an)


def test_projected_mem_formula(tmp_path):
    an = np.zeros((4, 6))
    a = make_zarr(tmp_path, "a.zarr", an, (2, 3))
    op = blockwise(
        nxp.negative,
        ("i", "j"),
        a,
        ("i", "j"),
        allowed_mem=10**7,
        reserved_mem=1000,
        target_store=str(tmp_path / "out.zarr"),
        shape=(4, 6),
        dtype=np.float64,
        chunks=normalize_chunks((2, 3), (4, 6), dtype=np.float64),
        in_names=["a"],
        out_name="out",
        extra_projected_mem=50,
    )
    chunk_bytes = 2 * 3 * 8
    assert op.projected_mem == 1000 + 50 + 2 * chunk_bytes + 2 * chunk_bytes


def test_allowed_mem_exceeded(tmp_path):
    an = np.zeros((100, 100))
    a = make_zarr(tmp_path, "a.zarr", an, (100, 100))
    with pytest.raises(ValueError, match="exceeds allowed_mem"):
        blockwise(
            nxp.negative,
            ("i", "j"),
            a,
            ("i", "j"),
            allowed_mem=1000,
            reserved_mem=0,
            target_store=str(tmp_path / "out.zarr"),
            shape=(100, 100),
            dtype=np.float64,
            chunks=normalize_chunks((100, 100), (100, 100), dtype=np.float64),
            in_names=["a"],
            out_name="out",
        )


def test_make_blockwise_function_matching():
    bf = make_blockwise_function(
        "out",
        ("i", "j"),
        [("a", ("i", "j")), ("b", ("i", "j"))],
        {"a": (2, 3), "b": (2, 3)},
    )
    assert bf(("out", 1, 2)) == (("a", 1, 2), ("b", 1, 2))


def test_make_blockwise_function_broadcast():
    bf = make_blockwise_function(
        "out",
        ("i", "j"),
        [("a", ("i", "j")), ("b", ("j",))],
        {"a": (2, 3), "b": (3,)},
    )
    assert bf(("out", 1, 2)) == (("a", 1, 2), ("b", 2))
    # broadcast: single-block dim clamps to 0
    bf2 = make_blockwise_function(
        "out",
        ("i", "j"),
        [("a", ("i", "j")), ("b", ("i", "j"))],
        {"a": (2, 3), "b": (1, 3)},
    )
    assert bf2(("out", 1, 2)) == (("a", 1, 2), ("b", 0, 2))


def test_make_blockwise_function_contraction():
    bf = make_blockwise_function(
        "out",
        ("i",),
        [("a", ("i", "k"))],
        {"a": (2, 3)},
    )
    assert bf(("out", 1)) == ([("a", 1, 0), ("a", 1, 1), ("a", 1, 2)],)


def test_fuse_unary_chain(tmp_path):
    an = np.arange(12.0).reshape(3, 4)
    a = make_zarr(tmp_path, "a.zarr", an, (1, 2))
    chunks = normalize_chunks((1, 2), (3, 4), dtype=np.float64)
    op1 = blockwise(
        nxp.negative, ("i", "j"), a, ("i", "j"),
        allowed_mem=10**7, reserved_mem=0,
        target_store=str(tmp_path / "t1.zarr"), shape=(3, 4), dtype=np.float64,
        chunks=chunks, in_names=["a"], out_name="t1",
    )
    op2 = blockwise(
        nxp.abs, ("i", "j"), op1.target_array, ("i", "j"),
        allowed_mem=10**7, reserved_mem=0,
        target_store=str(tmp_path / "out.zarr"), shape=(3, 4), dtype=np.float64,
        chunks=chunks, in_names=["t1"], out_name="out",
    )
    assert can_fuse_pipelines(op1, op2)
    fused = fuse(op1, op2)
    assert fused.num_tasks == op2.num_tasks
    execute_pipeline(fused)
    out = fused.target_array.open()
    np.testing.assert_array_equal(out[...], np.abs(-an))


def test_fuse_multiple_binary(tmp_path):
    an = np.arange(12.0).reshape(3, 4)
    bn = an * 2
    a = make_zarr(tmp_path, "a.zarr", an, (1, 2))
    b = make_zarr(tmp_path, "b.zarr", bn, (1, 2))
    chunks = normalize_chunks((1, 2), (3, 4), dtype=np.float64)

    def mk(f, arr, name, store):
        return blockwise(
            f, ("i", "j"), arr, ("i", "j"),
            allowed_mem=10**7, reserved_mem=0,
            target_store=str(tmp_path / store), shape=(3, 4), dtype=np.float64,
            chunks=chunks, in_names=[name], out_name=f"{name}-neg",
        )

    op_a = mk(nxp.negative, a, "a", "ta.zarr")
    op_b = mk(nxp.negative, b, "b", "tb.zarr")
    op_add = blockwise(
        nxp.add, ("i", "j"),
        op_a.target_array, ("i", "j"),
        op_b.target_array, ("i", "j"),
        allowed_mem=10**7, reserved_mem=0,
        target_store=str(tmp_path / "out.zarr"), shape=(3, 4), dtype=np.float64,
        chunks=chunks, in_names=["a-neg", "b-neg"], out_name="out",
    )
    fused = fuse_multiple(op_add, op_a, op_b)
    execute_pipeline(fused)
    out = fused.target_array.open()
    np.testing.assert_array_equal(out[...], -an + -bn)
    # fused memory models the sequential predecessor execution
    assert fused.projected_mem >= op_add.projected_mem


def test_dict_output_structured_write(tmp_path):
    an = np.arange(12.0).reshape(3, 4)
    a = make_zarr(tmp_path, "a.zarr", an, (3, 2))

    def mean_chunk(x):
        return {
            "n": nxp.full((1, x.shape[1]), x.shape[0], dtype=np.int64),
            "total": nxp.sum(x, axis=0, keepdims=True),
        }

    dtype = np.dtype([("n", np.int64), ("total", np.float64)])
    op = blockwise(
        mean_chunk, ("i", "j"), a, ("i", "j"),
        allowed_mem=10**7, reserved_mem=0,
        target_store=str(tmp_path / "out.zarr"), shape=(1, 4), dtype=dtype,
        chunks=((1,), (2, 2)), in_names=["a"], out_name="out",
    )
    execute_pipeline(op)
    out = op.target_array.open()
    rec = out[...]
    np.testing.assert_array_equal(rec["n"], np.full((1, 4), 3))
    np.testing.assert_array_equal(rec["total"], an.sum(axis=0, keepdims=True))
