"""Array-API indexing functions. Reference parity:
cubed/array_api/indexing_functions.py (4 LoC)."""

from __future__ import annotations

import numpy as np


def take(x, indices, /, *, axis=None):
    if axis is None:
        if x.ndim != 1:
            raise ValueError("axis must be specified for multi-dimensional take")
        axis = 0
    axis = axis % x.ndim
    return x[(slice(None),) * axis + (indices,)]
