"""The rechunk primitive: change an array's chunking without changing its
shape or dtype, under the plan-time memory bound.

Planning reimplements the rechunker algorithm's essence (reference vendors it:
cubed/vendor/rechunker/algorithm.py): copy directly when the source region
covering one write chunk fits in the memory budget; otherwise stage through an
intermediate array chunked at the elementwise minimum of source and target
chunks (which always fits), giving two bounded copy passes. Read/write chunks
are consolidated up to the budget to reduce task counts.

On the TPU executor this storage round-trip is replaced by an in-HBM reshard
(XLA all-to-all over the mesh) whenever the array is resident — see
cubed_tpu/runtime/executors/jax.py. This primitive remains the spill path for
arrays exceeding aggregate HBM.

Reference parity: cubed/primitive/rechunk.py (behavioral; clean-room).
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Optional

import numpy as np

from ..chunks import blockdims_from_blockshape
from ..storage.zarr import LazyZarrArray, lazy_empty
from ..utils import chunk_memory, get_item, itemsize as dtype_itemsize, memory_repr
from .types import (
    CubedArrayProxy,
    CubedCopySpec,
    CubedPipeline,
    PrimitiveOperation,
)
from .blockwise import gensym


def copy_read_to_write(chunk_key, *, config: CubedCopySpec) -> None:
    """Task body: read one region from the source and write it to the target."""
    read_arr = config.read.open()
    write_arr = config.write.open()
    sel = chunk_key
    data = read_arr[sel]
    write_arr[sel] = data


class ChunkKeys:
    """Iterable of slice-tuples over the write-chunk grid (lazily enumerated)."""

    def __init__(self, shape: tuple[int, ...], write_chunks: tuple[int, ...]):
        self.shape = shape
        self.write_chunks = write_chunks

    def __iter__(self):
        chunkset = blockdims_from_blockshape(self.shape, self.write_chunks)
        nb = tuple(len(c) for c in chunkset)
        for idx in itertools.product(*(range(n) for n in nb)):
            yield get_item(chunkset, idx)

    def __len__(self):
        chunkset = blockdims_from_blockshape(self.shape, self.write_chunks)
        return math.prod(len(c) for c in chunkset)


def _covering_bytes(
    shape: tuple[int, ...],
    region_chunks: tuple[int, ...],
    source_chunks: tuple[int, ...],
    itemsize: int,
) -> int:
    """Worst-case bytes of the source-chunk-aligned region covering one
    region_chunks-sized write region."""
    total = itemsize
    for s, r, c in zip(shape, region_chunks, source_chunks):
        covered = min(s, (math.ceil((r - 1) / c) + 1) * c)
        total *= max(1, covered)
    return total


def _consolidate_chunks(
    shape: tuple[int, ...],
    chunks: tuple[int, ...],
    itemsize: int,
    max_mem: int,
    multiple_of: Optional[tuple[int, ...]] = None,
) -> tuple[int, ...]:
    """Grow chunks (last axis first) while staying under max_mem, keeping each
    grown chunk an exact multiple of the original (so region writes stay
    aligned to the original chunk grid)."""
    chunks = list(int(c) for c in chunks)
    for axis in reversed(range(len(chunks))):
        base = chunks[axis]
        while True:
            candidate = list(chunks)
            grown = min(shape[axis], chunks[axis] * 2)
            # keep multiples of the base chunk unless we span the whole axis
            if grown != shape[axis]:
                grown = (grown // base) * base
            if grown == chunks[axis]:
                break
            candidate[axis] = grown
            if math.prod(candidate) * itemsize > max_mem:
                break
            chunks = candidate
    return tuple(chunks)


def rechunking_plan(
    shape: tuple[int, ...],
    source_chunks: tuple[int, ...],
    target_chunks: tuple[int, ...],
    itemsize: int,
    max_mem: int,
) -> tuple[tuple[int, ...], Optional[tuple[int, ...]], tuple[int, ...]]:
    """Choose (read_chunks, int_chunks, write_chunks) for a bounded rechunk.

    int_chunks is None when a single direct copy pass suffices.
    """
    # direct: write at target granularity, reading the covering source region
    write_chunks = tuple(min(t, s) for t, s in zip(target_chunks, shape))
    direct_bytes = _covering_bytes(shape, write_chunks, source_chunks, itemsize)
    if direct_bytes + math.prod(write_chunks) * itemsize <= max_mem:
        # grow write chunks while the (recomputed) covering read still fits
        grown = write_chunks
        while True:
            candidate = _consolidate_chunks(shape, grown, itemsize, 2 * math.prod(grown) * itemsize)
            if candidate == grown:
                break
            cb = _covering_bytes(shape, candidate, source_chunks, itemsize)
            if cb + math.prod(candidate) * itemsize > max_mem:
                break
            grown = candidate
        # grown write chunks must remain aligned to the target chunk grid
        if all(g % t == 0 or g == s for g, t, s in zip(grown, write_chunks, shape)):
            write_chunks = grown
        return source_chunks, None, write_chunks

    # staged: intermediate at elementwise min; both passes are bounded
    int_chunks = tuple(min(s, t) for s, t in zip(source_chunks, target_chunks))
    return source_chunks, int_chunks, tuple(min(t, s) for t, s in zip(target_chunks, shape))


def _copy_op(
    source,
    target: LazyZarrArray,
    write_chunks: tuple[int, ...],
    allowed_mem: int,
    reserved_mem: int,
    source_chunks: tuple[int, ...],
) -> PrimitiveOperation:
    shape = tuple(target.shape)
    isz = target.dtype.itemsize
    read_bytes = _covering_bytes(shape, write_chunks, source_chunks, isz)
    write_bytes = math.prod(write_chunks) * isz if write_chunks else isz
    projected_mem = reserved_mem + 2 * read_bytes + 2 * write_bytes
    if projected_mem > allowed_mem:
        raise ValueError(
            f"Projected rechunk memory ({memory_repr(projected_mem)}) exceeds "
            f"allowed_mem ({memory_repr(allowed_mem)}), including "
            f"reserved_mem ({memory_repr(reserved_mem)})"
        )
    spec = CubedCopySpec(
        read=CubedArrayProxy(source, source_chunks),
        write=CubedArrayProxy(target, tuple(target.chunks)),
    )
    keys = ChunkKeys(shape, write_chunks)
    pipeline = CubedPipeline(copy_read_to_write, gensym("rechunk"), keys, spec)
    return PrimitiveOperation(
        pipeline=pipeline,
        source_array_names=[],
        target_array=target,
        projected_mem=projected_mem,
        allowed_mem=allowed_mem,
        reserved_mem=reserved_mem,
        num_tasks=len(keys),
        fusable=False,
        write_chunks=write_chunks,
    )


def rechunk(
    source,
    source_chunks: tuple[int, ...],
    target_chunks: tuple[int, ...],
    allowed_mem: int,
    reserved_mem: int,
    target_store: str,
    temp_store: Optional[str] = None,
    storage_options: Optional[dict] = None,
) -> list[PrimitiveOperation]:
    """Rechunk *source* to *target_chunks*, as one or two bounded copy ops."""
    shape = tuple(source.shape)
    dtype = source.dtype
    isz = np.dtype(dtype).itemsize

    # the factor-of-4 headroom mirrors the reference's compressed/uncompressed
    # x read/write safety margin (cubed/primitive/rechunk.py:52-57)
    max_mem = (allowed_mem - reserved_mem) // 4
    read_chunks, int_chunks, write_chunks = rechunking_plan(
        shape, tuple(source_chunks), tuple(target_chunks), isz, max_mem
    )

    target = lazy_empty(
        shape, dtype=dtype, chunks=tuple(min(t, s) for t, s in zip(target_chunks, shape)) if shape else (),
        store=target_store, storage_options=storage_options,
    )

    if int_chunks is None:
        return [
            _copy_op(source, target, write_chunks, allowed_mem, reserved_mem, tuple(source_chunks))
        ]
    if temp_store is None:
        raise ValueError("temp_store required for staged rechunk")
    intermediate = lazy_empty(
        shape, dtype=dtype, chunks=int_chunks, store=temp_store,
        storage_options=storage_options,
    )
    op1 = _copy_op(
        source, intermediate, int_chunks, allowed_mem, reserved_mem, tuple(source_chunks)
    )
    op2 = _copy_op(
        intermediate, target, write_chunks, allowed_mem, reserved_mem, int_chunks
    )
    return [op1, op2]
