"""TqdmProgressBar: one progress bar per op, driven by the unified callback
lifecycle — a bar opens on ``on_operation_start``, advances on
``on_task_end``, and closes on ``on_operation_end`` (so ops that never ran,
e.g. under ``resume``, never show a bar).

Reference parity: cubed/extensions/tqdm.py:10-55. Falls back to a plain
line-printing bar when tqdm is unavailable.
"""

from __future__ import annotations

import sys
from typing import Dict

from ..runtime.types import Callback, TaskEndEvent


class _PlainBar:
    def __init__(self, desc: str, total: int):
        self.desc = desc
        self.total = total
        self.n = 0

    def update(self, n: int = 1):
        self.n += n
        pct = 100.0 * self.n / self.total if self.total else 100.0
        sys.stderr.write(f"\r{self.desc}: {self.n}/{self.total} ({pct:.0f}%)")
        if self.n >= self.total:
            sys.stderr.write("\n")
        sys.stderr.flush()

    def close(self):
        pass


class TqdmProgressBar(Callback):
    def __init__(self, **tqdm_kwargs):
        self.tqdm_kwargs = tqdm_kwargs
        self.bars: Dict[str, object] = {}
        self._position = 0

    def on_compute_start(self, event) -> None:
        self.bars = {}
        self._position = 0
        try:
            from tqdm.auto import tqdm  # noqa: F401

            self._tqdm = tqdm
        except ImportError:
            self._tqdm = None

    def on_operation_start(self, event) -> None:
        if event.name in self.bars:
            return
        if self._tqdm is not None:
            self.bars[event.name] = self._tqdm(
                desc=event.name,
                total=event.num_tasks,
                position=self._position,
                **self.tqdm_kwargs,
            )
        else:
            self.bars[event.name] = _PlainBar(event.name, event.num_tasks)
        self._position += 1

    def on_task_end(self, event: TaskEndEvent) -> None:
        bar = self.bars.get(event.array_name)
        if bar is not None:
            bar.update(event.num_tasks)

    def on_operation_end(self, event) -> None:
        bar = self.bars.get(event.name)
        if bar is not None:
            bar.close()

    def on_compute_end(self, event) -> None:
        for bar in self.bars.values():
            bar.close()
