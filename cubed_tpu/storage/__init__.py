from .integrity import ChunkIntegrityError  # noqa: F401
from .store import ZarrV2Array, open_zarr_array  # noqa: F401
from .zarr import (  # noqa: F401
    LazyZarrArray,
    lazy_empty,
    lazy_full,
    open_if_lazy_zarr_array,
)
from .virtual import (  # noqa: F401
    VirtualEmptyArray,
    VirtualFullArray,
    VirtualInMemoryArray,
    VirtualOffsetsArray,
    virtual_empty,
    virtual_full,
    virtual_in_memory,
    virtual_offsets,
)
