"""Sequential in-process executor — the correctness oracle.

Reference parity: cubed/runtime/executors/python.py:14-32, extended with the
full callback lifecycle (task start / operation end) and opt-in classified
retries (``retries=0`` by default: the oracle surfaces a task's first
failure undisturbed unless asked otherwise).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from ...observability.metrics import get_registry
from ..memory import count_resource_failure, resource_abort_error
from ..pipeline import (
    RecomputeResolver,
    ResumeState,
    pending_mappable,
    visit_nodes,
)
from ..resilience import (
    Classification,
    RetryPolicy,
    budget_exhausted_error,
    compute_retry_budget,
    integrity_payload,
    resolve_policy,
)
from ..types import (
    DagExecutor,
    OperationEndEvent,
    OperationStartEvent,
    callbacks_on,
)
from ..utils import chunk_key, execute_with_stats, fire_task_start, handle_callbacks

logger = logging.getLogger(__name__)


class PythonDagExecutor(DagExecutor):
    """For each op in topological order, run its tasks one by one in-process."""

    def __init__(
        self,
        retries: int = 0,
        retry_policy: Optional[RetryPolicy] = None,
        **kwargs,
    ):
        self.retries = retries
        self.retry_policy = retry_policy
        self.kwargs = kwargs

    @property
    def name(self) -> str:
        return "single-threaded"

    def execute_dag(
        self,
        dag,
        callbacks=None,
        resume=None,
        spec=None,
        retries: Optional[int] = None,
        retry_policy: Optional[RetryPolicy] = None,
        journal=None,
        cancellation=None,
        **kwargs,
    ) -> None:
        retries = self.retries if retries is None else retries
        policy = resolve_policy(retry_policy or self.retry_policy, retries)
        budget = compute_retry_budget(policy, dag)
        from ..dataflow import requested_scheduler

        if requested_scheduler(spec) == "dataflow":
            # the oracle's value IS its strict op ordering (bitwise
            # reference for the overlapped executors) — documented no-op;
            # only an EXPLICIT request is worth a note now that dataflow
            # is the async executors' default
            logger.debug(
                "scheduler=dataflow requested; the sequential oracle "
                "keeps op-level ordering by design"
            )
        metrics = get_registry()
        state = (
            ResumeState(quarantine=True, journal=journal) if resume else None
        )
        resolver = RecomputeResolver(dag)
        for name, node in visit_nodes(dag, resume=resume, state=state):
            primitive_op = node["primitive_op"]
            pipeline = primitive_op.pipeline
            callbacks_on(
                callbacks, "on_operation_start",
                OperationStartEvent(name, primitive_op.num_tasks),
            )
            mappable, _ = pending_mappable(name, node, resume, state)
            for m in mappable:
                if cancellation is not None and cancellation.cancelled:
                    from ..cancellation import abort as _cancel_abort

                    raise _cancel_abort(cancellation)
                created = time.time()
                key = chunk_key(m)
                failures = 0
                while True:
                    fire_task_start(
                        callbacks, name, chunk_key_str=key, attempt=failures
                    )
                    try:
                        _, stats = execute_with_stats(
                            pipeline.function, m, config=pipeline.config
                        )
                        break
                    except Exception as exc:
                        cls = policy.classify(exc)
                        from ...observability.collect import (
                            record_decision,
                            record_failed_task,
                        )

                        record_decision(
                            "task_failed",
                            op=name, chunk=key, attempt=failures,
                            error_type=type(exc).__name__,
                            error=str(exc)[:200],
                            classification=cls.name.lower(),
                        )
                        record_failed_task(name, key, failures, exc)
                        if cls is Classification.RECOMPUTE:
                            from .python_async import _count_integrity_failure

                            _count_integrity_failure(metrics, exc)
                        if cls is Classification.RESOURCE:
                            # the oracle already runs at concurrency 1, so
                            # there is nothing to step down; retries still
                            # help when host pressure is external, but an
                            # exhausted task surfaces the actionable form
                            count_resource_failure(metrics, exc)
                        failures += 1
                        if cls is Classification.CANCELLED:
                            # the compute was cancelled / hit its
                            # deadline: abort, never retry, zero budget
                            if cancellation is not None:
                                from ..cancellation import (
                                    abort as _cancel_abort,
                                )

                                raise _cancel_abort(cancellation) from exc
                            raise
                        # REQUEUE cannot arise in-process; treat it as RETRY
                        if cls is Classification.FAIL_FAST:
                            metrics.counter("task_failfast").inc()
                            raise
                        if failures > policy.retries:
                            if cls is Classification.RESOURCE:
                                # the oracle IS concurrency 1
                                raise resource_abort_error(name, exc) from exc
                            raise
                        if not budget.consume():
                            raise budget_exhausted_error(exc, budget) from exc
                        if cls is Classification.RECOMPUTE:
                            # a corrupt (quarantined) input chunk: re-run
                            # its producing task, then retry this one with
                            # no extra backoff
                            repair = resolver.resolve(integrity_payload(exc))
                            if repair is not None:
                                try:
                                    repair()
                                    continue
                                except Exception:
                                    logger.exception(
                                        "upstream recompute for task %s "
                                        "failed; falling back to a backoff "
                                        "retry", key,
                                    )
                        delay = policy.backoff_delay(failures)
                        logger.info(
                            "retrying task %s (attempt %d) in %.3fs",
                            key, failures + 1, delay,
                        )
                        metrics.counter("task_retries").inc()
                        metrics.histogram("retry_backoff_s").observe(delay)
                        record_decision(
                            "retry", op=name, chunk=key, attempt=failures,
                            delay_s=round(delay, 4),
                        )
                        if delay > 0:
                            time.sleep(delay)
                handle_callbacks(
                    callbacks,
                    dict(
                        stats,
                        array_name=name,
                        task_create_tstamp=created,
                        chunk_key=key,
                        attempt=failures,
                        executor=self.name,
                    ),
                )
            callbacks_on(
                callbacks, "on_operation_end",
                OperationEndEvent(name, primitive_op.num_tasks),
            )
