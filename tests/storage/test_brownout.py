"""Store-brownout tolerance: THROTTLE classification, the per-store
health breaker's AIMD pacing + half-open recovery, and the chaos proof —
a seeded brownout completes bitwise with the breaker engaged and a
strictly lower retry-budget draw than breaker-off.

Marked ``chaos`` (seeded, deterministic, tier-1)."""

from __future__ import annotations

import contextlib
import itertools
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.runtime.faults import FaultInjectedThrottleError
from cubed_tpu.runtime.resilience import Classification, RetryPolicy
from cubed_tpu.storage import health

pytestmark = pytest.mark.chaos

BROWNOUT = dict(seed=23, storage_throttle_rate=0.25)


@pytest.fixture(autouse=True)
def _fresh_breakers():
    health.reset_breakers()
    yield
    health.reset_breakers()


class _StatsCapture:
    stats: dict = {}

    def on_compute_end(self, event):
        self.stats = event.executor_stats or {}


# -- classification ------------------------------------------------------


def test_is_throttle_error_shapes():
    assert health.is_throttle_error(OSError("503 SlowDown"))
    assert health.is_throttle_error(OSError("HTTP 429 Too Many Requests"))
    assert health.is_throttle_error(
        ConnectionError("rate limit exceeded, retry later")
    )
    assert health.is_throttle_error(
        FaultInjectedThrottleError("injected store throttle (503 SlowDown)")
    )
    assert not health.is_throttle_error(OSError("connection reset by peer"))
    assert not health.is_throttle_error(ValueError("503"))  # not IO-shaped
    # status codes match word-bounded only: digits embedded in paths or
    # shape tuples are not brownouts
    assert not health.is_throttle_error(
        OSError("/tmp/tmp429ab/chunk 0.0 missing")
    )
    assert health.is_throttle_error(OSError("HTTP 503: Service Unavailable"))


def test_is_throttle_error_remote_non_io_types_never_match():
    from cubed_tpu.runtime.distributed import RemoteTaskError

    # a remote ValueError whose message mentions 503 (a broadcast-shape
    # complaint) must never classify as a brownout
    remote = RemoteTaskError(
        "operands could not be broadcast together with shapes (503,) (502,)",
        remote_type="ValueError",
    )
    assert not health.is_throttle_error(remote)
    remote_io = RemoteTaskError(
        "OSError: 503 SlowDown", remote_type="OSError"
    )
    assert health.is_throttle_error(remote_io)


def test_throttle_classification_local_and_remote():
    from cubed_tpu.runtime.distributed import RemoteTaskError

    policy = RetryPolicy()
    assert policy.classify(OSError("SlowDown")) is Classification.THROTTLE
    assert policy.classify(
        FaultInjectedThrottleError("injected store throttle")
    ) is Classification.THROTTLE
    remote = RemoteTaskError(
        "boom", remote_type="FaultInjectedThrottleError"
    )
    assert policy.classify(remote) is Classification.THROTTLE
    # ordinary transient errors keep their RETRY classification
    assert policy.classify(OSError("connection reset")) is (
        Classification.RETRY
    )


def test_throttle_wait_has_its_own_analyze_bucket():
    from cubed_tpu.observability.analytics import BUCKETS, SPAN_BUCKETS

    assert SPAN_BUCKETS.get("throttle_wait") == "throttle_wait"
    assert "throttle_wait" in BUCKETS


# -- breaker units -------------------------------------------------------


def test_breaker_halves_and_restores_to_unbounded():
    b = health.StoreHealthBreaker("s3://unit")
    b.PROBE_IDLE_S = 0.05  # fast recovery probing for the unit test
    b.STEP_COOLDOWN_S = 0.0
    # simulate 8 concurrent IOs, then a throttle salvo
    for _ in range(8):
        b.acquire()
    assert b.state == "closed"
    delay = b.on_throttle()
    assert 0 < delay <= 1.0
    assert b.state == "open" and b._limit == 4
    b.on_throttle()
    assert b._limit == 2
    for _ in range(8):
        b.release()
    time.sleep(0.06)  # past the probe window: half-open
    assert b.state == "half_open"
    # a success streak doubles back to unbounded
    for _ in range(64):
        b.on_success()
    assert b.state == "closed" and b._limit is None


def test_breaker_acquire_blocks_until_release():
    b = health.StoreHealthBreaker("s3://block")
    b.STEP_COOLDOWN_S = 0.0
    b.acquire()
    b.on_throttle()  # limit -> 1 while one IO is in flight
    assert b._limit == 1
    acquired = threading.Event()

    def second():
        b.acquire()
        acquired.set()
        b.release()

    t = threading.Thread(target=second, daemon=True)
    t.start()
    assert not acquired.wait(0.2), "second IO ran past a limit of 1"
    b.release()
    assert acquired.wait(2.0), "releasing the slot should unblock the wait"
    t.join(timeout=2.0)


def test_breaker_env_off_disables_pacing(monkeypatch):
    monkeypatch.setenv(health.BREAKER_ENV_VAR, "off")
    assert not health.breaker_enabled()
    monkeypatch.setenv(health.BREAKER_ENV_VAR, "")
    assert health.breaker_enabled()


# -- chaos proofs --------------------------------------------------------


@contextlib.contextmanager
def _pinned_plan_names(base: int):
    """Injector decisions hash the gensym'd array names in chunk keys;
    pin the process-global counter so the breaker-on and breaker-off
    runs (and any suite ordering) roll identical decisions, then resume
    it where natural flow would have landed."""
    from cubed_tpu import utils as ct_utils

    resume_at = next(ct_utils.sym_counter)
    ct_utils.sym_counter = itertools.count(base)
    try:
        yield
    finally:
        used = next(ct_utils.sym_counter) - base
        ct_utils.sym_counter = itertools.count(resume_at + used)


def _brownout_run(tmp_path, name: str, base: int):
    """One seeded brownout compute; returns (result, metrics delta)."""
    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    with _pinned_plan_names(base):
        spec = ct.Spec(
            work_dir=str(tmp_path / name), allowed_mem="500MB",
            fault_injection=BROWNOUT,
        )
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 100 chunks
        b = a * 2.0
        before = get_registry().snapshot()
        result = b.compute(
            executor=AsyncPythonDagExecutor(
                max_workers=4,
                retry_policy=RetryPolicy(
                    retries=6, backoff_base=0.01, seed=0
                ),
            ),
        )
    return result, get_registry().snapshot_delta(before)


def test_chaos_brownout_completes_bitwise_with_breaker_engaged(tmp_path):
    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    result, delta = _brownout_run(tmp_path, "on", base=41_000)
    np.testing.assert_array_equal(result, an * 2.0)
    assert delta.get("store_throttled", 0) > 0, delta
    assert delta.get("store_breaker_trips", 0) > 0, delta


def test_chaos_breaker_draws_strictly_less_budget_than_off(
    tmp_path, monkeypatch
):
    """The acceptance differential: same seed, same plan names — with
    the breaker the brownout is absorbed by paced in-place retries
    (near-zero task-retry draw); without it every surfaced throttle
    burns a task retry from the shared budget."""
    an = np.arange(400, dtype=np.float64).reshape(20, 20)

    monkeypatch.setenv(health.BREAKER_ENV_VAR, "off")
    result_off, delta_off = _brownout_run(tmp_path, "off", base=42_000)
    np.testing.assert_array_equal(result_off, an * 2.0)
    draw_off = delta_off.get("task_retries", 0)

    health.reset_breakers()
    monkeypatch.delenv(health.BREAKER_ENV_VAR, raising=False)
    result_on, delta_on = _brownout_run(tmp_path, "on", base=42_000)
    np.testing.assert_array_equal(result_on, an * 2.0)
    draw_on = delta_on.get("task_retries", 0)

    assert draw_off > 0, (
        f"breaker-off baseline drew no retries ({delta_off}) — the seeded "
        "brownout is not surfacing"
    )
    assert draw_on < draw_off, (
        f"breaker drew {draw_on} task retries vs {draw_off} without it"
    )
    assert delta_on.get("store_throttled", 0) > 0


def test_chaos_distributed_brownout_bitwise(tmp_path):
    from cubed_tpu.runtime.executors.distributed import (
        DistributedDagExecutor,
    )

    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=dict(seed=31, storage_throttle_rate=0.2),
    )
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    b = a + 0.5
    cap = _StatsCapture()
    with DistributedDagExecutor(n_local_workers=2) as ex:
        result = b.compute(
            executor=ex, callbacks=[cap],
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
        )
    np.testing.assert_array_equal(result, an + 0.5)
    # worker-side throttles ride the task-stats scoped-counter channel
    # back into the client's per-compute stats
    assert cap.stats.get("store_throttled", 0) > 0, cap.stats
