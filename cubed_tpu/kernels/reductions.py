"""Pallas TPU kernels for the framework's hot reduction paths.

The reference delegates all compute to NumPy; here the hot ops are XLA
programs, and Pallas covers the cases where XLA's fusion is not optimal:
single-pass fused elementwise+reduction over tiles streamed HBM->VMEM, with
grid accumulation into a revisited output block (TPU grids execute
sequentially, so accumulating into the same output block across grid steps is
well-defined; see /opt/skills/guides/pallas_guide.md "Grid and Block
Specifications").

Kernels operate on f32/bf16 tiles (TPU-native dtypes); callers fall back to
XLA for f64. ``interpret=True`` is used automatically off-TPU so the kernels
are testable on the CPU mesh.
"""

from __future__ import annotations

import functools

import numpy as np


def _on_tpu() -> bool:
    import jax

    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _pl():
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl, pltpu


import contextlib


@contextlib.contextmanager
def _x32_scope():
    """Mosaic rejects x64-typed grid scalars (func.return (i32, i64)
    legalization failure); trace and compile kernels with x64 off. Kernels are
    invoked eagerly by executors, never inside an outer x64 trace."""
    import jax

    prev = jax.config.jax_enable_x64
    try:
        jax.config.update("jax_enable_x64", False)
        yield
    finally:
        jax.config.update("jax_enable_x64", prev)


#: VPU-friendly tile: multiples of the f32 (8, 128) min tile. A v5e tile
#: sweep (512-2048 per dim, benchmarks/pallas_vs_xla.py harness) showed
#: ~300-350 GB/s for the accumulating sum kernels at every tile size vs
#: ~890 GB/s for XLA's fused reduction of the same expression — the single
#: revisited accumulator block serializes the grid, where XLA emits
#: parallel partial sums. The executor therefore keeps these kernels
#: opt-in (JaxExecutor(use_pallas=True)); see benchmarks/PALLAS_MICRO.json.
TILE_M = 512
TILE_N = 512


def _sum_tiles_kernel(x_ref, out_ref):
    import jax.numpy as jnp

    pl, _ = _pl()
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((i == 0) & (j == 0))
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # every lane of the (8,128) accumulator holds the running total; the
    # caller reads [0, 0] (scalar SMEM stores hit Mosaic legalization bugs)
    out_ref[:] += jnp.sum(x_ref[:])


def block_sum(x, *, interpret: bool | None = None):
    """Single-pass tiled sum of a 2-d f32 array (one scalar out).

    Tiles stream HBM->VMEM along the grid; a (1,1) SMEM-resident output block
    is revisited by every grid step and accumulates the per-tile partial.
    """
    import jax
    import jax.numpy as jnp

    import jax.numpy as jnp

    if interpret is None:
        interpret = not _on_tpu()
    if x.ndim != 2:
        x = jnp.reshape(x, (x.shape[0] if x.ndim else 1, -1))
    x = _pad_to_tiles(x)  # zero padding is sum-neutral
    with _x32_scope():
        fn = _sum_call(x.shape, interpret)
        out = fn(x.astype(jnp.float32))
    return out[0, 0]


@functools.lru_cache(maxsize=256)
def _sum_call(shape, interpret):
    import jax
    import jax.numpy as jnp

    pl, pltpu = _pl()
    m, n = shape
    tm, tn = min(TILE_M, m), min(TILE_N, n)
    return jax.jit(
        pl.pallas_call(
            _sum_tiles_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            grid=(pl.cdiv(m, tm), pl.cdiv(n, tn)),
            in_specs=[pl.BlockSpec((tm, tn), lambda i, j: (i, j))],
            out_specs=pl.BlockSpec((8, 128), lambda i, j: (0, 0)),
            interpret=interpret,
        )
    )


def _pad_to_tiles(x):
    """Zero-pad so both dims are tile multiples (out-of-bounds tile reads are
    undefined in pallas; zero padding keeps sums exact)."""
    import jax.numpy as jnp

    m, n = x.shape
    tm, tn = min(TILE_M, m), min(TILE_N, n)
    pm = (-m) % tm
    pn = (-n) % tn
    if pm or pn:
        x = jnp.pad(x, ((0, pm), (0, pn)))
    return x


def _col_sum_kernel(x_ref, out_ref):
    import jax.numpy as jnp

    pl, _ = _pl()
    i = pl.program_id(1)  # row-tile step: the INNER grid axis

    @pl.when(i == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # stream row-tiles HBM->VMEM, accumulating the column partial into a
    # revisited (8, tn) block. The grid is (col-tiles, row-tiles) with the
    # row axis innermost, so every revisit of an output block is consecutive
    # (the canonical TPU accumulation pattern) and each column tile's input
    # block stays within the VMEM budget regardless of array width. The
    # (1, tn) keepdims partial broadcasts over the 8 sublanes — every row
    # holds the total, the caller reads row 0; a (1, tn) output would break
    # the f32 (8, 128) min tile.
    out_ref[:] += jnp.sum(x_ref[:], axis=0, keepdims=True)


def _tile_width(n: int) -> int:
    """Largest lane-aligned tile width (multiple of 128, <= TILE_N) dividing
    ``n`` (itself a multiple of 128) — so padding never exceeds the 128
    alignment cost."""
    for d in range(min(TILE_N, n), 0, -128):
        if n % d == 0:
            return d
    return 128


@functools.lru_cache(maxsize=256)
def _col_sum_call(shape, interpret):
    import jax
    import jax.numpy as jnp

    pl, pltpu = _pl()
    m, n = shape
    tm = min(TILE_M, m)
    tn = _tile_width(n)
    return jax.jit(
        pl.pallas_call(
            _col_sum_kernel,
            out_shape=jax.ShapeDtypeStruct((8, n), jnp.float32),
            grid=(pl.cdiv(n, tn), pl.cdiv(m, tm)),
            in_specs=[pl.BlockSpec((tm, tn), lambda j, i: (i, j))],
            out_specs=pl.BlockSpec((8, tn), lambda j, i: (0, j)),
            interpret=interpret,
        )
    )


def region_sum(x, axis, *, keepdims=True, interpret: bool | None = None):
    """Pallas sum of an N-d f32 array over an axis set.

    Reduced axes are transposed to the front and collapsed to rows, kept axes
    to columns; a streaming column-sum kernel accumulates row-tiles in VMEM.
    Full reductions route to the tiled ``block_sum``. Returns the keepdims
    result (or the squeezed one with ``keepdims=False``).
    """
    import jax.numpy as jnp

    if interpret is None:
        interpret = not _on_tpu()
    axis = tuple(sorted(ax % x.ndim for ax in axis))
    kept = tuple(d for d in range(x.ndim) if d not in axis)
    out_keep_shape = tuple(1 if d in axis else x.shape[d] for d in range(x.ndim))

    if not kept or all(x.shape[d] == 1 for d in kept):
        total = block_sum(x, interpret=interpret)
        out = jnp.reshape(total, out_keep_shape)
    else:
        perm = axis + kept
        rows = 1
        for d in axis:
            rows *= x.shape[d]
        cols = 1
        for d in kept:
            cols *= x.shape[d]
        x2 = jnp.reshape(jnp.transpose(x, perm), (rows, cols))
        # zero-pad both dims to whole grid tiles (out-of-bounds tile reads are
        # undefined in pallas); _col_sum_call recomputes the same tile sizes
        # from the padded shape, so padded dims must be tile multiples
        n128 = cols + ((-cols) % 128)
        pn = n128 - cols  # _col_sum_call picks a tile width dividing n128
        rows8 = rows + ((-rows) % 8)
        tm = min(TILE_M, rows8)
        pm = (-rows) % tm
        if pn or pm:
            x2 = jnp.pad(x2, ((0, pm), (0, pn)))
        with _x32_scope():
            fn = _col_sum_call(x2.shape, interpret)
            col = fn(x2.astype(jnp.float32))
        col = col[0:1, :cols]
        out = jnp.reshape(col, tuple(x.shape[d] for d in kept))
        out = jnp.reshape(out, out_keep_shape)
    if not keepdims:
        out = jnp.reshape(out, tuple(x.shape[d] for d in kept))
    return out


def _fma_mean_kernel(a_ref, x_ref, b_ref, y_ref, out_ref):
    import jax.numpy as jnp

    pl, _ = _pl()
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((i == 0) & (j == 0))
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    # one fused VPU pass: two multiplies, one add, one reduction — the
    # vorticity inner loop with the intermediate never leaving VMEM
    out_ref[:] += jnp.sum(a_ref[:] * x_ref[:] + b_ref[:] * y_ref[:])


def fused_fma_mean(a, x, b, y, *, interpret: bool | None = None):
    """mean(a*x + b*y) in a single fused streaming pass (f32).

    The pangeo-vorticity inner loop as one kernel: four tile streams in, one
    accumulator out; no materialized intermediate at any level of the memory
    hierarchy below VMEM.
    """
    import jax
    import jax.numpy as jnp

    import jax.numpy as jnp

    if interpret is None:
        interpret = not _on_tpu()

    orig_size = a.size
    a2 = jnp.reshape(a, (-1, a.shape[-1])) if a.ndim != 2 else a
    a2 = _pad_to_tiles(a2)
    x2 = _pad_to_tiles(jnp.reshape(x, (-1, x.shape[-1])) if x.ndim != 2 else x)
    b2 = _pad_to_tiles(jnp.reshape(b, (-1, b.shape[-1])) if b.ndim != 2 else b)
    y2 = _pad_to_tiles(jnp.reshape(y, (-1, y.shape[-1])) if y.ndim != 2 else y)

    with _x32_scope():
        fn = _fma_call(a2.shape, interpret)
        total = fn(
            a2.astype(jnp.float32),
            x2.astype(jnp.float32),
            b2.astype(jnp.float32),
            y2.astype(jnp.float32),
        )
    return total[0, 0] / orig_size


@functools.lru_cache(maxsize=256)
def _fma_call(shape, interpret):
    import jax
    import jax.numpy as jnp

    pl, pltpu = _pl()
    m, n = shape
    tm, tn = min(TILE_M, m), min(TILE_N, n)
    spec = pl.BlockSpec((tm, tn), lambda i, j: (i, j))
    return jax.jit(
        pl.pallas_call(
            _fma_mean_kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            grid=(pl.cdiv(m, tm), pl.cdiv(n, tn)),
            in_specs=[spec, spec, spec, spec],
            out_specs=pl.BlockSpec((8, 128), lambda i, j: (0, 0)),
            interpret=interpret,
        )
    )
