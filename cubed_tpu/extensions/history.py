"""HistoryCallback: record plan-time projections and per-task measurements,
write CSVs, and compute projected-memory utilization.

A thin view over the unified observability event stream
(``observability.EventLogCallback`` collects plan rows, task events and op
timings; this class only adds the CSV dump).

Reference parity: cubed/extensions/history.py:11-103.
"""

from __future__ import annotations

import csv
import os
import time
from dataclasses import asdict

from ..observability.events import EventLogCallback, PlanRow  # noqa: F401


class HistoryCallback(EventLogCallback):
    def __init__(self, history_dir: str = "history"):
        super().__init__()
        self.history_dir = history_dir

    def on_compute_end(self, event) -> None:
        super().on_compute_end(event)
        ts = int(time.time())
        os.makedirs(self.history_dir, exist_ok=True)
        self._write_csv(
            os.path.join(self.history_dir, f"plan-{ts}.csv"),
            [asdict(r) for r in self.plan],
        )
        self._write_csv(
            os.path.join(self.history_dir, f"events-{ts}.csv"),
            [asdict(e) for e in self.events],
        )
        stats = self.stats()
        if stats:
            self._write_csv(os.path.join(self.history_dir, f"stats-{ts}.csv"), stats)

    def stats(self) -> list[dict]:
        """Join plan projections against measured peaks per op."""
        return self.projected_vs_measured()

    @staticmethod
    def _write_csv(path: str, rows: list[dict]) -> None:
        if not rows:
            return
        with open(path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
            writer.writeheader()
            writer.writerows(rows)
