"""Speculative straggler-backup policy.

Reference parity: cubed/runtime/backup.py:7-32 — launch a duplicate of a
running task when enough peers have completed and this task is an outlier
(>3x the median completed duration). Safe because tasks are idempotent and
chunk writes are atomic.
"""

from __future__ import annotations

from typing import Dict, TypeVar

T = TypeVar("T")

#: policy constants (reference values)
MIN_TASKS_STARTED = 10
MIN_COMPLETED_FRACTION = 0.5
SLOWDOWN_FACTOR = 3.0


def should_launch_backup(
    task: T,
    now: float,
    start_times: Dict[T, float],
    end_times: Dict[T, float],
    min_tasks: int = MIN_TASKS_STARTED,
    min_completed_fraction: float = MIN_COMPLETED_FRACTION,
    slow_factor: float = SLOWDOWN_FACTOR,
) -> bool:
    if len(start_times) < min_tasks:
        return False
    if len(end_times) < min_completed_fraction * len(start_times):
        return False
    durations = sorted(
        end_times[t] - start_times[t] for t in end_times if t in start_times
    )
    if not durations:
        return False
    median = durations[len(durations) // 2]
    return now - start_times[task] > slow_factor * median
