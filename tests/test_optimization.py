"""Graph-optimization (fusion) tests: fusion shapes, task/array count deltas,
result correctness, fan-in limits and overrides.

Reference parity: cubed/tests/test_optimization.py (708 LoC, behavioral).
"""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.core.optimization import (
    fuse_all_optimize_dag,
    fuse_only_optimize_dag,
    multiple_inputs_optimize_dag,
    simple_optimize_dag,
)


def num_ops(plan, optimize_function=None, optimize_graph=True):
    finalized = plan._finalize(
        optimize_graph=optimize_graph, optimize_function=optimize_function
    )
    return finalized.num_ops()


def test_unary_chain_fuses(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.negative(b)
    d = xp.negative(c)
    unopt = num_ops(d.plan, optimize_graph=False)
    opt = num_ops(d.plan, optimize_function=simple_optimize_dag)
    assert opt < unopt
    np.testing.assert_allclose(
        d.compute(optimize_function=simple_optimize_dag), -an * 1.0 * -1 * -1
    )


def test_scalar_chain_fuses_with_multiple_inputs(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    d = xp.add(c, 1)
    unopt = num_ops(d.plan, optimize_graph=False)
    opt = num_ops(d.plan, optimize_function=multiple_inputs_optimize_dag)
    assert opt < unopt
    np.testing.assert_array_equal(d.compute(), np.full((6, 6), 4.0))


def test_binary_fuses_with_multiple_inputs(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.add(xp.negative(a), xp.negative(b))
    unopt = num_ops(c.plan, optimize_graph=False)
    opt = num_ops(c.plan, optimize_function=multiple_inputs_optimize_dag)
    assert opt < unopt
    np.testing.assert_allclose(c.compute(), -an + -an)


def test_diamond(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.add(b, b)  # diamond: b consumed twice by the same op
    np.testing.assert_allclose(c.compute(), -an + -an)


def test_other_dependents_blocks_fusion(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.add(b, 1)
    # b is also a requested output: it must not be fused away
    rb, rc = ct.compute(b, c)
    np.testing.assert_allclose(rb, -an)
    np.testing.assert_allclose(rc, -an + 1)


def test_fuse_all(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    opt = num_ops(c.plan, optimize_function=fuse_all_optimize_dag)
    # create-arrays + single fused op
    assert opt <= 2
    np.testing.assert_array_equal(
        c.compute(optimize_function=fuse_all_optimize_dag), np.full((6, 6), 3.0)
    )


def test_fuse_only(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    # find the op node producing c
    dag = c.plan.dag
    target_op = [n for n in dag.predecessors(c.name)][0]
    opt_dag = fuse_only_optimize_dag(dag.copy(), only_fuse={target_op})
    assert target_op in opt_dag
    np.testing.assert_array_equal(
        c.compute(optimize_function=lambda d, array_names=None: fuse_only_optimize_dag(
            d, array_names=array_names, only_fuse={target_op})),
        np.full((6, 6), 3.0),
    )


def test_max_total_source_arrays_gate(spec):
    arrays = [xp.ones((4, 4), chunks=(2, 2), spec=spec) for _ in range(6)]
    s = arrays[0]
    for a in arrays[1:]:
        s = xp.add(s, a)
    # default gate (4) still yields a correct result
    np.testing.assert_array_equal(s.compute(), np.full((4, 4), 6.0))


def test_fusion_preserves_num_tasks(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    ntasks_unopt = b.plan.num_tasks(optimize_graph=False)
    ntasks_opt = b.plan.num_tasks(optimize_graph=True)
    assert ntasks_opt <= ntasks_unopt


def test_rechunk_not_fused(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = b.rechunk((3, 3))
    d = xp.add(c, 1)
    np.testing.assert_allclose(d.compute(), an + 2)


def test_fused_different_chunk_elementwise(spec):
    # inputs with different chunking unify (rechunk) then fuse downstream
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(an, chunks=(6, 6), spec=spec)
    c = xp.add(a, b)
    np.testing.assert_allclose(c.compute(), an * 2)
