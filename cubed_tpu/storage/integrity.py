"""End-to-end chunk integrity: checksums, sidecar manifests, quarantine.

The execution model rests on strongly-consistent storage and idempotent
tasks (docs/reliability.md) — but consistency says nothing about *content*:
a bit-flipped or truncated chunk is served as valid data, and a resume scan
that only counts files declares a corrupt output "done", silently poisoning
every downstream op. This module closes that gap:

- **Checksums.** Every chunk write records a CRC32C-style checksum (CRC-32,
  ``zlib.crc32`` — the stdlib's castagnoli-class polynomial CRC; no C
  extension needed) of the bytes as stored (post-compression), plus the
  byte length and a timestamp, in a per-array sidecar manifest.

- **Sidecar manifests, Zarr-layout-preserving.** Manifests are extra
  dot-prefixed keys (``.manifest-<writer>.json``) next to ``.zarray`` — any
  plain Zarr v2 reader still reads the array and ignores them. Each writer
  *process* owns one shard per array, so concurrent writers — duplicate
  tasks, speculative backups, distinct worker processes — never contend on
  one file. Local shards are append-only JSONL (one line per chunk write,
  O(1)); object stores, which cannot append, atomically rewrite a
  whole-document shard. Readers merge all shards with last-write-wins on
  identical keys (by recorded timestamp; duplicate/backup writers write
  identical bytes, so ties are harmless). Undecodable content — a whole
  bad shard, or a single torn line — is skipped: those chunks simply lose
  their entries and verification treats them as untrustworthy (recompute),
  never as valid.

- **Quarantine.** A chunk that fails verification is renamed to
  ``<key>.quarantine.<ts>`` (kept for forensics, invisible to chunk-name
  scans) and counted (``chunks_corrupt_detected`` / ``chunks_quarantined``).
  Its manifest entry is *kept*: a quarantined chunk must read as "written
  but missing" — an integrity error — not as a never-written chunk that
  legitimately serves fill values.

- **Modes.** ``integrity="off" | "write" | "verify"`` (default ``write``):
  ``write`` records checksums on every chunk write (what makes resume
  trustworthy); ``verify`` additionally verifies every task-scope chunk
  read, raising :class:`ChunkIntegrityError` on mismatch (classified
  RECOMPUTE by the resilience layer: the producing task re-runs). ``off``
  disables both and resume falls back to existence-only accounting.
  Resolution order: ``CUBED_TPU_INTEGRITY`` env var (operator override) >
  ``activate()``/``Spec(integrity=...)`` (process-global, armed by
  ``Plan.execute`` for the compute's duration and exported to the env so
  spawned workers inherit it; distributed task messages mirror it to
  pre-started fleets) > the ``write`` default.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
import zlib
from typing import Any, Optional

from ..observability.accounting import current_scope, record_scoped_counter

logger = logging.getLogger(__name__)

#: env var overriding the integrity mode everywhere (and how spawned worker
#: processes inherit a Spec-level setting)
INTEGRITY_ENV_VAR = "CUBED_TPU_INTEGRITY"

MODES = ("off", "write", "verify")
DEFAULT_MODE = "write"

#: sidecar manifest shard prefix/suffix (dot-prefixed: plain Zarr v2
#: readers and the chunk-name scan both ignore it)
MANIFEST_PREFIX = ".manifest-"
MANIFEST_SUFFIX = ".json"


class ChunkIntegrityError(RuntimeError):
    """A stored chunk failed integrity verification.

    ``kind`` is ``"checksum"`` (content mismatch — bit rot, torn write,
    codec-level corruption) or ``"missing"`` (the manifest says the chunk
    was written but no file exists — e.g. it was quarantined, or the store
    lost it). Carries enough structure (``store``, ``chunk_key``) for the
    runtime to re-run the producing task (RECOMPUTE classification), and
    survives pickling across process/fleet boundaries.
    """

    def __init__(
        self,
        message: str,
        store: Optional[str] = None,
        chunk_key: Optional[str] = None,
        kind: str = "checksum",
        expected: Any = None,
        actual: Any = None,
    ):
        super().__init__(message)
        self.store = store
        self.chunk_key = chunk_key
        self.kind = kind
        self.expected = expected
        self.actual = actual

    def __reduce__(self):
        return (
            ChunkIntegrityError,
            (
                self.args[0] if self.args else "",
                self.store,
                self.chunk_key,
                self.kind,
                self.expected,
                self.actual,
            ),
        )

    @property
    def wire_payload(self) -> dict:
        """Plain-dict form that rides distributed error frames, so the
        coordinator-side retry machinery can locate the producing task
        without sharing the exception object."""
        return {
            "store": self.store,
            "chunk_key": self.chunk_key,
            "kind": self.kind,
            "expected": self.expected,
            "actual": self.actual,
        }


def checksum(data: bytes) -> int:
    """The chunk checksum: CRC-32 of the bytes as stored."""
    return zlib.crc32(data) & 0xFFFFFFFF


# ----------------------------------------------------------------------
# mode resolution
# ----------------------------------------------------------------------

_lock = threading.Lock()
_active_mode: Optional[str] = None


def _validate(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(
            f"invalid integrity mode {mode!r}; expected one of {MODES}"
        )
    return mode


def current_mode() -> str:
    """The effective integrity mode for this process (env > activated >
    default). A malformed env value raises loudly — a typo silently
    downgrading integrity to the default would be worse than an error."""
    raw = os.environ.get(INTEGRITY_ENV_VAR)
    if raw:
        return _validate(raw)
    if _active_mode is not None:
        return _active_mode
    return DEFAULT_MODE


def verify_reads_active() -> bool:
    """True when task-scope chunk reads must be verified: mode ``verify``
    and a task scope is active (plan-construction metadata IO and
    client-side result fetches are never verified — the same boundary the
    fault injector uses)."""
    return current_mode() == "verify" and current_scope() is not None


def activate(mode: Optional[str], export_env: bool = False) -> None:
    """Set the process-global integrity mode (and, with ``export_env``,
    the env var so child processes spawned afterwards inherit it)."""
    global _active_mode
    if mode is not None:
        _validate(mode)
    with _lock:
        _active_mode = mode
    if export_env:
        if mode is None:
            os.environ.pop(INTEGRITY_ENV_VAR, None)
        else:
            os.environ[INTEGRITY_ENV_VAR] = mode


def wire_mode() -> str:
    """The client's resolved mode, attached to every distributed task
    message so pre-started fleet workers mirror the client exactly."""
    return current_mode()


def arm_from_wire(mode: Optional[str]) -> None:
    """Fleet-worker side: adopt the mode a task message carried."""
    global _active_mode
    if mode is not None:
        try:
            _validate(mode)
        except ValueError:
            logger.warning("ignoring invalid integrity mode from wire: %r", mode)
            return
    with _lock:
        _active_mode = mode


class scoped:
    """Arm an integrity mode for a ``with`` block (``Plan.execute`` uses
    this for ``Spec(integrity=...)``); ``None`` is a no-op so callers need
    no conditional. Like fault injection, arming is process-global for the
    duration — tasks run on arbitrary pool threads."""

    def __init__(self, mode: Optional[str] = None, export_env: bool = False):
        self._mode = mode
        self._export_env = export_env

    def __enter__(self):
        if self._mode is None:
            return None
        self._prev = _active_mode
        self._prev_env = os.environ.get(INTEGRITY_ENV_VAR)
        # the env var is the OPERATOR's override and wins over Spec-level
        # modes everywhere (current_mode resolution order) — so when it is
        # already set, arming must not clobber it: the process-global mode
        # is recorded (harmless, env shadows it) but the env passes through
        # to this process and every spawned worker untouched
        activate(
            self._mode,
            export_env=self._export_env and self._prev_env is None,
        )
        return self._mode

    def __exit__(self, *exc) -> None:
        if self._mode is None:
            return
        global _active_mode
        with _lock:
            _active_mode = self._prev
        if self._export_env:
            if self._prev_env is None:
                os.environ.pop(INTEGRITY_ENV_VAR, None)
            else:
                os.environ[INTEGRITY_ENV_VAR] = self._prev_env


# ----------------------------------------------------------------------
# manifest shards
# ----------------------------------------------------------------------

#: this process's writer id (shard filename component); lazy so forked
#: children that never write share nothing
_writer_id: Optional[str] = None

#: store root -> {"entries": {...}, "lock": Lock}; one shard per
#: (process, array store)
_shards: dict = {}
_shards_lock = threading.Lock()


def _get_writer_id() -> str:
    global _writer_id
    if _writer_id is None or _writer_id.split("-", 1)[0] != str(os.getpid()):
        # pid guard: a forked child must not reuse (and clobber) the
        # parent's shard name
        _writer_id = f"{os.getpid()}-{uuid.uuid4().hex[:6]}"
        with _shards_lock:
            _shards.clear()
    return _writer_id


def shard_name() -> str:
    return f"{MANIFEST_PREFIX}{_get_writer_id()}{MANIFEST_SUFFIX}"


def record_checksum(io, store_root: str, chunk_key: str, data: bytes) -> dict:
    """Record ``chunk_key``'s checksum in this process's manifest shard for
    the array at ``store_root``. Returns the recorded entry.

    Local stores append one JSONL line — O(1) per chunk write, no fsync
    (losing an unsynced manifest tail costs recomputation on resume, never
    correctness; the chunk's own write is the fsynced, load-bearing one),
    and a torn trailing line from a crash is skipped by the line-tolerant
    loader without poisoning earlier lines. IO backends without append
    (object stores) fall back to atomically rewriting the whole shard
    document. Shard writes bypass fault injection (``inject=False``) so a
    chaos profile's "chunk write failure rate" means chunk writes."""
    name = shard_name()
    with _shards_lock:
        state = _shards.get(store_root)
        if state is None:
            state = _shards[store_root] = {"entries": {}, "lock": threading.Lock()}
    entry = {"c": checksum(data), "n": len(data), "t": time.time()}
    with state["lock"]:
        state["entries"][chunk_key] = entry
        if hasattr(io, "append_bytes"):
            line = json.dumps({"k": chunk_key, **entry}) + "\n"
            io.append_bytes(name, line.encode())
        else:
            payload = json.dumps(
                {"writer": _get_writer_id(), "entries": state["entries"]}
            ).encode()
            io.write_bytes_atomic(name, payload, inject=False)
    return entry


def _merge_entry(entries: dict, key, ent) -> None:
    """Fold one (key, entry) into the merged view, last-write-wins by
    recorded timestamp on identical keys."""
    if not isinstance(ent, dict) or "c" not in ent or "n" not in ent:
        return
    if not isinstance(key, str):
        return
    prev = entries.get(key)
    if prev is None or ent.get("t", 0) >= prev.get("t", 0):
        entries[key] = ent


def load_manifest(io) -> tuple[dict, bool]:
    """Merge all manifest shards of one array: ``(entries, had_shards)``.

    ``entries`` maps chunk key -> ``{"c": crc, "n": nbytes, "t": ts}``,
    last-write-wins by recorded timestamp on identical keys. ``had_shards``
    is False when no shard file exists at all (an array written with
    integrity off, or by a pre-integrity version) — callers fall back to
    existence-only accounting then. Both shard formats are read: JSONL
    (one ``{"k", "c", "n", "t"}`` line per write — local stores) and a
    whole-document ``{"entries": {...}}`` rewrite (object stores).
    Undecodable content — a whole bad shard, or any single torn/garbage
    line — is skipped: those chunks lose their entries and verify as
    untrustworthy, never valid. Corrupt manifest data can cost
    recomputation, never correctness.
    """
    names = [
        n
        for n in io.list_names()
        if n.startswith(MANIFEST_PREFIX) and n.endswith(MANIFEST_SUFFIX)
    ]
    entries: dict = {}
    had_shards = bool(names)
    for name in names:
        try:
            raw = io.read_bytes(name)
        except OSError:
            logger.warning("skipping unreadable manifest shard %s", name)
            continue
        try:
            # whole-document shard (object stores; also external tools
            # that pretty-print — any shape, as long as it has "entries")
            doc = json.loads(raw)
            if isinstance(doc, dict) and isinstance(doc.get("entries"), dict):
                for key, ent in doc["entries"].items():
                    _merge_entry(entries, key, ent)
                continue
        except (ValueError, UnicodeDecodeError):
            pass
        bad_lines = 0
        for line in raw.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
                if not isinstance(doc, dict):
                    raise ValueError("not an object")
            except (ValueError, UnicodeDecodeError):
                bad_lines += 1
                continue
            _merge_entry(entries, doc.get("k"), doc)
        if bad_lines:
            logger.warning(
                "manifest shard %s: skipped %d undecodable line(s) (their "
                "chunks will verify as untrustworthy and recompute)",
                name, bad_lines,
            )
    return entries, had_shards


def quarantine_chunk(io, chunk_key: str, store: str = "") -> Optional[str]:
    """Rename a bad chunk file out of the chunk namespace
    (``<key>.quarantine.<ts>``), count it, and return the new name (None if
    the rename failed — e.g. a concurrent quarantine already moved it)."""
    qname = f"{chunk_key}.quarantine.{int(time.time() * 1000)}"
    try:
        io.rename(chunk_key, qname)
    except OSError:
        logger.warning(
            "could not quarantine corrupt chunk %s/%s", store, chunk_key
        )
        return None
    record_scoped_counter("chunks_quarantined")
    logger.warning("quarantined corrupt chunk %s/%s -> %s", store, chunk_key, qname)
    return qname
