"""The JAX/TPU executor: HBM-resident execution of the plan.

Design (SURVEY.md section 7; north star in BASELINE.json):

- **Residency.** Arrays live as ``jax.Array``s in HBM, keyed by their target
  store path. Zarr is only touched at plan boundaries: sources are loaded once,
  and requested outputs are flushed at the end. Intermediates never hit
  storage (the reference pays a full storage round-trip per op).
- **Whole-array fast path.** Ops whose kernel is shape-invariant (elementwise /
  broadcasting chains, including everything the optimizer fused) and whose
  block mapping is 1:1-with-broadcast run as ONE jitted call on whole resident
  arrays — XLA fuses the entire chain; intermediates stay in registers/HBM.
- **Chunked fallback.** Any other op (tree-reduce combines, map_direct,
  index, reshape, block_id kernels) runs per output chunk: inputs are sliced
  from resident arrays on device (XLA slice, no host transfer), the chunk
  kernel is jitted once per shape, and results assemble by concatenation.
- **Rechunk is free.** Resident arrays are whole arrays, so a rechunk op is
  pure metadata (an alias). Under a device mesh the corresponding physical
  movement is a resharding (``device_put`` with a new NamedSharding), which
  XLA lowers to all-to-all over ICI — not a storage round-trip.
- **Mesh / SPMD.** With ``mesh`` set, resident arrays are placed with a
  ``NamedSharding`` over the chunk grid's largest dim and whole-array kernels
  run under that sharding; XLA's partitioner inserts the collectives
  (psum trees for reductions riding ICI).
- **Spill path.** If HBM residency would exceed ``device_mem``, least-recently
  used arrays are flushed to their Zarr targets and dropped; reads fall back
  to storage. This keeps the bounded-memory story for arrays larger than HBM.
- **Scheduling.** This executor always keeps op ordering and ignores
  ``Spec(scheduler="dataflow")``: whole (fused) segments compile to single
  XLA programs over HBM-resident arrays, so there is no per-chunk task
  frontier for the chunk-granular scheduler to overlap — XLA's own
  scheduler already overlaps at the instruction level inside each program
  (``runtime/dataflow.py`` is the multi-host fleet's analogue).

Reference parity: replaces cubed's serverless executors
(cubed/runtime/executors/*) with a device-mesh substrate.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import math
import threading
import time
from collections import Counter
from typing import Any, Dict, Iterator, Optional

import numpy as np

from ...chunks import blockdims_from_blockshape
from ...primitive.blockwise import BlockwiseSpec, apply_blockwise
from ...primitive.rechunk import copy_read_to_write
from ...core.plan import create_zarr_array
from ...storage.store import ZarrV2Array
from ...storage.virtual import (
    VirtualEmptyArray,
    VirtualFullArray,
    VirtualInMemoryArray,
    VirtualOffsetsArray,
)
from ...storage.zarr import LazyZarrArray
from ...utils import get_item
from ..pipeline import ResumeState, visit_nodes
from ..types import (
    Callback,
    DagExecutor,
    OperationEndEvent,
    OperationStartEvent,
    TaskEndEvent,
    callbacks_on,
)
from ..utils import fire_task_start

logger = logging.getLogger(__name__)


class _TraceAbort(Exception):
    """Raised when an op cannot be traced into a fused segment program
    (a host-side storage read or flush inside the trace); the segment falls
    back to eager per-op execution."""


def _jax():
    import jax

    return jax


class _Resident:
    """An HBM-resident array (or dict-of-arrays pytree) plus bookkeeping."""

    __slots__ = ("value", "nbytes", "last_used", "target")

    def __init__(self, value, nbytes: int, target):
        self.value = value
        self.nbytes = nbytes
        self.last_used = time.monotonic()
        self.target = target

    def touch(self):
        self.last_used = time.monotonic()


def _value_nbytes(value) -> int:
    if isinstance(value, dict):
        return sum(_value_nbytes(v) for v in value.values())
    return int(np.prod(value.shape)) * value.dtype.itemsize if value.shape else value.dtype.itemsize


class JaxExecutor(DagExecutor):
    """Executes the plan with HBM residency on the default jax backend.

    Parameters
    ----------
    mesh : jax.sharding.Mesh | None
        Shard resident arrays and whole-array kernels over this mesh.
    device_mem : int | None
        HBM residency budget in bytes (default: 75% of one device's memory,
        times the number of mesh devices when sharded).
    compute_dtype : str | None
        ``"float32"`` opts f64 plans into single-precision on-device
        compute ("f32 ingestion"): the executor runs every trace with jax
        x64 canonicalization disabled, so float64 kernels — including
        threefry random GENERATION, the dominant device cost of f64
        pipelines on v5e, which has no native f64 — produce float32, and
        results cast back to the declared f64 dtype at the Zarr store
        boundary (storage/store.py:380). Error bounds: each elementwise
        op contributes relative error <= f32 eps (1.19e-7); a k-element
        tree-sum accumulates <= (log2(k)+chunks) * eps * sum|a| absolute
        error — ~1e-4 relative for the 1e8-element bench reductions —
        versus ~1e-13 in f64. Accuracy-sensitive pipelines should stay on
        the default. Conformance runs exclude this mode (it intentionally
        diverges from the f64 oracle past f32 eps;
        tests/conformance/SKIPS.txt). Side effect: the first f32 DAG
        installs a process-global warnings filter ignoring jax's
        "requested dtype float64 is not available" message (see
        _install_f32_truncation_filter for why and what it costs).
    """

    def __init__(
        self,
        mesh=None,
        device_mem: Optional[int] = None,
        fuse_plan: bool = True,
        compute_dtype: Optional[str] = None,
        matmul_precision: Optional[str] = None,
        **kwargs,
    ):
        self.mesh = mesh
        self.device_mem = device_mem
        if compute_dtype not in (None, "float32", "float64"):
            raise ValueError(
                "compute_dtype must be None, 'float32' or 'float64'; "
                f"got {compute_dtype!r}"
            )
        self.compute_dtype = compute_dtype
        if matmul_precision not in (
            None, "bfloat16", "bfloat16_3x", "tensorfloat32", "float32",
            "highest", "default",
        ):
            raise ValueError(
                "matmul_precision must be one of None, 'bfloat16', "
                "'bfloat16_3x', 'tensorfloat32', 'float32', 'highest', "
                f"'default'; got {matmul_precision!r}"
            )
        #: contraction precision for every dot/conv in the DAG, applied as
        #: the thread-local ``jax.default_matmul_precision`` scope. On TPU
        #: the MXU is a native bf16xbf16->f32 systolic array: 'bfloat16'
        #: is one MXU pass per contraction (fastest, ~3 decimal digits of
        #: input precision), 'bfloat16_3x' error-compensates with 3 passes,
        #: 'highest' emulates full f32 (6 passes). Combine with
        #: ``compute_dtype='float32'`` for the canonical f64-source opt-in:
        #: f32 storage/elementwise, bf16 MXU contractions.
        self.matmul_precision = matmul_precision
        #: trace consecutive traceable ops into ONE jitted XLA program
        self.fuse_plan = fuse_plan
        if "use_pallas" in kwargs:
            # removed in round 5 (see BENCH_PROFILE.md "Pallas verdict");
            # a silent no-op would misread as the kernels running
            import warnings

            warnings.warn(
                "use_pallas was removed: the Pallas streaming-reduction "
                "kernels were retired on measured evidence "
                "(benchmarks/BENCH_PROFILE.md); reductions use XLA's "
                "fused combines",
                FutureWarning,
                stacklevel=2,
            )
            kwargs.pop("use_pallas")
        self.kwargs = kwargs
        self._tracing = False
        self._prepared_bases: Dict[int, Any] = {}
        self._placement = None  # factorized placement mesh, built lazily
        #: execution-path counters for the last ``execute_dag`` call, reported
        #: via ``ComputeEndEvent.executor_stats``. Keys: ``segments_traced``,
        #: ``segments_compiled``, ``segment_cache_hits``, ``segment_struct_hits``,
        #: ``segment_mem_aborts``, ``segment_hbm_footprint``,
        #: ``whole_array_hits``, ``whole_concat_hits``, ``batched_ops``,
        #: ``chunked_ops``, ``rechunk_alias`` (zero-copy), ``rechunk_virtual``
        #: (materialized), ``eager_ops``, and the
        #: failure counters ``eager_fallbacks`` / ``trace_failures`` /
        #: ``whole_array_errors`` / ``batched_errors`` / ``whole_select_errors``
        #: / ``jit_kernel_errors``
        #: (``eager_fallbacks`` must stay 0 on fused-path plans — tests pin it)
        self.stats: Counter = Counter()

    @property
    def name(self) -> str:
        return "jax"

    # ------------------------------------------------------------------

    def _budget(self) -> int:
        if self.device_mem is not None:
            return self.device_mem
        jax = _jax()
        try:
            stats = jax.devices()[0].memory_stats()
            per_device = int(stats["bytes_limit"] * 0.75)
        except Exception:
            per_device = 8 * 2**30  # CPU/virtual devices: pick a sane default
        n = len(self.mesh.devices.flat) if self.mesh is not None else 1
        return per_device * n

    def _placement_mesh(self):
        """Prime-factorized view of the mesh used for all array placement
        (parallel/mesh.py:factorized_mesh) — cached per executor."""
        if self._placement is None:
            from ...parallel.mesh import factorized_mesh

            self._placement = factorized_mesh(self.mesh)
        return self._placement

    def _keep_sharding_constraint(self, value, target):
        """Pin a traced segment output to the executor's mesh sharding.

        Batched kernels gather/stack/reassemble chunks inside the trace,
        after which XLA may propagate a REPLICATED layout to the output.
        Replication is only a memory detail single-process, but under
        multi-controller SPMD the per-host flush derives chunk ownership
        from the output's sharding — a replicated output degrades to
        "host 0 writes everything". Constraining the kept outputs keeps
        ownership (and the write path of docs/multihost.md) split across
        hosts."""
        jax = _jax()
        if self.mesh is None or isinstance(value, dict) or target is None:
            return value
        shape = tuple(getattr(target, "shape", ()) or ())
        if not shape or tuple(value.shape) != shape:
            return value
        cs = (
            blockdims_from_blockshape(shape, target.chunks)
            if getattr(target, "chunks", None)
            else None
        )
        sharding = self._sharding_for(shape, cs)
        if sharding is None:
            return value
        return jax.lax.with_sharding_constraint(value, sharding)

    def _sharding_for(self, shape: tuple[int, ...], chunkset=None):
        """The chunk-grid-aligned sharding policy (parallel/mesh.py).

        One policy for the whole executor: dims ranked by block count then
        extent, mesh prime factors stacked per-dim, so ragged grids (e.g. the
        vorticity slice (499, 450, 400)) shard instead of replicating.
        """
        if self.mesh is None or not shape:
            return None
        from ...parallel.mesh import sharding_for_chunks

        return sharding_for_chunks(self._placement_mesh(), chunkset, shape)

    def _virtual_to_device(self, arr):
        """Materialize a whole virtual array on device, mesh-aware (sharded
        placement under a mesh); None when ``arr`` isn't a virtual type."""
        if isinstance(arr, VirtualInMemoryArray):
            return self._device_put(np.asarray(arr.array), tuple(arr.shape))
        if isinstance(arr, (VirtualEmptyArray, VirtualFullArray)):
            fill = getattr(arr, "fill_value", 0)
            return self._full(tuple(arr.shape), fill, arr.dtype)
        return None

    def _full(self, shape, fill_value, dtype):
        """Materialize a constant array, sharded over the mesh if present."""
        jax = _jax()
        sharding = self._sharding_for(tuple(shape))
        if sharding is not None:
            fn = jax.jit(
                lambda: jax.numpy.full(shape, fill_value, dtype=dtype),
                out_shardings=sharding,
            )
            return fn()
        return jax.numpy.full(shape, fill_value, dtype=dtype)

    def _device_put(self, value, shape, chunkset=None):
        jax = _jax()
        sharding = self._sharding_for(shape, chunkset)
        if sharding is not None:
            if isinstance(value, dict):
                return {
                    k: jax.device_put(v, self._sharding_for(v.shape, chunkset))
                    for k, v in value.items()
                }
            return jax.device_put(value, sharding)
        if isinstance(value, dict):
            return {k: jax.device_put(v) for k, v in value.items()}
        return jax.device_put(value)

    # ------------------------------------------------------------------

    def execute_dag(
        self,
        dag,
        callbacks: Optional[list[Callback]] = None,
        array_names=None,
        resume=None,
        spec=None,
        **kwargs,
    ) -> None:
        jax = _jax()
        with contextlib.ExitStack() as stack:
            if self.compute_dtype == "float32" and jax.config.jax_enable_x64:
                # f32 ingestion: run the whole DAG with x64 canonicalization
                # off. ``jax.enable_x64(False)`` is THREAD-LOCAL, so a
                # concurrent thread computing with a default executor keeps
                # f64, and an exception anywhere in the DAG restores the
                # flag on context exit. The structural segment cache keys on
                # jax_enable_x64 (thread-local-aware), so f32 and f64
                # executions of one plan shape never share a compiled
                # program. jax warns per f64 request it truncates; that's
                # this mode working as designed, so silence it — with a
                # once-per-process permanent filter rather than
                # warnings.catch_warnings, whose save/restore of GLOBAL
                # filter state races concurrent executor threads (a
                # restore landing mid-flight would re-enable or swallow
                # another thread's filters). See the helper's docstring
                # for the cost: the filter stays installed process-wide,
                # so other x64-off code in this process loses the same
                # truncation warning.
                _install_f32_truncation_filter()
                stack.enter_context(jax.enable_x64(False))
            if self.matmul_precision is not None:
                # thread-local contraction-precision scope (MXU pass count)
                stack.enter_context(
                    jax.default_matmul_precision(self.matmul_precision)
                )
            if self.mesh is not None:
                # RNG kernels must stay fused threefry under a mesh: the
                # CPU Philox pure_callback path (random.generation_mode)
                # doesn't partition across an SPMD program
                from ...random import _mode_scope

                stack.enter_context(_mode_scope("threefry"))
            return self._execute_dag_inner(
                dag, callbacks, array_names, resume, spec, **kwargs
            )

    def _execute_dag_inner(
        self,
        dag,
        callbacks: Optional[list[Callback]] = None,
        array_names=None,
        resume=None,
        spec=None,
        journal=None,
        **kwargs,
    ) -> None:
        jax = _jax()
        self.stats = Counter()
        resident: Dict[str, _Resident] = {}
        budget = self._budget()

        # map array-node name -> target, to know what must be flushed
        requested_stores = set()
        node_targets = {}
        for name, d in dag.nodes(data=True):
            if d.get("type") == "array" and d.get("target") is not None:
                node_targets[name] = d["target"]
                if array_names is None or name in array_names:
                    t = d["target"]
                    if isinstance(t, (LazyZarrArray, ZarrV2Array)):
                        requested_stores.add(str(t.store))

        segment: list = []

        def run_segment():
            if segment:
                ops, segment[:] = list(segment), []
                self._run_segment(
                    ops, dag, resident, budget, requested_stores, callbacks
                )

        def run_eager(name, node):
            primitive_op = node["primitive_op"]
            pipeline = primitive_op.pipeline
            callbacks_on(
                callbacks, "on_operation_start",
                OperationStartEvent(name, primitive_op.num_tasks),
            )
            fire_task_start(callbacks, name, num_tasks=primitive_op.num_tasks)
            t0 = time.time()
            self.stats["eager_ops"] += 1
            # observe-only guard (see _run_segment): measure, never enforce
            from ..memory import task_guard

            with task_guard(f"eager:{name}", observe_only=True) as guard:
                if pipeline.function is apply_blockwise:
                    self._exec_blockwise(primitive_op, resident, budget)
                elif pipeline.function is copy_read_to_write:
                    self._exec_rechunk(primitive_op, resident, budget)
                elif pipeline.function is create_zarr_array:
                    # create metadata only for arrays that will actually be
                    # persisted; residency replaces the rest
                    for lazy in pipeline.mappable:
                        if str(lazy.store) in requested_stores:
                            lazy.create(mode="a")
                else:  # pragma: no cover - unknown pipeline type: run as-is
                    for m in pipeline.mappable:
                        pipeline.function(m, config=pipeline.config)
            t1 = time.time()
            callbacks_on(
                callbacks, "on_task_end",
                TaskEndEvent(
                    array_name=name,
                    num_tasks=primitive_op.num_tasks,
                    task_create_tstamp=t0,
                    function_start_tstamp=t0,
                    function_end_tstamp=t1,
                    task_result_tstamp=t1,
                    executor=self.name,
                    guard_mem_peak=guard.measured,
                ),
            )
            callbacks_on(
                callbacks, "on_operation_end",
                OperationEndEvent(name, primitive_op.num_tasks),
            )

        # resume is op-granular here (segments run as whole-array device
        # programs, so per-task skip doesn't apply), but the skip decision
        # is still checksum-verified: a corrupt persisted output re-runs
        # (and is quarantined by the scan) instead of being trusted; a
        # loaded compute journal (resume_from_journal) further requires an
        # op to be journaled fully complete before it may skip
        resume_state = (
            ResumeState(quarantine=True, journal=journal) if resume else None
        )
        cancellation = kwargs.get("cancellation")
        for name, node in visit_nodes(dag, resume=resume, state=resume_state):
            if cancellation is not None and cancellation.cancelled:
                # cooperative abort at the op/segment boundary (a fused
                # device segment is not an interruptible unit): flushes
                # nothing partial — materialized arrays are whole
                from ..cancellation import abort as _cancel_abort

                raise _cancel_abort(cancellation)
            primitive_op = node["primitive_op"]
            kind = self._classify(primitive_op) if self.fuse_plan else "eager"
            if kind == "trace":
                segment.append((name, node))
            else:
                run_segment()
                run_eager(name, node)
        run_segment()

        # flush requested outputs that are still resident
        for store, res in list(resident.items()):
            if store in requested_stores:
                self._flush(res)

    # ------------------------------------------------------------------
    # plan fusion: trace runs of ops into ONE jitted XLA program
    # ------------------------------------------------------------------

    def _classify(self, primitive_op) -> str:
        """'trace' if this op's execution is a pure device computation given
        resident inputs (so it can join a fused segment program); 'eager'
        otherwise. Decisions use plan metadata only, never values."""
        pipeline = primitive_op.pipeline
        if pipeline.function is copy_read_to_write:
            return "trace"  # rechunk: resident alias (or preloaded source)
        if pipeline.function is not apply_blockwise:
            return "eager"  # create-arrays (host metadata) / unknown
        f = pipeline.config.function
        if getattr(f, "host_data_nbytes", 0) > 2**18:
            # kernel closes over non-trivial host data (from_array): tracing
            # would bake it into the program as CONSTANTS — bloating the
            # program, defeating the structural cache (the fingerprint and
            # compiled executable become data-dependent), and inviting
            # XLA's compile-time constant folding to evaluate whole op
            # chains (a sort network over a 4 MB baked source measured
            # MINUTES of folding). Run the source op eagerly: it
            # materializes once as a resident device array and downstream
            # segments take it as a program INPUT.
            return "eager"
        side_inputs = getattr(f, "side_inputs", None)
        if side_inputs and not (
            (
                len(side_inputs) == 1
                and (
                    getattr(f, "resident_identity", False)
                    or getattr(f, "whole_select", None) is not None
                )
            )
            or getattr(f, "whole_concat", None) is not None
        ):
            # generic map_direct: the task body reads storage directly
            return "eager"
        return "trace"

    def _segment_sources(self, ops) -> tuple[list, list]:
        """(concrete source arrays to preload, offsets arrays to hoist)."""
        preload, offsets = [], []
        seen = set()
        for _, node in ops:
            pipeline = node["primitive_op"].pipeline
            if pipeline.function is copy_read_to_write:
                proxies = [pipeline.config.read]
            else:
                spec = pipeline.config
                proxies = list(spec.reads_map.values())
                proxies += [
                    type("P", (), {"array": a})
                    for a in (getattr(spec.function, "side_inputs", None) or [])
                ]
            for proxy in proxies:
                arr = proxy.array
                key = str(getattr(arr, "store", id(arr)))
                if key in seen:
                    continue
                seen.add(key)
                if isinstance(arr, VirtualOffsetsArray):
                    offsets.append(arr)
                elif isinstance(arr, (ZarrV2Array, LazyZarrArray)):
                    preload.append(arr)
        return preload, offsets

    def _preload(self, arr, resident, budget) -> bool:
        """Load a concrete storage array onto the device (outside any trace)
        so segment programs take it as an input, not a baked constant.

        Under a mesh, ingestion goes through ``make_array_from_callback``:
        each process materializes only the storage regions its addressable
        shards cover — the per-host Zarr IO sharding seam of
        docs/multihost.md (on one host this degenerates to reading
        everything, shard by shard)."""
        jax = _jax()
        key = str(arr.store)
        if key in resident:
            return True
        try:
            concrete = arr.open() if isinstance(arr, LazyZarrArray) else arr
        except FileNotFoundError:
            return False
        nbytes = int(np.prod(concrete.shape or (1,))) * concrete.dtype.itemsize
        if nbytes > budget:
            return False
        cs = (
            blockdims_from_blockshape(concrete.shape, concrete.chunks)
            if concrete.shape and getattr(concrete, "chunks", None)
            else None
        )
        shape = tuple(concrete.shape)
        sharding = self._sharding_for(shape, cs)
        if (
            sharding is not None
            and shape
            and concrete.dtype.fields is None
        ):
            value = jax.make_array_from_callback(
                shape, sharding, lambda idx: np.asarray(concrete[idx])
            )
            self._admit(resident, key, value, arr, budget)
            return True
        data = concrete[...] if concrete.shape else concrete[()]
        if data.dtype.fields is not None:
            value = {
                k: self._device_put(np.ascontiguousarray(data[k]), data.shape, cs)
                for k in data.dtype.names
            }
        else:
            value = self._device_put(data, data.shape, cs)
        self._admit(resident, key, value, arr, budget)
        return True

    def _segment_keep(self, ops, dag, requested_stores) -> Dict[str, Any]:
        """store -> target for segment outputs that must materialize: arrays
        consumed by ops outside the segment or requested as plan outputs."""
        seg_names = {name for name, _ in ops}
        keep: Dict[str, Any] = {}
        for name, _ in ops:
            for arr_name in dag.successors(name):
                target = dag.nodes[arr_name].get("target")
                if target is None or not hasattr(target, "store"):
                    continue
                store = str(target.store)
                consumers = set(dag.successors(arr_name))
                if store in requested_stores or not consumers <= seg_names:
                    keep[store] = target
        return keep

    def _run_segment(
        self, ops, dag, resident, budget, requested_stores, callbacks
    ) -> None:
        jax = _jax()
        t0 = time.time()
        for name, node in ops:
            callbacks_on(
                callbacks, "on_operation_start",
                OperationStartEvent(name, node["primitive_op"].num_tasks),
            )
            fire_task_start(
                callbacks, name, num_tasks=node["primitive_op"].num_tasks
            )

        # observe-only memory guard: the fused segment is one program, not
        # a retryable task, so enforcement (which degrades via retry) makes
        # no sense here — but the host-RSS measurement still feeds the
        # projected-vs-measured summary and observe-mode warnings
        from ..memory import task_guard

        seg_key = ",".join(name for name, _ in ops)
        with task_guard(f"segment:{seg_key}", observe_only=True) as guard:
            traced = False
            if len(ops) > 0:
                try:
                    traced = self._trace_segment(
                        ops, dag, resident, budget, requested_stores
                    )
                    if traced:
                        self.stats["segments_traced"] += 1
                    else:
                        self.stats["segment_mem_aborts"] += 1
                        from ...observability.collect import record_decision

                        record_decision(
                            "jax_segment_mem_abort", segment=seg_key
                        )
                except Exception:
                    logger.exception(
                        "segment trace failed; falling back to eager"
                    )
                    self.stats["trace_failures"] += 1
                    self.stats["eager_fallbacks"] += 1
                    from ...observability.collect import record_decision

                    record_decision("jax_eager_fallback", segment=seg_key)
                    traced = False
            if not traced:
                for name, node in ops:
                    primitive_op = node["primitive_op"]
                    if primitive_op.pipeline.function is apply_blockwise:
                        self._exec_blockwise(primitive_op, resident, budget)
                    else:
                        self._exec_rechunk(primitive_op, resident, budget)

        t1 = time.time()
        # the segment ran as ONE fused program; apportion its wall time across
        # the member ops by task count so history/timeline totals sum to the
        # real segment duration instead of len(ops) x duration
        total_tasks = sum(node["primitive_op"].num_tasks for _, node in ops) or 1
        elapsed = t1 - t0
        start = t0
        for name, node in ops:
            num_tasks = node["primitive_op"].num_tasks
            end = start + elapsed * (num_tasks / total_tasks)
            callbacks_on(
                callbacks, "on_task_end",
                TaskEndEvent(
                    array_name=name,
                    num_tasks=num_tasks,
                    task_create_tstamp=start,
                    function_start_tstamp=start,
                    function_end_tstamp=end,
                    task_result_tstamp=end,
                    executor=self.name,
                    # the guard measured the WHOLE segment: attributing
                    # that aggregate to each member op would flag
                    # correctly-modelled ops as over-projected, so per-op
                    # attribution only exists for single-op segments
                    guard_mem_peak=guard.measured if len(ops) == 1 else None,
                ),
            )
            callbacks_on(
                callbacks, "on_operation_end",
                OperationEndEvent(name, num_tasks),
            )
            start = end

    def _structural_key(
        self, ops, dag, in_keys, resident, keep_list, seeded
    ) -> Optional[str]:
        """A pre-trace fingerprint of the segment program.

        Tracing + lowering a large fused segment costs ~0.6 s of pure Python
        per compute — ~80% of the warm vorticity benchmark — even when the
        compiled executable is cached by HLO hash. This key lets a repeat
        compute of a structurally identical plan skip tracing entirely.

        It must capture EVERYTHING that shapes the traced program. Op
        kernels and block functions are fingerprinted by cloudpickle (code
        objects + closure values); quantities that provably do NOT enter the
        program are masked so they don't defeat the cache:

        - array store paths (asserted out of the jitted signature by design;
          masked to order-of-first-use tokens),
        - RNG seeds (``VirtualOffsetsArray.base``) — ONLY for arrays whose
          every consuming kernel honors seed hoisting (``traced_offsets``);
          otherwise the base may be baked as a constant and stays in the key,
        - Spec resources (work_dir / mem budgets: plan-time-only).

        Returns None when fingerprinting fails (caller traces as usual).
        """
        import hashlib
        import io

        try:
            import cloudpickle
        except Exception:
            return None
        jax = _jax()

        from ...random import generation_mode as _generation_mode
        from ...core.plan import Plan
        from ...spec import Spec
        from ...utils import StackSummary

        # seed-hoist eligibility: every consumer must declare traced_offsets
        honored: Dict[int, bool] = {}
        for _, node in ops:
            pipeline = node["primitive_op"].pipeline
            if pipeline.function is not apply_blockwise:
                continue
            spec_ = pipeline.config
            f_traced = getattr(spec_.function, "traced_offsets", False)
            for proxy in spec_.reads_map.values():
                arr = proxy.array
                if isinstance(arr, VirtualOffsetsArray):
                    honored[id(arr)] = honored.get(id(arr), True) and f_traced
        maskable = {
            id(a) for a in seeded if honored.get(id(a), False)
        }

        tokens: Dict[str, str] = {}

        def tok(path: str) -> str:
            return tokens.setdefault(path, f"@{len(tokens)}")

        # gensym identifiers to canonicalize: the dag's node names plus every
        # reads_map key encountered while pickling (fused kernels nest the
        # specs of fused-away ops whose names no longer exist as dag nodes)
        plan_names = {str(n) for n in dag.nodes}

        class _MaskingPickler(cloudpickle.CloudPickler):
            def reducer_override(self, obj):  # noqa: D401
                if isinstance(obj, BlockwiseSpec):
                    plan_names.update(obj.reads_map.keys())
                if isinstance(obj, (LazyZarrArray, ZarrV2Array)):
                    return (
                        str,
                        (
                            f"zarr:{tok(str(obj.store))}:{tuple(obj.shape)}:"
                            f"{obj.dtype}:{tuple(getattr(obj, 'chunks', ()) or ())}",
                        ),
                    )
                if isinstance(obj, VirtualOffsetsArray):
                    base = "H" if id(obj) in maskable else obj.base
                    return (str, (f"offsets:{tuple(obj.shape)}:{base}",))
                if isinstance(obj, (VirtualEmptyArray, VirtualFullArray)):
                    return (
                        str,
                        (
                            f"vconst:{tuple(obj.shape)}:{obj.dtype}:"
                            f"{getattr(obj, 'fill_value', 0)}",
                        ),
                    )
                if isinstance(obj, VirtualInMemoryArray):
                    h = hashlib.sha256(
                        np.ascontiguousarray(obj.array).tobytes()
                    ).hexdigest()
                    return (
                        str,
                        (f"vmem:{obj.array.shape}:{obj.array.dtype}:{h}",),
                    )
                if isinstance(obj, Spec):
                    return (str, ("spec",))
                if isinstance(obj, (Plan, StackSummary)):
                    # plan/provenance metadata reachable through kernel
                    # closures: never part of the traced program, and carries
                    # per-build noise (caller linenos, op display names)
                    return (str, ("meta",))
                # cloudpickle implements its function-by-value support in
                # reducer_override itself — delegate, don't swallow it
                return super().reducer_override(obj)

        def aval(v):
            if isinstance(v, dict):
                return tuple(
                    sorted((k, tuple(x.shape), str(x.dtype)) for k, x in v.items())
                )
            return (tuple(v.shape), str(v.dtype))

        payload: list = [("inputs", tuple((tok(k), aval(resident[k].value)) for k in in_keys))]
        for _, node in ops:
            pop = node["primitive_op"]
            pipeline = pop.pipeline
            if pipeline.function is copy_read_to_write:
                cfg = pipeline.config
                payload.append(("copy", cfg.read, cfg.write, pop.num_tasks))
            else:
                spec_ = pipeline.config
                payload.append(
                    (
                        "blockwise",
                        spec_.function,
                        spec_.block_function,
                        getattr(spec_, "shape_invariant", False),
                        tuple(spec_.writes),
                        tuple(
                            (n, spec_.reads_map[n])
                            for n in sorted(spec_.reads_map)
                        ),
                        pop.num_tasks,
                    )
                )
        payload.append(("keep", tuple(tok(k) for k in keep_list)))
        payload.append(("bases", len(seeded)))
        devices = (
            tuple(d.id for d in self.mesh.devices.flat)
            if self.mesh is not None
            else (jax.devices()[0].id,)
        )
        payload.append(
            (
                "env",
                bool(jax.config.jax_enable_x64),
                devices,
                jax.devices()[0].platform,
                # executor config that changes the traced program: the Pallas
                # opt-in swaps combine kernels; the mesh SHAPE (not just the
                # flat device order) determines shardings; the contraction
                # precision changes MXU pass counts inside the same HLO shape
                str(self.matmul_precision),
                tuple(self.mesh.devices.shape) if self.mesh is not None else None,
                tuple(self.mesh.axis_names) if self.mesh is not None else None,
                # RNG kernels branch on the resolved generation mode at
                # trace time (random.generation_mode), so threefry- and
                # philox-traced programs of one plan shape must not share
                # a cache entry
                _generation_mode(),
            )
        )
        buf = io.BytesIO()
        try:
            _MaskingPickler(buf).dump(payload)
        except Exception:
            return None
        # gensym'd plan identifiers ("array-012", "op-047", ...) differ
        # between structurally identical plans and leak into pickled closures
        # (block functions carry argument names, fused kernels nest inner
        # specs); canonicalize them by order of first appearance in the byte
        # stream. Only the EXACT identifiers present in this plan's dag are
        # rewritten — a user string can collide only by literally equaling
        # one of this plan's own gensym names.
        import re

        if not plan_names:
            return hashlib.sha256(buf.getvalue()).hexdigest()
        pattern = re.compile(
            b"|".join(
                re.escape(n.encode())
                for n in sorted(plan_names, key=len, reverse=True)
            )
        )
        seen: Dict[bytes, bytes] = {}

        def repl(m):
            s = m.group(0)
            if s not in seen:
                seen[s] = b"N%06d" % len(seen)
            return seen[s]

        norm = pattern.sub(repl, buf.getvalue())
        if _STRUCT_DEBUG is not None:
            _STRUCT_DEBUG.append(norm)
        return hashlib.sha256(norm).hexdigest()

    def _trace_segment(
        self, ops, dag, resident, budget, requested_stores
    ) -> bool:
        """Trace every op in the segment into one jitted program and run it.

        Returns False when the segment should run eagerly instead (memory
        pre-check failed); raises on trace failure (caller falls back)."""
        jax = _jax()

        preload, offsets_arrays = self._segment_sources(ops)
        for arr in preload:
            self._preload(arr, resident, budget)

        # memory pre-check: resident inputs + every segment output must fit
        # (tracing cannot evict; the eager path can spill instead)
        out_bytes = 0
        for _, node in ops:
            pipeline = node["primitive_op"].pipeline
            cfg = pipeline.config
            for w in getattr(cfg, "writes", None) or (cfg.write,):
                target = w.array
                shape = tuple(getattr(target, "shape", ()) or ())
                dt = np.dtype(target.dtype)
                out_bytes += int(np.prod(shape or (1,))) * dt.itemsize
        in_bytes = sum(r.nbytes for r in resident.values())
        if in_bytes + out_bytes > budget:
            return False

        # hoist per-plan RNG seeds (VirtualOffsetsArray.base) to inputs so the
        # traced program's HLO is seed-independent (stable compile cache).
        # base_vals is positional in topo order of first appearance, so the
        # jitted arg order is identical for structurally equal plans; id(arr)
        # is used only as an in-trace lookup key and never enters the program
        seeded = [a for a in offsets_arrays if getattr(a, "base", 0)]

        # positional inputs/outputs: store paths must not appear in the jitted
        # signature (they leak into arg/result debug info, which enters the
        # persistent-cache key — tempdir paths would bust the cache every run)
        in_keys = sorted(resident.keys())
        in_vals = [resident[k].value for k in in_keys]
        base_vals = [np.int64(arr.base) for arr in seeded]
        keep = self._segment_keep(ops, dag, requested_stores)
        produced = set()
        for _, node in ops:
            cfg = node["primitive_op"].pipeline.config
            for w in getattr(cfg, "writes", None) or (cfg.write,):
                produced.add(str(w.array.store))
        keep_list = [k for k in keep if k in produced or k in in_keys]

        # structural fast path: a repeat compute of an identical plan shape
        # reuses the compiled program WITHOUT re-tracing (the dominant warm
        # cost); store paths/seeds are re-bound positionally
        skey = self._structural_key(ops, dag, in_keys, resident, keep_list, seeded)
        with _CACHE_LOCK:
            cached_struct = (
                _STRUCT_CACHE.get(skey) if skey is not None else None
            )
        if cached_struct is not None:
            compiled, footprint = cached_struct
            self.stats["segment_struct_hits"] += 1
            if footprint:
                self.stats["segment_hbm_footprint"] = max(
                    self.stats.get("segment_hbm_footprint", 0), footprint
                )
            outs = compiled(in_vals, base_vals)
            for store, value in zip(keep_list, outs):
                self._admit(resident, store, value, keep[store], budget)
            return True

        targets = {k: resident[k].target for k in in_keys}

        def seg_fn(vals, bases):
            local = {
                k: _Resident(v, 0, targets[k]) for k, v in zip(in_keys, vals)
            }
            self._tracing = True
            self._prepared_bases = {
                id(arr): b for arr, b in zip(seeded, bases)
            }
            try:
                for _, node in ops:
                    primitive_op = node["primitive_op"]
                    if primitive_op.pipeline.function is apply_blockwise:
                        self._exec_blockwise(
                            primitive_op, local, budget=float("inf")
                        )
                    else:
                        self._exec_rechunk(
                            primitive_op, local, budget=float("inf")
                        )
            finally:
                self._tracing = False
                self._prepared_bases = {}
            return [
                self._keep_sharding_constraint(local[k].value, keep.get(k))
                for k in keep_list
            ]

        lowered = jax.jit(seg_fn).lower(in_vals, base_vals)
        try:
            import hashlib

            # key on HLO text PLUS the device set: the same program lowered
            # for a different mesh/device assignment must not reuse an
            # executable compiled for another topology
            devices = (
                tuple(d.id for d in self.mesh.devices.flat)
                if self.mesh is not None
                else (jax.devices()[0].id,)
            )
            fingerprint = lowered.as_text() + repr(devices)
            key = hashlib.sha256(fingerprint.encode()).hexdigest()
        except Exception:
            key = None
        with _CACHE_LOCK:
            cached = _SEGMENT_CACHE.get(key) if key is not None else None
        if cached is None:
            compiled = lowered.compile()
            self.stats["segments_compiled"] += 1
            footprint = _hbm_footprint(compiled)
            if key is not None:
                with _CACHE_LOCK:
                    if len(_SEGMENT_CACHE) >= 64:
                        _SEGMENT_CACHE.pop(next(iter(_SEGMENT_CACHE)))
                    _SEGMENT_CACHE[key] = (compiled, footprint)
        else:
            compiled, footprint = cached
            self.stats["segment_cache_hits"] += 1
        if footprint:
            self.stats["segment_hbm_footprint"] = max(
                self.stats.get("segment_hbm_footprint", 0), footprint
            )
        if skey is not None:
            with _CACHE_LOCK:
                if len(_STRUCT_CACHE) >= 64:
                    _STRUCT_CACHE.pop(next(iter(_STRUCT_CACHE)))
                _STRUCT_CACHE[skey] = (compiled, footprint)
        outs = compiled(in_vals, base_vals)
        for store, value in zip(keep_list, outs):
            self._admit(resident, store, value, keep[store], budget)
        return True

    # ------------------------------------------------------------------
    # blockwise
    # ------------------------------------------------------------------

    def _exec_blockwise(self, op, resident: Dict[str, _Resident], budget: int) -> None:
        jax = _jax()
        spec: BlockwiseSpec = op.pipeline.config
        target = spec.write.array  # LazyZarrArray (or concrete for store ops)
        out_shape = tuple(target.shape)
        out_store = str(target.store)

        side_inputs = getattr(spec.function, "side_inputs", None)

        # whole-op concat: every source resident -> ONE device concatenate
        # along the declared axis (traceable; no storage round-trip)
        wc_axis = getattr(spec.function, "whole_concat", None)
        if side_inputs and wc_axis is not None:
            jnp = jax.numpy
            vals = []
            for arr in side_inputs:
                skey = str(getattr(arr, "store", id(arr)))
                res = resident.get(skey)
                if res is not None and not isinstance(res.value, dict):
                    res.touch()
                    vals.append(res.value)
                    continue
                virt = self._virtual_to_device(arr)
                if virt is None:
                    vals = None
                    break
                vals.append(virt)
            if vals is not None:
                value = (
                    vals[0] if len(vals) == 1 else jnp.concatenate(vals, axis=wc_axis)
                )
                if tuple(value.shape) == out_shape:
                    self.stats["whole_concat_hits"] += 1
                    self._admit(resident, out_store, value, target, budget)
                    return

        # residency-native fast paths for map_direct-family ops whose task
        # bodies declared their access pattern
        if side_inputs and len(side_inputs) == 1:
            skey = str(getattr(side_inputs[0], "store", id(side_inputs[0])))
            if skey in resident:
                res = resident[skey]
                if getattr(spec.function, "resident_identity", False):
                    # merge_chunks: values pass through; chunking is metadata
                    res.touch()
                    self._admit(resident, out_store, res.value, target, budget)
                    return
                ws = getattr(spec.function, "whole_select", None)
                if ws is not None:
                    value = self._apply_whole_select(res.value, ws)
                    if value is not None and (
                        isinstance(value, dict) or tuple(value.shape) == out_shape
                    ):
                        res.touch()
                        self._admit(resident, out_store, value, target, budget)
                        return

        # other map_direct ops read arbitrary regions from storage inside the
        # task: materialize any resident side inputs first (they stay resident
        # for later consumers too)
        if side_inputs:
            for arr in side_inputs:
                skey = str(getattr(arr, "store", id(arr)))
                if skey in resident:
                    self._flush(resident[skey])

        inputs = self._whole_inputs(spec, resident)

        value = None
        if (
            spec.shape_invariant
            and not spec.writes_rest
            and not getattr(spec.function, "needs_block_id", False)
        ):
            mapping = self._probe_one_to_one(spec, op)
            if mapping and inputs is not None:
                try:
                    fn = jax.jit(spec.function)
                    full = [inputs[n] for n in mapping]
                    value = fn(*full)
                    if not isinstance(value, dict) and tuple(value.shape) != out_shape:
                        value = None  # kernel wasn't truly shape-invariant
                    else:
                        self.stats["whole_array_hits"] += 1
                except _TraceAbort:
                    raise
                except Exception:
                    logger.exception("whole-array path failed; falling back")
                    self.stats["whole_array_errors"] += 1
                    self.stats["eager_fallbacks"] += 1
                    value = None

        if (
            value is None
            and not getattr(spec.function, "needs_block_id", False)
            and not getattr(spec.function, "host_block_id", False)
        ):
            try:
                value = self._exec_batched(op, spec, resident)
                if value is not None:
                    self.stats["batched_ops"] += 1
            except _TraceAbort:
                raise
            except Exception:
                logger.exception("batched path failed; falling back")
                self.stats["batched_errors"] += 1
                self.stats["eager_fallbacks"] += 1
                value = None

        if value is None:
            value = self._exec_chunked(op, spec, resident)
            self.stats["chunked_ops"] += 1

        if spec.writes_rest:
            # multi-output: value is one device array per output proxy
            for proxy, v in zip(spec.writes, value):
                t = proxy.array
                if tuple(v.shape) != tuple(t.shape):
                    raise ValueError(
                        f"multi-output op produced shape {tuple(v.shape)}, "
                        f"target expects {tuple(t.shape)} (kernel/block-"
                        "function contract violation)"
                    )
                self._admit(resident, str(t.store), v, t, budget)
            return

        if not isinstance(value, dict) and tuple(value.shape) != out_shape:
            # chunked is the last resort: a shape mismatch here is a kernel
            # contract violation that must fail loudly, not assemble garbage
            raise ValueError(
                f"op produced shape {tuple(value.shape)}, target expects "
                f"{out_shape} (kernel/block-function contract violation)"
            )
        self._admit(resident, out_store, value, target, budget)

    def _apply_whole_select(self, value, selections):
        """Apply a per-axis orthogonal selection to a resident array on device."""
        jax = _jax()
        jnp = jax.numpy
        try:
            v = value
            for ax, s in enumerate(selections):
                if isinstance(s, tuple):  # resolved slice (start, stop, step)
                    s0, s1, st = s
                    if st < 0 and s1 < 0:
                        # .indices() reports "walked past index 0" as stop=-1,
                        # which a literal slice bound would wrap to the end
                        s1 = None
                    sel = (slice(None),) * ax + (slice(s0, s1, st),)
                    v = (
                        {k: vv[sel] for k, vv in v.items()}
                        if isinstance(v, dict)
                        else v[sel]
                    )
                else:
                    idx = jnp.asarray(np.asarray(s))
                    v = (
                        {k: jnp.take(vv, idx, axis=ax) for k, vv in v.items()}
                        if isinstance(v, dict)
                        else jnp.take(v, idx, axis=ax)
                    )
            return v
        except Exception:
            logger.exception("whole-select fast path failed")
            self.stats["whole_select_errors"] += 1
            self.stats["eager_fallbacks"] += 1
            return None

    def _whole_inputs(self, spec: BlockwiseSpec, resident) -> Optional[Dict[str, Any]]:
        """Whole arrays for every input, from residency or storage."""
        jax = _jax()
        out = {}
        for name, proxy in spec.reads_map.items():
            arr = proxy.array
            key = str(getattr(arr, "store", id(arr)))
            if key in resident:
                resident[key].touch()
                out[name] = resident[key].value
            elif isinstance(
                arr, (VirtualFullArray, VirtualEmptyArray, VirtualInMemoryArray)
            ):
                out[name] = self._virtual_to_device(arr)
            elif isinstance(arr, VirtualOffsetsArray):
                return None  # block-id arrays have no whole-array meaning
            elif isinstance(arr, ZarrV2Array):
                if self._tracing:
                    raise _TraceAbort("storage read inside traced segment")
                data = arr[...] if arr.shape else arr[()]
                if data.dtype.fields is not None:
                    out[name] = {
                        k: self._device_put(np.ascontiguousarray(data[k]), data.shape)
                        for k in data.dtype.names
                    }
                else:
                    out[name] = self._device_put(data, data.shape)
            elif isinstance(arr, LazyZarrArray):
                if self._tracing:
                    raise _TraceAbort("storage read inside traced segment")
                try:
                    concrete = arr.open()
                except FileNotFoundError:
                    return None
                data = concrete[...] if concrete.shape else concrete[()]
                out[name] = self._device_put(data, data.shape)
            else:
                return None
        return out

    def _probe_one_to_one(self, spec: BlockwiseSpec, op) -> Optional[list[str]]:
        """Check the block mapping is 1:1 (with broadcast-clamp) and return the
        per-argument input names in order."""
        mappable = op.pipeline.mappable
        try:
            keys = list(itertools.islice(iter(mappable), 0, 3))
        except TypeError:
            return None
        if not keys:
            return None
        names: Optional[list[str]] = None
        for out_key in keys:
            try:
                structure = spec.block_function(out_key)
            except Exception:
                return None
            out_coords = out_key[1:]
            cur = []
            for entry in structure:
                if not (isinstance(entry, tuple) and entry and isinstance(entry[0], str)):
                    return None  # contraction/iterator: not 1:1
                name, coords = entry[0], entry[1:]
                proxy = spec.reads_map.get(name)
                if proxy is None:
                    return None
                arr = proxy.array
                nb = (
                    tuple(
                        len(c)
                        for c in blockdims_from_blockshape(arr.shape, proxy.chunks)
                    )
                    if arr.shape
                    else ()
                )
                # coords must equal out coords (rightmost-aligned) or clamp to
                # 0 on broadcast dims
                oc = out_coords[len(out_coords) - len(coords):]
                for c, o, n in zip(coords, oc, nb):
                    if c != o and not (c == 0 and n == 1):
                        return None
                cur.append(name)
            if names is None:
                names = cur
            elif names != cur:
                return None
        return names

    # ------------------------------------------------------------------
    # batched: ALL tasks of a uniform-grid op in ONE vmapped XLA dispatch
    # ------------------------------------------------------------------

    def _exec_batched(self, op, spec: BlockwiseSpec, resident):
        """Stack every task's input chunks on device and run vmap(kernel) once.

        Collapses the reference's task fan-out (one dispatch per chunk through
        storage) into a single XLA program: per-task host overhead and tunnel
        round-trips vanish, and XLA tiles the batched kernel onto the MXU/VPU.
        Returns None when the op isn't batchable (ragged grid, streamed reads,
        non-uniform structure)."""
        jax = _jax()
        jnp = jax.numpy
        _register_pred_pytrees()

        target = spec.write.array
        out_shape = tuple(target.shape)
        if not out_shape:
            return None
        out_chunkset = blockdims_from_blockshape(out_shape, spec.write.chunks)
        out_nb = tuple(len(c) for c in out_chunkset)

        keys = list(op.pipeline.mappable)
        if len(keys) <= 1:
            return None
        # mappable is the C-order product over the out grid by construction
        structures = [spec.block_function(k) for k in keys]

        # flatten each task's key structure to leaves; all tasks must agree
        treedef0, leaves0 = _flatten_keys(structures[0])
        if treedef0 is None:
            return None
        task_leaves = [leaves0]
        for s in structures[1:]:
            td, leaves = _flatten_keys(s)
            if td != treedef0 or len(leaves) != len(leaves0):
                return None
            task_leaves.append(leaves)

        # per-leaf metadata (source array + chunk grid), shared by all buckets
        leaf_meta = []
        for k in leaves0:
            name = k[0]
            proxy = spec.reads_map.get(name)
            if proxy is None:
                return None
            arr = proxy.array
            chunkset = (
                blockdims_from_blockshape(arr.shape, proxy.chunks)
                if arr.shape
                else ()
            )
            leaf_meta.append((name, proxy, arr, chunkset))
        for leaves in task_leaves:
            for k, (name, _, _, _) in zip(leaves, leaf_meta):
                if k[0] != name:
                    return None  # leaf source varies across tasks

        def chunk_shape_at(chunkset, coords):
            return tuple(chunkset[d][c] for d, c in enumerate(coords))

        # bucket tasks by their full chunk-shape signature: each bucket is one
        # vmapped dispatch, so ragged grids cost one extra program per distinct
        # edge-chunk shape instead of one program per chunk
        buckets: Dict[tuple, list[int]] = {}
        for t, key in enumerate(keys):
            out_coords = tuple(key[1:])
            sig = (chunk_shape_at(out_chunkset, out_coords),) + tuple(
                chunk_shape_at(cs, tuple(k[1:])) if arr.shape else ()
                for k, (_, _, arr, cs) in zip(task_leaves[t], leaf_meta)
            )
            buckets.setdefault(sig, []).append(t)

        if len(buckets) > max(8, len(keys) // 4):
            return None  # too ragged: batching would hardly help

        fn = spec.function
        td = treedef0

        def task_fn(*flat):
            args = _unflatten_keys(td, list(flat))
            return fn(*args)

        chunk_grid: Dict[tuple, Any] = {}
        for tasks in buckets.values():
            T = len(tasks)
            stacked_leaves = []
            in_axes_leaves = []
            for i, (name, proxy, arr, chunkset) in enumerate(leaf_meta):
                leaf_keys = [task_leaves[t][i] for t in tasks]
                coords = [tuple(k[1:]) for k in leaf_keys]
                if all(c == coords[0] for c in coords):
                    # same chunk for every task: broadcast (no stacking)
                    stacked_leaves.append(
                        self._resolve(
                            leaf_keys[0],
                            spec,
                            resident,
                            getattr(spec.function, "traced_offsets", False),
                        )
                    )
                    in_axes_leaves.append(None)
                    continue

                if isinstance(arr, VirtualOffsetsArray):
                    base = getattr(arr, "base", 0)
                    rel = np.asarray(
                        [np.ravel_multi_index(c, arr.shape) for c in coords],
                        dtype=arr.dtype,
                    ).reshape((T,) + (1,) * len(arr.shape))
                    if self._tracing and id(arr) in self._prepared_bases:
                        # seed rides a hoisted input; relative offsets are a
                        # seed-independent constant -> stable HLO across plans
                        offs = (
                            jnp.asarray(rel)
                            + self._prepared_bases[id(arr)].astype(arr.dtype)
                        )
                    else:
                        offs = self._device_put(rel + base, None)
                    stacked_leaves.append(offs)
                    in_axes_leaves.append(0)
                    continue
                if isinstance(arr, (VirtualEmptyArray, VirtualFullArray)):
                    fill = getattr(arr, "fill_value", 0)
                    cshape = chunk_shape_at(chunkset, coords[0])
                    stacked_leaves.append(
                        jnp.full(cshape, fill, dtype=arr.dtype)
                    )
                    in_axes_leaves.append(None)  # constant: broadcast
                    continue

                store_key = str(getattr(arr, "store", id(arr)))
                if store_key in resident:
                    res = resident[store_key]
                    res.touch()
                    value = res.value
                    nb = tuple(len(c) for c in chunkset)
                    if all(len(set(c)) == 1 for c in chunkset):
                        idx = np.asarray(
                            [np.ravel_multi_index(c, nb) for c in coords],
                            dtype=np.int32,
                        )
                        chunk_shape = tuple(c[0] for c in chunkset)
                        stacked = _gather_blocks(value, nb, chunk_shape, idx)
                    else:
                        stacked = _gather_subgrid(value, chunkset, coords)
                        if stacked is None:
                            # irregular coord set: stack device slices
                            sels = [get_item(chunkset, c) for c in coords]
                            if isinstance(value, dict):
                                stacked = {
                                    k: jnp.stack([v[s] for s in sels])
                                    for k, v in value.items()
                                }
                            else:
                                stacked = jnp.stack([value[s] for s in sels])
                    stacked_leaves.append(stacked)
                    in_axes_leaves.append(0)
                    continue

                # host source (in-memory / zarr): stack once, transfer once
                if self._tracing and isinstance(arr, (ZarrV2Array, LazyZarrArray)):
                    raise _TraceAbort("storage read inside traced segment")
                opened = proxy.open()
                host = np.stack(
                    [np.asarray(opened[get_item(chunkset, c)]) for c in coords]
                )
                if host.dtype.fields is not None:
                    stacked_leaves.append(
                        {
                            k: self._device_put(
                                np.ascontiguousarray(host[k]), None
                            )
                            for k in host.dtype.names
                        }
                    )
                else:
                    stacked_leaves.append(self._device_put(host, None))
                in_axes_leaves.append(0)

            if all(ax is None for ax in in_axes_leaves):
                return None

            batched = jax.jit(jax.vmap(task_fn, in_axes=tuple(in_axes_leaves)))
            out_stacked = batched(*stacked_leaves)

            for ti, t in enumerate(tasks):
                out_coords = tuple(keys[t][1:])
                if spec.writes_rest:
                    if not isinstance(out_stacked, (tuple, list)) or len(
                        out_stacked
                    ) != len(spec.writes):
                        return None
                    for j, (w, stacked) in enumerate(
                        zip(spec.writes, out_stacked)
                    ):
                        cs_j = blockdims_from_blockshape(
                            tuple(w.array.shape), w.chunks
                        )
                        if tuple(stacked.shape[1:]) != chunk_shape_at(
                            cs_j, out_coords
                        ):
                            return None
                    chunk_grid[out_coords] = tuple(v[ti] for v in out_stacked)
                elif isinstance(out_stacked, dict):
                    chunk_grid[out_coords] = {
                        k: v[ti] for k, v in out_stacked.items()
                    }
                else:
                    expect = chunk_shape_at(out_chunkset, out_coords)
                    if tuple(out_stacked.shape[1:]) != expect:
                        return None
                    chunk_grid[out_coords] = out_stacked[ti]

        if spec.writes_rest:
            return tuple(
                _assemble(
                    {c: v[j] for c, v in chunk_grid.items()}, out_nb
                )
                for j in range(len(spec.writes))
            )
        value = _assemble(chunk_grid, out_nb)
        if not isinstance(value, dict) and tuple(value.shape) != out_shape:
            return None
        return value

    # ------------------------------------------------------------------

    def _exec_chunked(self, op, spec: BlockwiseSpec, resident):
        """Per-output-chunk execution with on-device slicing."""
        jax = _jax()
        target = spec.write.array
        out_shape = tuple(target.shape)
        chunkset = (
            blockdims_from_blockshape(out_shape, spec.write.chunks)
            if out_shape
            else ()
        )
        nb = tuple(len(c) for c in chunkset)
        needs_block_id = getattr(spec.function, "needs_block_id", False)

        jitted = _JitCache(spec.function, self.stats)
        region_fn = getattr(spec.function, "combine_region", None)
        jitted_region = (
            _JitCache(region_fn, self.stats) if region_fn is not None else None
        )

        traced_offsets = self._tracing and getattr(
            spec.function, "traced_offsets", False
        )

        chunk_grid: Dict[tuple, Any] = {}
        for out_key in op.pipeline.mappable:
            out_coords = tuple(out_key[1:])
            structure = spec.block_function(out_key)
            result = None
            if (
                jitted_region is not None
                and structure
                and all(isinstance(e, Iterator) for e in structure)
            ):
                # one contiguous region per argument (N=1 for plain
                # reductions; one per field for pytree intermediates held
                # as N arrays), combined in a single jitted call
                keyss = [list(e) for e in structure]
                regions = [
                    self._resolve_region(keys, spec, resident)
                    for keys in keyss
                ]
                if all(r is not None for r in regions):
                    result = jitted_region(*regions)
                else:
                    structure = tuple(iter(keys) for keys in keyss)
            if result is None:
                args = [
                    self._resolve(entry, spec, resident, traced_offsets)
                    for entry in structure
                ]
                if needs_block_id:
                    result = spec.function(*args, block_id=out_coords)
                else:
                    result = jitted(*args)
            chunk_grid[out_coords] = result

        if spec.writes_rest:
            # multi-output: per-chunk tuples -> one assembled array per output
            return tuple(
                _assemble({c: v[j] for c, v in chunk_grid.items()}, nb)
                if out_shape
                else chunk_grid[()][j]
                for j in range(len(spec.writes))
            )
        if not out_shape:
            return chunk_grid[()]
        return _assemble(chunk_grid, nb)

    def _resolve_region(self, keys, spec: BlockwiseSpec, resident):
        """Slice the contiguous region covering a group of blocks of one
        resident array — one device slice replaces a streamed combine."""
        if not keys:
            return None
        names = {k[0] for k in keys}
        if len(names) != 1:
            return None
        name = keys[0][0]
        proxy = spec.reads_map.get(name)
        if proxy is None:
            return None
        arr = proxy.array
        key = str(getattr(arr, "store", id(arr)))
        if key not in resident or not arr.shape:
            return None
        res = resident[key]
        res.touch()
        chunkset = blockdims_from_blockshape(arr.shape, proxy.chunks)
        coords = [tuple(k[1:]) for k in keys]
        ndim = len(arr.shape)
        los = [min(c[d] for c in coords) for d in range(ndim)]
        his = [max(c[d] for c in coords) for d in range(ndim)]
        # must be the full dense block range
        if len(coords) != math.prod(h - l + 1 for l, h in zip(los, his)):
            return None
        sel = tuple(
            slice(
                sum(chunkset[d][: los[d]]),
                sum(chunkset[d][: his[d] + 1]),
            )
            for d in range(ndim)
        )
        value = res.value
        if isinstance(value, dict):
            return {k: v[sel] for k, v in value.items()}
        return value[sel]

    def _resolve(self, entry, spec: BlockwiseSpec, resident, traced_offsets=False):
        """Resolve a key structure to device chunks (sliced from residents)."""
        from ...primitive.blockwise import PredArgs, PredKeys, _is_key

        if isinstance(entry, PredKeys):
            return PredArgs(
                [self._resolve(e, spec, resident, traced_offsets) for e in entry]
            )
        if isinstance(entry, (list, tuple)) and not _is_key(entry):
            return [self._resolve(e, spec, resident, traced_offsets) for e in entry]
        if isinstance(entry, Iterator):
            return (self._resolve(e, spec, resident, traced_offsets) for e in entry)
        name, coords = entry[0], tuple(entry[1:])
        proxy = spec.reads_map[name]
        arr = proxy.array
        key = str(getattr(arr, "store", id(arr)))
        if (
            traced_offsets
            and isinstance(arr, VirtualOffsetsArray)
            and id(arr) in self._prepared_bases
        ):
            # kernel accepts a traced seed: relative offset is a stable
            # constant, the per-plan seed rides the hoisted segment input
            jnp = _jax().numpy
            rel = np.ravel_multi_index(coords, arr.shape) if arr.shape else 0
            off = self._prepared_bases[id(arr)].astype(arr.dtype) + rel
            return jnp.reshape(off, (1,) * len(arr.shape))
        if key in resident:
            res = resident[key]
            res.touch()
            chunkset = (
                blockdims_from_blockshape(arr.shape, proxy.chunks) if arr.shape else ()
            )
            sel = get_item(chunkset, coords) if arr.shape else ()
            value = res.value
            if isinstance(value, dict):
                return {k: v[sel] for k, v in value.items()}
            return value[sel]
        # constant-valued chunks are created on device — no host transfer
        if isinstance(arr, (VirtualEmptyArray, VirtualFullArray)):
            jax = _jax()
            chunkset = (
                blockdims_from_blockshape(arr.shape, proxy.chunks) if arr.shape else ()
            )
            sel = get_item(chunkset, coords) if arr.shape else ()
            shape = tuple(s.stop - s.start for s in sel)
            fill = getattr(arr, "fill_value", 0)
            return jax.numpy.full(shape, fill, dtype=arr.dtype)
        if isinstance(arr, VirtualOffsetsArray):
            # raw numpy, NOT backend-converted: inside a traced segment the
            # backend conversion turns the block into a (constant-valued)
            # tracer, which a host_block_id kernel's int(offset) cannot
            # consume — the whole segment then trace-fails to eager. The
            # hoisted-seed path above serves traced_offsets kernels; every
            # other consumer wants a concrete value (it IS concrete: pure
            # plan metadata).
            sel = get_item(
                blockdims_from_blockshape(arr.shape, proxy.chunks), coords
            ) if arr.shape else ()
            return np.asarray(arr[sel])
        # storage / small-virtual fallback (host read + device transfer)
        if self._tracing and isinstance(arr, (ZarrV2Array, LazyZarrArray)):
            raise _TraceAbort("storage read inside traced segment")
        from ...primitive.blockwise import get_chunk

        opened = proxy.open()
        chunkset = (
            blockdims_from_blockshape(opened.shape, proxy.chunks)
            if opened.shape
            else ()
        )
        return get_chunk(opened, chunkset, coords)

    # ------------------------------------------------------------------
    # rechunk: resident alias / storage fallback
    # ------------------------------------------------------------------

    def _exec_rechunk(self, op, resident: Dict[str, _Resident], budget: int) -> None:
        config = op.pipeline.config  # CubedCopySpec
        src = config.read.array
        dst = config.write.array
        src_key = str(getattr(src, "store", id(src)))
        dst_key = str(dst.store)

        if src_key in resident:
            # chunking is metadata; the resident value is the whole array
            res = resident[src_key]
            res.touch()
            self.stats["rechunk_alias"] += 1
            self._admit(resident, dst_key, res.value, dst, budget)
            return

        # virtual sources materialize on device directly (trace-safe) — a
        # real materialization, counted apart from zero-copy aliases
        virt = self._virtual_to_device(src)
        if virt is not None:
            self.stats["rechunk_virtual"] += 1
            self._admit(resident, dst_key, virt, dst, budget)
            return

        # source lives in storage: load whole if it fits, else host-side copy
        if self._tracing:
            raise _TraceAbort("rechunk storage source inside traced segment")
        try:
            opened = src.open() if hasattr(src, "open") else src
        except FileNotFoundError:
            opened = None
        if opened is not None and opened.nbytes < budget // 2:
            data = opened[...] if opened.shape else opened[()]
            if data.dtype.fields is not None:
                value = {
                    k: self._device_put(np.ascontiguousarray(data[k]), data.shape)
                    for k in data.dtype.names
                }
            else:
                value = self._device_put(data, data.shape)
            self._admit(resident, dst_key, value, dst, budget)
        else:
            # bounded host-side copy (the spill path)
            for m in op.pipeline.mappable:
                op.pipeline.function(m, config=config)

    # ------------------------------------------------------------------
    # residency bookkeeping
    # ------------------------------------------------------------------

    def _admit(self, resident, store: str, value, target, budget: int) -> None:
        nbytes = _value_nbytes(value)
        self._evict(resident, budget - nbytes, exclude=store)
        resident[store] = _Resident(value, nbytes, target)

    def _evict(self, resident, budget: int, exclude: Optional[str] = None) -> None:
        total = sum(r.nbytes for r in resident.values())
        if total <= budget:
            return
        for store, res in sorted(resident.items(), key=lambda kv: kv[1].last_used):
            if store == exclude:
                continue
            self._flush(res)
            del resident[store]
            total -= res.nbytes
            if total <= budget:
                return

    def _flush(self, res: _Resident) -> None:
        """Write a resident array to its Zarr target, chunk by chunk."""
        if self._tracing:
            raise _TraceAbort("flush inside traced segment")
        target = res.target
        if isinstance(target, LazyZarrArray):
            concrete = target.create(mode="a")
        elif isinstance(target, ZarrV2Array):
            concrete = target
        else:
            return
        value = res.value
        shape = tuple(concrete.shape)
        if not shape:
            if isinstance(value, dict):
                rec = np.empty((), dtype=concrete.dtype)
                for k in concrete.dtype.names:
                    rec[k] = np.asarray(value[k])
                concrete[()] = rec
            else:
                concrete[()] = np.asarray(value)
            return
        chunkset = blockdims_from_blockshape(shape, concrete.chunks)
        coords_iter = itertools.product(*(range(len(c)) for c in chunkset))
        sharding = getattr(value, "sharding", None)
        jax = _jax()
        if (
            self.mesh is not None
            and not isinstance(value, dict)
            and sharding is not None
            and jax.process_count() > 1
        ):
            # per-host write sharding (docs/multihost.md): under
            # multi-controller SPMD every process runs this flush, but each
            # writes only the chunks its own devices own — together exactly
            # the full grid, each byte written once. Single-process runs
            # skip the assignment scan (every chunk is addressable anyway).
            from ...parallel.multihost import (
                chunk_within_owner_shard,
                local_chunks,
            )

            mine = local_chunks(sharding, shape, tuple(concrete.chunks))
            for coords in mine:
                if not chunk_within_owner_shard(
                    sharding, shape, chunkset, coords
                ):
                    raise NotImplementedError(
                        "multi-host flush requires a chunk-aligned sharding "
                        f"(chunk {coords} straddles shard boundaries); "
                        "rechunk or choose a chunk-aligned mesh layout "
                        "(parallel.mesh.sharding_for_chunks prefers one)"
                    )
            coords_iter = iter(mine)
        for idx in coords_iter:
            sel = get_item(chunkset, idx)
            if isinstance(value, dict):
                fields = {k: np.asarray(v[sel]) for k, v in value.items()}
                first = next(iter(fields.values()))
                rec = np.empty(first.shape, dtype=concrete.dtype)
                for k in concrete.dtype.names:
                    rec[k] = fields[k]
                concrete[sel] = rec
            else:
                concrete[sel] = np.asarray(value[sel])


#: in-process cache of (compiled segment program, HBM footprint) keyed by the
#: sha256 hex digest of (lowered HLO text, device-id tuple): repeat computes
#: of structurally equal plans on the same device set skip compilation (and
#: re-analysis) entirely, while a different mesh/topology gets its own entry
_SEGMENT_CACHE: Dict[str, Any] = {}


#: structural-fingerprint cache: (compiled program, HBM footprint) keyed by
#: the pre-trace segment fingerprint (see JaxExecutor._structural_key) —
#: repeat computes of structurally identical plans skip tracing entirely
_STRUCT_CACHE: Dict[str, Any] = {}

#: debugging hook: set to a list to collect normalized fingerprint payloads
_STRUCT_DEBUG: Optional[list] = None

#: guards the two module-level program caches: concurrent computes (the
#: multi-tenant service drives Plan.execute from many threads) would
#: otherwise interleave the size-check/evict/insert sequences and could
#: evict an entry a sibling just read or resurrect one past the bound
_CACHE_LOCK = threading.Lock()


def _hbm_footprint(compiled) -> int:
    """XLA's own accounting of a program's device footprint (args + outputs
    + temps); 0 when the backend offers no analysis. Computed once per
    compile — it never changes for a given executable."""
    try:
        ma = compiled.memory_analysis()
        return (
            int(getattr(ma, "argument_size_in_bytes", 0))
            + int(getattr(ma, "output_size_in_bytes", 0))
            + int(getattr(ma, "temp_size_in_bytes", 0))
        )
    except Exception:
        return 0

_F32_FILTER_ENTRY = None


def _install_f32_truncation_filter() -> None:
    """Silence jax's per-request "requested dtype float64 is not
    available" warning with a process-global filter.

    Prepending a filter is effectively atomic under the GIL and is never
    restored by us, so concurrent executor threads can't observe
    half-saved filter state (unlike ``warnings.catch_warnings``, which
    save/restores the GLOBAL filter list and races other threads).
    Presence is re-checked against ``warnings.filters`` on every DAG —
    not a trust-me flag — because an enclosing ``catch_warnings`` scope
    (e.g. pytest's warnings plugin around each test) discards the entry
    on exit.

    Caveat, stated rather than hidden: while installed, the filter also
    suppresses this warning for any OTHER code in the process that runs
    with x64 canonicalization off (its own ``jax.enable_x64(False)``
    scope). That is the documented cost of ``compute_dtype="float32"``:
    it mutates global warnings state instead of save/restoring it
    thread-unsafely."""
    global _F32_FILTER_ENTRY
    import warnings

    if _F32_FILTER_ENTRY is not None and _F32_FILTER_ENTRY in warnings.filters:
        return
    warnings.filterwarnings(
        "ignore", message=".*requested dtype.*is not available.*"
    )
    _F32_FILTER_ENTRY = warnings.filters[0]


_PYTREES_REGISTERED = False


def _register_pred_pytrees() -> None:
    """Register fusion marker types as jax pytrees so vmap maps through them."""
    global _PYTREES_REGISTERED
    if _PYTREES_REGISTERED:
        return
    import jax

    from ...primitive.blockwise import PredArgs

    try:
        jax.tree_util.register_pytree_node(
            PredArgs,
            lambda x: (list(x), None),
            lambda _, children: PredArgs(children),
        )
    except ValueError:
        pass  # already registered
    _PYTREES_REGISTERED = True


def _flatten_keys(structure):
    """Flatten a block-function result into (treedef, leaf keys).

    Treedef is a comparable nested template: 'leaf' for a chunk key,
    ('pred', ...) for fused-predecessor groups, ('list', ...) for contraction
    lists, ('args', ...) at the top. Returns (None, None) on iterators
    (streamed reads are not batchable)."""
    from ...primitive.blockwise import PredKeys, _is_key

    leaves: list = []

    def walk(node):
        if isinstance(node, PredKeys):
            return ("pred", tuple(walk(c) for c in node))
        if _is_key(node):
            leaves.append(node)
            return "leaf"
        if isinstance(node, (list, tuple)):
            return ("list", tuple(walk(c) for c in node))
        return None  # Iterator / unknown

    out = []
    for entry in structure:
        t = walk(entry)
        if t is None or _contains_none(t):
            return None, None
        out.append(t)
    return ("args", tuple(out)), leaves


def _contains_none(t) -> bool:
    if t is None:
        return True
    if isinstance(t, tuple) and len(t) == 2 and t[0] in ("pred", "list"):
        return any(_contains_none(c) for c in t[1])
    return False


def _unflatten_keys(treedef, flat: list):
    """Rebuild the argument structure with chunks in place of keys.

    PredKeys groups become PredArgs (the resolved-chunk marker the fused
    kernel expects); contraction groups become plain lists."""
    from ...primitive.blockwise import PredArgs

    it = iter(flat)

    def build(t):
        if t == "leaf":
            return next(it)
        kind, children = t
        if kind == "pred":
            return PredArgs([build(c) for c in children])
        return [build(c) for c in children]

    kind, entries = treedef
    assert kind == "args"
    return tuple(build(e) for e in entries)


def _gather_subgrid(value, chunkset, coords):
    """Gather a bucket's blocks as ONE region slice + reshape.

    A shape-bucket over a ragged grid is a rectangular subgrid whose per-dim
    chunk size is uniform; when its per-dim indices are consecutive the whole
    bucket is a contiguous region — one slice, then an interleave reshape to
    (T, *chunk). Returns None when the coords don't form such a product
    (caller falls back to per-task slices). This keeps the traced program's
    memory traffic at one read of the region instead of one windowed read per
    task, which XLA otherwise fails to fuse (~50x bytes-accessed blowup)."""
    import jax.numpy as jnp

    ndim = len(chunkset)
    per_dim = []
    for d in range(ndim):
        idxs = sorted({c[d] for c in coords})
        if idxs != list(range(idxs[0], idxs[-1] + 1)):
            return None
        sizes = {chunkset[d][i] for i in idxs}
        if len(sizes) != 1:
            return None
        per_dim.append(idxs)
    if len(coords) != math.prod(len(p) for p in per_dim):
        return None
    if sorted(coords) != coords:
        return None  # caller must supply C-ordered tasks
    sel = tuple(
        slice(
            sum(chunkset[d][: per_dim[d][0]]),
            sum(chunkset[d][: per_dim[d][-1] + 1]),
        )
        for d in range(ndim)
    )
    nb = tuple(len(p) for p in per_dim)
    chunk_shape = tuple(chunkset[d][per_dim[d][0]] for d in range(ndim))

    def one(v):
        region = v[sel]
        inter = []
        for n, c in zip(nb, chunk_shape):
            inter.extend([n, c])
        r = region.reshape(tuple(inter))
        perm = list(range(0, 2 * ndim, 2)) + list(range(1, 2 * ndim, 2))
        return r.transpose(perm).reshape((-1,) + chunk_shape)

    if isinstance(value, dict):
        return {k: one(v) for k, v in value.items()}
    return one(value)


def _gather_blocks(value, nb, chunk_shape, idx):
    """(full array, grid, chunk shape, task->block index) -> (T, *chunk)."""
    import jax.numpy as jnp

    def one(v):
        inter = []
        for n, c in zip(nb, chunk_shape):
            inter.extend([n, c])
        r = v.reshape(tuple(inter))
        perm = list(range(0, 2 * len(nb), 2)) + list(range(1, 2 * len(nb), 2))
        blocks = r.transpose(perm).reshape((-1,) + tuple(chunk_shape))
        return blocks[idx]

    if isinstance(value, dict):
        return {k: one(v) for k, v in value.items()}
    return one(value)


class _JitCache:
    """jit a chunk kernel lazily, falling back to eager on trace failure."""

    def __init__(self, function, stats: Optional[Counter] = None):
        self.function = function
        self.stats = stats
        self._jitted = None
        # host-bound kernels (block_id sync, closed-over host data) can't jit
        self._use_eager = getattr(function, "host_block_id", False) or bool(
            getattr(function, "host_data_nbytes", 0)
        )

    def __call__(self, *args):
        # iterators / nested lists can't be jitted as-is; run eagerly
        if self._use_eager or any(
            isinstance(a, Iterator) or isinstance(a, list) for a in args
        ):
            return self.function(*args)
        jax = _jax()
        if self._jitted is None:
            self._jitted = jax.jit(self.function)
        try:
            return self._jitted(*args)
        except Exception:
            logger.exception("chunk-kernel jit failed; running eagerly")
            if self.stats is not None:
                self.stats["jit_kernel_errors"] += 1
                self.stats["eager_fallbacks"] += 1
            self._use_eager = True
            return self.function(*args)


def _assemble(chunk_grid: Dict[tuple, Any], nb: tuple[int, ...]):
    """Assemble a grid of device chunks into one array by axis-wise concat."""
    jax = _jax()
    jnp = jax.numpy

    def concat(vals, axis):
        if isinstance(vals[0], dict):
            return {k: concat([v[k] for v in vals], axis) for k in vals[0]}
        if len(vals) == 1:
            return vals[0]
        return jnp.concatenate(vals, axis=axis)

    def build(prefix: tuple, axis: int):
        if axis == len(nb):
            return chunk_grid[prefix]
        vals = [build(prefix + (i,), axis + 1) for i in range(nb[axis])]
        return concat(vals, axis)

    return build((), 0)
