"""Plan/result cache units: structural-fingerprint stability across
rebuilds, input-digest invalidation, cache bounds, and in-flight request
coalescing."""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.core.plan import arrays_to_plan
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.service import ComputeService
from cubed_tpu.service.cache import (
    PlanCache,
    ResultCache,
    input_state_digest,
    structural_fingerprint,
)


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


def _plus_one(x):
    return x + 1.0


def _times_two(x):
    return x * 2.0


AN = np.arange(64, dtype=np.float64).reshape(8, 8)


def _build(spec, fn=_plus_one, data=AN):
    a = ct.from_array(data, chunks=(4, 4), spec=spec)
    return ct.map_blocks(fn, a, dtype=np.float64)


# ----------------------------------------------------------------------
# structural fingerprint
# ----------------------------------------------------------------------


def test_fingerprint_stable_across_rebuilds(spec):
    """Two builds of the same query fingerprint equal even though every
    gensym name and intermediate path differs."""
    d1 = arrays_to_plan(_build(spec)).dag
    d2 = arrays_to_plan(_build(spec)).dag
    f1, c1 = structural_fingerprint(d1)
    f2, c2 = structural_fingerprint(d2)
    assert f1 is not None
    assert f1 == f2
    assert len(c1) == len(c2)
    # the canonical orders are positionally aligned but name-disjoint
    assert set(c1).isdisjoint(set(c2))


def test_fingerprint_distinguishes_kernels(spec):
    f1, _ = structural_fingerprint(arrays_to_plan(_build(spec, _plus_one)).dag)
    f2, _ = structural_fingerprint(arrays_to_plan(_build(spec, _times_two)).dag)
    assert f1 != f2


def test_fingerprint_distinguishes_input_values(spec):
    """In-memory inputs are value-hashed: different data, different key."""
    f1, _ = structural_fingerprint(arrays_to_plan(_build(spec, data=AN)).dag)
    f2, _ = structural_fingerprint(
        arrays_to_plan(_build(spec, data=AN + 1.0)).dag
    )
    assert f1 != f2


def test_fingerprint_distinguishes_shapes_and_chunks(spec):
    a = ct.from_array(AN, chunks=(4, 4), spec=spec)
    b = ct.from_array(AN, chunks=(2, 2), spec=spec)
    f1, _ = structural_fingerprint(
        arrays_to_plan(ct.map_blocks(_plus_one, a, dtype=np.float64)).dag
    )
    f2, _ = structural_fingerprint(
        arrays_to_plan(ct.map_blocks(_plus_one, b, dtype=np.float64)).dag
    )
    assert f1 != f2


def test_fingerprint_distinguishes_source_stores(tmp_path, spec):
    """Two structurally identical queries over DIFFERENT zarr input
    stores must not collide — a plan-cache hit across them would compute
    over the wrong store's data."""
    src_a = str(tmp_path / "a.zarr")
    src_b = str(tmp_path / "b.zarr")
    ct.to_zarr(ct.from_array(AN, chunks=(4, 4), spec=spec), src_a)
    ct.to_zarr(ct.from_array(AN + 1.0, chunks=(4, 4), spec=spec), src_b)

    def build(src):
        a = ct.from_zarr(src, spec=spec)
        return ct.map_blocks(_plus_one, a, dtype=np.float64)

    f1, _ = structural_fingerprint(arrays_to_plan(build(src_a)).dag)
    f2, _ = structural_fingerprint(arrays_to_plan(build(src_b)).dag)
    assert f1 is not None and f1 != f2
    # same store twice still hashes equal (rebuild stability holds)
    f3, _ = structural_fingerprint(arrays_to_plan(build(src_a)).dag)
    assert f1 == f3

    # end-to-end through the service: each store serves its own data
    with ComputeService(max_concurrent=2) as svc:
        h1 = svc.submit(build(src_a), tenant="t")
        np.testing.assert_array_equal(h1.result(60), AN + 1.0)
        h2 = svc.submit(build(src_b), tenant="t")
        np.testing.assert_array_equal(h2.result(60), AN + 2.0)
        assert not h2.plan_cache_hit and not h2.result_cache_hit


def test_input_digest_tracks_manifest_changes(tmp_path, spec):
    """A zarr-backed source's digest changes when the store is rewritten
    (integrity manifests change), and is stable when it isn't."""
    src = str(tmp_path / "input.zarr")
    ct.to_zarr(ct.from_array(AN, chunks=(4, 4), spec=spec), src)

    def build():
        a = ct.from_zarr(src, spec=spec)
        return ct.map_blocks(_plus_one, a, dtype=np.float64)

    d1 = input_state_digest(arrays_to_plan(build()).dag)
    d2 = input_state_digest(arrays_to_plan(build()).dag)
    assert d1 is not None and d1 == d2
    ct.to_zarr(ct.from_array(AN + 5.0, chunks=(4, 4), spec=spec), src)
    d3 = input_state_digest(arrays_to_plan(build()).dag)
    assert d3 != d1


# ----------------------------------------------------------------------
# cache containers
# ----------------------------------------------------------------------


def test_result_cache_lru_eviction_by_bytes():
    cache = ResultCache(max_bytes=3 * AN.nbytes // 2)  # room for one
    reg = get_registry()
    before = reg.snapshot()
    assert cache.put("f1", "i1", AN)
    assert cache.put("f2", "i2", AN + 1.0)  # evicts f1
    assert len(cache) == 1
    assert cache.lookup("f1", "i1") is None
    got = cache.lookup("f2", "i2")
    np.testing.assert_array_equal(got, AN + 1.0)
    delta = reg.snapshot_delta(before)
    assert delta.get("result_cache_evictions", 0) >= 1
    # an oversize result is refused, not cached at the cost of the rest
    assert not cache.put("f3", "i3", np.zeros((1000, 1000)))


def test_result_cache_invalidates_on_input_digest_change():
    cache = ResultCache()
    reg = get_registry()
    cache.put("fp", "digest-a", AN)
    before = reg.snapshot()
    assert cache.lookup("fp", "digest-CHANGED") is None
    delta = reg.snapshot_delta(before)
    assert delta.get("result_cache_invalidations", 0) == 1
    assert len(cache) == 0  # the stale entry is gone, not just skipped


def test_result_cache_hit_returns_a_copy():
    cache = ResultCache()
    cache.put("fp", "i", AN)
    got = cache.lookup("fp", "i")
    got[0, 0] = -999.0
    again = cache.lookup("fp", "i")
    assert again[0, 0] == AN[0, 0]


def test_plan_cache_bound():
    cache = PlanCache(max_entries=2)
    for i in range(4):
        cache.put(f"f{i}", object(), [])
    assert len(cache) == 2
    assert cache.get("f0") is None
    assert cache.get("f3") is not None


# ----------------------------------------------------------------------
# service-level caching behavior
# ----------------------------------------------------------------------


def test_repeat_identical_query_hits_result_cache_zero_tasks(spec):
    reg = get_registry()
    with ComputeService(max_concurrent=2) as svc:
        h1 = svc.submit(_build(spec), tenant="a")
        np.testing.assert_array_equal(h1.result(60), AN + 1.0)
        assert not h1.result_cache_hit
        before = reg.snapshot()
        h2 = svc.submit(_build(spec), tenant="b")
        np.testing.assert_array_equal(h2.result(60), AN + 1.0)
        delta = reg.snapshot_delta(before)
        assert h2.result_cache_hit
        # the acceptance bar: the repeat ran NOTHING
        assert delta.get("tasks_completed", 0) == 0
        assert delta.get("result_cache_hits", 0) == 1


def test_mutated_input_manifest_invalidates_result_cache(tmp_path, spec):
    src = str(tmp_path / "in.zarr")
    ct.to_zarr(ct.from_array(AN, chunks=(4, 4), spec=spec), src)

    def build():
        a = ct.from_zarr(src, spec=spec)
        return ct.map_blocks(_times_two, a, dtype=np.float64)

    reg = get_registry()
    with ComputeService(max_concurrent=2) as svc:
        h1 = svc.submit(build(), tenant="a")
        np.testing.assert_array_equal(h1.result(60), AN * 2.0)
        h2 = svc.submit(build(), tenant="a")
        np.testing.assert_array_equal(h2.result(60), AN * 2.0)
        assert h2.result_cache_hit
        # rewrite the input: its integrity manifests change
        ct.to_zarr(ct.from_array(AN + 10.0, chunks=(4, 4), spec=spec), src)
        before = reg.snapshot()
        h3 = svc.submit(build(), tenant="a")
        np.testing.assert_array_equal(h3.result(60), (AN + 10.0) * 2.0)
        delta = reg.snapshot_delta(before)
        assert not h3.result_cache_hit
        assert h3.plan_cache_hit  # planning was still skipped
        assert delta.get("result_cache_invalidations", 0) >= 1
        assert delta.get("tasks_completed", 0) > 0  # it really re-ran


def test_identical_inflight_requests_coalesce(spec):
    """Two identical requests running concurrently share ONE execution."""

    def slow_plus(x):
        time.sleep(0.3)
        return x + 1.0

    def build():
        a = ct.from_array(AN, chunks=(8, 8), spec=spec)  # one task
        return ct.map_blocks(slow_plus, a, dtype=np.float64)

    reg = get_registry()
    before = reg.snapshot()
    with ComputeService(max_concurrent=2) as svc:
        h1 = svc.submit(build(), tenant="a")
        h2 = svc.submit(build(), tenant="b")
        np.testing.assert_array_equal(h1.result(60), AN + 1.0)
        np.testing.assert_array_equal(h2.result(60), AN + 1.0)
    delta = reg.snapshot_delta(before)
    # one of the two coalesced onto the other (or, if the first finished
    # before the second started, the second hit the result cache)
    assert (
        delta.get("service_requests_coalesced", 0)
        + delta.get("result_cache_hits", 0)
    ) >= 1


def test_concurrent_identical_requests_serialize_on_shared_plan(spec):
    """With the result cache OFF (so no coalescing gate), two identical
    concurrent requests share one cached FinalizedPlan — its exec lock
    must serialize them so the shared store paths are never written by
    two computes at once, and both results stay bitwise-correct."""

    def slow_plus(x):
        time.sleep(0.2)
        return x + 1.0

    def build():
        a = ct.from_array(AN, chunks=(4, 4), spec=spec)
        return ct.map_blocks(slow_plus, a, dtype=np.float64)

    with ComputeService(max_concurrent=2, result_cache=False) as svc:
        h1 = svc.submit(build(), tenant="a")
        h2 = svc.submit(build(), tenant="b")
        np.testing.assert_array_equal(h1.result(60), AN + 1.0)
        np.testing.assert_array_equal(h2.result(60), AN + 1.0)
        assert h1.plan_cache_hit or h2.plan_cache_hit


def test_caches_can_be_disabled(spec, monkeypatch):
    monkeypatch.setenv("CUBED_TPU_SERVICE_PLAN_CACHE", "off")
    monkeypatch.setenv("CUBED_TPU_SERVICE_RESULT_CACHE", "off")
    with ComputeService(max_concurrent=1) as svc:
        assert svc.plan_cache is None
        assert svc.result_cache is None
        h1 = svc.submit(_build(spec), tenant="a")
        h2 = svc.submit(_build(spec), tenant="a")
        np.testing.assert_array_equal(h1.result(60), AN + 1.0)
        np.testing.assert_array_equal(h2.result(60), AN + 1.0)
        assert not h2.result_cache_hit and not h2.plan_cache_hit
