"""Live fleet dashboard: ``python -m cubed_tpu.top [host:port]``.

Renders the telemetry endpoint's ``/snapshot.json`` (armed via
``Spec(telemetry_port=...)`` or ``CUBED_TPU_TELEMETRY_PORT``; see
``docs/observability.md`` "Live telemetry") as a refreshing terminal
view:

- a **fleet table** — one row per worker: connectivity, draining/
  pressured flags, RSS, load (outstanding/threads), lifetime tasks,
  peer-cache footprint and hit rate;
- a **COST panel** — per-tenant consumption when a multi-tenant service
  is live: task-seconds, store bytes read/written, peer bytes, retry
  draw (the service's ``_CostTracker`` fold, also exported as the
  ``tenant_cost_*`` series on ``/metrics``);
- a **DISPATCH panel** — the control plane's saturation flight deck:
  dispatch-loop utilization, estimated tasks/sec capacity, queue depth,
  cumulative serialize/send/lock-wait costs, and per-message-type frame
  counts on the coordinator link (see docs/observability.md
  "Control-plane observability");
- **compute progress** — tasks done/total with a live task rate and ETA
  (rate from the ``compute_tasks_done`` series' trailing window);
- **recent alerts** — the alert engine's last firings, active ones
  flagged.

``--once`` prints a single refresh and exits (scripts, tests);
``--interval`` sets the refresh period; ``--snapshot <file>`` renders a
saved ``/snapshot.json`` offline (no live fleet needed) and exits. The
endpoint defaults to ``127.0.0.1:$CUBED_TPU_TELEMETRY_PORT``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Optional
from urllib.request import urlopen

from .observability.alerts import format_alert_row
from .utils import memory_repr

#: ANSI clear-screen + cursor-home (suppressed when stdout is not a tty)
_CLEAR = "\x1b[2J\x1b[H"


def fetch_snapshot(endpoint: str, timeout: float = 5.0) -> dict:
    """GET ``http://<endpoint>/snapshot.json`` and parse it."""
    if "://" not in endpoint:
        endpoint = f"http://{endpoint}"
    with urlopen(f"{endpoint}/snapshot.json", timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def _series_rate(snapshot: dict, name: str, labels: dict,
                 window_s: float = 30.0) -> Optional[float]:
    """Per-second rate of one dumped series over its trailing window."""
    for row in snapshot.get("series") or []:
        if row.get("name") != name or row.get("labels") != labels:
            continue
        pts = row.get("points") or []
        now = snapshot.get("ts") or time.time()
        pts = [p for p in pts if p[0] >= now - window_s]
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return None
        return max(0.0, (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0]))
    return None


def _fmt_mem(v) -> str:
    if not isinstance(v, (int, float)):
        return "-"
    return memory_repr(int(v))


def _fmt_eta(seconds) -> str:
    if seconds is None or seconds != seconds or seconds < 0:
        return "-"
    seconds = int(seconds)
    if seconds >= 3600:
        return f"{seconds // 3600}h{(seconds % 3600) // 60:02d}m"
    if seconds >= 60:
        return f"{seconds // 60}m{seconds % 60:02d}s"
    return f"{seconds}s"


def _worker_hit_rate(row: dict) -> str:
    metrics = row.get("metrics") or {}
    hits = metrics.get("peer_hits") or 0
    misses = metrics.get("peer_misses") or 0
    if not hits and not misses:
        return "-"
    return f"{hits / (hits + misses):.0%}"


def render(snapshot: dict, width: int = 100) -> str:
    """One dashboard frame from a ``/snapshot.json`` payload."""
    out: list = []
    ts = snapshot.get("ts")
    stamp = (
        time.strftime("%H:%M:%S", time.localtime(ts))
        if isinstance(ts, (int, float)) else "-"
    )
    fleet = snapshot.get("fleet") or {}
    metrics = snapshot.get("metrics") or {}
    out.append(
        f"cubed_tpu.top  {stamp}  epoch {fleet.get('epoch', 0)}  "
        f"workers {fleet.get('workers_live', 0)} "
        f"({fleet.get('workers_pressured', 0)} pressured, "
        f"{fleet.get('workers_disconnected', 0)} disconnected)  "
        f"tasks_completed {metrics.get('tasks_completed', 0)}  "
        f"alerts_fired {metrics.get('alerts_fired', 0)}"
    )
    out.append("=" * width)

    # -- deadlines / cancellation / store health -----------------------
    breaker = {0: "closed", 1: "half-open", 2: "OPEN"}.get(
        metrics.get("store_breaker_state"), "closed"
    )
    out.append(
        f"TIME & STORE  cancellations {metrics.get('cancellations', 0)}  "
        f"deadline_aborts {metrics.get('deadline_aborts', 0)}  "
        f"store_throttled {metrics.get('store_throttled', 0)}  "
        f"breaker {breaker}"
    )
    out.append("")

    # -- control plane: the dispatch-saturation flight deck ------------
    dispatch = snapshot.get("dispatch") or {}
    util = dispatch.get(
        "dispatch_utilization", metrics.get("dispatch_utilization")
    )
    if dispatch or util is not None:
        util_s = f"{util:.0%}" if isinstance(util, (int, float)) else "-"
        cap = dispatch.get(
            "dispatch_capacity_estimate",
            metrics.get("dispatch_capacity_estimate"),
        )
        cap_s = f"{cap:.0f}/s" if isinstance(cap, (int, float)) else "-"
        depth = metrics.get("queue_depth", 0)
        out.append(
            f"DISPATCH  utilization {util_s}  capacity ~{cap_s}  "
            f"queue_depth {depth}  "
            f"serialize {dispatch.get('dispatch_serialize_s', 0):.2f}s  "
            f"send {dispatch.get('dispatch_send_s', 0):.2f}s  "
            f"lock_wait {dispatch.get('dispatch_lock_wait_s', 0):.2f}s"
        )
        frames = dispatch.get("frames") or {}
        for direction in ("sent", "recv"):
            rows = frames.get(direction)
            if not rows:
                continue
            parts = [
                f"{mtype} {count} ({_fmt_mem(nbytes)})"
                for mtype, (count, nbytes) in sorted(
                    rows.items(), key=lambda kv: -kv[1][0]
                )[:5]
            ]
            out.append(f"  frames {direction}: " + "  ".join(parts))
        out.append("")

    # -- fleet table ---------------------------------------------------
    workers = (fleet.get("workers") or {})
    out.append(
        f"{'WORKER':<16}{'STATE':<14}{'EPOCH':>6}{'RSS':>10}{'LOAD':>8}"
        f"{'TASKS':>8}{'CACHE':>10}{'HIT%':>6}  CLOCK"
    )
    if not workers:
        out.append("  (no live workers — is a fleet running?)")
    for name in sorted(workers):
        row = workers[name]
        state = "up"
        if not row.get("connected", True):
            state = "disconnected"
        elif row.get("draining"):
            state = "draining"
        elif row.get("pressured"):
            state = "pressured"
        nthreads = row.get("nthreads") or 1
        load = f"{row.get('outstanding', 0)}/{nthreads}"
        cache = row.get("peer_cache") or {}
        off = row.get("clock_offset")
        clock = f"{off:+.3f}s" if isinstance(off, (int, float)) else "-"
        epoch = row.get("epoch")
        epoch_s = str(epoch) if isinstance(epoch, int) else "-"
        out.append(
            f"{name:<16}{state:<14}{epoch_s:>6}"
            f"{_fmt_mem(row.get('rss')):>10}"
            f"{load:>8}{row.get('tasks_sent', 0):>8}"
            f"{_fmt_mem(cache.get('bytes')):>10}"
            f"{_worker_hit_rate(row):>6}  {clock}"
        )
    out.append("")

    # -- tenants (multi-tenant service front door) ---------------------
    service = snapshot.get("service") or {}
    tenants = service.get("tenants") or {}
    overload = service.get("overload") or {}
    if overload.get("enabled") or overload.get("breakers_open"):
        level = overload.get("level", 0)
        name = overload.get("name", "normal")
        breakers = overload.get("breakers_open") or []
        out.append(
            f"OVERLOAD  L{level} ({name})  "
            f"shed {overload.get('requests_shed', 0)}  "
            f"transitions {overload.get('transitions', 0)}  "
            f"miss-rate {overload.get('miss_rate', 0.0):.0%}  "
            "breakers open "
            f"{','.join(breakers) if breakers else '-'}"
        )
        out.append("")
    if tenants:
        throttle = " THROTTLING" if service.get("throttling") else ""
        out.append(
            f"TENANTS  ({service.get('running', 0)} running / "
            f"{service.get('slots', '?')} slots, queue "
            f"{service.get('queue_depth', 0)}{throttle})"
        )
        out.append(
            f"{'TENANT':<16}{'WEIGHT':>7}{'QUEUED':>8}{'RUN':>5}"
            f"{'DONE':>7}{'FAIL':>6}{'CACHE%':>8}{'THROTTLED':>11}"
        )
        for name in sorted(tenants):
            row = tenants[name]
            done = row.get("completed") or 0
            hits = (
                (row.get("plan_cache_hits") or 0)
                + (row.get("result_cache_hits") or 0)
            )
            cache = f"{hits / done:.0%}" if done else "-"
            out.append(
                f"{name:<16}{row.get('weight', 1):>7.1f}"
                f"{row.get('queued', 0):>8}{row.get('running', 0):>5}"
                f"{done:>7}{row.get('failed', 0):>6}{cache:>8}"
                f"{row.get('throttled', 0):>11}"
            )
        out.append("")

        # -- per-tenant cost accounting --------------------------------
        costs = {
            name: row.get("cost")
            for name, row in tenants.items()
            if isinstance(row.get("cost"), dict)
        }
        if costs:
            out.append("COST  (per-tenant consumption, cumulative)")
            out.append(
                f"{'TENANT':<16}{'TASK-SEC':>10}{'READ':>11}"
                f"{'WRITTEN':>11}{'PEER':>11}{'RETRIES':>9}"
            )
            for name in sorted(costs):
                cost = costs[name]
                secs = cost.get("task_seconds")
                secs_s = (
                    f"{secs:.2f}" if isinstance(secs, (int, float)) else "-"
                )
                out.append(
                    f"{name:<16}{secs_s:>10}"
                    f"{_fmt_mem(cost.get('bytes_read')):>11}"
                    f"{_fmt_mem(cost.get('bytes_written')):>11}"
                    f"{_fmt_mem(cost.get('peer_bytes')):>11}"
                    f"{cost.get('retries', 0):>9}"
                )
            out.append("")

    # -- per-tenant SLOs (error budget + multi-window burn rates) ------
    slo = service.get("slo") or {}
    if slo:
        out.append("SLO  (error budget + burn rates; burn 1.0 = on pace)")
        out.append(
            f"{'TENANT':<16}{'OBJECTIVE':>16}{'P99':>9}{'GOOD%':>8}"
            f"{'BUDGET':>8}{'5m':>7}{'1h':>7}{'6h':>7}{'3d':>7}  STATE"
        )
        for name in sorted(slo):
            row = slo[name]
            spec_row = row.get("spec") or {}
            if spec_row.get("latency_s") is not None:
                objective = (
                    f"p{spec_row.get('latency_objective', 0) * 100:.0f}"
                    f"<{spec_row['latency_s']:g}s"
                )
            elif spec_row.get("availability_objective") is not None:
                objective = (
                    f"avail{spec_row['availability_objective'] * 100:g}%"
                )
            else:
                objective = "-"
            lat = row.get("latency") or {}
            p99 = lat.get("p99_s")
            p99_s = f"{p99:.3f}s" if isinstance(p99, (int, float)) else "-"
            good = row.get("good_fraction")
            good_s = f"{good:.1%}" if isinstance(good, (int, float)) else "-"
            budget = row.get("budget_remaining")
            budget_s = (
                f"{budget:.0%}" if isinstance(budget, (int, float)) else "-"
            )
            burn = row.get("burn") or {}

            def _b(k):
                v = burn.get(k)
                return f"{v:.1f}" if isinstance(v, (int, float)) else "-"

            state = "OK"
            if row.get("fast_burn"):
                state = "FAST BURN"
            elif row.get("slow_burn"):
                state = "SLOW BURN"
            out.append(
                f"{name:<16}{objective:>16}{p99_s:>9}{good_s:>8}"
                f"{budget_s:>8}{_b('5m'):>7}{_b('1h'):>7}{_b('6h'):>7}"
                f"{_b('3d'):>7}  {state}"
            )
        out.append("")

    # -- compute progress ----------------------------------------------
    out.append("COMPUTES")
    computes = snapshot.get("computes") or []
    if not computes:
        out.append("  (none tracked)")
    for row in computes[-5:]:
        done = row.get("tasks_done") or 0
        total = row.get("tasks_total") or 0
        # retries/backup twins can complete more attempts than the plan
        # has tasks: clamp so the bar (and percentage) never overflow
        frac = min(1.0, done / total) if total else 0.0
        bar_w = 24
        filled = min(bar_w, int(round(frac * bar_w)))
        bar = "#" * filled + "-" * (bar_w - filled)
        rate = _series_rate(
            snapshot, "compute_tasks_done",
            {"compute": row.get("compute_id")},
        )
        eta = None
        if rate and total:
            eta = (total - done) / rate
        status = row.get("status") or "?"
        line = (
            f"  {row.get('compute_id', '?'):<16}[{bar}] "
            f"{done}/{total} ({frac:.0%}) {status}"
        )
        if status == "running":
            line += (
                f"  {rate:.1f} tasks/s  ETA {_fmt_eta(eta)}"
                if rate else "  rate - ETA -"
            )
        out.append(line)
    out.append("")

    # -- alerts --------------------------------------------------------
    active = set(snapshot.get("alerts_active") or [])
    alerts = snapshot.get("alerts") or []
    out.append(f"ALERTS ({len(active)} active)")
    if not alerts:
        out.append("  (none fired)")
    for firing in alerts[-8:]:
        fts = firing.get("ts")
        fstamp = (
            time.strftime("%H:%M:%S", time.localtime(fts))
            if isinstance(fts, (int, float)) else "-"
        )
        flag = "*" if firing.get("rule") in active else " "
        out.append(f" {flag}{fstamp} {format_alert_row(firing)}")
    return "\n".join(out) + "\n"


def default_endpoint() -> str:
    port = os.environ.get("CUBED_TPU_TELEMETRY_PORT", "").strip()
    if not port or port in ("0", "off"):
        port = "9090"
    return f"127.0.0.1:{port}"


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cubed_tpu.top", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "endpoint", nargs="?", default=None,
        help="telemetry endpoint host:port (default "
        "127.0.0.1:$CUBED_TPU_TELEMETRY_PORT)",
    )
    parser.add_argument(
        "--interval", type=float, default=2.0,
        help="refresh period in seconds (default 2)",
    )
    parser.add_argument(
        "--once", action="store_true",
        help="render one frame and exit",
    )
    parser.add_argument(
        "--snapshot", metavar="FILE", default=None,
        help="render a saved /snapshot.json file offline and exit "
        "(no live endpoint needed — post-mortems, tests, CI)",
    )
    args = parser.parse_args(argv)
    if args.snapshot:
        try:
            with open(args.snapshot) as f:
                snapshot = json.load(f)
        except (OSError, ValueError) as e:
            print(
                f"cannot read snapshot file {args.snapshot!r}: {e}",
                file=sys.stderr,
            )
            return 2
        sys.stdout.write(render(snapshot))
        return 0
    endpoint = args.endpoint or default_endpoint()
    while True:
        try:
            snapshot = fetch_snapshot(endpoint)
        except Exception as e:
            print(
                f"cannot reach telemetry endpoint {endpoint}: {e}\n"
                "arm it with Spec(telemetry_port=...) or "
                "CUBED_TPU_TELEMETRY_PORT on the client process",
                file=sys.stderr,
            )
            return 2
        frame = render(snapshot)
        if args.once:
            sys.stdout.write(frame)
            return 0
        sys.stdout.write((_CLEAR if sys.stdout.isatty() else "") + frame)
        sys.stdout.flush()
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
