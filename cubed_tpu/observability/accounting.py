"""Byte accounting for storage IO, with per-task attribution.

The storage layer calls ``record_bytes_read`` / ``record_bytes_written`` on
every chunk transfer. Attribution rules:

- Inside an active **task scope** (``task_scope()`` — entered by
  ``execute_with_stats`` around every task body), bytes accumulate on the
  scope object and ride back to the client in the task's stats dict. This is
  what makes the numbers survive process boundaries: multiprocess and
  distributed workers measure their own IO and the client aggregates it from
  ``TaskEndEvent``s.
- Outside any task scope (the JAX executor's whole-array preloads/flushes,
  plan-level metadata ops), bytes go straight to the process registry.

The two paths are exclusive by construction, so summing task-event bytes
into the registry (``callback._ComputeAggregator``) never double-counts.

A bounded per-store breakdown (``store_totals()``) is kept in-process either
way, for debugging which store dominates IO; overflow beyond
``MAX_TRACKED_STORES`` aggregates under ``"<other>"``.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from . import clock
from .metrics import get_registry

#: cap on per-store breakdown entries (plans create one temp store per
#: intermediate array; an unbounded dict would grow with every plan)
MAX_TRACKED_STORES = 128

#: cap on spans buffered per task: a pathological task (thousands of chunk
#: reads) must not ship a megabyte of span payload with its result — excess
#: spans drop with a count, surfaced as the ``spans_dropped`` counter
MAX_TASK_SPANS = 128

#: operator override for span recording ("1" forces it on everywhere; also
#: how a client's arming reaches spawned pool workers)
SPANS_ENV_VAR = "CUBED_TPU_TASK_SPANS"

#: process-global arming state (None = defer to env/default-off). Span
#: recording is opt-in per compute: ``Plan.execute`` arms it only while a
#: ``TraceCollector``/``FlightRecorder`` is attached, so an unobserved
#: compute records no span dicts and ships no span payload in its result
#: frames — the same arming pattern fault injection and the integrity mode
#: use (env export for pool spawns, task-message mirroring for fleets)
_spans_armed: Optional[bool] = None

_tls = threading.local()

_store_lock = threading.Lock()
_store_totals: Dict[str, list] = {}

#: a human-readable label for THIS process ("local-0" for a fleet worker,
#: None for the client / pool workers) — stamped on task stats so merged
#: traces can give each worker its own lane and look up its clock offset
_process_label: Optional[str] = None


def set_process_label(label: Optional[str]) -> None:
    global _process_label
    _process_label = label


def get_process_label() -> Optional[str]:
    return _process_label


def spans_enabled() -> bool:
    """Whether ``scope_span`` records anything (env > armed > off)."""
    env = os.environ.get(SPANS_ENV_VAR)
    if env:
        return env == "1"
    if _spans_armed is not None:
        return _spans_armed
    return False


def spans_wire() -> bool:
    """The client's resolved arming, attached to every fleet task message
    so pre-started workers record spans exactly when the client collects
    them (and stop when it doesn't)."""
    return spans_enabled()


def arm_spans_from_wire(armed) -> None:
    """Fleet-worker side: mirror the arming a task message carried."""
    global _spans_armed
    _spans_armed = None if armed is None else bool(armed)


class spans_scoped:
    """Arm span recording for a ``with`` block (``Plan.execute`` uses this
    while a trace collector is attached); ``None`` is a no-op. With
    ``export_env`` the env var is set so pool workers spawned inside the
    block inherit the arming — unless the operator already set it, in
    which case their override passes through untouched (the same env-wins
    rule the integrity/memory-guard scopes follow)."""

    def __init__(self, armed: Optional[bool] = None, export_env: bool = False):
        self._armed = armed
        self._export_env = export_env

    def __enter__(self):
        if self._armed is None:
            return None
        global _spans_armed
        self._prev = _spans_armed
        self._prev_env = os.environ.get(SPANS_ENV_VAR)
        _spans_armed = bool(self._armed)
        if self._export_env and self._armed and self._prev_env is None:
            os.environ[SPANS_ENV_VAR] = "1"
        return self._armed

    def __exit__(self, *exc) -> None:
        if self._armed is None:
            return
        global _spans_armed
        _spans_armed = self._prev
        if self._export_env:
            if self._prev_env is None:
                os.environ.pop(SPANS_ENV_VAR, None)
            else:
                os.environ[SPANS_ENV_VAR] = self._prev_env


class TaskScope:
    """Accumulates IO (and named event counts) attributed to one task body."""

    __slots__ = (
        "bytes_read",
        "bytes_written",
        "chunks_read",
        "chunks_written",
        "virtual_bytes_read",
        "counters",
        "spans",
        "spans_dropped",
    )

    def __init__(self):
        self.bytes_read = 0
        self.bytes_written = 0
        self.chunks_read = 0
        self.chunks_written = 0
        self.virtual_bytes_read = 0
        #: named counts (integrity verifications/corruption/quarantines)
        #: recorded inside this scope — riding the stats dict across process
        #: boundaries exactly like the byte counters
        self.counters: Dict[str, int] = {}
        #: bounded buffer of spans recorded inside this task body (storage
        #: reads/writes, kernel apply, integrity verify, retry sleeps) —
        #: measured on THIS process's clock, shipped back in the stats dict
        #: like the byte counters so remote work becomes visible in the
        #: merged trace (observability/collect.py)
        self.spans: list = []
        self.spans_dropped = 0

    def add_span(
        self, name: str, start: float, end: float, cat: str = "span", **attrs
    ) -> None:
        if len(self.spans) >= MAX_TASK_SPANS:
            self.spans_dropped += 1
            return
        span = {"name": name, "ts": start, "dur": max(0.0, end - start),
                "cat": cat}
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)

    def stats(self) -> dict:
        return {
            "bytes_read": self.bytes_read,
            "bytes_written": self.bytes_written,
            "chunks_read": self.chunks_read,
            "chunks_written": self.chunks_written,
            "virtual_bytes_read": self.virtual_bytes_read,
            "counters": dict(self.counters),
            "spans": list(self.spans),
            "spans_dropped": self.spans_dropped,
        }


class task_scope:
    """Context manager establishing a per-task accounting scope.

    Scopes nest (a task body running a nested compute): each byte is
    attributed to the INNERMOST scope only, never folded outward — the
    inner task's event already carries those bytes into client-side
    aggregation, so folding them into the outer task's stats as well would
    count them twice.
    """

    def __enter__(self) -> TaskScope:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._scope = TaskScope()
        stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc) -> None:
        _tls.stack.pop()


def current_scope() -> Optional[TaskScope]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


class scope_span:
    """Time a block of code as a span on the current task scope.

    A no-op (no timestamps taken, nothing allocated beyond this object)
    when no task scope is active — metadata/plan-level IO stays unspanned —
    or when span recording is disarmed (``spans_enabled``): a compute with
    no trace collector attached pays nothing for span bookkeeping.
    The ``attrs`` dict is mutable until exit, so callers can attach
    results measured inside the block (byte counts, retry counts). A block
    that raises still records its span, closed at the raise instant with
    ``error=True`` — failures are when the trace matters most.
    """

    __slots__ = ("name", "cat", "attrs", "_scope", "_start")

    def __init__(self, name: str, cat: str = "span", **attrs):
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self._scope: Optional[TaskScope] = None

    def __enter__(self) -> "scope_span":
        self._scope = current_scope() if spans_enabled() else None
        if self._scope is not None:
            self._start = clock.now()
        return self

    def __exit__(self, exc_type, *exc) -> None:
        scope = self._scope
        if scope is None:
            return
        if exc_type is not None:
            self.attrs["error"] = True
            self.attrs["error_type"] = exc_type.__name__
        scope.add_span(
            self.name, self._start, clock.now(), cat=self.cat, **self.attrs
        )


def _track_store(store: str, read: int, written: int) -> None:
    key = str(store)
    with _store_lock:
        entry = _store_totals.get(key)
        if entry is None:
            if len(_store_totals) >= MAX_TRACKED_STORES:
                key = "<other>"
                entry = _store_totals.get(key)
            if entry is None:
                entry = _store_totals[key] = [0, 0]
        entry[0] += read
        entry[1] += written


def record_bytes_read(store: str, n: int) -> None:
    scope = current_scope()
    if scope is not None:
        scope.bytes_read += n
        scope.chunks_read += 1
    else:
        reg = get_registry()
        reg.counter("bytes_read").inc(n)
        reg.counter("chunks_read").inc()
    _track_store(store, n, 0)


def record_bytes_written(store: str, n: int) -> None:
    scope = current_scope()
    if scope is not None:
        scope.bytes_written += n
        scope.chunks_written += 1
    else:
        reg = get_registry()
        reg.counter("bytes_written").inc(n)
        reg.counter("chunks_written").inc()
    _track_store(store, 0, n)


def record_scoped_counter(name: str, n: int = 1) -> None:
    """Count a named event with per-task attribution.

    Inside a task scope the count rides the task's stats dict back to the
    client (surviving process/fleet boundaries) and the compute aggregator
    folds it into the client registry; outside any scope it goes straight
    to the process registry. Used by the integrity layer so worker-side
    verification/corruption/quarantine counts reach compute stats."""
    scope = current_scope()
    if scope is not None:
        scope.counters[name] = scope.counters.get(name, 0) + n
    else:
        get_registry().counter(name).inc(n)


def record_virtual_read(n: int) -> None:
    """A read served by a virtual (never-materialized) array: logical bytes,
    no IO — tracked separately from ``bytes_read`` so that stays an IO
    number, but still scope-attributed so worker-side virtual reads reach
    the client like real IO does."""
    scope = current_scope()
    if scope is not None:
        scope.virtual_bytes_read += n
    else:
        get_registry().counter("virtual_bytes_read").inc(n)


def store_totals() -> Dict[str, dict]:
    """Per-store {bytes_read, bytes_written} seen by THIS process."""
    with _store_lock:
        return {
            k: {"bytes_read": r, "bytes_written": w}
            for k, (r, w) in _store_totals.items()
        }


def reset_store_totals() -> None:
    with _store_lock:
        _store_totals.clear()
