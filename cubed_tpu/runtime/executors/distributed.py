"""Distributed executor: the multi-host fleet analogue.

Fills the role of the reference's cloud executors (lithops/modal/beam/dask —
SURVEY §2.4): a coordinator in the client process fans chunk tasks out to
worker processes on many hosts over TCP, with the same reliability contract
(idempotent whole-chunk Zarr writes + retries + speculative straggler
backups, all via the shared ``map_unordered`` machinery). See
``cubed_tpu/runtime/distributed.py`` for the fabric and
``docs/multihost.md`` for the pod-deployment story.

Two ways to get workers:

- ``DistributedDagExecutor(n_local_workers=4)`` spawns that many local
  worker subprocesses (single-host parallelism, and how the tests exercise
  the full network path).
- ``DistributedDagExecutor(listen="0.0.0.0:8765", min_workers=4)`` binds a
  fixed address and waits for out-of-band workers
  (``python -m cubed_tpu.runtime.worker coordinator-host:8765`` on each
  host) to join before the first compute.

The executor (and its worker fleet) persists across ``compute()`` calls;
``close()`` — or using it as a context manager — tears the fleet down.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
from typing import Optional

from ...observability import accounting
from ...observability import logs as obs_logs
from .. import transfer
from ..dataflow import (
    DataflowScheduler,
    effective_scheduler,
    record_scheduler_mode,
    task_hint_key,
    task_tag,
)
from ..distributed import Coordinator, NoWorkersError
from ..memory import AdmissionController
from ..pipeline import (
    RecomputeResolver,
    ResumeState,
    pending_mappable,
    visit_node_generations,
    visit_nodes,
)
from ..resilience import DEFAULT_RETRIES, RetryPolicy, resolve_policy
from ..types import (
    DagExecutor,
    OperationEndEvent,
    OperationStartEvent,
    callbacks_on,
)
from ..utils import end_generation, merge_generation
from .multiprocess import _PLUGIN_ENV_PREFIXES
from .python_async import compute_retry_budget, map_unordered

logger = logging.getLogger(__name__)


#: per-compute client state that must NOT leak into persistent fleet
#: workers: these env exports exist for per-compute pool spawns, but a fleet
#: outlives the compute that spawned it and gets the live values on every
#: task message — an inherited copy would outrank the wire (env > armed) and
#: pin spans/compute-id to the spawning compute forever
_PER_COMPUTE_ENV_VARS = (
    accounting.SPANS_ENV_VAR,
    obs_logs.COMPUTE_ID_ENV_VAR,
)


def _worker_env() -> dict:
    """Hermetic env for locally spawned workers: CPU jax, no device plugin
    registration (workers do chunk IO + host compute; the client process owns
    any device executor)."""
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(_PLUGIN_ENV_PREFIXES)
        and k not in _PER_COMPUTE_ENV_VARS
    }
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    )
    prev = env.get("PYTHONPATH")
    env["PYTHONPATH"] = repo_root + (os.pathsep + prev if prev else "")
    return env


class DistributedDagExecutor(DagExecutor):
    """Coordinator/worker fleet executor (multi-host control plane)."""

    def __init__(
        self,
        n_local_workers: Optional[int] = None,
        listen: Optional[str] = None,
        min_workers: Optional[int] = None,
        max_workers: Optional[int] = None,
        autoscale: Optional[bool] = None,
        autoscale_policy=None,
        drain_grace_s: float = 30.0,
        worker_threads: int = 1,
        worker_start_timeout: float = 60.0,
        task_timeout: Optional[float] = None,
        timeout_strikes: int = 2,
        lease_s: float = 15.0,
        peer_transfer: Optional[bool] = None,
        retries: int = DEFAULT_RETRIES,
        use_backups: bool = True,
        batch_size: Optional[int] = None,
        compute_arrays_in_parallel: bool = False,
        retry_policy: Optional[RetryPolicy] = None,
        control_dir: Optional[str] = None,
        takeover_grace_s: Optional[float] = None,
        **kwargs,
    ):
        if n_local_workers is None and listen is None:
            n_local_workers = 2
        self.n_local_workers = n_local_workers
        self.listen = listen
        self.min_workers = min_workers if min_workers is not None else (
            n_local_workers or 1
        )
        self.max_workers = max_workers
        if max_workers is not None:
            floor = max(self.min_workers, n_local_workers or 0)
            if max_workers < floor:
                raise ValueError(
                    f"max_workers={max_workers} is below the fleet floor "
                    f"(min_workers={self.min_workers}, n_local_workers="
                    f"{n_local_workers}): the ceiling could never be "
                    "honored — lower the initial fleet or raise max_workers"
                )
        # the autoscaler is on when asked for explicitly, or implied by a
        # max_workers ceiling / a full policy object; a plain fixed-size
        # fleet (the historical constructor) keeps its exact old behavior
        self.autoscale = (
            autoscale
            if autoscale is not None
            else (max_workers is not None or autoscale_policy is not None)
        )
        self.autoscale_policy = autoscale_policy
        self.drain_grace_s = drain_grace_s
        self.worker_threads = worker_threads
        self.worker_start_timeout = worker_start_timeout
        self.task_timeout = task_timeout
        self.timeout_strikes = timeout_strikes
        #: how long a disconnected worker keeps its in-flight tasks before
        #: they requeue as worker loss (runtime/distributed.py leases)
        self.lease_s = lease_s
        #: peer-to-peer chunk transfer (runtime/transfer.py): None defers
        #: to CUBED_TPU_P2P / Spec(peer_transfer=...), the effective
        #: default being ON — store-only (peer_transfer=False or
        #: CUBED_TPU_P2P=off) is the explicit escape hatch
        self.peer_transfer = peer_transfer
        self.retries = retries
        self.use_backups = use_backups
        self.batch_size = batch_size
        self.compute_arrays_in_parallel = compute_arrays_in_parallel
        self.retry_policy = retry_policy
        #: control-plane durability directory (runtime/journal.py
        #: ControlLog): the coordinator persists its epoch, worker roster,
        #: and dispatch frontier there and advertises its address in
        #: ``rendezvous.json``. A fresh executor pointed at the same dir
        #: after a coordinator crash comes up as the next epoch and adopts
        #: the still-running fleet instead of cold-starting.
        self.control_dir = control_dir
        self.takeover_grace_s = takeover_grace_s
        self.kwargs = kwargs
        self._coordinator: Optional[Coordinator] = None
        #: append-only spawn log: worker ``local-<i>`` is ``_procs[i]``
        #: forever (replacements append with fresh indices), which keeps
        #: the exit probe correct across the autoscaler's churn; retired/
        #: dead entries stay (a reaped Popen costs nothing to re-wait)
        self._procs: list[subprocess.Popen] = []
        self._procs_lock = threading.Lock()
        self._autoscaler = None

    @property
    def name(self) -> str:
        return "distributed"

    # -- fleet lifecycle -----------------------------------------------

    @property
    def stats(self) -> dict:
        """Coordinator counters (blobs_sent, tasks_sent, task_timeouts,
        workers_lost, drains_completed, workers_preempted,
        tasks_abandoned_on_drain) plus a per-worker load snapshot and, when
        the autoscaler runs, its scale counters; empty before the fleet
        starts."""
        if self._coordinator is None:
            return {}
        out = self._coordinator.stats_snapshot()
        if self._autoscaler is not None:
            out["autoscale"] = dict(self._autoscaler.stats)
        return out

    @property
    def coordinator_address(self) -> Optional[str]:
        if self._coordinator is None:
            return None
        host, port = self._coordinator.address
        return f"{host}:{port}"

    def _ensure_fleet(self) -> Coordinator:
        if self._coordinator is not None:
            return self._coordinator
        if self.listen is not None:
            host, _, port = self.listen.rpartition(":")
            coord = Coordinator(host or "0.0.0.0", int(port or 0),
                                task_timeout=self.task_timeout,
                                timeout_strikes=self.timeout_strikes,
                                lease_s=self.lease_s,
                                control_dir=self.control_dir,
                                takeover_grace_s=self.takeover_grace_s)
            logger.info(
                "coordinator listening on %s:%s; waiting for %d workers",
                coord.address[0], coord.address[1], self.min_workers,
            )
        else:
            coord = Coordinator("127.0.0.1", 0, task_timeout=self.task_timeout,
                                timeout_strikes=self.timeout_strikes,
                                lease_s=self.lease_s,
                                control_dir=self.control_dir,
                                takeover_grace_s=self.takeover_grace_s)
        self._coordinator = coord
        initial_names: list = []
        if self.n_local_workers:
            for _ in range(self.n_local_workers):
                initial_names.append(self._spawn_local_worker())
            # locally spawned workers have inspectable exit codes: a
            # dropped connection plus -9/137 reads as OOM-killed, and the
            # WorkerLostError message says so instead of a bare reset
            coord.exit_probe = self._local_worker_exitcode
        if self.autoscale:
            from ..autoscale import Autoscaler, AutoscalePolicy

            initial = self.n_local_workers or self.min_workers or 1
            mw = max(1, self.min_workers or 1)
            policy = self.autoscale_policy or AutoscalePolicy(
                min_workers=mw,
                max_workers=self.max_workers or max(8, initial, mw),
                drain_grace_s=self.drain_grace_s,
            )
            factory = (
                _LocalWorkerFactory(self) if self.n_local_workers else None
            )
            self._autoscaler = Autoscaler(
                coord, factory=factory, policy=policy,
                initial_workers=initial, pending_workers=initial_names,
            )
            self._autoscaler.start()
        try:
            coord.wait_for_workers(self.min_workers, self.worker_start_timeout)
        except TimeoutError:
            self.close()
            raise
        return coord

    def _spawn_local_worker(self) -> str:
        """Spawn one local worker subprocess; returns its name. Used for
        the initial fleet and as the autoscaler's ``WorkerFactory`` — the
        single-host stand-in for asking the cloud for another (spot)
        instance."""
        coord = self._coordinator
        assert coord is not None
        host, port = coord.address
        cmd = [
            sys.executable,
            "-m",
            "cubed_tpu.runtime.worker",
            f"{host}:{port}",
            "--threads",
            str(self.worker_threads),
        ]
        # operator convention: the env knob wins (it feeds the worker
        # CLI's --drain-grace default); only without it does the
        # executor's configured grace ride the command line
        if "CUBED_TPU_DRAIN_GRACE_S" not in os.environ:
            cmd += ["--drain-grace", str(self.drain_grace_s)]
        if self.control_dir is not None:
            # workers chase a successor coordinator through the
            # advertisement file instead of dying with the old socket
            from ..journal import rendezvous_path

            cmd += ["--rendezvous", rendezvous_path(self.control_dir)]
        with self._procs_lock:
            i = len(self._procs)
            name = f"local-{i}"
            self._procs.append(
                subprocess.Popen(
                    cmd + ["--name", name], env=_worker_env()
                )
            )
        return name

    def _proc_for(self, name: str) -> Optional[subprocess.Popen]:
        """Popen for a locally spawned worker name (``local-<i>``), or
        None for out-of-band names / unknown indices."""
        if not name.startswith("local-"):
            return None
        try:
            i = int(name.split("-", 1)[1])
        except ValueError:
            return None
        with self._procs_lock:
            try:
                return self._procs[i]
            except IndexError:
                return None

    def _retire_local_worker(self, name: str) -> None:
        """Reap a worker whose graceful drain was already requested: wait
        for it to exit on its own inside the grace window, escalate to
        SIGTERM/SIGKILL if it lingers. Runs on a daemon thread so the
        autoscaler's policy loop never blocks on a slow exit."""
        proc = self._proc_for(name)
        if proc is None:
            return
        # the reap deadline must cover the grace the DRAIN was granted —
        # the autoscaler's policy grace when it initiated the retirement,
        # which may exceed this executor's own drain_grace_s default
        scaler = self._autoscaler
        grace = (
            scaler.policy.drain_grace_s if scaler is not None
            else self.drain_grace_s
        )

        def reap() -> None:
            try:
                proc.wait(timeout=grace + 10)
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=5)

        threading.Thread(
            target=reap, name=f"reap-{name}", daemon=True
        ).start()

    def _local_worker_exitcode(self, name: str):
        """Exit code of a locally spawned worker (names ``local-<i>``), or
        None while it still runs / for out-of-band workers. Polls briefly:
        the process usually finishes dying within a few ms of its socket
        resetting, and a definite code is worth a short wait."""
        import time

        proc = self._proc_for(name)
        if proc is None:
            return None
        for _ in range(10):
            code = proc.poll()
            if code is not None:
                return code
            time.sleep(0.05)
        return None

    def close(self) -> None:
        """Tear down the autoscaler, the coordinator, and every locally
        spawned worker — including ones mid-drain or retired earlier (the
        spawn log is append-only, so nothing is ever orphaned)."""
        if self._autoscaler is not None:
            # first, so it cannot backfill workers we are tearing down
            self._autoscaler.stop()
            self._autoscaler = None
        if self._coordinator is not None:
            self._coordinator.close()
            self._coordinator = None
        with self._procs_lock:
            procs = list(self._procs)
            self._procs.clear()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)

    def __enter__(self):
        self._ensure_fleet()
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; explicit close() is the contract
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        # the executor can ride inside a Spec that gets serialized into task
        # payloads; the fleet (sockets, subprocesses) is process-local state
        # a worker neither needs nor could use
        state = self.__dict__.copy()
        state["_coordinator"] = None
        state["_procs"] = []
        state["_procs_lock"] = None
        state["_autoscaler"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._procs_lock = threading.Lock()

    # -- execution -----------------------------------------------------

    def resume_compute(self, array, journal: str, **kwargs):
        """Continue a compute whose client/coordinator process crashed.

        Rebuild the SAME plan (same code ⇒ same deterministic op names),
        then call this with the journal file the crashed run was writing
        (``Spec(journal=...)``): coordinator-side progress is rebuilt from
        the journal's completed-task frontier intersected with the
        chunk-integrity resume scan, and only the remainder re-runs —
        bitwise-identical to an uninterrupted run. Returns the computed
        numpy array. Equivalent to
        ``array.compute(executor=self, resume_from_journal=journal)``."""
        return array.compute(
            executor=self, resume_from_journal=str(journal), **kwargs
        )

    def execute_dag(
        self,
        dag,
        callbacks=None,
        array_names=None,
        resume=None,
        spec=None,
        retries: Optional[int] = None,
        use_backups: Optional[bool] = None,
        batch_size: Optional[int] = None,
        compute_arrays_in_parallel: Optional[bool] = None,
        retry_policy: Optional[RetryPolicy] = None,
        journal=None,
        cancellation=None,
        **kwargs,
    ) -> None:
        retries = self.retries if retries is None else retries
        use_backups = self.use_backups if use_backups is None else use_backups
        batch_size = self.batch_size if batch_size is None else batch_size
        if compute_arrays_in_parallel is None:
            compute_arrays_in_parallel = self.compute_arrays_in_parallel
        policy = resolve_policy(retry_policy or self.retry_policy, retries)
        budget = compute_retry_budget(policy, dag)
        # one controller per compute: a worker-side OOM (RESOURCE off the
        # wire) steps coordinator-side task admission down for all ops
        admission = AdmissionController()

        coord = self._ensure_fleet()
        from ...observability.collect import record_decision

        # the fleet's shape at compute start anchors the decision timeline
        # (a later worker loss reads very differently at 8 workers vs 1)
        record_decision(
            "fleet_compute", n_workers=coord.n_workers,
            coordinator=f"{coord.address[0]}:{coord.address[1]}",
        )
        if coord.n_workers == 0 and (
            self._autoscaler is not None and self.min_workers > 0
        ):
            # the fleet self-heals (autoscaler holds a min_workers floor):
            # a momentarily empty fleet — e.g. a poison task just took out
            # every worker at once — is a backfill in flight, not a
            # configuration error, so ride it out instead of failing the
            # compute in the gap
            try:
                coord.wait_for_workers(1, timeout=self.worker_start_timeout)
            except TimeoutError:
                pass  # fall through to the zero-workers diagnostic
        if coord.n_workers == 0:
            # fail fast with a diagnostic instead of letting the first
            # submit discover it mid-plan (min_workers=0 configurations
            # sail past wait_for_workers without anyone ever joining)
            host, port = coord.address
            raise NoWorkersError(
                f"compute submitted with zero live workers (coordinator "
                f"{host}:{port}, min_workers={self.min_workers}); start "
                "workers with 'python -m cubed_tpu.runtime.worker "
                f"{host}:{port}' or configure n_local_workers/min_workers "
                "so the fleet is populated before computing"
            )

        if cancellation is not None:
            # the moment the token trips — an explicit cancel from any
            # thread, or the dispatch loop observing an expired deadline —
            # broadcast a compute_cancel frame so every fleet worker
            # aborts cooperatively at its next safe boundary instead of
            # waiting for its next task message to carry the tripped state
            cid = obs_logs.current_compute_id()
            cancellation.on_abort(
                lambda: coord.broadcast_cancel(
                    cid, reason=cancellation.reason
                )
            )

        state = (
            ResumeState(quarantine=True, journal=journal) if resume else None
        )
        # integrity failures cross the wire as RemoteTaskError carrying the
        # corrupt chunk's (store, key); the repair task runs client-side
        # against the shared store the whole fleet reads
        resolver = RecomputeResolver(dag)
        # a defaulted dataflow yields to an explicit batch_size (the rule
        # lives in dataflow.effective_scheduler); explicit requests win
        # and warn below
        scheduler = effective_scheduler(spec, batch_size)
        record_scheduler_mode(scheduler, executor=self.name)
        # peer-to-peer chunk transfer: env > Spec > executor arg > off.
        # Armed for this compute's duration — the coordinator attaches the
        # wire config to every task message, so pre-started fleet workers
        # cache/advertise/fetch exactly when this compute asked for it
        peer_on = transfer.resolve_peer_transfer(spec, self.peer_transfer)
        record_decision(
            "peer_transfer", enabled=peer_on, scheduler=scheduler,
        )
        with transfer.client_scoped(peer_on):
            if scheduler == "dataflow":
                # the coordinator already routes per-item (op, task) pairs
                # (_InterleavedPool); dataflow just widens the item set to
                # the whole DAG and gates each on its own input chunks
                if batch_size:
                    logger.warning(
                        "batch_size=%s is ignored under scheduler="
                        "\"dataflow\" (the whole DAG is one dependency-"
                        "gated map)",
                        batch_size,
                    )
                sched = DataflowScheduler(
                    dag, resume=resume, state=state, callbacks=callbacks
                )
                sched.start()
                try:
                    if sched.items:
                        map_unordered(
                            _InterleavedPool(
                                coord, sched.pipelines,
                                # the chunk graph knows each task's input
                                # chunks: dispatch scores workers by input
                                # bytes already cache-resident (only
                                # meaningful with the peer data plane on)
                                locality_hints=(
                                    sched.locality_hints() if peer_on
                                    else None
                                ),
                            ),
                            None,
                            sched.items,
                            retry_policy=policy,
                            retry_budget=budget,
                            use_backups=use_backups,
                            callbacks=callbacks,
                            array_names=sched.array_names,
                            executor_name=self.name,
                            recompute_resolver=resolver,
                            admission=admission,
                            dependencies=sched.dependencies,
                            on_input_submit=sched.on_submit,
                            on_input_done=sched.on_done,
                            cancellation=cancellation,
                        )
                finally:
                    sched.finish()
            elif compute_arrays_in_parallel:
                for generation in visit_node_generations(
                    dag, resume=resume, state=state
                ):
                    merged, pipelines = merge_generation(
                        generation, callbacks, resume=resume,
                        resume_state=state,
                    )
                    if not merged:
                        end_generation(generation, callbacks)
                        continue
                    map_unordered(
                        _InterleavedPool(coord, pipelines),
                        None,
                        merged,
                        retry_policy=policy,
                        retry_budget=budget,
                        use_backups=use_backups,
                        batch_size=batch_size,
                        callbacks=callbacks,
                        array_names=[name for name, _ in merged],
                        executor_name=self.name,
                        recompute_resolver=resolver,
                        admission=admission,
                        cancellation=cancellation,
                    )
                    end_generation(generation, callbacks)
            else:
                for name, node in visit_nodes(dag, resume=resume, state=state):
                    primitive_op = node["primitive_op"]
                    pipeline = primitive_op.pipeline
                    callbacks_on(
                        callbacks, "on_operation_start",
                        OperationStartEvent(name, primitive_op.num_tasks),
                    )
                    mappable, _ = pending_mappable(name, node, resume, state)
                    map_unordered(
                        _OpPool(coord, pipeline, name),
                        pipeline.function,
                        mappable,
                        retry_policy=policy,
                        retry_budget=budget,
                        use_backups=use_backups,
                        batch_size=batch_size,
                        callbacks=callbacks,
                        array_name=name,
                        executor_name=self.name,
                        recompute_resolver=resolver,
                        admission=admission,
                        cancellation=cancellation,
                        config=pipeline.config,
                    )
                    callbacks_on(
                        callbacks, "on_operation_end",
                        OperationEndEvent(name, primitive_op.num_tasks),
                    )


class _LocalWorkerFactory:
    """The autoscaler's :class:`~cubed_tpu.runtime.autoscale.WorkerFactory`
    for locally spawned fleets: another worker subprocess on this host
    (the single-host stand-in for another spot instance), reaped after its
    graceful drain."""

    def __init__(self, executor: DistributedDagExecutor):
        self._executor = executor

    def start_worker(self):
        return self._executor._spawn_local_worker()

    def stop_worker(self, name: str) -> None:
        self._executor._retire_local_worker(name)

    def spawn_failed(self, name: str) -> bool:
        proc = self._executor._proc_for(name)
        return proc is not None and proc.poll() is not None


class _OpPool:
    """concurrent.futures-shaped adapter routing one op's tasks to the
    coordinator (map_unordered calls
    ``pool.submit(execute_with_stats, function, input, config=...)``)."""

    def __init__(self, coordinator: Coordinator, pipeline, op_name=None):
        self.coordinator = coordinator
        self.pipeline = pipeline
        self.op_name = op_name

    def submit(self, stats_wrapper, function, task_input, *, config=None):
        tag = (
            task_tag(self.op_name, task_input)
            if self.op_name is not None
            else None
        )
        return self.coordinator.submit(
            stats_wrapper, function, task_input, config=config, tag=tag
        )


class _InterleavedPool:
    """Adapter for generation-interleaved items ``(op_name, m)``: resolves
    each item's pipeline so every op keeps its own (function, config) blob.

    ``locality_hints`` (dataflow + peer transfer) maps ``(op, chunk key)``
    to the task's input chunks so the coordinator can place it on the
    worker already holding those bytes."""

    def __init__(
        self, coordinator: Coordinator, pipelines: dict,
        locality_hints: Optional[dict] = None,
    ):
        self.coordinator = coordinator
        self.pipelines = pipelines
        self.locality_hints = locality_hints

    def submit(self, stats_wrapper, _fn, item, **kwargs):
        name, m = item
        pipeline = self.pipelines[name]
        locality = None
        if self.locality_hints is not None and isinstance(m, (tuple, list)):
            # blockwise out-key items key by their dotted chunk key,
            # rechunk slice-regions by their region identity (shared
            # contract: dataflow.task_hint_key) — create-arrays items
            # carry other shapes and simply have no hints
            locality = self.locality_hints.get((name, task_hint_key(m)))
        return self.coordinator.submit(
            stats_wrapper, pipeline.function, m, config=pipeline.config,
            locality=locality, tag=task_tag(name, m),
        )
