"""Straggler-backup policy unit tests. Reference parity:
cubed/tests/runtime/test_backup.py, extended with edge cases (zero-duration
tasks, single-task ops, already-backed-up tasks) and the
``speculative_backups`` metrics contract."""

import concurrent.futures

from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.backup import should_launch_backup


def test_not_enough_started():
    start = {i: 0.0 for i in range(5)}
    end = {i: 1.0 for i in range(4)}
    assert not should_launch_backup(4, 100.0, start, end)


def test_not_enough_completed():
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(5)}  # <50%
    assert not should_launch_backup(19, 100.0, start, end)


def test_not_slow_enough():
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(15)}
    # median duration 1.0; task at 2.5x is under the 3x threshold
    assert not should_launch_backup(19, 2.5, start, end)


def test_backup_launched_for_straggler():
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(15)}
    assert should_launch_backup(19, 3.5, start, end)


def test_zero_duration_tasks_make_any_elapsed_task_a_straggler():
    """All completed tasks took ~0s -> the median is 0, so 3x the median is
    0 and any task that has been running a measurable time is an outlier.
    That is the intended reading: against instant peers, a runner IS slow."""
    start = {i: 0.0 for i in range(20)}
    end = {i: 0.0 for i in range(15)}  # zero-duration completions
    assert should_launch_backup(19, 0.001, start, end)
    # but a task with zero elapsed time is not (0 > 3*0 is false)
    assert not should_launch_backup(19, 0.0, start, end)


def test_single_task_op_never_launches_backup():
    """A 1-task op can't establish a median; the min-started floor keeps
    the policy silent rather than duplicating the only task."""
    assert not should_launch_backup(0, 1e9, {0: 0.0}, {})
    assert not should_launch_backup(0, 1e9, {0: 0.0}, {0: 5.0})


def test_no_completed_durations_never_launches_backup():
    """Enough tasks started but nothing finished: no duration distribution
    to call anyone an outlier against (also guards the empty-median path)."""
    start = {i: 0.0 for i in range(20)}
    assert not should_launch_backup(19, 1e9, start, {})


def test_end_times_without_start_times_ignored():
    """Durations only count tasks present in BOTH maps (a backup twin's end
    can outlive its original's bookkeeping)."""
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(15)}
    end[99] = 0.0  # no matching start: must not poison the median
    assert should_launch_backup(19, 3.5, start, end)


def test_map_unordered_backs_up_each_task_at_most_once(monkeypatch):
    """Once a task has a backup twin, the policy is not consulted again for
    it — 'all tasks already backed up' launches nothing new — and every
    launch increments the speculative_backups counter."""
    import cubed_tpu.runtime.executors.python_async as pa

    monkeypatch.setattr(pa, "should_launch_backup", lambda *a: True)

    class SlowThenDonePool:
        """First submission per input stays pending long enough for several
        backup-scan rounds; everything completes once backups exist."""

        def __init__(self):
            self.futs = []

        def submit(self, fn, *args, **kwargs):
            f = concurrent.futures.Future()
            self.futs.append(f)
            if len(self.futs) >= 4:  # 2 originals + 2 backups
                for g in self.futs:
                    if not g.done():
                        g.set_result((None, {}))
            return f

    before = get_registry().snapshot()
    pool = SlowThenDonePool()
    pa.map_unordered(
        pool, lambda x: x, [0, 1], use_backups=True, array_name="op"
    )
    # exactly one backup per input despite the always-yes policy
    assert len(pool.futs) == 4
    delta = get_registry().snapshot_delta(before)
    assert delta.get("speculative_backups", 0) == 2
