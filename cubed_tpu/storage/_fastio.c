/* Parallel chunk-file reader for the Zarr v2 storage layer.
 *
 * The TPU executor's residency preload reads every chunk file of an array
 * before a fused program runs; Python-side reads serialize on the GIL and
 * on per-file syscall latency. This tiny pthread pool reads N files into
 * caller-provided buffers concurrently, GIL-free (called via ctypes).
 *
 * Role parity: the reference delegates parallel chunk IO to the cloud
 * runtime's concurrent workers (fsspec/S3, cubed/runtime/executors/*); on a
 * single host feeding one chip, the analogous concurrency lives here.
 *
 * Per-file status: 0 = ok, 1 = missing (ENOENT: caller substitutes the
 * fill value), 2 = IO error / short read. Returns the count of status-2
 * files.
 */

#define _GNU_SOURCE
#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdatomic.h>
#include <string.h>
#include <unistd.h>

typedef struct {
    const char **paths;
    char **dsts;
    const long *sizes;
    int *status;
    int n;
    atomic_int next;
} pool_t;

static void read_one(pool_t *p, int i) {
    int fd = open(p->paths[i], O_RDONLY);
    if (fd < 0) {
        p->status[i] = (errno == ENOENT) ? 1 : 2;
        return;
    }
    long off = 0;
    long want = p->sizes[i];
    char *dst = p->dsts[i];
    while (off < want) {
        ssize_t got = read(fd, dst + off, (size_t)(want - off));
        if (got <= 0) {
            close(fd);
            p->status[i] = 2;
            return;
        }
        off += got;
    }
    close(fd);
    p->status[i] = 0;
}

static void *worker(void *arg) {
    pool_t *p = (pool_t *)arg;
    for (;;) {
        int i = atomic_fetch_add(&p->next, 1);
        if (i >= p->n)
            return NULL;
        read_one(p, i);
    }
}

int fastio_read_files(const char **paths, char **dsts, const long *sizes,
                      int *status, int n, int nthreads) {
    pool_t p = {paths, dsts, sizes, status, n, 0};
    atomic_store(&p.next, 0);
    if (nthreads < 1)
        nthreads = 1;
    if (nthreads > n)
        nthreads = n;
    if (nthreads > 64)
        nthreads = 64;

    pthread_t tids[64];
    int spawned = 0;
    for (int t = 0; t < nthreads - 1; t++) {
        if (pthread_create(&tids[spawned], NULL, worker, &p) == 0)
            spawned++;
    }
    worker(&p); /* this thread participates */
    for (int t = 0; t < spawned; t++)
        pthread_join(tids[t], NULL);

    int errs = 0;
    for (int i = 0; i < n; i++)
        if (status[i] == 2)
            errs++;
    return errs;
}
