"""Task execution instrumentation: wall time + peak host memory + storage
bytes per task.

Reference parity: cubed/runtime/utils.py:17-64, extended with per-task
storage byte accounting (observability/accounting.py) — the stats dict a
task returns carries the bytes it moved, measured in whichever process ran
it, so remote executors report IO accurately.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import time
from functools import partial
from typing import Iterable, Iterator, Optional, Sequence

from ..observability import clock, logs
from ..observability.accounting import (
    get_process_label,
    spans_enabled,
    task_scope,
)
from ..observability.metrics import get_registry
from ..utils import peak_measured_mem
from .types import (
    Callback,
    OperationEndEvent,
    OperationStartEvent,
    TaskEndEvent,
    TaskStartEvent,
    callbacks_on,
)


def execute_with_stats(function, *args, **kwargs):
    """Run a task function, returning (result, stats-dict).

    This wrapper runs wherever the task runs (client thread, pool process,
    fleet worker), which makes it the one chokepoint where chaos testing can
    inject task-level faults: an armed ``FaultInjector`` may sleep an
    artificial straggler delay or raise a (transient-classified) injected
    failure before the body runs — inside the task scope, so the retry
    machinery sees it exactly like a real task failure. It is likewise
    where the runtime memory guard (``runtime/memory.task_guard``) watches
    the body: per-task RSS-growth attribution measured in whichever process
    ran it, riding back in the stats dict like the byte counters — and,
    under ``memory_guard="enforce"``, failing the task with a picklable
    ``MemoryGuardExceededError`` when it exceeds ``allowed_mem``.
    """
    from .cancellation import check_current
    from .faults import get_injector
    from .memory import task_guard

    peak_before = peak_measured_mem()
    start = None
    try:
        with task_scope() as scope:
            # cooperative cancellation: a tripped token (deadline or
            # explicit cancel, mirrored off the task message on fleet
            # workers) aborts BEFORE the body runs; the storage layer
            # re-checks between chunk reads/writes inside the body
            check_current()
            injector = get_injector()
            key = chunk_key(args[0]) if args else ""
            # blockwise mappable items are (out_name, i, j, ...) tuples: the
            # first element names the op's output array — good enough task
            # attribution for log correlation without threading the op through
            op = None
            if args and isinstance(args[0], tuple) and args[0]:
                op = str(args[0][0])
            spike = 0
            if injector is not None:
                spike = injector.task_mem_spike(key)
            with logs.task_context(op=op, chunk=key):
                with task_guard(key, injected_bytes=spike) as guard:
                    start = clock.now()
                    # injected faults run inside the timed window: an injected
                    # straggler delay is part of the task's measured duration
                    # (exactly like a real slow task), so the live straggler
                    # watch and the merged trace see it
                    if injector is not None:
                        injector.task_fault(key)
                    result = function(*args, **kwargs)
                    end = clock.now()
    except Exception as e:
        # a raising task produces no stats dict, so its span buffer — the
        # part of the trace that matters most — would vanish. Attach it to
        # the exception instead: the attribute survives pickling (pool
        # workers) and the fleet error frame copies it explicitly, so the
        # client's failure handler can land the failed attempt on the
        # merged trace (observability/collect.record_failed_task). Only
        # when spans are armed: an unobserved compute adds nothing to its
        # exceptions.
        if spans_enabled():
            try:
                now_ts = clock.now()
                e.cubed_tpu_task_stats = dict(
                    function_start_tstamp=start if start is not None else now_ts,
                    function_end_tstamp=now_ts,
                    pid=os.getpid(),
                    worker=get_process_label(),
                    error_type=type(e).__name__,
                    **scope.stats(),
                )
            except Exception:
                pass  # salvage must never mask the task's own failure
        raise
    peak_after = peak_measured_mem()
    return result, dict(
        function_start_tstamp=start,
        function_end_tstamp=end,
        peak_measured_mem_start=peak_before,
        peak_measured_mem_end=peak_after,
        pid=os.getpid(),
        worker=get_process_label(),
        **guard.stats(),
        **scope.stats(),
    )


def execution_stats(function):
    """Decorator adding timing/memory stats to a task function's return value."""
    return partial(execute_with_stats, function)


def handle_callbacks(callbacks: Optional[Sequence[Callback]], stats: dict) -> None:
    if not callbacks:
        return
    if "task_result_tstamp" not in stats:
        stats = dict(stats, task_result_tstamp=time.time())
    event = TaskEndEvent(**stats)
    callbacks_on(callbacks, "on_task_end", event)


def chunk_key(task_input) -> str:
    """A short, human-readable key for a task's mappable item.

    Long keys are shortened but stay COLLISION-PROOF: the journal,
    resume frontier, and invariant auditor all identify tasks by
    ``(op, chunk_key)``, and a bare prefix truncation made distinct
    create-arrays tasks (whose keys embed long work-dir paths sharing a
    prefix) alias each other — the auditor flagged such aliases as
    duplicate result application. A digest of the full string keeps
    shortened keys unique."""
    try:
        s = str(task_input)
    except Exception:
        s = object.__repr__(task_input)
    if len(s) <= 120:
        return s
    digest = hashlib.sha1(s.encode("utf-8", "replace")).hexdigest()[:8]
    return f"{s[:108]}...#{digest}"


def _wants_task_start(callbacks) -> bool:
    """True if any callback actually overrides ``on_task_start`` (beyond the
    base no-op) — lets hot loops skip event construction entirely."""
    for cb in callbacks:
        fn = getattr(cb, "on_task_start", None)
        if fn is None:
            continue
        if getattr(fn, "__func__", None) is not Callback.on_task_start:
            return True
    return False


def fire_task_start(
    callbacks,
    array_name: str,
    task_input=None,
    attempt: int = 0,
    backup: bool = False,
    chunk_key_str: Optional[str] = None,
    key_fn=None,
    num_tasks: int = 1,
) -> None:
    """Count a submitted task attempt and fire ``on_task_start``.

    The ``tasks_started`` metric is counted here (every executor funnels
    submissions through this helper). The event itself — including the
    chunk-key stringification, via ``chunk_key_str`` or a lazy ``key_fn`` —
    is only built when some callback actually observes task starts, so the
    per-task hot path pays nothing for it otherwise."""
    get_registry().counter("tasks_started").inc(num_tasks)
    if not callbacks or not _wants_task_start(callbacks):
        return
    if chunk_key_str is None:
        if key_fn is not None:
            chunk_key_str = key_fn()
        elif task_input is not None:
            chunk_key_str = chunk_key(task_input)
    callbacks_on(
        callbacks,
        "on_task_start",
        TaskStartEvent(
            array_name=array_name,
            num_tasks=num_tasks,
            chunk_key=chunk_key_str,
            attempt=attempt,
            backup=backup,
        ),
    )


def merge_generation(
    generation, callbacks, resume=None, resume_state=None
) -> tuple[list, dict]:
    """Interleave one topological generation's tasks for a single map.

    Fires ``on_operation_start`` for every op in the generation and returns
    ``(items, pipelines)``: ``items`` is the merged ``(op_name, task_input)``
    list and ``pipelines`` maps op name → its pipeline, so the caller can
    resolve each item's ``(function, config)``. Shared by every executor
    that supports ``compute_arrays_in_parallel`` (reference:
    cubed/runtime/executors/python_async.py:93-114). With ``resume`` set,
    tasks whose output chunks already verify against the checksum manifest
    are dropped here (chunk-granular resume, ``pipeline.pending_mappable``).
    """
    from .pipeline import pending_mappable

    items: list = []
    pipelines: dict = {}
    for name, node in generation:
        primitive_op = node["primitive_op"]
        callbacks_on(
            callbacks, "on_operation_start",
            OperationStartEvent(name, primitive_op.num_tasks),
        )
        pipelines[name] = primitive_op.pipeline
        mappable, _skipped = pending_mappable(name, node, resume, resume_state)
        for m in mappable:
            items.append((name, m))
    return items, pipelines


def end_generation(generation, callbacks) -> None:
    """Fire ``on_operation_end`` for every op of a completed generation."""
    for name, node in generation:
        callbacks_on(
            callbacks, "on_operation_end",
            OperationEndEvent(name, node["primitive_op"].num_tasks),
        )


def batched(iterable: Iterable, n: int) -> Iterator[list]:
    """Yield successive lists of up to *n* items."""
    it = iter(iterable)
    while True:
        batch = list(itertools.islice(it, n))
        if not batch:
            return
        yield batch
