"""Distributed RNG tests. Reference parity: cubed/tests/test_random.py."""

import numpy as np

import cubed_tpu
import cubed_tpu.random


def test_random_basic(spec):
    a = cubed_tpu.random.random((10, 8), chunks=(4, 4), spec=spec)
    x = a.compute()
    assert x.shape == (10, 8)
    assert x.dtype == np.float64
    assert (x >= 0).all() and (x < 1).all()
    # not constant
    assert len(np.unique(x)) > 50


def test_random_deterministic_per_block(spec):
    # the same array computed twice gives identical results (per-block keys)
    a = cubed_tpu.random.random((8, 8), chunks=(4, 4), spec=spec)
    x1 = a.compute()
    x2 = a.compute()
    np.testing.assert_array_equal(x1, x2)


def test_random_different_arrays_differ(spec):
    a = cubed_tpu.random.random((8, 8), chunks=(4, 4), spec=spec)
    b = cubed_tpu.random.random((8, 8), chunks=(4, 4), spec=spec)
    assert not np.array_equal(a.compute(), b.compute())


def test_random_blocks_differ(spec):
    a = cubed_tpu.random.random((8, 8), chunks=(4, 4), spec=spec)
    x = a.compute()
    assert not np.array_equal(x[:4, :4], x[4:, 4:])
