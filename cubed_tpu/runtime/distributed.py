"""Multi-host control plane: a TCP coordinator/worker task fabric.

This is the >1-host analogue of the reference's fleet executors
(cubed/runtime/executors/lithops.py, modal.py, dask_distributed_async.py):
those ship ``(function, input, config)`` payloads to cloud workers and rely
on strongly-consistent object storage plus idempotent whole-chunk writes for
correctness under retries and speculative duplicates. Here the fleet is a
set of host processes — one per machine (on a TPU pod slice, one per TPU
host) — connected to the coordinator over TCP (DCN in a pod deployment).
All inter-task data still moves through the shared Zarr store (a shared
filesystem or object store mount), exactly like the reference; the fabric
carries only control messages and kilobyte-scale task payloads.

Design choices, and why:

- **Futures, not a new scheduler.** The coordinator exposes a
  ``concurrent.futures``-shaped ``submit`` so the existing completion-ordered
  machinery (``map_unordered``: retries, speculative straggler backups,
  batched submission — cubed/runtime/executors/asyncio.py:11-102 in the
  reference) drives remote tasks unchanged.
- **Op payloads ship once per worker.** A task message carries the op's
  ``(function, config)`` cloudpickle blob only the first time a given worker
  sees that op (content-addressed by SHA-1); subsequent tasks reference the
  blob id. This mirrors lithops' "upload the function once, map over inputs"
  split without needing a side channel.
- **Worker loss is an ordinary task failure.** A dropped connection fails
  that worker's in-flight futures with ``WorkerLostError``; ``map_unordered``
  resubmits (tasks are idempotent whole-chunk writes), and ``submit`` routes
  to the surviving workers. No global restart, unlike the in-process pool
  executor where a dead process breaks the whole pool.
- **Worker clocks stamp task stats.** ``execute_with_stats`` runs on the
  worker, so per-task timing/peak-RSS are measured where the work happens
  (reference lithops.py:221-231 standardizes worker timestamps the same
  way); cross-host clock skew is visible to timeline callbacks, as it is in
  any distributed trace.

Wire format: 8-byte big-endian length prefix + cloudpickle frame. The
fabric trusts its peers (same trust model as dask/lithops workers — they
already execute arbitrary user functions by design); deployments must scope
the listen address/network accordingly.

**Partition tolerance (PR 8).** The paper's data plane already tolerates
every failure — all chunk data moves through strongly-consistent storage
with idempotent whole-chunk writes — but this control plane used to treat
a socket error as worker death. Now the two are separated:

- **Session tokens + reconnect handshake.** Registration is answered with a
  ``hello_ack`` carrying a per-session token. A worker that loses its
  connection keeps running its in-flight tasks, reconnects, and presents
  the token; the coordinator swaps the socket into the existing
  ``_WorkerConn`` (same name, same outstanding futures). A hello claiming a
  live *connected* worker's name without its token is rejected as an
  impostor.
- **Lease-based task ownership.** Only lease expiry — never socket EOF —
  declares ``WorkerLostError``. A disconnect starts a ``lease_s`` clock
  (renewed by any received frame while connected); a worker that
  reconnects inside its lease keeps every in-flight task (no requeue, no
  retry-budget draw), one that stays dark past it is dropped and its tasks
  requeue exactly once as worker loss. Locally spawned workers whose
  process has verifiably exited skip the lease (a dead process cannot
  reconnect).
- **Sequenced, replayed results.** Every consequential worker→coordinator
  message (result / error / drained / abandoned) carries a monotonic
  ``seq``, is acked by the coordinator, and is retained in a bounded
  worker-side outbox until acked; a reconnect replays unacked messages in
  order. The coordinator drops any ``seq`` at or below the highest it has
  processed (``fleet_messages_deduped``), and workers drop re-delivered
  task assignments by task id (``fleet_assignments_deduped``) — so
  injected duplication or replay can never apply a result twice.
- **Frame robustness.** A truncated/garbage frame (bad length prefix,
  unpicklable payload) raises :class:`CorruptFrameError` — counted
  (``frames_corrupt``) and treated as a connection-level error on that
  peer (clean disconnect, lease rules apply) instead of killing the recv
  thread.

Chaos coverage for all of this lives in ``runtime/faults.py`` (seeded
message drop/delay/duplication/reset and a timed one-way partition of a
named worker) and ``tests/runtime/test_partition.py``.
"""

from __future__ import annotations

import hashlib
import logging
import os
import socket
import struct
import threading
import time
import traceback
import uuid
from collections import OrderedDict, deque
from concurrent.futures import Future
from typing import Any, Dict, Optional

from ..observability.dispatchprofile import TimedLock
from ..observability.metrics import get_registry
from .transfer import ChunkLocationRegistry, pick_worker_by_locality

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">Q")
#: frames above this are rejected as corrupt/hostile length prefixes
MAX_FRAME = 1 << 31


class WorkerLostError(RuntimeError):
    """The worker owning a task is gone for good: its lease expired without
    a reconnect, its process verifiably exited, or the fleet shut down. A
    mere socket error is NOT this — a disconnected worker keeps task
    ownership until its lease runs out (see the module docstring)."""


class CorruptFrameError(ConnectionError):
    """A frame with a hostile length prefix or an undecodable payload.

    A ``ConnectionError`` subclass on purpose: once the stream carries
    garbage, nothing after it can be trusted — the only safe handling is to
    drop the connection (counted in ``frames_corrupt``) and let the
    reconnect/lease machinery decide what the peer's silence means."""


class WorkerDrainedError(WorkerLostError):
    """A draining worker (scale-down, or a spot preemption notice) abandoned
    this task before completing it. A subclass of ``WorkerLostError`` so the
    retry policy classifies it ``REQUEUE``: the task reroutes to a survivor
    without drawing the user-visible retry budget — chunk-granular resume
    (PR 3) makes the replay cheap, and the worker's completed chunks are
    already durable in the shared store."""


class TaskTimeoutError(RuntimeError):
    """A task exceeded the coordinator's ``task_timeout`` without a result."""


class RemoteTaskError(RuntimeError):
    """A task raised on a worker; carries the remote traceback text plus the
    root exception's class name (``remote_type``) so the retry policy can
    classify remote programming errors as fail-fast without a shared type.
    ``remote_payload`` is the root exception's structured wire payload when
    it has one (``ChunkIntegrityError.wire_payload``: the corrupt chunk's
    store + key, what the client-side RECOMPUTE repair needs)."""

    def __init__(
        self,
        message: str = "",
        remote_type: Optional[str] = None,
        remote_payload: Optional[dict] = None,
    ):
        super().__init__(message)
        self.remote_type = remote_type
        self.remote_payload = remote_payload


class NoWorkersError(RuntimeError):
    """No live workers are connected to the coordinator."""


def frame_bytes(obj: Any) -> bytes:
    """One wire frame (length prefix + cloudpickle payload), materialized
    eagerly so pickling errors surface before anything is queued or sent —
    the ONE place the frame format lives."""
    import cloudpickle

    payload = cloudpickle.dumps(obj)
    return _LEN.pack(len(payload)) + payload


def send_frame(sock: socket.socket, obj: Any, lock: Optional[threading.Lock] = None) -> None:
    data = frame_bytes(obj)
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


#: per-thread timing of the LAST ``recv_frame`` on this thread (unpickle
#: cost + wire size) — the dispatch ledger's result-deserialize stamp.
#: Thread-local because each worker link has its own recv loop: the reader
#: (``_recv_loop``) always runs on the same thread as the recv it measures
_recv_timing = threading.local()


def recv_frame(sock: socket.socket) -> Any:
    import cloudpickle

    header = _recv_exact(sock, _LEN.size)
    (n,) = _LEN.unpack(header)
    if n > MAX_FRAME:
        raise CorruptFrameError(f"frame length {n} exceeds limit")
    payload = _recv_exact(sock, n)
    t0 = time.perf_counter()
    try:
        obj = cloudpickle.loads(payload)
    except Exception as e:
        # torn or garbage payload: the stream is desynchronized — surface a
        # connection-level error, never an uncaught exception that would
        # kill the receiving thread
        raise CorruptFrameError(
            f"undecodable {n}-byte frame ({type(e).__name__}: {e})"
        ) from e
    _recv_timing.unpickle_s = time.perf_counter() - t0
    _recv_timing.nbytes = _LEN.size + n
    return obj


def _fail_future(fut: Future, exc: BaseException) -> None:
    """set_exception tolerating a caller-cancelled future.

    ``map_unordered`` cancels losing backup twins and pending futures on
    retry exhaustion from its own thread; racing that with set_exception
    raises InvalidStateError, which must not kill a coordinator daemon
    thread (the fleet outlives computes, so a dead timeout/receiver thread
    would silently disable enforcement for every later plan)."""
    if fut.done():
        return
    try:
        fut.set_exception(exc)
    except Exception:
        pass  # cancelled (or completed) concurrently: the race is benign


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


class _WorkerConn:
    """Coordinator-side handle for one connected worker."""

    def __init__(self, sock: socket.socket, address, hello: dict):
        self.sock = sock
        self.address = address
        self.name = hello.get("name") or f"{address[0]}:{address[1]}"
        self.nthreads = int(hello.get("nthreads", 1))
        self.send_lock = threading.Lock()
        self.outstanding: Dict[int, Future] = {}
        #: task_id -> (monotonic deadline, started) — only under task_timeout.
        #: ``started`` flips when the worker acks actual execution start (post
        #: blob decode); a timeout before that is cold-start/queueing load,
        #: rerouted without counting as a hang
        self.deadlines: Dict[int, list] = {}
        #: consecutive timed-out STARTED tasks; reset on any result
        self.timeout_strikes = 0
        #: task_ids of threads still burned by timed-out-but-running tasks;
        #: counted in routing load so retries don't queue behind the very
        #: hang that timed them out; a ghost is removed when ITS late reply
        #: arrives (replies for never-started timeouts must not free a
        #: different ghost's slot)
        self.ghost_ids: set[int] = set()
        self.blobs_sent: set[str] = set()
        #: total tasks ever routed to this worker (load diagnostics)
        self.tasks_sent = 0
        self.alive = True
        #: the worker announced (or was asked) to drain: routing passes it
        #: over while any non-draining worker is live, and its abandoned
        #: tasks requeue free (WorkerDrainedError)
        self.draining = False
        #: guards _drop_worker against double-drops (recv-loop error racing
        #: a timeout-loop eviction or a clean drained departure)
        self.dropped = False
        #: last heartbeat-reported RSS (bytes) and memory-pressure flag —
        #: the coordinator stops dispatching to a pressured worker while
        #: any unpressured one is live (runtime/memory.py watermarks)
        self.rss: Optional[int] = None
        self.pressured = False
        #: NTP-style clock estimate from the heartbeat echo handshake:
        #: coordinator_time ≈ worker_time + clock_offset, accurate to about
        #: clock_rtt/2 — what the trace merger uses to land this worker's
        #: spans on the client timeline (observability/collect.py)
        self.clock_offset: Optional[float] = None
        self.clock_rtt: Optional[float] = None
        #: the worker's peer chunk-server address (ip, port) from the
        #: hello, or None for workers without the p2p data plane; refreshed
        #: on reconnect (the port survives, the reachable ip may not)
        self.peer_addr = tuple(hello["peer_addr"]) if hello.get("peer_addr") else None
        #: latest heartbeat-reported peer-cache stats (bytes/entries/
        #: evictions) for stats_snapshot/diagnose
        self.peer_cache: Optional[dict] = None
        #: cumulative worker-side counters, folded from the bounded
        #: ``metrics_delta`` payloads piggybacked on heartbeat frames —
        #: the per-worker dimension the live telemetry pipeline samples
        #: (tasks completed, peer hits/misses, retries ... as counted
        #: WHERE the work ran, continuously, not once at compute end)
        self.metrics: Dict[str, float] = {}
        #: per-session secret: a reconnecting worker must present it, so a
        #: stranger claiming a live worker's name cannot steal its tasks
        self.token = uuid.uuid4().hex
        #: False while the worker is disconnected-but-leased: routing skips
        #: it, its task deadlines freeze, and only lease expiry drops it
        self.connected = True
        #: bumped on every reconnect; a recv loop whose generation is stale
        #: was superseded and must exit without touching the conn
        self.generation = 0
        #: highest sequenced (important) message processed; replayed or
        #: duplicated frames at/below it are acked but not re-applied
        self.last_seq = 0
        #: monotonic deadline after which a disconnected worker is declared
        #: lost; renewed by every received frame while connected
        self.lease_deadline = float("inf")
        self.disconnect_reason: Optional[str] = None
        #: coordinator epoch this session last (re)joined under — the
        #: per-worker EPOCH column in ``top`` (an adopted worker shows the
        #: prior epoch until its reconnect lands on the successor)
        self.joined_epoch = 0


class Coordinator:
    """Accepts worker connections and fans tasks out to them.

    ``submit(execute_with_stats, function, input, config=...)`` matches how
    ``map_unordered`` drives a ``concurrent.futures`` pool; the stats wrapper
    runs worker-side, and the returned Future resolves to
    ``(result, stats_dict)``.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        task_timeout: Optional[float] = None,
        timeout_strikes: int = 2,
        blob_cache_size: int = 1024,
        lease_s: float = 15.0,
        control_dir: Optional[str] = None,
        takeover_grace_s: Optional[float] = None,
    ):
        self._server = socket.create_server((host, port))
        self._server.settimeout(0.2)
        self.address = self._server.getsockname()[:2]
        self._workers: list[_WorkerConn] = []
        #: lifetime count of workers that ever joined (diagnostics: a
        #: zero-worker submit reads very differently when 4 joined and died
        #: vs when nothing ever connected)
        self._workers_ever = 0
        #: names of every worker that ever joined — the autoscaler settles
        #: its pending-spawn bookkeeping against this, so a worker that
        #: registers and dies between two policy ticks still reads as a
        #: hole to backfill, not as still-pending capacity (strings only;
        #: unbounded but tiny even for a fleet churning thousands)
        self._worker_names_ever: set = set()
        #: set (>0) by an attached Autoscaler: a momentarily-empty fleet is
        #: expected to be backfilled, so submit() waits up to this long for
        #: a replacement to register before raising NoWorkersError
        self.backfill_grace_s: float = 0.0
        #: the coordinator's hot lock, instrumented: contended-acquire wait
        #: feeds ``dispatch_lock_wait_s`` and the per-submit ledger's
        #: ``lock_wait_s`` (observability/dispatchprofile.TimedLock — a
        #: drop-in Lock; the Condition below works through the stdlib's
        #: generic acquire/release fallbacks)
        self._lock = TimedLock()
        self._next_task_id = 0
        self._closed = threading.Event()
        self._worker_joined = threading.Condition(self._lock)
        #: LRU over (id(function), id(config)) — bounded so a long-lived
        #: listen-mode coordinator serving many plans doesn't pin every
        #: op's objects forever; an evicted pair is simply re-pickled on
        #: the next submit (same bytes -> same blob_id -> workers that
        #: already hold it are not resent)
        self._blob_cache: OrderedDict[tuple, tuple] = OrderedDict()
        self._blob_cache_size = max(1, blob_cache_size)
        #: final load rows of workers that left (crash/shutdown), so the
        #: stats snapshot doesn't erase history exactly when a worker is
        #: lost; bounded LRU (a long-lived fleet churns workers)
        self._departed: OrderedDict[str, dict] = OrderedDict()
        self.task_timeout = task_timeout
        self.timeout_strikes = timeout_strikes
        #: how long a disconnected worker keeps owning its in-flight tasks;
        #: a reconnect inside the lease costs nothing, expiry requeues its
        #: tasks exactly once as worker loss
        self.lease_s = float(lease_s)
        #: optional hook mapping a worker name to its process exit code
        #: (the executor sets it for locally spawned workers): a dropped
        #: connection plus exitcode -9/137 reads as an OOM-killed worker,
        #: which the WorkerLostError message then says out loud
        self.exit_probe = None
        #: diagnostics: blob bytes actually sent vs referenced by id
        self.stats: Dict[str, int] = {
            "blobs_sent": 0, "tasks_sent": 0, "task_timeouts": 0,
            "workers_lost": 0, "drains_completed": 0, "workers_preempted": 0,
            "tasks_abandoned_on_drain": 0, "workers_disconnected": 0,
            "workers_reconnected": 0, "leases_expired": 0,
            "frames_corrupt": 0, "workers_rejected": 0,
            "peer_locate_requests": 0, "placement_locality_hits": 0,
            "compute_cancels_sent": 0, "coordinator_takeovers": 0,
            "stale_epoch_frames": 0, "tasks_readopted": 0,
            "assignments_requeued": 0,
        }
        #: (store, chunk key) -> producing worker, fed by the `produced`
        #: lists piggybacked on sequenced result frames; drives the
        #: chunk_locate RPC and locality-aware dispatch (runtime/transfer.py)
        self.chunk_registry = ChunkLocationRegistry()
        #: fleet-wide accumulation of the workers' heartbeat metric deltas
        #: (counters add; the per-worker split lives on each conn) — what
        #: the telemetry sampler and stats_snapshot read as the merged
        #: worker-side view
        self.fleet_metrics: Dict[str, float] = {}
        #: per-message-type frame/byte counts on the coordinator link, both
        #: directions ({"sent"/"recv": {mtype: [frames, bytes]}}) — the
        #: control-plane traffic breakdown stats_snapshot/top expose; plain
        #: dict increments (GIL-atomic enough for diagnostics), bounded by
        #: the fixed message-type vocabulary plus a hard key cap
        self._frame_counts: Dict[str, Dict[str, list]] = {
            "sent": {}, "recv": {},
        }
        #: decision-ring entries for locality placement are throttled (the
        #: counters carry the totals; the ring is bounded)
        self._locality_decisions_left = 16
        #: live coordinator failover (runtime/journal.ControlLog): the
        #: epoch fences frames across coordinator incarnations, and the
        #: control log is the bounded snapshot a successor pointed at the
        #: same ``control_dir`` re-adopts the running fleet from
        self.epoch = 0
        self.control_dir = control_dir
        self._control = None
        self._control_sink = None
        #: takeover window: until this monotonic deadline, adopted-but-
        #: silent workers stay leased (the autoscaler must not backfill
        #: them) and adopted futures wait for worker outbox replays
        self._takeover_deadline = 0.0
        #: (op, chunk-key) tag -> adopted Future for the prior epoch's
        #: in-flight dispatches: ``submit`` with the same tag hands the
        #: adopted future back instead of re-dispatching (tasks_readopted)
        self._adopted: Dict[tuple, Future] = {}
        #: adopted futures actually handed out via submit(); the lease
        #: loop's takeover backstop requeues any still pending once the
        #: window closes (a genuinely lost assignment: no replay owned it)
        self._adopted_issued: list = []
        #: (conn, task_id, tag, fut) for every adoption, so the backstop
        #: can clear the stub bookkeeping exactly once
        self._adopted_pending: list = []
        if control_dir is not None:
            self._init_control_plane(takeover_grace_s)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="coordinator-accept", daemon=True
        )
        self._accept_thread.start()
        if task_timeout is not None:
            threading.Thread(
                target=self._timeout_loop, name="coordinator-timeouts",
                daemon=True,
            ).start()
        threading.Thread(
            target=self._lease_loop, name="coordinator-leases", daemon=True
        ).start()
        # the live telemetry sampler (observability/timeseries.py) polls
        # registered fleets for per-worker series; weak registration, so a
        # leaked coordinator can't pin itself into the telemetry loop
        from ..observability.timeseries import register_fleet

        register_fleet(self)

    # -- live failover: control-plane snapshot + fleet adoption ---------

    def _init_control_plane(self, takeover_grace_s: Optional[float]) -> None:
        """Open the control log; when it already records a prior epoch,
        this coordinator is a SUCCESSOR: bump the epoch, re-adopt the
        snapshot's fleet (workers re-attach through their session tokens,
        in-flight dispatches become adopted futures), fence the old epoch,
        and advertise the new one in the rendezvous file. Runs inside
        ``__init__`` before any service thread starts — no locking."""
        from ..observability.collect import add_decision_sink
        from .journal import ControlLog, control_log_path, load_control

        prior = load_control(control_log_path(self.control_dir))
        self._control = ControlLog(self.control_dir)
        if prior["epoch"] >= 0:
            self.epoch = prior["epoch"] + 1
        # successor task ids must never collide with the prior epoch's:
        # workers keep their assignment-dedup state across a resumed
        # reconnect, and a colliding id would be silently swallowed as a
        # duplicate — shift each epoch into its own id space
        self._next_task_id = self.epoch << 40
        grace = (
            float(takeover_grace_s) if takeover_grace_s is not None
            else max(2 * self.lease_s, 30.0)
        )
        if self.epoch > 0:
            self._takeover_deadline = time.monotonic() + grace
            self._adopt_fleet(prior, grace)
        self._control.record_epoch(self.epoch, self.address)
        self._control.advertise(self.epoch, self.address)
        get_registry().gauge("coordinator_epoch").set(self.epoch)
        # mirror connectivity decisions into the control log so the NEXT
        # successor can stitch a two-epoch timeline; replayed prior-epoch
        # entries carry an ``epoch`` attr and are not re-mirrored
        kinds = {
            "worker_disconnected", "worker_reconnected", "lease_expired",
            "worker_rejected", "worker_drain_requested", "worker_draining",
            "worker_drained", "scale_up", "scale_down", "spawn_died",
        }
        control, epoch = self._control, self.epoch

        def _sink(entry: dict) -> None:
            if entry.get("kind") in kinds and entry.get("epoch") is None:
                control.record_decision(epoch, entry)

        self._control_sink = _sink
        add_decision_sink(_sink)

    def _adopt_fleet(self, prior: dict, grace: float) -> None:
        """Rebuild the prior epoch's fleet from its snapshot: every
        recorded worker becomes a disconnected-but-leased session (same
        name, same token — the reconnect handshake resumes it), every
        in-flight dispatch becomes an adopted future keyed by its (op,
        chunk-key) tag, and the chunk-location registry is replayed.
        Nothing is re-dispatched here: ``submit`` hands an adopted future
        back when the DAG re-asks for that task, the worker's outbox
        replay resolves it, and the lease loop's backstop requeues only
        what the takeover window proves genuinely lost."""
        from ..observability.collect import record_decision

        deadline = time.monotonic() + grace
        for name, rec in prior["workers"].items():
            hello = {
                "name": name,
                "nthreads": rec.get("nthreads", 1),
                "peer_addr": rec.get("peer_addr"),
            }
            conn = _WorkerConn(
                None, tuple(rec.get("address") or ("?", 0)), hello
            )
            conn.token = rec["token"]
            conn.connected = False
            conn.disconnect_reason = "adopted after coordinator takeover"
            conn.lease_deadline = deadline
            conn.joined_epoch = max(0, prior["epoch"])
            self._workers.append(conn)
            self._workers_ever += 1
            self._worker_names_ever.add(name)
        by_name = {w.name: w for w in self._workers}
        readopted = 0
        for task_id, rec in prior["inflight"].items():
            tag, conn = rec.get("tag"), by_name.get(rec.get("worker"))
            if not tag or conn is None:
                continue
            fut: Future = Future()
            conn.outstanding[int(task_id)] = fut
            self._adopted[tuple(tag)] = fut
            self._adopted_pending.append((conn, int(task_id), tuple(tag), fut))
            readopted += 1
        for loc in prior["chunk_locations"]:
            wname = loc.get("worker")
            if wname in by_name:
                self.chunk_registry.record(
                    wname,
                    [(loc.get("store"), loc.get("key"),
                      int(loc.get("nbytes") or 0))],
                )
        self.stats["coordinator_takeovers"] += 1
        get_registry().counter("coordinator_takeovers").inc()
        # replay the prior epoch's connectivity decisions (bounded) into
        # THIS process's ring, keeping their original ``epoch`` attr, so
        # diagnose renders one stitched two-epoch timeline
        for entry in prior["decisions"]:
            kind = entry.get("decision")
            if not kind:
                continue
            attrs = {
                k: v for k, v in entry.items()
                if k not in ("kind", "decision", "t", "ts", "version")
            }
            record_decision(kind, **attrs)
        record_decision(
            "coordinator_takeover", epoch=self.epoch,
            prior_epoch=prior["epoch"],
            workers_adopted=len(prior["workers"]),
            inflight_readopted=readopted, grace_s=round(grace, 3),
        )
        self._control.record_decision(self.epoch, {
            "kind": "coordinator_takeover", "prior_epoch": prior["epoch"],
            "workers_adopted": len(prior["workers"]),
            "inflight_readopted": readopted,
        })
        logger.warning(
            "coordinator takeover: epoch %d adopted %d worker(s) and %d "
            "in-flight dispatch(es) from epoch %d (takeover window %.1fs)",
            self.epoch, len(prior["workers"]), readopted, prior["epoch"],
            grace,
        )

    def in_takeover(self) -> bool:
        """True while the successor's takeover window is open: adopted
        workers count as leased capacity (the autoscaler must not treat
        them as holes to backfill) and adopted futures wait for worker
        outbox replays before anything is requeued."""
        return time.monotonic() < self._takeover_deadline

    def _record_worker_control(self, conn: _WorkerConn, pid=None) -> None:
        if self._control is None:
            return
        self._control.record_worker(
            conn.name, conn.token, conn.nthreads,
            peer_addr=conn.peer_addr, address=conn.address, pid=pid,
        )

    # -- worker management ---------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                sock, addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                hello = recv_frame(sock)
                if not isinstance(hello, dict) or hello.get("type") != "hello":
                    raise ConnectionError(f"bad hello: {hello!r}")
            except Exception:
                logger.exception("rejecting connection from %s", addr)
                sock.close()
                continue
            self._register(sock, addr, hello)

    def _register(self, sock, addr, hello: dict) -> None:
        """Handle one hello: a token-bearing reconnect re-adopts the
        existing session; a token-less hello claiming a live CONNECTED
        worker's name is rejected as an impostor; a token-less hello under
        a disconnected worker's name supersedes the old session (a
        restarted process cannot resume work it no longer holds)."""
        from ..observability.collect import record_decision

        name = hello.get("name") or f"{addr[0]}:{addr[1]}"
        token = hello.get("token")
        with self._lock:
            existing = next(
                (w for w in self._workers if w.alive and w.name == name), None
            )
        if existing is not None and token and token == existing.token:
            if self._adopt_reconnect(existing, sock, addr, hello):
                return
            # the lease expired between the lookup and the adopt: the old
            # session is gone — fall through to a fresh registration
            existing = None
        elif existing is not None and existing.connected:
            with self._lock:
                self.stats["workers_rejected"] += 1
            get_registry().counter("workers_rejected").inc()
            record_decision("worker_rejected", worker=name)
            logger.warning(
                "rejecting hello from %s claiming live worker %s "
                "(missing/wrong session token)", addr, name,
            )
            try:
                send_frame(sock, {
                    "type": "hello_reject",
                    "reason": f"name {name!r} belongs to a live connected "
                    "worker (wrong or missing session token)",
                })
            except (ConnectionError, OSError):
                pass
            sock.close()
            return
        elif existing is not None:
            # disconnected-but-leased, and the newcomer has no (valid)
            # token: a restarted process under the same name — the old
            # session's in-flight work is unrecoverable, hand it back now
            self._drop_worker(
                existing,
                "superseded by a new registration under the same name",
            )
        conn = _WorkerConn(sock, addr, hello)
        conn.lease_deadline = time.monotonic() + self.lease_s
        conn.joined_epoch = self.epoch
        # register BEFORE acking — acking first left a window where a fast
        # client's submit() raised NoWorkersError against a worker that
        # believed itself registered — but keep the conn UNROUTABLE
        # (connected=False) until the ack is on the wire: the hello_ack
        # must be the first frame the worker receives, so a racing
        # submit() must not slip a task frame ahead of it (submit's
        # no-connected-workers path waits on _worker_joined, which the
        # flip below notifies)
        conn.connected = False
        with self._lock:
            self._workers.append(conn)
            self._workers_ever += 1
            self._worker_names_ever.add(conn.name)
            self._worker_joined.notify_all()
        try:
            send_frame(sock, {
                "type": "hello_ack", "token": conn.token, "resume": False,
                "lease_s": self.lease_s, "epoch": self.epoch,
            })
        except (ConnectionError, OSError) as e:
            logger.warning("hello_ack to %s failed: %s", name, e)
            # roll the registration back quietly: the worker never saw the
            # ack (it retries with a fresh hello) and was never routable
            # (connected=False), so this is NOT a worker loss — no
            # workers_lost count, no departed row
            with self._lock:
                conn.dropped = True
                conn.alive = False
                if conn in self._workers:
                    self._workers.remove(conn)
            try:
                sock.close()
            except OSError:
                pass
            return
        with self._lock:
            conn.connected = True
            self._worker_joined.notify_all()
        # fsync'd AFTER the ack: the worker is durably part of the fleet a
        # successor would adopt only once both sides agree it registered
        self._record_worker_control(conn, pid=hello.get("pid"))
        threading.Thread(
            target=self._recv_loop,
            args=(conn, sock, conn.generation),
            name=f"coordinator-recv-{conn.name}",
            daemon=True,
        ).start()
        logger.info("worker %s joined (%d threads)", conn.name, conn.nthreads)

    def _adopt_reconnect(self, conn: _WorkerConn, sock, addr, hello=None) -> bool:
        """Swap a reconnecting worker's new socket into its live session:
        outstanding futures, lease, and blob bookkeeping all survive. The
        superseded recv loop notices its stale generation and exits."""
        from ..observability.collect import record_decision

        with self._lock:
            if conn.dropped:
                return False
            old_sock = conn.sock
            conn.sock = sock
            conn.address = addr
            if hello is not None and hello.get("peer_addr"):
                # the peer server survives the reconnect, but the reachable
                # ip may have changed with the new route
                conn.peer_addr = tuple(hello["peer_addr"])
            conn.connected = True
            conn.generation += 1
            gen = conn.generation
            conn.lease_deadline = time.monotonic() + self.lease_s
            conn.disconnect_reason = None
            conn.joined_epoch = self.epoch
            self.stats["workers_reconnected"] += 1
            # reconcile against what the worker actually HOLDS (its
            # assignment-dedup set plus unacked outbox frames, carried on
            # the resume hello): an assignment this side sent that the
            # dead link ate is outstanding here but unknown there — no
            # replay will ever resolve it, and the renewed lease would
            # shield the hole forever. Requeue exactly those.
            requeue = []
            holding = hello.get("holding") if hello else None
            if holding is not None:
                held = set(holding)
                issued = {id(f) for f in self._adopted_issued}
                for tid in [t for t in conn.outstanding if t not in held]:
                    fut = conn.outstanding.pop(tid)
                    conn.deadlines.pop(tid, None)
                    conn.ghost_ids.discard(tid)
                    if fut.done():
                        continue
                    entry = next(
                        (e for e in self._adopted_pending
                         if e[0] is conn and e[1] == tid), None,
                    )
                    if entry is not None:
                        # an adopted dispatch the prior epoch logged but
                        # never delivered: settle it now instead of
                        # waiting out the takeover window
                        self._adopted_pending.remove(entry)
                        if id(fut) not in issued:
                            # never handed out via submit: forget the tag
                            # so the DAG dispatches it fresh
                            self._adopted.pop(entry[2], None)
                            continue
                        self._adopted_issued = [
                            f for f in self._adopted_issued if f is not fut
                        ]
                    requeue.append((tid, fut))
                self.stats["assignments_requeued"] += len(requeue)
            outstanding = len(conn.outstanding)
            self._worker_joined.notify_all()
        if old_sock is not None:  # None: an adopted stub re-attaching
            try:
                old_sock.close()
            except OSError:
                pass
        for tid, fut in requeue:
            _fail_future(fut, WorkerLostError(
                f"assignment {tid} never reached worker {conn.name} "
                "(lost with the dead link); requeueing"
            ))
        if requeue:
            get_registry().counter("assignments_requeued").inc(len(requeue))
            logger.warning(
                "worker %s reconnected without %d assignment(s) this side "
                "thought it held; requeued them", conn.name, len(requeue),
            )
        get_registry().counter("workers_reconnected").inc()
        record_decision(
            "worker_reconnected", worker=conn.name, outstanding=outstanding,
            requeued=len(requeue),
        )
        logger.warning(
            "worker %s reconnected (%d in-flight tasks kept under its "
            "lease)", conn.name, outstanding,
        )
        try:
            send_frame(sock, {
                "type": "hello_ack", "token": conn.token, "resume": True,
                "lease_s": self.lease_s, "epoch": self.epoch,
            }, conn.send_lock)
        except (ConnectionError, OSError) as e:
            self._on_disconnect(conn, f"hello_ack failed: {e}", gen=gen)
            return True  # adopted (and immediately disconnected again)
        # refresh the snapshot row (the peer address may have moved with
        # the new route, and a successor's log needs this worker recorded
        # under ITS epoch too)
        self._record_worker_control(conn, pid=(hello or {}).get("pid"))
        threading.Thread(
            target=self._recv_loop,
            args=(conn, sock, gen),
            name=f"coordinator-recv-{conn.name}",
            daemon=True,
        ).start()
        return True

    def wait_for_workers(self, count: int, timeout: float = 60.0) -> None:
        with self._lock:
            ok = self._worker_joined.wait_for(
                lambda: len([w for w in self._workers if w.alive]) >= count,
                timeout=timeout,
            )
        if not ok:
            host, port = self.address
            with self._lock:
                ever = self._workers_ever
            raise TimeoutError(
                f"only {self.n_workers} of {count} workers joined the "
                f"coordinator at {host}:{port} (epoch {self.epoch}) "
                f"within {timeout}s "
                f"({ever} ever joined, {self.stats['workers_lost']} lost); "
                "start workers with 'python -m cubed_tpu.runtime.worker "
                f"{host}:{port}' on each host, or raise "
                "worker_start_timeout if they are still booting"
            )

    @property
    def n_workers(self) -> int:
        with self._lock:
            return len([w for w in self._workers if w.alive])

    def _drop_worker(
        self, conn: _WorkerConn, reason: str, clean: bool = False,
        only_if_disconnected: bool = False,
    ) -> bool:
        """Remove a worker; True when this call actually dropped it.
        ``clean=True`` marks an orderly departure (a completed drain): it
        is not counted as ``workers_lost`` — the fleet asked it to leave
        (or it left within its preemption notice), and its in-flight work
        was already handed back explicitly. ``only_if_disconnected=True``
        (the lease-expiry path) aborts if a reconnect won the race between
        the expiry check and this call — the re-adopted live session must
        not be torn down."""
        with self._lock:
            if conn.dropped:
                return False  # recv-loop error racing another drop: done
            if only_if_disconnected and conn.connected:
                return False  # a reconnect won: the lease no longer applies
            conn.dropped = True
        if (
            self.exit_probe is not None
            and not clean
            and reason != "shutdown"
            and not reason.startswith("hung")
        ):
            # best-effort: the worker process usually finishes dying within
            # a few ms of its socket resetting; -9/137 turns a cause-less
            # "connection reset" into "likely OOM-killed". Hung-worker
            # evictions skip this: the process is alive by definition, so
            # the probe's brief reap-wait would only delay the eviction
            try:
                code = self.exit_probe(conn.name)
            except Exception:
                code = None
            if code is not None:
                if code in (-9, 137) and conn.draining:
                    # the drain protocol's own hard-kill deadline exits
                    # 137 — a worker we KNEW was draining did not OOM
                    hint = " — hard-killed at end of drain/preemption notice"
                elif code in (-9, 137):
                    hint = " — likely OOM-killed (SIGKILL)"
                else:
                    hint = ""
                reason = f"{reason} (worker process exitcode {code}{hint})"
        with self._lock:
            conn.alive = False
            if conn in self._workers:
                self._workers.remove(conn)
            orphans = list(conn.outstanding.items())
            self._departed[conn.name] = {
                "alive": False,
                "reason": reason,
                "nthreads": conn.nthreads,
                "outstanding": 0,
                "ghosts": len(conn.ghost_ids),
                "tasks_sent": conn.tasks_sent,
                "drained": clean,
                "clock_offset": conn.clock_offset,
                "clock_rtt": conn.clock_rtt,
            }
            while len(self._departed) > 32:
                self._departed.popitem(last=False)
            conn.outstanding.clear()
            conn.deadlines.clear()
        if conn.sock is not None:  # None: an adopted stub that never re-attached
            try:
                conn.sock.close()
            except OSError:
                pass
        if self._control is not None:
            # fsync'd: a successor must not re-adopt a worker this epoch
            # already declared gone (its tasks were requeued HERE)
            self._control.record_worker_gone(conn.name)
        # a departed worker can no longer serve peer fetches: drop its
        # chunk locations so readers go straight to the store instead of
        # timing out against a corpse
        self.chunk_registry.drop_worker(conn.name)
        exc_cls = WorkerDrainedError if clean else WorkerLostError
        nthreads = max(1, conn.nthreads or 1)
        for idx, (task_id, fut) in enumerate(orphans):
            err = exc_cls(f"worker {conn.name} lost: {reason}")
            if not clean:
                # only the task slots actually executing at the abrupt
                # death can have CAUSED it. Dispatch and slot execution
                # are both FIFO and completed tasks pop out of
                # `outstanding`, so the oldest `nthreads` remaining
                # entries were the ones running — everything behind them
                # was merely queued on the corpse and must not collect a
                # poison-quarantine strike for its neighbor's crime
                err.was_executing = idx < nthreads
            _fail_future(fut, err)
        if clean and orphans:
            # tasks still queued on the worker when its drain closed the
            # socket: abandoned like the in-flight ones, requeued free
            with self._lock:
                self.stats["tasks_abandoned_on_drain"] += len(orphans)
            get_registry().counter("tasks_abandoned_on_drain").inc(
                len(orphans)
            )
        if (orphans or reason != "shutdown") and not clean:
            with self._lock:
                self.stats["workers_lost"] += 1
            get_registry().counter("workers_lost").inc()
            logger.warning(
                "worker %s dropped (%s); failed %d in-flight tasks",
                conn.name, reason, len(orphans),
            )
        elif clean:
            logger.info("worker %s departed cleanly (%s)", conn.name, reason)
        return True

    def _on_disconnect(
        self, conn: _WorkerConn, reason: str, gen: Optional[int] = None
    ) -> None:
        """A worker's socket died. Socket EOF is NOT worker death: unless
        the worker's process has verifiably exited (local exit probe), was
        draining, or the fleet is shutting down, the worker enters the
        disconnected-but-leased state — routing skips it, its task
        deadlines freeze, and only lease expiry (or a reconnect) resolves
        it."""
        from ..observability.collect import record_decision

        with self._lock:
            if conn.dropped or not conn.connected:
                return
            if gen is not None and conn.generation != gen:
                return  # a reconnect already superseded this socket
            # pin the generation we are disconnecting: an adopt that lands
            # during the exit probe below bumps it, and must not have its
            # freshly installed socket closed by this stale failure
            gen = conn.generation
        if self._closed.is_set() or conn.draining:
            if (
                conn.draining
                and not self._closed.is_set()
                and not conn.outstanding
            ):
                # the drain already finished every task (nothing in
                # flight) but the link died before the ``drained`` frame
                # landed — e.g. a reconnect loop that exhausted its
                # retries mid-drain. Seal the drain instead of counting a
                # worker loss: the departure is exactly as clean as if
                # the frame had arrived
                self._on_drained(
                    conn,
                    {"reason": "drain-complete (link lost after completion)"},
                )
                return
            # shutdown, or a drainer that died mid-drain: the old semantics
            # (and the old diagnostics, e.g. the drain hard-kill hint)
            self._drop_worker(conn, reason)
            return
        if self.exit_probe is not None:
            # a locally spawned worker whose process already exited can
            # never reconnect: skip the lease and fail over immediately —
            # this keeps crash recovery exactly as fast as before leases
            try:
                code = self.exit_probe(conn.name)
            except Exception:
                code = None
            if code is not None:
                self._drop_worker(conn, reason)
                return
        with self._lock:
            if (
                conn.dropped
                or not conn.connected
                or conn.generation != gen
            ):
                return  # raced a concurrent drop/reconnect during the probe
            conn.connected = False
            conn.disconnect_reason = reason
            conn.lease_deadline = time.monotonic() + self.lease_s
            outstanding = len(conn.outstanding)
            self.stats["workers_disconnected"] += 1
            # captured under the lock: an adopt racing this close must not
            # have its freshly installed socket shut by us
            sock_to_close = conn.sock
        try:
            sock_to_close.close()
        except OSError:
            pass
        get_registry().counter("workers_disconnected").inc()
        record_decision(
            "worker_disconnected", worker=conn.name, reason=reason,
            outstanding=outstanding, lease_s=self.lease_s,
        )
        logger.warning(
            "worker %s disconnected (%s); %d in-flight task(s) stay leased "
            "to it for %.1fs pending a reconnect",
            conn.name, reason, outstanding, self.lease_s,
        )

    def _lease_loop(self) -> None:
        """Declare disconnected workers lost once their lease runs out —
        the ONLY path (besides a verified process exit and shutdown) that
        turns a network fault into ``WorkerLostError``.

        A CONNECTED worker whose lease lapses (no frame received for a
        whole window — heartbeats renew it every second, so this means a
        vanished host whose TCP stack never sent a reset) is first demoted
        to the disconnected state, earning one more lease window for its
        side's watchdog to reconnect; only then does expiry drop it. Total
        time to declare such a host lost: 2 x lease_s — finite, where it
        used to hang forever without a ``task_timeout``."""
        from ..observability.collect import record_decision

        interval = max(0.05, min(1.0, self.lease_s / 5))
        while not self._closed.wait(interval):
            now = time.monotonic()
            with self._lock:
                expired = [
                    w for w in self._workers
                    if w.alive and not w.connected
                    and now > w.lease_deadline
                ]
                silent = [
                    w for w in self._workers
                    if w.alive and w.connected and now > w.lease_deadline
                ]
            for conn in silent:
                self._on_disconnect(
                    conn,
                    f"no frames received for {self.lease_s}s "
                    "(half-open link or vanished host)",
                )
            for conn in expired:
                reason = conn.disconnect_reason
                if not self._drop_worker(
                    conn,
                    f"lease expired {self.lease_s}s after disconnect "
                    f"({reason})",
                    only_if_disconnected=True,
                ):
                    continue  # a reconnect won the race: nothing expired
                with self._lock:
                    self.stats["leases_expired"] += 1
                get_registry().counter("leases_expired").inc()
                record_decision(
                    "lease_expired", worker=conn.name, reason=reason,
                )
            # takeover backstop: once the window closes, any adopted
            # future still pending was a genuinely lost assignment — no
            # surviving worker replayed its result and no lease expiry
            # settled it. Requeue issued ones exactly once (_fail_future's
            # done-guard absorbs a racing late replay); forget the rest so
            # a later submit of that tag dispatches fresh.
            if self._adopted_pending and not self.in_takeover():
                with self._lock:
                    pending = self._adopted_pending
                    self._adopted_pending = []
                    issued = {id(f) for f in self._adopted_issued}
                    self._adopted_issued = []
                    requeue = []
                    for conn, tid, tag, fut in pending:
                        if fut.done():
                            continue
                        conn.outstanding.pop(tid, None)
                        conn.deadlines.pop(tid, None)
                        if id(fut) in issued:
                            requeue.append((tid, fut))
                        else:
                            self._adopted.pop(tag, None)
                for tid, fut in requeue:
                    _fail_future(fut, WorkerLostError(
                        f"adopted task {tid} from the prior epoch never "
                        "replayed a result inside the takeover window; "
                        "requeueing as worker loss"
                    ))
                if requeue:
                    logger.warning(
                        "takeover window closed: requeued %d adopted "
                        "task(s) with no replayed result", len(requeue),
                    )

    def _count_frame(self, direction: str, mtype, nbytes: int) -> None:
        """Fold one link frame into the per-message-type breakdown and the
        registry's coordinator-link counters (frames + bytes, per
        direction). Lock-free on purpose: a racing increment can lose one
        count, which diagnostics tolerate and the dispatch path's latency
        budget appreciates."""
        bucket = self._frame_counts[direction]
        key = str(mtype or "unknown")
        row = bucket.get(key)
        if row is None:
            if len(bucket) >= 32:
                key, row = "other", bucket.get("other")
            if row is None:
                row = bucket[key] = [0, 0]
        row[0] += 1
        row[1] += nbytes
        reg = get_registry()
        if direction == "sent":
            reg.counter("coord_frames_sent").inc()
            reg.counter("coord_frame_bytes_sent").inc(nbytes)
        else:
            reg.counter("coord_frames_recv").inc()
            reg.counter("coord_frame_bytes_recv").inc(nbytes)

    def _recv_loop(self, conn: _WorkerConn, sock, gen: int) -> None:
        try:
            while conn.alive:
                msg = recv_frame(sock)
                # the ledger's deserialize stamp: recv_frame times its
                # cloudpickle.loads on THIS thread (see _recv_timing)
                unpickle_s = getattr(_recv_timing, "unpickle_s", 0.0)
                if not isinstance(msg, dict):
                    raise CorruptFrameError(
                        f"non-dict frame from {conn.name}: "
                        f"{type(msg).__name__}"
                    )
                self._count_frame(
                    "recv", msg.get("type"),
                    getattr(_recv_timing, "nbytes", 0),
                )
                get_registry().counter(
                    "dispatch_unpickle_s"
                ).inc(unpickle_s)
                fepoch = msg.get("epoch")
                if fepoch is not None and int(fepoch) != self.epoch:
                    # a frame stamped by another coordinator incarnation:
                    # fence it — neither applied NOR acked, since an ack
                    # under this epoch would clear an outbox frame the
                    # epoch that owns it never processed
                    with self._lock:
                        self.stats["stale_epoch_frames"] += 1
                    get_registry().counter("stale_epoch_frames").inc()
                    logger.warning(
                        "fenced stale-epoch frame from %s (frame epoch "
                        "%s, ours %d)", conn.name, fepoch, self.epoch,
                    )
                    continue
                with self._lock:
                    if conn.generation != gen:
                        return  # a reconnect superseded this socket
                    # any frame from a connected worker renews its lease
                    conn.lease_deadline = time.monotonic() + self.lease_s
                seq = msg.get("seq")
                if seq is not None:
                    with self._lock:
                        dup = seq <= conn.last_seq
                        if not dup:
                            conn.last_seq = seq
                    if dup:
                        # an outbox replay (or injected duplication) of a
                        # message already applied: never process twice.
                        # Counted BEFORE the ack goes out — the ack is the
                        # observable "fully processed" signal, so anything
                        # the frame implies (this counter) must be done
                        # when a peer sees it
                        get_registry().counter(
                            "fleet_messages_deduped"
                        ).inc()
                    # ack even a duplicate: the ack for the original may be
                    # the very frame the partition ate
                    try:
                        send_frame(
                            conn.sock,
                            {"type": "ack", "seq": seq, "epoch": self.epoch},
                            conn.send_lock,
                        )
                    except (ConnectionError, OSError):
                        pass  # recv will notice the dead socket
                    if dup:
                        continue
                mtype = msg.get("type")
                if mtype in ("result", "error"):
                    produced = msg.get("produced")
                    if produced:
                        # the producer's advertisement piggybacks on the
                        # (sequenced, deduped) result frame: record BEFORE
                        # the future resolves so a consumer dispatched by
                        # this completion can already locate the bytes
                        self.chunk_registry.record(conn.name, produced)
                        if self._control is not None:
                            self._control.record_chunk_locations(
                                conn.name, produced
                            )
                    with self._lock:
                        fut = conn.outstanding.pop(msg["task_id"], None)
                        conn.deadlines.pop(msg["task_id"], None)
                        conn.timeout_strikes = 0  # it is producing results
                        # a ghost (started-then-timed-out task) finished:
                        # its thread is usable again
                        conn.ghost_ids.discard(msg["task_id"])
                    if fut is None or fut.done():
                        continue  # duplicate/late reply, or a cancelled twin
                    if self._control is not None:
                        # flushed, not fsync'd: losing this line costs one
                        # idempotent re-run after the NEXT takeover, never
                        # correctness
                        self._control.record_done(msg["task_id"])
                    if mtype == "result":
                        stats = msg.get("stats", {}) or {}
                        disp = getattr(fut, "_dispatch", None)
                        if disp is not None:
                            # complete the coordinator side of the ledger:
                            # submit() stamped serialize/send/lock-wait on
                            # this future; the receive side adds the
                            # result-arrival stamp and unpickle cost, and
                            # the whole dict rides the existing stats
                            # channel to map_unordered's success path
                            stats = dict(stats)
                            stats["dispatch"] = dict(
                                disp,
                                result_recv_tstamp=time.time(),
                                unpickle_s=unpickle_s,
                            )
                        try:
                            fut.set_result((msg.get("result"), stats))
                        except Exception:
                            pass  # cancelled concurrently (losing twin)
                    else:
                        err = RemoteTaskError(
                            msg.get("error", ""),
                            msg.get("error_type"),
                            msg.get("error_payload"),
                        )
                        task_stats = msg.get("task_stats")
                        if task_stats:
                            # the failed attempt's salvaged span buffer
                            # (collect.record_failed_task reads it off the
                            # exception on the client side)
                            err.cubed_tpu_task_stats = task_stats
                        _fail_future(fut, err)
                elif mtype == "started":
                    # execution begins now: restart the timeout clock and
                    # make a subsequent timeout count as a real hang
                    if self.task_timeout is not None:
                        with self._lock:
                            entry = conn.deadlines.get(msg["task_id"])
                            if entry is not None:
                                entry[0] = time.monotonic() + self.task_timeout
                                entry[1] = True
                elif mtype == "heartbeat":
                    # the worker's own memory telemetry: last RSS reading
                    # plus its local pressure verdict (watermarks evaluated
                    # where the memory actually is); routing skips
                    # pressured workers while an unpressured one is live
                    if msg.get("peer_cache_flush"):
                        # the worker's cache emptied (hard pressure): its
                        # advertised locations are all stale now
                        self.chunk_registry.drop_worker(conn.name)
                    elif msg.get("peer_evicted"):
                        self.chunk_registry.remove(
                            conn.name, msg["peer_evicted"]
                        )
                    delta = msg.get("metrics_delta")
                    with self._lock:
                        conn.rss = msg.get("rss")
                        conn.pressured = bool(msg.get("pressured"))
                        if msg.get("peer_cache") is not None:
                            conn.peer_cache = msg["peer_cache"]
                        if msg.get("clock_offset") is not None:
                            conn.clock_offset = msg["clock_offset"]
                            conn.clock_rtt = msg.get("clock_rtt")
                        if isinstance(delta, dict):
                            # bounded per-window counter deltas shipped by
                            # the worker: fold into the per-worker and the
                            # fleet-wide cumulative views the telemetry
                            # sampler reads (heartbeats are lossy by
                            # design — a dropped frame costs one window's
                            # increments, never correctness: the
                            # authoritative per-compute numbers still ride
                            # the task result stats)
                            for k, v in delta.items():
                                if isinstance(v, (int, float)):
                                    conn.metrics[k] = (
                                        conn.metrics.get(k, 0) + v
                                    )
                                    self.fleet_metrics[k] = (
                                        self.fleet_metrics.get(k, 0) + v
                                    )
                    if isinstance(delta, dict):
                        get_registry().counter(
                            "heartbeat_metric_deltas"
                        ).inc()
                    if conn.rss is not None:
                        get_registry().gauge("fleet_worker_rss_bytes").set(
                            conn.rss
                        )
                        # worker memory telemetry feeds the merged trace's
                        # per-worker memory lane client-side (the worker's
                        # own sampler ring never crosses the process
                        # boundary) — stamped at receipt on the client
                        # clock, so no alignment needed
                        from ..observability.collect import record_sample

                        record_sample(rss=conn.rss, worker=conn.name)
                    if msg.get("t0") is not None:
                        # clock handshake: echo the worker's send timestamp
                        # with our own receipt time — the worker computes an
                        # NTP-style offset from the pair and ships it back
                        # on the next heartbeat (and immediately via a
                        # "clock" message, so even sub-second computes have
                        # aligned worker spans)
                        try:
                            send_frame(
                                conn.sock,
                                {
                                    "type": "heartbeat_echo",
                                    "t0": msg["t0"],
                                    "t_coord": time.time(),
                                    "epoch": self.epoch,
                                },
                                conn.send_lock,
                            )
                        except (ConnectionError, OSError):
                            pass  # recv will notice the dead socket
                elif mtype == "clock":
                    with self._lock:
                        conn.clock_offset = msg.get("clock_offset")
                        conn.clock_rtt = msg.get("clock_rtt")
                elif mtype == "draining":
                    # the worker stops accepting work NOW (scale-down drain
                    # or a spot preemption notice); routing passes it over,
                    # in-flight tasks finish or come back as "abandoned"
                    from ..observability.collect import record_decision

                    reason = msg.get("reason") or "drain"
                    with self._lock:
                        conn.draining = True
                        if reason == "preempted":
                            self.stats["workers_preempted"] += 1
                    if reason == "preempted":
                        get_registry().counter("workers_preempted").inc()
                    record_decision(
                        "worker_draining", worker=conn.name, reason=reason,
                        grace_s=msg.get("grace_s"),
                    )
                    logger.info(
                        "worker %s draining (%s, grace %.3fs)",
                        conn.name, reason, msg.get("grace_s", 0) or 0,
                    )
                elif mtype == "abandoned":
                    # a task that reached a draining worker before routing
                    # noticed: handed back unexecuted — a free requeue
                    with self._lock:
                        fut = conn.outstanding.pop(msg["task_id"], None)
                        conn.deadlines.pop(msg["task_id"], None)
                        conn.ghost_ids.discard(msg["task_id"])
                    if fut is not None:
                        with self._lock:
                            self.stats["tasks_abandoned_on_drain"] += 1
                        get_registry().counter("tasks_abandoned_on_drain").inc()
                        _fail_future(
                            fut,
                            WorkerDrainedError(
                                f"worker {conn.name} draining: task "
                                f"{msg['task_id']} abandoned before start"
                            ),
                        )
                elif mtype == "drained":
                    self._on_drained(conn, msg)
                    return  # the worker closes its socket right after
                elif mtype == "chunk_locate":
                    # the peer-fetch lookup RPC: name + dialable address of
                    # the worker whose cache holds this chunk (None when
                    # unknown, departed, or currently disconnected — the
                    # reader then goes straight to the store)
                    wname = self.chunk_registry.locate(
                        msg.get("store"), msg.get("key")
                    )
                    peer_addr = None
                    if wname is not None:
                        with self._lock:
                            target = next(
                                (
                                    w for w in self._workers
                                    if w.alive and w.connected
                                    and w.name == wname
                                ),
                                None,
                            )
                            peer_addr = (
                                target.peer_addr if target is not None
                                else None
                            )
                    with self._lock:
                        self.stats["peer_locate_requests"] += 1
                    get_registry().counter("peer_locate_requests").inc()
                    try:
                        send_frame(conn.sock, {
                            "type": "chunk_location",
                            "req_id": msg.get("req_id"),
                            "worker": wname if peer_addr is not None else None,
                            "addr": peer_addr,
                            "epoch": self.epoch,
                        }, conn.send_lock)
                    except (ConnectionError, OSError):
                        pass  # the reader's locate times out -> store read
                elif mtype == "blob_dropped":
                    # the worker evicted this blob from its bounded caches;
                    # forget we sent it so the next task of that op
                    # re-ships the bytes (a task already in flight when the
                    # eviction raced it fails with unknown-blob and heals
                    # through the normal retry -> resend path)
                    with self._lock:
                        conn.blobs_sent.discard(msg.get("blob_id"))
                else:
                    logger.warning("unknown message from %s: %r", conn.name, mtype)
        except CorruptFrameError as e:
            # a torn/garbage frame desynchronizes the stream: count it,
            # drop THIS connection cleanly, and let the lease decide what
            # the peer's silence means — never kill the recv thread
            with self._lock:
                self.stats["frames_corrupt"] += 1
            get_registry().counter("frames_corrupt").inc()
            logger.warning(
                "corrupt frame from worker %s: %s — dropping the "
                "connection", conn.name, e,
            )
            if not self._closed.is_set():
                self._on_disconnect(conn, f"corrupt frame: {e}", gen=gen)
        except (ConnectionError, OSError) as e:
            if not self._closed.is_set():
                self._on_disconnect(
                    conn, str(e) or type(e).__name__, gen=gen
                )
        except Exception:
            logger.exception("receiver for %s crashed", conn.name)
            self._on_disconnect(conn, "receiver crash", gen=gen)

    def _on_drained(self, conn: _WorkerConn, msg: dict) -> None:
        """A worker finished its drain: fail its abandoned in-flight tasks
        with ``WorkerDrainedError`` (free requeue), count the drain, and
        remove the worker cleanly (not a ``workers_lost``)."""
        from ..observability.collect import record_decision

        reason = msg.get("reason") or "drain"
        abandoned = list(msg.get("abandoned") or [])
        pairs = []
        with self._lock:
            for tid in abandoned:
                pairs.append((tid, conn.outstanding.pop(tid, None)))
                conn.deadlines.pop(tid, None)
                conn.ghost_ids.discard(tid)
        n_abandoned = 0
        for tid, fut in pairs:
            if fut is None:
                continue  # its late result won the race: nothing to requeue
            n_abandoned += 1
            _fail_future(
                fut,
                WorkerDrainedError(
                    f"worker {conn.name} drained ({reason}): in-flight task "
                    f"{tid} abandoned at the end of the drain window"
                ),
            )
        with self._lock:
            # stats increments stay under the coordinator lock: concurrent
            # per-worker recv threads (a coordinated reclaim drains many
            # workers at once) must not lose dict '+=' interleavings
            if n_abandoned:
                self.stats["tasks_abandoned_on_drain"] += n_abandoned
            self.stats["drains_completed"] += 1
        if n_abandoned:
            get_registry().counter("tasks_abandoned_on_drain").inc(n_abandoned)
        get_registry().counter("drains_completed").inc()
        record_decision(
            "worker_drained", worker=conn.name, reason=reason,
            abandoned=n_abandoned,
        )
        self._drop_worker(conn, f"drained ({reason})", clean=True)

    def request_drain(
        self, name: str, grace_s: float = 30.0, reason: str = "scale_down"
    ) -> bool:
        """Ask worker ``name`` to drain: stop accepting tasks, finish (or
        abandon) in-flight work within ``grace_s``, report ``drained`` and
        leave. Routing passes the worker over from this call on. Returns
        False when no live worker has that name (already gone)."""
        from ..observability.collect import record_decision

        with self._lock:
            conn = next(
                (
                    w for w in self._workers
                    if w.alive and w.connected and w.name == name
                ),
                None,
            )
            if conn is None:
                return False  # gone, or disconnected (a drain can't reach it)
            conn.draining = True  # stop routing immediately, not on the ack
        try:
            send_frame(
                conn.sock,
                {"type": "drain", "grace_s": grace_s, "reason": reason,
                 "epoch": self.epoch},
                conn.send_lock,
            )
        except (ConnectionError, OSError) as e:
            self._drop_worker(conn, f"drain send failed: {e}")
            return False
        record_decision(
            "worker_drain_requested", worker=name, reason=reason,
            grace_s=grace_s,
        )
        return True

    def known_worker_names(self) -> set:
        """Every worker name that ever joined (live or departed)."""
        with self._lock:
            return set(self._worker_names_ever)

    def load_view(self) -> list:
        """Per-worker load rows for the autoscaler's policy loop: one dict
        per live worker (name, draining, pressured, outstanding incl. ghost
        slots, nthreads). Cheap — one pass under the lock."""
        with self._lock:
            return [
                {
                    "name": w.name,
                    "draining": w.draining,
                    "pressured": w.pressured,
                    # disconnected-but-leased: NOT a hole to backfill (the
                    # lease may still resolve to a reconnect), but not a
                    # drain candidate either — the autoscaler reads this
                    "connected": w.connected,
                    "outstanding": len(w.outstanding) + len(w.ghost_ids),
                    "nthreads": w.nthreads,
                }
                for w in self._workers
                if w.alive
            ]

    def _timeout_loop(self) -> None:
        """Fail tasks that exceed ``task_timeout`` so the caller's retry
        machinery reroutes them; a worker that keeps timing out without
        producing any result is treated as hung and dropped (its remaining
        tasks fail with WorkerLostError and reroute too). The reference's
        fleet executors get this from their platforms' per-call timeouts."""
        interval = max(0.05, min(1.0, (self.task_timeout or 1.0) / 4))
        while not self._closed.wait(interval):
            now = time.monotonic()
            hung: list[_WorkerConn] = []
            timed_out: list[tuple[Future, str, int]] = []
            with self._lock:
                for conn in self._workers:
                    if not conn.connected:
                        # a partitioned-but-leased worker cannot deliver
                        # results; the LEASE governs its tasks, not the
                        # task timeout — freeze their clocks so a
                        # reconnect resumes them with a full window, and
                        # never count a partition as a hang
                        for entry in conn.deadlines.values():
                            entry[0] = now + self.task_timeout
                        continue
                    overdue = [
                        (tid, entry[1])
                        for tid, entry in conn.deadlines.items()
                        if entry[0] < now
                    ]
                    for tid, started in overdue:
                        fut = conn.outstanding.pop(tid, None)
                        conn.deadlines.pop(tid, None)
                        if started:
                            conn.ghost_ids.add(tid)
                        if fut is not None and not fut.done():
                            timed_out.append((fut, conn.name, tid))
                    if overdue:
                        self.stats["task_timeouts"] += len(overdue)
                        get_registry().counter("task_timeouts").inc(len(overdue))
                        # only tasks the worker acked as started count as
                        # hangs; queued/cold-start timeouts just reroute
                        conn.timeout_strikes += sum(
                            1 for _, started in overdue if started
                        )
                        if conn.timeout_strikes >= self.timeout_strikes:
                            hung.append(conn)
            for fut, wname, tid in timed_out:
                _fail_future(
                    fut,
                    TaskTimeoutError(
                        f"task {tid} exceeded {self.task_timeout}s on "
                        f"worker {wname}"
                    ),
                )
            for conn in hung:
                self._drop_worker(
                    conn, f"hung: {conn.timeout_strikes} consecutive timeouts"
                )

    # -- task submission -----------------------------------------------

    def _blob_for(self, function, config) -> tuple[str, bytes]:
        import cloudpickle

        # the cached value keeps (function, config) alive so the id()-pair
        # key can never be reused by a different object while the entry
        # lives (bytes must stay resendable: workers joining later, or
        # losing tasks to a crash, receive the blob on their first task of
        # that op); eviction is safe because a miss just re-pickles
        key = (id(function), id(config))
        hit = self._blob_cache.get(key)
        if hit is not None:
            self._blob_cache.move_to_end(key)
            return hit[2], hit[3]
        blob = cloudpickle.dumps((function, config))
        blob_id = hashlib.sha1(blob).hexdigest()
        self._blob_cache[key] = (function, config, blob_id, blob)
        while len(self._blob_cache) > self._blob_cache_size:
            self._blob_cache.popitem(last=False)
        return blob_id, blob

    def submit(
        self, _stats_wrapper, function, task_input, *, config=None,
        locality=None, tag=None,
    ) -> Future:
        """Ship one task to the least-loaded live worker — or, when
        ``locality`` names the task's input chunks ``[(store, key), ...]``
        and peer transfer is on, to the non-pressured worker already
        holding the most of those bytes in its chunk cache (within a load
        slack of the least-loaded; see ``transfer.pick_worker_by_locality``).

        The first positional argument exists to mirror
        ``pool.submit(execute_with_stats, function, input, config=...)``; the
        wrapper always runs worker-side.

        ``tag`` is the task's durable ``(op, chunk-key)`` identity. After a
        coordinator takeover, a submit whose tag matches a dispatch adopted
        from the prior epoch returns the adopted future — the worker may
        still be running that task (or its replayed result already resolved
        it), so re-dispatching would re-run completed work.
        """
        if tag is not None and self._adopted:
            with self._lock:
                adopted = self._adopted.pop(tuple(tag), None)
                if adopted is not None:
                    self.stats["tasks_readopted"] += 1
                    self._adopted_issued.append(adopted)
            if adopted is not None:
                get_registry().counter("tasks_readopted").inc()
                return adopted
        # dispatch ledger: zero the hot-lock accumulator for THIS submit,
        # and fold the op-blob pickle (cached after first use) into the
        # serialize cost — submit runs inline on the dispatch loop, so
        # everything timed here is coordinator overhead by definition
        self._lock.reset_thread_wait()
        t_blob = time.perf_counter()
        blob_id, blob = self._blob_for(function, config)
        blob_cost = time.perf_counter() - t_blob
        fut: Future = Future()
        # routing may need a second try if a send races a worker death
        while True:
            with self._lock:
                live = [w for w in self._workers if w.alive and w.connected]
                if (
                    not live
                    and any(w.alive for w in self._workers)
                    and not self._closed.is_set()
                ):
                    # every worker is disconnected-but-leased (a fleet-wide
                    # partition): they are not lost yet — wait for a
                    # reconnect, or for the leases to resolve the question
                    self._worker_joined.wait_for(
                        lambda: any(
                            w.alive and w.connected for w in self._workers
                        )
                        or not any(w.alive for w in self._workers)
                        or self._closed.is_set(),
                        timeout=self.lease_s,
                    )
                    live = [
                        w for w in self._workers if w.alive and w.connected
                    ]
                if (
                    not live
                    and self.backfill_grace_s > 0
                    and self._workers_ever > 0
                    and not self._closed.is_set()
                ):
                    # an attached autoscaler owes the fleet a replacement
                    # (e.g. the LAST worker was preempted/drained and the
                    # backfill subprocess is still booting): wait for it to
                    # register instead of failing the compute the drain
                    # protocol promised to protect
                    self._worker_joined.wait_for(
                        lambda: any(
                            w.alive and w.connected for w in self._workers
                        )
                        or self._closed.is_set(),
                        timeout=self.backfill_grace_s,
                    )
                    live = [
                        w for w in self._workers if w.alive and w.connected
                    ]
                if not live:
                    host, port = self.address
                    ever = self._workers_ever
                    lost = self.stats["workers_lost"]
                    if ever == 0:
                        hint = (
                            "no worker ever connected — start workers with "
                            "'python -m cubed_tpu.runtime.worker "
                            f"{host}:{port}' on each host (or use "
                            "n_local_workers/min_workers so the executor "
                            "waits for them before submitting)"
                        )
                    else:
                        hint = (
                            f"{ever} worker(s) joined over this "
                            f"coordinator's lifetime and {lost} were lost "
                            "(crash/hang/shutdown) — check worker logs, "
                            "task_timeout, and host health"
                        )
                    raise NoWorkersError(
                        f"cannot submit task: no live workers connected to "
                        f"coordinator {host}:{port} (epoch {self.epoch}); "
                        f"{hint}"
                    )
                if (
                    self.backfill_grace_s > 0
                    and not self._closed.is_set()
                    and all(w.draining for w in live)
                ):
                    # every live worker is draining (a coordinated spot
                    # reclaim hit the whole fleet): routing to a drainer
                    # is an instant abandon->requeue ping-pong that burns
                    # the free requeue allowance in milliseconds — far
                    # faster than any replacement can boot. Wait for the
                    # backfill to register; drainers remain the fallback
                    # if none arrives within the grace window.
                    self._worker_joined.wait_for(
                        lambda: any(
                            w.alive and w.connected and not w.draining
                            for w in self._workers
                        )
                        or self._closed.is_set(),
                        timeout=self.backfill_grace_s,
                    )
                    live = [
                        w for w in self._workers if w.alive and w.connected
                    ]
                    if not live:
                        continue  # drainers gone: the no-live path decides
                # draining workers are passed over while any non-draining
                # one is live (an all-draining fleet still takes the task:
                # it may be abandoned and requeued, which beats failing the
                # compute outright when no replacement can come)
                active = [w for w in live if not w.draining] or live
                # memory-pressured workers are passed over while any
                # unpressured one is live (never deadlock: an all-pressured
                # fleet still gets the least-loaded worker — the admission
                # controller is what sheds load in that state)
                unpressured = [w for w in active if not w.pressured]
                if unpressured and len(unpressured) < len(active):
                    get_registry().counter("dispatch_skipped_pressured").inc()
                candidates = unpressured or active

                def _load(w):
                    return (
                        len(w.outstanding) + len(w.ghost_ids)
                    ) / max(w.nthreads, 1)

                conn = None
                if locality and len(candidates) > 1:
                    # locality-aware placement: prefer the (non-pressured —
                    # an all-pressured fleet falls through to candidates,
                    # where load wins) worker whose chunk cache already
                    # holds the most input bytes
                    resident = self.chunk_registry.resident_bytes(locality)
                    conn = pick_worker_by_locality(
                        candidates, resident, _load
                    )
                    if conn is not None:
                        self.stats["placement_locality_hits"] += 1
                        get_registry().counter(
                            "placement_locality_hits"
                        ).inc()
                        if self._locality_decisions_left > 0:
                            self._locality_decisions_left -= 1
                            locality_note = (
                                conn.name, resident.get(conn.name, 0)
                            )
                        else:
                            locality_note = None
                    else:
                        locality_note = None
                else:
                    locality_note = None
                if conn is None:
                    conn = min(candidates, key=_load)
                task_id = self._next_task_id
                self._next_task_id += 1
                conn.outstanding[task_id] = fut
                first_use = blob_id not in conn.blobs_sent
                if self.task_timeout is not None:
                    # registered BEFORE the send, under the same lock as
                    # outstanding: a fast worker's 'started' ack must find
                    # the entry (racing it would permanently mark the task
                    # cold-start and exempt a real hang from eviction)
                    conn.deadlines[task_id] = [
                        time.monotonic() + self.task_timeout, False
                    ]
            from ..observability import accounting, logs
            from ..observability.collect import record_decision
            from ..storage import integrity
            from . import cancellation as cancel_mod
            from . import memory
            from . import transfer as p2p
            from .faults import get_injector, wire_config

            if locality_note is not None:
                record_decision(
                    "placement_locality", worker=locality_note[0],
                    resident_bytes=locality_note[1], task_id=task_id,
                )
            msg = {
                "type": "task",
                "task_id": task_id,
                "epoch": self.epoch,
                "blob_id": blob_id,
                "blob": blob if first_use else None,
                "input": task_input,
                # the client's compute id rides with every task so worker
                # log lines/spans correlate to the compute that asked
                "compute_id": logs.current_compute_id(),
                # ack execution start only when someone is watching the clock
                "ack": self.task_timeout is not None,
                # the client's fault-injection arming state rides with every
                # task: workers mirror it exactly (pre-started fleets still
                # inject; disarming propagates instead of lingering in
                # spawn-time env), see faults.wire_config
                "faults": wire_config(),
                # the client's integrity mode rides the same way, so a
                # pre-started fleet verifies (or not) exactly as the client
                # asked for THIS compute
                "integrity": integrity.wire_mode(),
                # ... as does the memory-guard config (mode + allowed_mem),
                # so workers enforce the same per-task budget the client's
                # Spec promised
                "memory_guard": memory.wire_config(),
                # ... and the span-recording arming: workers buffer/ship
                # spans exactly when the client has a collector to merge
                # them, and stop when it doesn't
                "spans": accounting.spans_wire(),
                # ... and the peer-transfer arming (None = off, which also
                # disarms a pre-started worker a previous compute enabled):
                # workers cache/advertise/fetch exactly when this compute
                # asked for the p2p data plane
                "peer": p2p.wire_config(),
                # ... and the compute's cancellation token (deadline epoch
                # + cancelled flag, None = unbounded): workers abort
                # cooperatively between chunk reads/writes the moment it
                # trips — read per submit, so a cancel mid-compute rides
                # every later task message even if the broadcast was lost
                "cancel": cancel_mod.wire_for_compute(
                    logs.current_compute_id()
                ),
            }
            try:
                # serialize and send timed separately: pickle time vs
                # socket time are different saturation stories (batch the
                # frame build vs shard the link), so the ledger keeps them
                # apart
                t_ser = time.perf_counter()
                data = frame_bytes(msg)
                serialize_s = blob_cost + time.perf_counter() - t_ser
                t_send = time.perf_counter()
                with conn.send_lock:
                    conn.sock.sendall(data)
                send_s = time.perf_counter() - t_send
            except (ConnectionError, OSError) as e:
                with self._lock:
                    conn.outstanding.pop(task_id, None)
                    conn.deadlines.pop(task_id, None)
                # a failed send means the socket is dead, not the worker:
                # lease rules decide its fate while this task re-routes
                self._on_disconnect(conn, f"send failed: {e}")
                continue  # pick another worker for the same future
            except Exception:
                # e.g. an unpicklable task input: the worker never saw the
                # message, so only this submission's bookkeeping rolls back
                with self._lock:
                    conn.outstanding.pop(task_id, None)
                    conn.deadlines.pop(task_id, None)
                raise
            # coordinator half of the dispatch ledger, attached to the
            # future the instant the send lands (the recv loop merges it
            # into the result's stats; a reply racing this attribute set
            # just ships without a ledger — it stays Optional end to end)
            fut._dispatch = {
                "serialize_s": serialize_s,
                "send_s": send_s,
                "lock_wait_s": self._lock.thread_wait_s(),
                "sent_tstamp": time.time(),
            }
            self._count_frame("sent", "task", len(data))
            reg = get_registry()
            reg.counter("dispatch_serialize_s").inc(serialize_s)
            reg.counter("dispatch_send_s").inc(send_s)
            with self._lock:
                # only mark the blob delivered once the send has succeeded
                conn.blobs_sent.add(blob_id)
                conn.tasks_sent += 1
            self.stats["tasks_sent"] += 1
            if first_use:
                self.stats["blobs_sent"] += 1
            if self._control is not None and tag is not None:
                # the dispatch-frontier record a successor folds: which
                # (op, chunk-key) was in flight where (flushed — a lost
                # line costs one idempotent re-run; untagged tasks have no
                # durable identity to readopt, so they aren't recorded)
                self._control.record_dispatch(task_id, tag, conn.name)
            inj = get_injector()
            if inj is not None and inj.coordinator_dispatch_tick(self.epoch):
                # chaos hook: the coordinator process hard-exits after the
                # Nth real dispatch (crash / crash-during-takeover knobs)
                logger.warning(
                    "coordinator: injected crash after dispatch %d "
                    "(epoch %d)", task_id, self.epoch,
                )
                os._exit(137)
            return fut

    def broadcast_cancel(
        self, compute_id: Optional[str], reason: Optional[str] = None
    ) -> int:
        """Send a ``compute_cancel`` frame to every connected worker so
        the fleet aborts that compute's tasks cooperatively (between
        chunk reads/writes). Best-effort by design: a worker that misses
        the frame (disconnected, mid-partition) still learns from the
        tripped token riding any later task message, and its in-flight
        results are simply discarded client-side. Returns the number of
        workers notified."""
        if not compute_id:
            return 0
        with self._lock:
            conns = [
                w for w in self._workers if w.alive and w.connected
            ]
        notified = 0
        # one frame build for the whole fleet (the payload is identical)
        data = frame_bytes({
            "type": "compute_cancel",
            "compute": compute_id,
            "reason": reason,
            "epoch": self.epoch,
        })
        for conn in conns:
            try:
                with conn.send_lock:
                    conn.sock.sendall(data)
                self._count_frame("sent", "compute_cancel", len(data))
                notified += 1
            except (ConnectionError, OSError):
                continue  # the task-message path is the backstop
        self.stats["compute_cancels_sent"] += notified
        logger.info(
            "broadcast compute_cancel for %s to %d worker(s)",
            compute_id, notified,
        )
        return notified

    def stats_snapshot(self) -> dict:
        """Counters plus a per-worker load view (outstanding tasks, ghost
        slots, lifetime tasks routed) for ``executor_stats``/debugging.
        Departed workers keep their final row (``alive: False`` + drop
        reason) so worker loss remains visible in the snapshot."""
        out: dict = dict(self.stats)
        out["epoch"] = self.epoch
        with self._lock:
            workers: dict = {name: dict(row) for name, row in self._departed.items()}
            for w in self._workers:
                workers[w.name] = {
                    "alive": w.alive,
                    "connected": w.connected,
                    "epoch": w.joined_epoch,
                    "nthreads": w.nthreads,
                    "outstanding": len(w.outstanding),
                    "ghosts": len(w.ghost_ids),
                    "tasks_sent": w.tasks_sent,
                    "rss": w.rss,
                    "pressured": w.pressured,
                    "draining": w.draining,
                    "clock_offset": w.clock_offset,
                    "clock_rtt": w.clock_rtt,
                    "peer_cache": w.peer_cache,
                    "metrics": dict(w.metrics) or None,
                }
        out["workers"] = workers
        out["chunk_locations"] = self.chunk_registry.stats()
        with self._lock:
            out["fleet_metrics"] = dict(self.fleet_metrics) or None
        # per-message-type link traffic ({direction: {type: [frames,
        # bytes]}}) — the DISPATCH panel's frame breakdown
        out["frames"] = {
            d: {k: list(v) for k, v in rows.items()}
            for d, rows in self._frame_counts.items()
        }
        return out

    def close(self) -> None:
        from ..observability.timeseries import unregister_fleet

        unregister_fleet(self)
        self._closed.set()
        with self._lock:
            workers = list(self._workers)
            # wake any submit() blocked on a backfill wait: closed wins
            self._worker_joined.notify_all()
        for conn in workers:
            if conn.sock is not None:
                try:
                    send_frame(
                        conn.sock,
                        {"type": "shutdown", "epoch": self.epoch},
                        conn.send_lock,
                    )
                except (ConnectionError, OSError):
                    pass
            self._drop_worker(conn, "shutdown")
        if self._control_sink is not None:
            from ..observability.collect import remove_decision_sink

            remove_decision_sink(self._control_sink)
            self._control_sink = None
        if self._control is not None:
            self._control.close()
        try:
            self._server.close()
        except OSError:
            pass


# ----------------------------------------------------------------------
# worker side
# ----------------------------------------------------------------------


#: unacked important messages a worker retains for replay across reconnects;
#: beyond this the OLDEST is dropped (counted) — results live in the shared
#: store anyway, so a dropped result frame costs a requeue, never data
OUTBOX_CAP = 256

#: worker stale-link watchdog thresholds. A healthy link echoes every 1s
#: heartbeat and acks important frames within ~RTT, so silence past these
#: windows reads as a half-open link and forces a reconnect. Known
#: limitation: progress is measured per COMPLETE frame, so a single frame
#: whose transfer legitimately exceeds the window (a huge op blob on a
#: very slow link) would be cut and retransmitted from zero — the control
#: plane ships kilobyte-scale frames by design (blobs once per worker, data
#: through Zarr), but blob-heavy deployments on constrained links should
#: raise these
RX_STALE_S = 4.0
ACK_STALE_S = 1.5

#: task-scope counters additionally folded into the WORKER's own registry
#: (so the heartbeat metrics_delta carries a live per-worker view of
#: them); bounded allowlist — scoped counters already reach the CLIENT
#: registry via task stats, this fold only feeds the worker-side telemetry
#: dimension and never crosses into client metrics
_WORKER_FOLD_COUNTERS = (
    "peer_hits", "peer_misses", "chunks_verified",
    "chunks_corrupt_detected", "store_throttled",
)

#: cap on the per-heartbeat metrics-delta payload (numeric keys): the
#: heartbeat frame must stay kilobyte-scale whatever the metric namespace
#: grows to; overflow keys are dropped deterministically (sorted order)
#: and the drop is itself counted in the shipped delta
HEARTBEAT_DELTA_MAX_KEYS = 64


def heartbeat_metrics_delta(reg, prev_snapshot: dict) -> tuple:
    """The bounded worker->coordinator metrics payload for one heartbeat.

    Returns ``(delta_dict_or_None, new_snapshot)``: numeric per-window
    increments only (histogram windows and gauge ``_max`` marks stay out
    — ``snapshot_delta`` already windowed gauges away, counting them in
    ``gauges_dropped_in_delta``, which DOES ship so a fleet gauge can
    never vanish silently), zero increments elided, at most
    ``HEARTBEAT_DELTA_MAX_KEYS`` keys. The delta and the returned new
    baseline are the SAME snapshot observation — two separate snapshots
    would ship increments landing between them twice."""
    snap = reg.snapshot()
    delta = reg.snapshot_delta(prev_snapshot, now=snap)
    out = {}
    overflow = 0
    for k in sorted(delta):
        v = delta[k]
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        if v == 0 or k.endswith("_max"):
            continue
        if len(out) >= HEARTBEAT_DELTA_MAX_KEYS:
            overflow += 1
            continue
        out[k] = v
    if overflow:
        out["heartbeat_delta_keys_dropped"] = overflow
    return (out or None), snap


class _WorkerLink:
    """The worker's side of the coordinator connection.

    Owns the socket, the monotonic ``seq`` counter, and a bounded outbox of
    unacked *important* frames (result / error / drained / abandoned —
    anything whose loss would strand coordinator state). ``send`` never
    raises for link trouble: a failed or injected-away send leaves
    important frames queued, and the reconnect path replays them in order
    (the coordinator drops duplicates by ``seq``). Seeded control-plane
    fault injection (``runtime/faults.py``: message drop / dup / delay /
    reset, one-way partition) is applied here, per frame, worker-side for
    both directions of the conversation."""

    def __init__(self, wname: str, sock: Optional[socket.socket] = None,
                 outbox_cap: int = OUTBOX_CAP):
        self.wname = wname
        self.sock = sock
        self.lock = threading.Lock()
        self.seq = 0
        #: the coordinator epoch this link last handshook under (from the
        #: hello_ack). Every outbound frame is stamped with it AT FRAME
        #: TIME, and outbox replays re-stamp — "replay the unacked outbox
        #: to the new epoch" is what lets a successor accept a result the
        #: crashed epoch dispatched. Inbound frames with an OLDER epoch
        #: (a zombie prior coordinator) are fenced by the recv loop
        self.epoch = 0
        #: (seq, enqueue-monotonic, message dict) — dicts, not frames, so
        #: a replay can re-stamp the current epoch; enqueue times are
        #: refreshed at replay so the staleness watchdog measures THIS
        #: link's silence
        self.outbox: deque = deque()
        self.outbox_cap = int(outbox_cap)
        #: monotonic time of the last frame actually delivered to us —
        #: the heartbeat watchdog reconnects when it goes stale
        self.last_rx = time.monotonic()
        #: session token from the coordinator's hello_ack; presenting it on
        #: reconnect is what re-adopts our in-flight leases
        self.token: Optional[str] = None
        #: the coordinator's advertised lease window (reconnect sizing hint)
        self.lease_hint: Optional[float] = None

    def held_task_ids(self) -> set:
        """Task ids named by an unacked important frame in the outbox:
        a replay will re-deliver their result/error/abandoned outcome, so
        the coordinator may keep waiting on them."""
        with self.lock:
            return {
                m["task_id"] for (_s, _t, m) in self.outbox
                if "task_id" in m
            }

    def send(self, msg: dict, important: bool = False) -> bool:
        """Frame and send one message. Important frames are sequenced and
        retained until acked. False = the link is down (important frames
        stay queued for replay); pickling errors propagate to the caller
        BEFORE anything is queued."""
        from .faults import get_injector

        inj = get_injector()
        with self.lock:
            if important:
                self.seq += 1
                msg = dict(msg, seq=self.seq)
            data = frame_bytes(dict(msg, epoch=self.epoch))
            if important:
                self.outbox.append((self.seq, time.monotonic(), msg))
                while len(self.outbox) > self.outbox_cap:
                    self.outbox.popleft()
                    get_registry().counter("outbox_dropped").inc()
            sock = self.sock
            if sock is None:
                return False
            act = None
            if inj is not None:
                if inj.partitioned(self.wname, "tx"):
                    # the wire ate it; important frames await the replay
                    return True
                act = inj.net_fault("tx", self.wname, msg.get("type"))
                if act == "drop":
                    return True
                if act == "reset":
                    try:
                        sock.close()
                    except OSError:
                        pass
                    return False
                if act == "delay":
                    time.sleep(inj.config.net_msg_delay_s)
            try:
                sock.sendall(data)
                if act == "dup":
                    sock.sendall(data)
                return True
            except (ConnectionError, OSError):
                return False

    def on_ack(self, seq: Optional[int]) -> None:
        """The coordinator acknowledged everything up to ``seq``."""
        if seq is None:
            return
        with self.lock:
            while self.outbox and self.outbox[0][0] <= seq:
                self.outbox.popleft()

    def unacked_age(self) -> float:
        """Seconds the oldest unacked important frame has been waiting on
        THIS link (0.0 with an empty outbox) — the half-open-link signal."""
        with self.lock:
            if not self.outbox:
                return 0.0
            return time.monotonic() - self.outbox[0][1]

    def adopt(self, sock: socket.socket, token: Optional[str],
              resumed: bool) -> None:
        """Install a freshly handshaken socket. ``resumed=False`` means the
        coordinator registered us as a NEW session (our old lease is gone,
        its tasks were requeued): the outbox is cleared — replaying results
        nobody is waiting for would only be deduped anyway. ``True``
        replays every unacked frame in order. Raises on replay failure (the
        caller treats it as a failed reconnect attempt)."""
        now = time.monotonic()
        with self.lock:
            self.token = token
            if not resumed:
                self.outbox.clear()
            # refresh enqueue stamps: the watchdog must measure the NEW
            # link's progress, not how long the partition lasted
            self.outbox = deque(
                (seq, now, msg) for seq, _t, msg in self.outbox
            )
            for _seq, _t, msg in self.outbox:
                # re-stamped with the CURRENT epoch: a successor fences
                # frames from the epoch that dispatched these tasks, so a
                # replay must speak the epoch it handshook
                sock.sendall(frame_bytes(dict(msg, epoch=self.epoch)))
            self.sock = sock
        self.last_rx = now


def _give_up_message(
    wname: str, endpoint: str, epoch: int, waited_s: float,
    rendezvous: Optional[str] = None,
) -> str:
    """The worker's reconnect-give-up diagnostic. A worker used to die of
    a bare socket error here, which is undebuggable from its own log —
    name the coordinator endpoint and the last epoch this worker was
    joined under, plus a ``NoWorkersError``-style hint table."""
    lines = [
        f"worker {wname!r}: could not reach the coordinator at {endpoint} "
        f"(last epoch {epoch}) for {waited_s:.0f}s; giving up.",
        "Likely causes: the coordinator process crashed or was killed "
        "(check its log / exit code)",
        f"the coordinator host or network path is down (try dialing "
        f"{endpoint} from this host)",
    ]
    if rendezvous:
        lines.append(
            f"no successor advertised a takeover in {rendezvous!r} — if a "
            "replacement coordinator is expected, check that it runs with "
            "the same control_dir"
        )
    else:
        lines.append(
            "no rendezvous file is configured (--rendezvous), so a "
            "restarted coordinator cannot re-adopt this worker"
        )
    lines.append(
        "raise --reconnect-give-up if the control plane can legitimately "
        "stay dark longer than this window"
    )
    return "; ".join(lines)


def run_worker(
    coordinator: str,
    nthreads: int = 1,
    name: Optional[str] = None,
    drain_grace_s: float = 10.0,
    reconnect_give_up_s: float = 30.0,
    rendezvous: Optional[str] = None,
) -> None:
    """Connect to ``host:port`` and execute tasks until shutdown/EOF.

    One process per host; ``nthreads`` concurrent task slots (chunk tasks are
    IO + numpy/jax compute, so a few threads per host overlap IO with
    compute the same way the threaded local executor does).

    The worker honors a graceful **drain** (used by autoscaler scale-down
    and by spot preemption): stop accepting tasks, finish — or, at the end
    of the grace window, abandon — in-flight work, report ``drained`` with
    the abandoned task ids, and exit. ``SIGTERM`` triggers the same path
    with spot semantics (``drain_grace_s`` models the preemption notice;
    the platform's hard kill at the end of the notice is modelled by a
    hard-exit timer so a wedged task can't outlive its notice).

    A lost connection is NOT fatal: in-flight tasks keep running, result
    frames queue in a bounded outbox, and the worker reconnects —
    presenting its session token so the coordinator re-adopts its leases —
    replaying unacked frames in order. A half-open link (one-way
    partition) is detected by the heartbeat watchdog: no frames received
    for a few seconds, or an important frame unacked past its window,
    forces the same reconnect path. Only after ``reconnect_give_up_s`` of
    failed attempts does the worker exit.

    ``rendezvous`` names the coordinator's advertisement file (see
    ``runtime/journal.write_rendezvous``): the reconnect loop re-reads it
    each attempt, re-targets its dial at a successor's address, and — for
    as long as the advertisement names a NEWER epoch than the one this
    worker last joined (an open takeover window) — the give-up clock is
    suspended, so a fleet mid-takeover never dies of impatience."""
    import cloudpickle
    import signal as _signal
    from concurrent.futures import ThreadPoolExecutor

    from ..observability import clock as obs_clock
    from ..observability import logs as obs_logs
    from ..observability.accounting import (
        arm_spans_from_wire,
        set_process_label,
    )
    from ..storage import integrity
    from ..utils import current_measured_mem
    from . import cancellation
    from . import memory
    from . import transfer as p2p
    from .faults import arm_from_wire, get_injector
    from .utils import chunk_key, execute_with_stats

    host, _, port = coordinator.rpartition(":")
    #: mutable dial target: a rendezvous advertisement re-points it at a
    #: successor coordinator's address mid-reconnect
    dial = {"host": host or "127.0.0.1", "port": int(port)}
    #: highest epoch ever seen advertised — each NEW epoch earns the
    #: reconnect loop one fresh give-up window, bounding how long a worker
    #: chases successors that never accept it
    adv_seen = {"epoch": -1}
    wname = name or f"{socket.gethostname()}:{os.getpid()}"
    #: the p2p data plane's worker half: chunk cache + serving socket. The
    #: listener is cheap and always started (its address must ride the
    #: FIRST hello, before any task message can arm fetching); the cache
    #: only fills — and fetches only happen — while a compute arms peer
    #: transfer over the wire. CUBED_TPU_P2P=off disables it entirely.
    peer_rt: Optional[p2p.PeerRuntime] = None
    if not p2p.env_disabled():
        try:
            peer_rt = p2p.PeerRuntime(wname)
            peer_rt.start_server()
            p2p.set_worker_runtime(peer_rt)
        except OSError as e:
            logger.warning(
                "worker %s: peer chunk server failed to start (%s); "
                "running store-only", wname, e,
            )
            peer_rt = None
            p2p.set_worker_runtime(None)
    # stamp this process's task stats with the worker name (its trace lane)
    # and adopt any test-injected clock skew before the first heartbeat
    set_process_label(wname)
    obs_clock.configure_from_env(wname)
    #: latest NTP-style clock estimate from the coordinator's heartbeat
    #: echoes (coordinator_time ≈ our clock.now() + offset); "best" is the
    #: lowest rtt ever observed — the fixed quality anchor for refreshes
    clock_est: Dict[str, Optional[float]] = {
        "offset": None, "rtt": None, "best": None,
    }
    link = _WorkerLink(wname)
    if peer_rt is not None:
        # chunk_locate RPCs ride the coordinator link (non-important: a
        # lost lookup is a locate timeout, which is a store fallback)
        peer_rt.link_send = link.send
    #: task ids ever accepted, bounded: a re-delivered assignment (injected
    #: duplication, or a frame replay) must be executed at most once —
    #: idempotent task-assignment, worker-side. Cleared whenever the
    #: coordinator registers us as a NEW session: a fresh coordinator's
    #: task-id counter restarts at 0, and its ids must not collide with a
    #: dead session's
    seen_tasks: OrderedDict[int, bool] = OrderedDict()

    class _RegistrationRejected(ConnectionError):
        """The coordinator refused our hello (impostor-name rejection):
        retrying cannot succeed — give up instead of hammering it."""

    def _connect() -> None:
        """One connection attempt: TCP connect + hello/hello_ack handshake
        + outbox replay. Raises on any failure — including an active
        injected partition, which blackholes new connections exactly like
        a real one."""
        inj = get_injector()
        if inj is not None and (
            inj.partitioned(wname, "tx") or inj.partitioned(wname, "rx")
        ):
            raise ConnectionError("injected network partition")
        s = socket.create_connection(
            (dial["host"], dial["port"]), timeout=10
        )
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hello = {
                "type": "hello",
                "name": wname,
                "nthreads": nthreads,
                "pid": os.getpid(),
            }
            if peer_rt is not None:
                # advertise the peer server on the interface this worker
                # reaches the coordinator from — the address other fleet
                # hosts can dial
                try:
                    local_ip = s.getsockname()[0]
                except OSError:
                    local_ip = "127.0.0.1"
                hello["peer_addr"] = peer_rt.advertised_addr(local_ip)
            if link.token is not None:
                hello["token"] = link.token
                # every task id this session ever accepted (the dedup
                # set covers queued, running, and finished work) plus
                # unacked outbox frames: the coordinator reconciles its
                # outstanding set against this and requeues assignments
                # the dead link ate — nothing here will ever complete
                # an assignment we never received
                hello["holding"] = sorted(
                    set(seen_tasks) | link.held_task_ids()
                )
            send_frame(s, hello)
            ack = recv_frame(s)
            if isinstance(ack, dict) and ack.get("type") == "hello_reject":
                raise _RegistrationRejected(str(ack.get("reason", "")))
            if not isinstance(ack, dict) or ack.get("type") != "hello_ack":
                raise ConnectionError(f"bad handshake reply: {ack!r}")
            s.settimeout(None)
            link.lease_hint = ack.get("lease_s")
            resumed = bool(ack.get("resume"))
            if not resumed:
                # a NEW session (first registration, or our old lease is
                # gone — possibly under a brand-new coordinator whose task
                # ids restart at 0): stale dedup state must not swallow
                # the new session's assignments
                seen_tasks.clear()
            # the epoch must be current BEFORE adopt replays the outbox:
            # replayed frames are re-stamped with it, and a successor
            # fences anything stamped by the epoch that crashed
            link.epoch = int(ack.get("epoch") or 0)
            link.adopt(s, ack.get("token"), resumed)
        except BaseException:
            try:
                s.close()
            except OSError:
                pass
            raise

    def _check_rendezvous() -> bool:
        """Re-read the successor advertisement, re-targeting the dial at
        its address. True exactly once per newly advertised epoch newer
        than the one this worker last joined — an open takeover window,
        which earns the reconnect loop a fresh give-up allowance."""
        if rendezvous is None:
            return False
        from .journal import read_rendezvous

        adv = read_rendezvous(rendezvous)
        if adv is None:
            return False
        if adv["addr"] != (dial["host"], dial["port"]):
            logger.warning(
                "worker %s: rendezvous advertises epoch %d at %s:%s; "
                "re-targeting the reconnect", wname, adv["epoch"],
                adv["addr"][0], adv["addr"][1],
            )
            dial["host"], dial["port"] = adv["addr"]
        if adv["epoch"] > link.epoch and adv["epoch"] > adv_seen["epoch"]:
            adv_seen["epoch"] = adv["epoch"]
            return True
        return False

    def _reconnect() -> bool:
        """Re-establish the coordinator link after a drop, with backoff,
        for up to ``reconnect_give_up_s`` — suspended (restarted) each
        time the rendezvous file advertises a NEW successor epoch. In-
        flight tasks keep running throughout; success replays the outbox.
        False = give up (exit)."""
        give_up = time.monotonic() + max(0.0, reconnect_give_up_s)
        delay = 0.05
        while not stop.is_set() and not drain["on"]:
            if _check_rendezvous():
                # a successor is mid-takeover: dying now would abandon a
                # fleet that is about to be re-adopted
                give_up = time.monotonic() + max(0.0, reconnect_give_up_s)
            if time.monotonic() > give_up:
                logger.error(
                    "%s",
                    _give_up_message(
                        wname, f"{dial['host']}:{dial['port']}", link.epoch,
                        reconnect_give_up_s, rendezvous,
                    ),
                )
                return False
            try:
                _connect()
            except _RegistrationRejected as e:
                if rendezvous is not None:
                    # a successor can reject transiently while its own
                    # adoption settles; the rendezvous window (give_up
                    # above) decides when chasing it stops being worth it
                    logger.warning(
                        "worker %s: registration rejected (%s); retrying "
                        "under the rendezvous window", wname, e,
                    )
                    time.sleep(delay)
                    delay = min(delay * 2, 1.0)
                    continue
                logger.error(
                    "worker %s: registration rejected (%s); exiting",
                    wname, e,
                )
                return False
            except (ConnectionError, OSError):
                time.sleep(delay)
                delay = min(delay * 2, 1.0)
                continue
            get_registry().counter("worker_link_reconnects").inc()
            logger.warning(
                "worker %s: reconnected to the coordinator (%d unacked "
                "frame(s) replayed)", wname, len(link.outbox),
            )
            return True
        return False

    _connect()  # the initial registration failure stays loud: raise
    raw_blobs: Dict[str, bytes] = {}
    #: LRU of decoded (function, config) pairs, bounded so a worker serving
    #: a long-lived coordinator across many plans doesn't pin every op's
    #: live objects (raw bytes are freed at decode, as before). Evicting
    #: notifies the coordinator (``blob_dropped``) so it re-ships the bytes
    #: with the next task of that op instead of assuming the worker still
    #: holds them.
    decoded_blobs: OrderedDict[str, tuple] = OrderedDict()
    try:
        decoded_cap = max(
            1, int(os.environ.get("CUBED_TPU_WORKER_BLOB_CAP", "256"))
        )
    except ValueError:
        decoded_cap = 256
    blob_lock = threading.Lock()
    stop = threading.Event()
    #: drain state: once armed, no new task starts; in-flight tasks get the
    #: grace window, then are abandoned. ``grace`` is mutable so an injected
    #: preemption can carry its own (shorter) notice window
    drain = {"on": False, "grace": float(drain_grace_s)}
    inflight: set[int] = set()
    inflight_lock = threading.Lock()

    def _drain_loop(reason: str, grace_s: float) -> None:
        deadline = time.monotonic() + max(0.0, grace_s)
        while time.monotonic() < deadline:
            with inflight_lock:
                if not inflight:
                    break
            time.sleep(0.02)
        with inflight_lock:
            abandoned = sorted(inflight)
        link.send(
            {"type": "drained", "reason": reason, "abandoned": abandoned},
            important=True,
        )
        stop.set()
        try:
            link.sock.close()  # unblocks the main recv loop
        except OSError:
            pass
        if abandoned and sigterm_installed:
            # abandoned tasks are still running on pool threads; the process
            # must not linger joining them past its drain window (the
            # "drained" frame is already in the kernel send buffer — a
            # graceful FIN flushes it). An embedded (non-main-thread)
            # worker does not own its process: leave the orphans to their
            # daemon threads instead of exiting the host
            os._exit(0)

    def _begin_drain(reason: str, grace_s: float) -> None:
        with inflight_lock:
            if drain["on"]:
                return
            drain["on"] = True
        logger.warning(
            "worker %s: draining (%s, grace %.3fs, %d in flight)",
            wname, reason, grace_s, len(inflight),
        )
        link.send(
            {"type": "draining", "reason": reason, "grace_s": grace_s},
            important=True,
        )
        if reason == "preempted" and sigterm_installed:
            # spot semantics: the platform hard-kills at the end of the
            # notice window regardless of progress — model it so a wedged
            # in-flight task cannot outlive its preemption notice (small
            # epsilon lets a just-finished drain report first). Embedded
            # workers don't own the process: no hard-kill modelling
            t = threading.Timer(grace_s + 0.5, os._exit, args=(137,))
            t.daemon = True
            t.start()
        threading.Thread(
            target=_drain_loop, args=(reason, grace_s),
            name=f"worker-drain-{wname}", daemon=True,
        ).start()

    def _on_sigterm(signum, frame):
        # the spot preemption notice: drain inside the window, then die.
        # Hand off to a thread — the handler interrupts the main thread
        # mid-anything, and _begin_drain takes the link lock/inflight_lock,
        # which the interrupted frame may be holding (a non-reentrant
        # lock acquired from the handler would self-deadlock)
        threading.Thread(
            target=_begin_drain, args=("preempted", drain["grace"]),
            name=f"worker-sigterm-{wname}", daemon=True,
        ).start()

    try:
        _signal.signal(_signal.SIGTERM, _on_sigterm)
        sigterm_installed = True
    except ValueError:
        # not the main thread (embedded use): no spot semantics — injected
        # preemptions must then drain directly instead of raising a
        # default-disposition SIGTERM that would kill the HOST process
        sigterm_installed = False

    def run_task(msg: dict) -> None:
        task_id = msg["task_id"]
        with inflight_lock:
            if drain["on"]:
                rejected = True
            else:
                rejected = False
                inflight.add(task_id)
        if rejected:
            # raced the drain start: hand the task back unexecuted so the
            # coordinator requeues it free instead of waiting for a timeout
            link.send(
                {"type": "abandoned", "task_id": task_id}, important=True
            )
            return
        try:
            _run_task_inner(msg)
        finally:
            with inflight_lock:
                inflight.discard(task_id)

    def _run_task_inner(msg: dict) -> None:
        task_id = msg["task_id"]
        # correlate every log line/span this task emits with the client's
        # compute (the id rides each task message; None clears stale state)
        cid_token = obs_logs.compute_id_var.set(msg.get("compute_id"))
        try:
            # chaos hook: a named worker hard-exits or wedges when its
            # executed-task count reaches the configured threshold —
            # modelling OOM-kills and hung hosts. The task message carries
            # the client's arming state (mirrored here, None = disarm);
            # messages from an old coordinator fall back to the spawn env
            if "faults" in msg:
                injector = arm_from_wire(msg.get("faults"))
            else:
                injector = get_injector()
            if "integrity" in msg:
                integrity.arm_from_wire(msg.get("integrity"))
            if "memory_guard" in msg:
                memory.arm_from_wire(msg.get("memory_guard"))
            if "spans" in msg:
                arm_spans_from_wire(msg.get("spans"))
            if "peer" in msg:
                p2p.arm_from_wire(msg.get("peer"))
            if msg.get("cancel") is not None:
                # the compute's cancellation token (deadline epoch +
                # cancelled flag), registered by compute id: the checks in
                # execute_with_stats and the storage layer resolve it via
                # this task's compute-id context, so concurrent computes
                # on one worker cancel independently
                cancellation.arm_from_wire(msg.get("cancel"))
            if injector is not None:
                action = injector.worker_task_tick(wname)
                if action == "crash":
                    logger.warning("worker %s: injected crash", wname)
                    os._exit(137)
                elif action == "hang":
                    logger.warning("worker %s: injected hang", wname)
                    time.sleep(injector.config.worker_hang_s)
                elif action == "preempt":
                    # injected spot preemption: SIGTERM ourselves so the
                    # REAL handler runs (notice -> drain -> hard kill); the
                    # current task stays in flight and races the window
                    logger.warning(
                        "worker %s: injected spot preemption (notice %.2fs)",
                        wname, injector.config.preempt_notice_s,
                    )
                    drain["grace"] = float(injector.config.preempt_notice_s)
                    if sigterm_installed:
                        os.kill(os.getpid(), _signal.SIGTERM)
                    else:
                        # embedded (non-main-thread) worker: no handler to
                        # receive the signal — drain directly
                        _begin_drain("preempted", drain["grace"])
                if injector.task_fatal(chunk_key(msg["input"])):
                    # the poison-task chaos shape: THIS input kills every
                    # worker it lands on (kernel OOM-kill / segfault),
                    # deterministically per chunk key — abrupt exit, no
                    # drain, no error frame; the coordinator sees a dead
                    # link and requeues, and the quarantine path in
                    # map_unordered must end the loop
                    logger.warning(
                        "worker %s: injected poison-task fatal (task %s)",
                        wname, task_id,
                    )
                    os._exit(137)
            blob_id = msg["blob_id"]
            # decode under a lock (concurrent same-blob tasks must not race
            # the decode/pop), inside the task try: an undeserializable op
            # (missing module on this host, version skew) fails THIS task
            # with a real traceback instead of killing the worker
            dropped = []
            missing = False
            with blob_lock:
                pair = decoded_blobs.get(blob_id)
                if pair is None:
                    raw = raw_blobs.get(blob_id)
                    if raw is None:
                        # eviction raced this task's dispatch. With
                        # worker_threads > 1 this error frame can reach
                        # the socket BEFORE the evicting thread's
                        # blob_dropped for the same blob, so the
                        # coordinator would retry once without re-shipping
                        # bytes and burn a retry; send our own
                        # blob_dropped first (coordinator discard is
                        # idempotent) so the first retry carries the bytes
                        missing = True
                    else:
                        pair = cloudpickle.loads(raw)
                        decoded_blobs[blob_id] = pair
                        # raw bytes are dead weight once decoded (late
                        # duplicate tasks hit decoded_blobs first)
                        raw_blobs.pop(blob_id, None)
                        while len(decoded_blobs) > decoded_cap:
                            dropped.append(
                                decoded_blobs.popitem(last=False)[0]
                            )
                else:
                    decoded_blobs.move_to_end(blob_id)
            if missing:
                dropped.append(blob_id)
            for gone in dropped:
                link.send({"type": "blob_dropped", "blob_id": gone})
            if missing:
                raise RuntimeError(
                    f"unknown blob {blob_id!r} (evicted or never sent); "
                    "blob_dropped sent, the coordinator re-ships it on "
                    "retry"
                )
            function, config = pair
            if msg.get("ack"):
                # ack actual execution start (post decode): the coordinator
                # restarts this task's timeout clock, separating cold-start
                # delay from a real hang. Not outbox-retained — a stale
                # started ack is useless after a reconnect
                link.send({"type": "started", "task_id": task_id})
            # collect the chunks this task writes (storage hook →
            # transfer.note_chunk_written) so the advertisement can
            # piggyback on the result frame; thread-local, so concurrent
            # task slots never mix their lists
            p2p.begin_task_produced()
            try:
                if config is not None:
                    result, stats = execute_with_stats(
                        function, msg["input"], config=config
                    )
                else:
                    result, stats = execute_with_stats(function, msg["input"])
            finally:
                produced = p2p.end_task_produced()
            # live-telemetry residue in the WORKER's own registry: the
            # per-worker counters the heartbeat metrics_delta ships (the
            # authoritative per-compute numbers still ride the task stats
            # to the client — this is the continuous, per-worker view).
            # Scoped counters deliberately bypass the local registry
            # (accounting.record_scoped_counter), so a bounded allowlist
            # is folded here where the worker identity is known
            reg = get_registry()
            reg.counter("worker_tasks_executed").inc()
            for key in _WORKER_FOLD_COUNTERS:
                v = (stats.get("counters") or {}).get(key)
                if isinstance(v, (int, float)) and v:
                    reg.counter(key).inc(int(v))
            try:
                # important: retained in the outbox and replayed across a
                # reconnect, so a partition between finishing the task and
                # delivering its result costs nothing
                link.send(
                    {"type": "result", "task_id": task_id, "result": result,
                     "stats": stats, "produced": produced or None},
                    important=True,
                )
            except Exception:
                # unpicklable result (TypeError, PicklingError, ...): the
                # value lives in the shared store anyway (tasks communicate
                # through Zarr) — the task SUCCEEDED, so report completion.
                # Loud, not silent: this is only safe while pipeline task
                # RESULTS are never consumed; a future value-returning
                # pipeline must not quietly receive None. (link.send frames
                # BEFORE queueing, so the bad payload never reaches the
                # outbox.)
                logger.warning(
                    "task %s: result of type %s is not picklable; "
                    "reporting completion with result=None (safe only "
                    "because pipeline results flow through the store, "
                    "not the return value)",
                    task_id, type(result).__name__,
                )
                link.send(
                    {"type": "result", "task_id": task_id, "result": None,
                     "stats": stats, "produced": produced or None},
                    important=True,
                )
        except Exception as e:
            get_registry().counter("worker_task_errors").inc()
            try:
                link.send(
                    {"type": "error", "task_id": task_id,
                     "error": traceback.format_exc(),
                     # root class name rides along so the coordinator-side
                     # retry policy can classify remote programming errors
                     "error_type": type(e).__name__,
                     # structured payload (ChunkIntegrityError: the corrupt
                     # chunk's store/key) for coordinator-side repair
                     "error_payload": getattr(e, "wire_payload", None),
                     # the failed attempt's salvaged span buffer (plain
                     # dict — execute_with_stats attached it), so the
                     # client can land the failure on the merged trace
                     "task_stats": getattr(
                         e, "cubed_tpu_task_stats", None
                     )},
                    important=True,
                )
            except Exception:
                # the traceback/payload itself failed to pickle: ship a
                # minimal but well-formed error frame instead of silence
                link.send(
                    {"type": "error", "task_id": task_id,
                     "error": f"{type(e).__name__}: {e}",
                     "error_type": type(e).__name__},
                    important=True,
                )
        finally:
            obs_logs.compute_id_var.reset(cid_token)

    def heartbeat_loop() -> None:
        """RSS/memory-pressure telemetry plus the clock handshake's t0.

        The first heartbeat goes out immediately (not after the 1s period)
        so the coordinator's echo — and with it this worker's clock offset
        — exists before the first task completes: even a sub-second compute
        gets aligned worker spans. The coordinator only ever *reads* these;
        a worker that never heartbeats (older build) simply stays eligible
        for dispatch.

        Doubles as the **stale-link watchdog**: a healthy link echoes every
        heartbeat within ~RTT and acks important frames promptly, so
        receiving NOTHING for a few periods — or an important frame going
        unacked past its window — means the link is half-open (a one-way
        partition, a silently dead TCP stream). The watchdog then closes
        the socket, forcing the main recv loop into its reconnect path;
        against a healthy coordinator a spurious reconnect is cheap and
        harmless (the session token re-adopts the lease).

        Since the live-telemetry PR each heartbeat also piggybacks a
        bounded ``metrics_delta`` — this process's counter increments
        since the previous heartbeat — so the coordinator's telemetry
        pipeline sees worker-side progress continuously instead of once
        per task result."""
        hb_metrics_prev = get_registry().snapshot()
        while True:
            rss = current_measured_mem()
            pressure = memory.pressure_level()
            if peer_rt is not None:
                # evict-on-pressure: the chunk cache's budget is accounted
                # against the memory guard — under pressure the fast path
                # yields its footprint before admission control has to
                peer_rt.pressure_tick(pressure)
            hb = {
                "type": "heartbeat",
                "rss": rss,
                "pressured": (rss is not None and pressure != "ok"),
                "t0": obs_clock.now(),
            }
            if peer_rt is not None:
                hb["peer_cache"] = peer_rt.cache.stats()
                # evicted chunks ride the heartbeat so the coordinator's
                # location registry stops steering readers at them; a lost
                # heartbeat costs a fetch-miss + store fallback, nothing
                # more, so no ack/replay is needed
                evicted, flush = peer_rt.cache.drain_evictions()
                if flush:
                    hb["peer_cache_flush"] = True
                elif evicted:
                    hb["peer_evicted"] = evicted
            if clock_est["offset"] is not None:
                hb["clock_offset"] = clock_est["offset"]
                hb["clock_rtt"] = clock_est["rtt"]
            delta, hb_metrics_prev = heartbeat_metrics_delta(
                get_registry(), hb_metrics_prev
            )
            if delta is not None:
                hb["metrics_delta"] = delta
            link.send(hb)  # link failures heal via the recv loop's reconnect
            if (
                not stop.is_set()
                and not drain["on"]
                and (
                    link.unacked_age() > ACK_STALE_S
                    or time.monotonic() - link.last_rx > RX_STALE_S
                )
            ):
                logger.warning(
                    "worker %s: link looks half-open (last rx %.1fs ago, "
                    "oldest unacked %.1fs); forcing a reconnect",
                    wname, time.monotonic() - link.last_rx,
                    link.unacked_age(),
                )
                with link.lock:
                    s = link.sock
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            if stop.wait(1.0):
                return

    threading.Thread(
        target=heartbeat_loop, name=f"worker-heartbeat-{wname}", daemon=True
    ).start()

    pool = ThreadPoolExecutor(max_workers=max(nthreads, 1))
    SEEN_TASKS_CAP = 4096

    def _handle(msg: dict) -> bool:
        """Process one delivered frame; False = leave the recv loop."""
        mtype = msg.get("type")
        if mtype == "task":
            task_id = msg.get("task_id")
            if task_id in seen_tasks:
                get_registry().counter("fleet_assignments_deduped").inc()
                return True
            seen_tasks[task_id] = True
            while len(seen_tasks) > SEEN_TASKS_CAP:
                seen_tasks.popitem(last=False)
            if msg.get("blob") is not None:
                raw_blobs[msg["blob_id"]] = msg["blob"]
            pool.submit(run_task, msg)
        elif mtype == "ack":
            link.on_ack(msg.get("seq"))
        elif mtype == "hello_ack":
            pass  # handshake frames are consumed in _connect; a stray
            # duplicate (injected) carries nothing new
        elif mtype == "chunk_location":
            if peer_rt is not None:
                peer_rt.on_location(msg)
        elif mtype == "compute_cancel":
            # cooperative cancellation: trip (or pre-record) the named
            # compute's token so every in-flight task aborts at its next
            # chunk-IO boundary and queued assignments of that compute
            # fail fast instead of running
            cancellation.cancel_compute(
                msg.get("compute"), msg.get("reason")
            )
        elif mtype == "drain":
            # graceful scale-down (or an operator-initiated drain):
            # same path as the SIGTERM handler, reason carried over
            # (grace_s=0.0 is a legitimate "abandon immediately" —
            # only an ABSENT grace falls back to the default)
            g = msg.get("grace_s")
            _begin_drain(
                msg.get("reason") or "scale_down",
                float(drain["grace"] if g is None else g),
            )
        elif mtype == "heartbeat_echo":
            # NTP-style: the coordinator echoed our t0 with its own
            # clock; offset = t_coord - midpoint(t0, t1), accurate
            # to ~rtt/2. Accept a sample when its rtt is comparable
            # to the BEST rtt ever seen (a fixed anchor — never
            # ratcheted by accepted samples — with a 1ms epsilon so
            # coarse clocks reporting rtt=0 still refresh), so slow
            # clock drift heals without estimate quality degrading
            # under rising load. Ship it back immediately — the
            # next task's spans may be exported before the next
            # 1s heartbeat
            t1 = obs_clock.now()
            t0, tc = msg.get("t0"), msg.get("t_coord")
            if t0 is not None and tc is not None:
                rtt = max(0.0, t1 - t0)
                best = clock_est.get("best")
                if best is None or rtt < best:
                    best = rtt
                clock_est["best"] = best
                if (
                    clock_est["rtt"] is None
                    or rtt <= best * 1.5 + 1e-3
                ):
                    clock_est["offset"] = tc - (t0 + t1) / 2
                    clock_est["rtt"] = rtt
                    link.send({
                        "type": "clock",
                        "clock_offset": clock_est["offset"],
                        "clock_rtt": rtt,
                    })
        elif mtype == "shutdown":
            return False
        else:
            logger.warning("worker: unknown message %r", mtype)
        return True

    try:
        while not stop.is_set():
            try:
                msg = recv_frame(link.sock)
            except CorruptFrameError as e:
                # a torn/garbage frame: the stream is useless from here —
                # count it, drop the connection, reconnect with a clean one
                get_registry().counter("frames_corrupt").inc()
                logger.warning(
                    "worker %s: corrupt frame from coordinator (%s); "
                    "reconnecting", wname, e,
                )
                try:
                    link.sock.close()
                except OSError:
                    pass
                if stop.is_set() or drain["on"] or not _reconnect():
                    break
                continue
            except (ConnectionError, OSError):
                if stop.is_set() or drain["on"]:
                    break  # shutdown or our own drain closed the socket
                if not _reconnect():
                    break  # coordinator unreachable past the give-up window
                continue
            if not isinstance(msg, dict):
                logger.warning(
                    "worker %s: non-dict frame %r ignored", wname,
                    type(msg).__name__,
                )
                continue
            mepoch = msg.get("epoch")
            if mepoch is not None and int(mepoch) < link.epoch:
                # a zombie prior-epoch coordinator still speaking on an
                # old socket: fence its frames — above all its acks,
                # which must not clear outbox results the successor epoch
                # has never processed
                get_registry().counter("stale_epoch_frames").inc()
                logger.warning(
                    "worker %s: fenced stale-epoch frame (%r, epoch %s < "
                    "%d)", wname, msg.get("type"), mepoch, link.epoch,
                )
                continue
            inj = get_injector()
            if inj is not None and inj.partitioned(wname, "rx"):
                # one-way partition, coordinator→worker leg: the frame was
                # never delivered — last_rx must NOT refresh, so the
                # watchdog sees the silence a real partition would cause
                continue
            link.last_rx = time.monotonic()
            if inj is not None:
                act = inj.net_fault("rx", wname, msg.get("type"))
                if act == "drop":
                    continue
                if act == "delay":
                    time.sleep(inj.config.net_msg_delay_s)
                if act == "reset":
                    try:
                        link.sock.close()
                    except OSError:
                        pass
                    continue  # the next recv notices and reconnects
                if act == "dup":
                    if not _handle(dict(msg)):
                        break
            if not _handle(msg):
                break
    finally:
        # every exit from the recv loop — shutdown frame, coordinator
        # unreachable past the reconnect window, or our own drain — means
        # this worker's outstanding futures are (or will be) failed or
        # requeued coordinator-side, so queued tasks produce results
        # nobody can receive: cancel them instead of running them out
        pool.shutdown(wait=False, cancel_futures=True)
    stop.set()  # silence the heartbeat/watchdog thread
    if peer_rt is not None:
        p2p.set_worker_runtime(None)
        peer_rt.close()
    try:
        link.sock.close()
    except OSError:
        pass
    if sigterm_installed:
        # give RUNNING tasks a moment to finish (their threads are
        # non-daemon: the interpreter would join them at exit), then
        # leave without blocking on a hung one — close() escalates to
        # SIGKILL after 10s otherwise, which is strictly worse. Embedded
        # (non-main-thread) workers don't own the process: they return
        # and leave stragglers to their own threads
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with inflight_lock:
                if not inflight:
                    return
            time.sleep(0.02)
        os._exit(0)
